"""Reasoning-content ("thinking") parsers, batch and streaming.

Role of reference lib/parsers/src/reasoning/ (base_parser.rs tag-pair
parser, granite_parser.rs marker phrases, gpt_oss_parser.rs harmony
analysis channel): split model output into (reasoning_content, content).
Streaming parsers are incremental — feed text deltas, get
(reasoning_delta, content_delta) back, with partial markers held until
disambiguated.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional, Tuple


@dataclass
class ParsedDelta:
    reasoning: str = ""
    content: str = ""


class BasicReasoningParser:
    """Tag-pair reasoning (`<think> ... </think>`), the reference's
    base_parser.rs with configurable tokens (deepseek-r1, qwen3, nemotron
    families). `starts_inside` models checkpoints that open mid-thought."""

    def __init__(
        self,
        start_token: str = "<think>",
        end_token: str = "</think>",
        starts_inside: bool = False,
    ):
        self.start_token = start_token
        self.end_token = end_token
        self.in_reasoning = starts_inside
        self._buf = ""

    # -- batch --------------------------------------------------------------
    def parse(self, text: str) -> Tuple[str, str]:
        """Complete-output split -> (reasoning, content)."""
        reasoning: list[str] = []
        content: list[str] = []
        rest = text
        inside = self.in_reasoning
        while rest:
            if inside:
                if self.end_token in rest:
                    seg, rest = rest.split(self.end_token, 1)
                    reasoning.append(seg)
                    inside = False
                else:
                    reasoning.append(rest)
                    rest = ""
            else:
                if self.start_token in rest:
                    seg, rest = rest.split(self.start_token, 1)
                    content.append(seg)
                    inside = True
                else:
                    content.append(rest)
                    rest = ""
        return "".join(reasoning).strip(), "".join(content).strip()

    # -- streaming ------------------------------------------------------------
    def _could_be_marker_prefix(self, tail: str) -> int:
        from .tool_calling import held_suffix_len

        return held_suffix_len(tail, (self.start_token, self.end_token))

    def feed(self, delta: str) -> ParsedDelta:
        self._buf += delta
        out = ParsedDelta()
        while True:
            marker = self.end_token if self.in_reasoning else self.start_token
            idx = self._buf.find(marker)
            if idx >= 0:
                seg = self._buf[:idx]
                if self.in_reasoning:
                    out.reasoning += seg
                else:
                    out.content += seg
                self._buf = self._buf[idx + len(marker):]
                self.in_reasoning = not self.in_reasoning
                continue
            hold = self._could_be_marker_prefix(self._buf)
            emit = self._buf[: len(self._buf) - hold]
            self._buf = self._buf[len(self._buf) - hold:]
            if self.in_reasoning:
                out.reasoning += emit
            else:
                out.content += emit
            return out

    def flush(self) -> ParsedDelta:
        out = ParsedDelta()
        if self._buf:
            if self.in_reasoning:
                out.reasoning = self._buf
            else:
                out.content = self._buf
            self._buf = ""
        return out


class GraniteReasoningParser(BasicReasoningParser):
    """IBM Granite phrase markers (reference granite_parser.rs):
    'Here is my thought process:' ... 'Here is my response:'."""

    def __init__(self):
        super().__init__(
            start_token="Here is my thought process:",
            end_token="Here is my response:",
        )


class GptOssReasoningParser(BasicReasoningParser):
    """GPT-OSS harmony channels (reference gpt_oss_parser.rs): the analysis
    channel is reasoning, the final channel is content; channel markers
    never reach the client in either mode."""

    _ANALYSIS = "<|channel|>analysis<|message|>"
    _FINAL = "<|channel|>final<|message|>"
    _ENDS = ("<|end|>", "<|return|>")
    # role headers are stripped, never shown (harmony message framing)
    _ROLES = ("<|start|>assistant", "<|start|>user", "<|start|>system")

    def __init__(self):
        super().__init__(start_token=self._ANALYSIS, end_token="<|end|>")
        self._markers = (self._ANALYSIS, self._FINAL) + self._ENDS + self._ROLES

    # -- streaming: marker-driven channel switch ---------------------------
    def feed(self, delta: str) -> ParsedDelta:
        from .tool_calling import held_suffix_len

        self._buf += delta
        out = ParsedDelta()
        while True:
            hit = None  # (index, marker); at equal index prefer the longest
            for m in self._markers:
                i = self._buf.find(m)
                if i >= 0 and (
                    hit is None or i < hit[0] or (i == hit[0] and len(m) > len(hit[1]))
                ):
                    hit = (i, m)
            if hit is not None:
                i, m = hit
                seg = self._buf[:i]
                if self.in_reasoning:
                    out.reasoning += seg
                else:
                    out.content += seg
                self._buf = self._buf[i + len(m):]
                if m == self._ANALYSIS:
                    self.in_reasoning = True
                elif m == self._FINAL or m in self._ENDS:
                    self.in_reasoning = False
                # role headers: no state change, just stripped
                continue
            hold = held_suffix_len(self._buf, self._markers)
            emit = self._buf[: len(self._buf) - hold]
            self._buf = self._buf[len(self._buf) - hold:]
            if self.in_reasoning:
                out.reasoning += emit
            else:
                out.content += emit
            return out

    def parse(self, text: str) -> Tuple[str, str]:
        reasoning = "".join(
            m.group(1)
            for m in re.finditer(
                r"<\|channel\|>analysis<\|message\|>(.*?)(?:<\|end\|>|$)",
                text,
                re.DOTALL,
            )
        )
        final = re.search(
            r"<\|channel\|>final<\|message\|>(.*?)(?:<\|end\|>|<\|return\|>|$)",
            text,
            re.DOTALL,
        )
        content = final.group(1) if final else ""
        if not reasoning and not final:
            return "", text
        return reasoning.strip(), content.strip()


REASONING_PARSERS = {
    "basic": BasicReasoningParser,
    "deepseek_r1": lambda: BasicReasoningParser(starts_inside=True),
    "granite": GraniteReasoningParser,
    "gpt_oss": GptOssReasoningParser,
}


def get_reasoning_parser(name: Optional[str]) -> Optional[BasicReasoningParser]:
    if name is None:
        return None
    if name not in REASONING_PARSERS:
        raise ValueError(
            f"unknown reasoning parser {name!r}; available: {sorted(REASONING_PARSERS)}"
        )
    return REASONING_PARSERS[name]()
