"""Model-output parsers: tool calls + reasoning content + jailed stream.

Role of the reference's lib/parsers crate (tool_calling/parsers.rs,
reasoning/mod.rs) and the JailedStream operator
(lib/llm/src/protocols/openai/chat_completions/jail.rs): per-model-family
extraction of structured tool calls and reasoning ("thinking") segments
from generated text, both batch and streaming.
"""

from .jail import JailedStream
from .reasoning import (
    BasicReasoningParser,
    GptOssReasoningParser,
    GraniteReasoningParser,
    get_reasoning_parser,
)
from .tool_calling import (
    ToolCallResult,
    detect_tool_call_start,
    get_available_tool_parsers,
    try_tool_call_parse,
)

__all__ = [
    "BasicReasoningParser",
    "GptOssReasoningParser",
    "GraniteReasoningParser",
    "JailedStream",
    "ToolCallResult",
    "detect_tool_call_start",
    "get_available_tool_parsers",
    "get_reasoning_parser",
    "try_tool_call_parse",
]
