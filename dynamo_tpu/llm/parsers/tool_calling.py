"""Tool-call parsing per model family.

Role of reference lib/parsers/src/tool_calling/ (parsers.rs registry,
config.rs token configs, json/ + pythonic/ + harmony/ strategies): given a
model's complete text output, split it into (tool_calls, normal_content).
Named configs cover the same families the reference registers
(parsers.rs:180-189): hermes, llama3_json, mistral, nemotron_deci, phi4,
deepseek_v3_1, pythonic, harmony, default.
"""

from __future__ import annotations

import ast
import json
import re
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple


@dataclass
class ToolCallResult:
    name: str
    arguments: str  # JSON-encoded argument object
    id: str = field(default_factory=lambda: f"call-{uuid.uuid4().hex[:16]}")


@dataclass(frozen=True)
class JsonToolConfig:
    """Token-delimited JSON tool-call format (config.rs JsonParserConfig)."""

    start_tokens: Tuple[str, ...] = ()
    end_tokens: Tuple[str, ...] = ()
    name_keys: Tuple[str, ...] = ("name",)
    args_keys: Tuple[str, ...] = ("arguments", "parameters")
    # accept bare JSON (no start token) that looks like a tool call
    allow_bare_json: bool = True


PARSER_CONFIGS: Dict[str, JsonToolConfig] = {
    "default": JsonToolConfig(
        start_tokens=("<TOOLCALL>", "<|python_tag|>"), end_tokens=("</TOOLCALL>",)
    ),
    "hermes": JsonToolConfig(
        start_tokens=("<tool_call>",), end_tokens=("</tool_call>",)
    ),
    "llama3_json": JsonToolConfig(
        start_tokens=("<|python_tag|>",), end_tokens=("<|eom_id|>",)
    ),
    "mistral": JsonToolConfig(
        start_tokens=("[TOOL_CALLS]",), end_tokens=()
    ),
    "nemotron_deci": JsonToolConfig(
        start_tokens=("<TOOLCALL>",), end_tokens=("</TOOLCALL>",),
        allow_bare_json=False,
    ),
    "phi4": JsonToolConfig(
        start_tokens=("functools",), end_tokens=(), allow_bare_json=False
    ),
    "deepseek_v3_1": JsonToolConfig(
        start_tokens=("<｜tool▁calls▁begin｜>",),
        end_tokens=("<｜tool▁calls▁end｜>",),
        allow_bare_json=False,
    ),
}


def get_available_tool_parsers() -> List[str]:
    return sorted(list(PARSER_CONFIGS) + ["pythonic", "harmony"])


def _start_tokens_for(parser: str) -> Tuple[Tuple[str, ...], bool]:
    """(start tokens, bare-json-allowed) for a parser name; raises
    ValueError on unknown names."""
    if parser == "pythonic":
        return ("[",), False
    if parser == "harmony":
        return ("<|channel|>", "<|start|>"), False
    if parser not in PARSER_CONFIGS:
        raise ValueError(
            f"unknown tool parser {parser!r}; available: {get_available_tool_parsers()}"
        )
    cfg = PARSER_CONFIGS[parser]
    return cfg.start_tokens, cfg.allow_bare_json


def held_suffix_len(text: str, markers: Sequence[str]) -> int:
    """Length of the longest suffix of `text` that is a PROPER prefix of any
    marker (a complete marker would have been found by search, so the scan
    is bounded at max marker length - 1). Shared by the streaming reasoning
    parsers and the tool-call jail."""
    max_len = max((len(m) for m in markers), default=0)
    for n in range(min(len(text), max_len - 1), 0, -1):
        suf = text[-n:]
        if any(m.startswith(suf) for m in markers):
            return n
    return 0


def find_tool_call_start(
    text: str, parser: Optional[str] = None, allow_bare: bool = True
) -> Tuple[Optional[int], int]:
    """Scan accumulated text for a tool-call region start. Returns
    (start_index or None, held_suffix_len): `start_index` is the earliest
    position of a complete start marker (everything from there must be
    jailed); `held_suffix_len` is the length of a trailing partial marker
    that must be held back until the next delta disambiguates it.
    `allow_bare=False` disables the bare-JSON heuristic (callers pass False
    once mid-message — a quoted JSON example must not become a tool call)."""
    parser = parser or "default"
    starts, cfg_allow_bare = _start_tokens_for(parser)
    idx: Optional[int] = None
    for tok in starts:
        i = text.find(tok)
        if i >= 0 and (idx is None or i < idx):
            idx = i
    if cfg_allow_bare and allow_bare and idx is None:
        stripped = text.lstrip()
        if stripped[:1] in ("{", "["):
            idx = len(text) - len(stripped)
    if idx is not None:
        return idx, 0
    return None, held_suffix_len(text, starts)


def detect_tool_call_start(text: str, parser: Optional[str] = None) -> bool:
    """True if `text` contains or could be the beginning of a tool-call
    region (parsers.rs detect_tool_call_start)."""
    idx, held = find_tool_call_start(text, parser)
    return idx is not None or held > 0


def _extract_call(obj: Any, cfg: JsonToolConfig) -> Optional[ToolCallResult]:
    if not isinstance(obj, dict):
        return None
    name = next((obj[k] for k in cfg.name_keys if k in obj), None)
    if not isinstance(name, str):
        # nested {"function": {...}} / {"type":"function","function":{...}}
        inner = obj.get("function")
        if isinstance(inner, dict):
            return _extract_call(inner, cfg)
        return None
    args = next((obj[k] for k in cfg.args_keys if k in obj), {})
    if isinstance(args, str):
        args_str = args
    else:
        args_str = json.dumps(args)
    return ToolCallResult(name=name, arguments=args_str)


def _parse_json_region(region: str, cfg: JsonToolConfig) -> List[ToolCallResult]:
    region = region.strip()
    calls: List[ToolCallResult] = []
    # try whole-region parse first (object or array)
    for candidate in _json_candidates(region):
        try:
            obj = json.loads(candidate)
        except json.JSONDecodeError:
            continue
        objs = obj if isinstance(obj, list) else [obj]
        for o in objs:
            c = _extract_call(o, cfg)
            if c:
                calls.append(c)
        if calls:
            return calls
    return calls


def _json_candidates(region: str) -> List[str]:
    """The region itself, plus `;`-separated chunks (llama3 parallel style)."""
    out = [region]
    if ";" in region:
        out.extend(part for part in region.split(";") if part.strip())
    return out


def _parse_token_delimited(
    text: str, cfg: JsonToolConfig
) -> Tuple[List[ToolCallResult], str]:
    calls: List[ToolCallResult] = []
    content = text
    for start in cfg.start_tokens:
        if start not in content:
            continue
        while start in content:
            pre, rest = content.split(start, 1)
            for end in cfg.end_tokens:
                if end and end in rest:
                    region, rest = rest.split(end, 1)
                    break
            else:
                region, rest = rest, ""
            calls.extend(_parse_json_region(region, cfg))
            content = pre + rest
        if calls:
            return calls, content.strip()
    if cfg.allow_bare_json:
        stripped = text.strip()
        if stripped[:1] in ("{", "["):
            calls = _parse_json_region(stripped, cfg)
            if calls:
                return calls, ""
    return [], text


def _parse_pythonic(text: str) -> Tuple[List[ToolCallResult], str]:
    """`[get_weather(city="SF"), f2(x=1)]` (pythonic/ in the reference)."""
    stripped = text.strip()
    m = re.search(r"\[.*\]", stripped, re.DOTALL)
    if not m:
        return [], text
    try:
        tree = ast.parse(m.group(0), mode="eval")
    except SyntaxError:
        return [], text
    if not isinstance(tree.body, ast.List):
        return [], text
    calls: List[ToolCallResult] = []
    for el in tree.body.elts:
        if not isinstance(el, ast.Call):
            return [], text
        if isinstance(el.func, ast.Name):
            name = el.func.id
        elif isinstance(el.func, ast.Attribute):
            name = el.func.attr
        else:
            return [], text
        args: Dict[str, Any] = {}
        try:
            for kw in el.keywords:
                args[kw.arg] = ast.literal_eval(kw.value)
        except (ValueError, SyntaxError):
            return [], text
        calls.append(ToolCallResult(name=name, arguments=json.dumps(args)))
    content = (stripped[: m.start()] + stripped[m.end():]).strip()
    return calls, content


_HARMONY_CALL = re.compile(
    r"<\|channel\|>commentary\s+to=(?:functions\.)?([\w.\-]+)"
    r".*?<\|message\|>(.*?)(?:<\|call\|>|$)",
    re.DOTALL,
)
_HARMONY_FINAL = re.compile(
    r"<\|channel\|>final<\|message\|>(.*?)(?:<\|end\|>|<\|return\|>|$)", re.DOTALL
)


def _parse_harmony(text: str) -> Tuple[List[ToolCallResult], str]:
    """GPT-OSS harmony channel format (harmony/ in the reference):
    `<|channel|>commentary to=functions.NAME ...<|message|>{args}<|call|>`."""
    calls = [
        ToolCallResult(name=m.group(1), arguments=m.group(2).strip())
        for m in _HARMONY_CALL.finditer(text)
    ]
    final = _HARMONY_FINAL.search(text)
    content = final.group(1).strip() if final else ""
    if not calls and not final:
        return [], text
    return calls, content


def try_tool_call_parse(
    text: str, parser: Optional[str] = None
) -> Tuple[List[ToolCallResult], str]:
    """Parse complete model output; returns (tool_calls, remaining_content).
    Unparseable input comes back as ([], text) — never raises."""
    parser = parser or "default"
    if parser == "pythonic":
        return _parse_pythonic(text)
    if parser == "harmony":
        return _parse_harmony(text)
    if parser not in PARSER_CONFIGS:
        raise ValueError(
            f"unknown tool parser {parser!r}; available: {get_available_tool_parsers()}"
        )
    return _parse_token_delimited(text, PARSER_CONFIGS[parser])
