"""SLA-driven autoscaling planner (reference: components/planner).

Observe frontend metrics → predict load → size prefill/decode replica
counts from profiled interpolators → scale via a connector. See
planner_core.py for the loop, profiler.py for the sweep that produces the
interpolation profiles, connector.py for scaling backends.
"""

from .connector import (
    DiscoveryWorkerCounts,
    LocalProcessConnector,
    NoopConnector,
    NoopMorphConnector,
    VirtualConnector,
)
from .load_predictor import (
    ARPredictor,
    ConstantPredictor,
    MovingAveragePredictor,
    make_predictor,
)
from .metrics_source import FrontendMetricsSource
from .perf_interpolation import DecodeInterpolator, PrefillInterpolator
from .planner_core import Metrics, Planner, ScaleDecision, SlaArgs

__all__ = [
    "ARPredictor",
    "ConstantPredictor",
    "DecodeInterpolator",
    "DiscoveryWorkerCounts",
    "FrontendMetricsSource",
    "LocalProcessConnector",
    "Metrics",
    "MovingAveragePredictor",
    "NoopConnector",
    "NoopMorphConnector",
    "Planner",
    "PrefillInterpolator",
    "ScaleDecision",
    "SlaArgs",
    "VirtualConnector",
    "make_predictor",
]
