"""Pre-deployment SLA profiler: sweep the JAX engine, write planner profiles.

Role of the reference's profiler (benchmarks/profiler/profile_sla.py +
docs/benchmarks/pre_deployment_profiling.md): measure, per chip, (a)
prefill throughput and TTFT across input lengths and (b) decode ITL and
throughput across (kv-cache usage, context length) operating points, then
write npz files in the exact raw_data layout the planner's interpolators
load (selected_prefill_interpolation/raw_data.npz and
selected_decode_interpolation/raw_data.npz, field names per
perf_interpolation.py — "gpu" in names reads "chip").

Timing follows bench.py: a scalar device_get fences each region (under the
axon TPU tunnel block_until_ready returns early).
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence

import numpy as np


def _fence(x) -> None:
    import jax

    np.asarray(jax.device_get(x.ravel()[0]))


def profile_prefill(
    cfg, isl_grid: Sequence[int], page: int = 64, num_chips: int = 1
) -> Dict[str, np.ndarray]:
    """Time single-sequence prefill at each ISL; returns the planner's
    prefill raw_data dict (ttft in ms, throughput in tok/s/chip)."""
    import jax
    import jax.numpy as jnp

    from ..engine.kv_cache import alloc_kv_arrays
    from ..models import llama

    isl_grid = sorted(isl_grid)
    max_isl = isl_grid[-1]
    pages_per_seq = (max_isl + page - 1) // page
    num_pages = pages_per_seq + 1
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    kv_k, kv_v = alloc_kv_arrays(
        cfg.num_layers, num_pages, page, cfg.num_kv_heads, cfg.head_dim, cfg.dtype
    )
    page_table = jnp.arange(pages_per_seq, dtype=jnp.int32)

    prefill = jax.jit(
        lambda p, kk, kv, t, pos, li: llama.prefill_forward(
            p, cfg, t, pos, kk, kv, page_table, jnp.asarray(0, jnp.int32), li
        ),
        donate_argnums=(1, 2),
    )

    ttft_ms: List[float] = []
    thpt: List[float] = []
    rng = np.random.RandomState(0)
    for isl in isl_grid:
        toks = jnp.asarray(rng.randint(3, cfg.vocab_size - 1, size=isl), jnp.int32)
        pos = jnp.arange(isl, dtype=jnp.int32)
        li = jnp.asarray(isl - 1, jnp.int32)
        # compile + warmup
        logits, kv_k, kv_v = prefill(params, kv_k, kv_v, toks, pos, li)
        _fence(logits)
        reps = 3
        t0 = time.perf_counter()
        for _ in range(reps):
            logits, kv_k, kv_v = prefill(params, kv_k, kv_v, toks, pos, li)
        _fence(logits)
        dt = (time.perf_counter() - t0) / reps
        ttft_ms.append(dt * 1000.0)
        thpt.append(isl / dt / num_chips)

    return {
        "prefill_isl": np.asarray(isl_grid, np.float64),
        "prefill_ttft": np.asarray(ttft_ms, np.float64),
        "prefill_thpt_per_gpu": np.asarray(thpt, np.float64),
    }


def profile_decode(
    cfg,
    context_grid: Sequence[int],
    kv_usage_grid: Sequence[float],
    max_kv_tokens: int,
    page: int = 64,
    num_chips: int = 1,
    decode_steps: int = 8,
) -> Dict[str, np.ndarray]:
    """Time batched decode at each (kv_usage, context) operating point
    (batch = kv_usage * max_kv_tokens / context); returns the planner's
    decode raw_data dict (itl in ms, throughput in tok/s/chip)."""
    import jax
    import jax.numpy as jnp

    from ..engine.kv_cache import alloc_kv_arrays
    from ..engine.sampling import SamplingParams, sample
    from ..models import llama

    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    num_pages = max_kv_tokens // page + 1
    kv_k, kv_v = alloc_kv_arrays(
        cfg.num_layers, num_pages, page, cfg.num_kv_heads, cfg.head_dim, cfg.dtype
    )

    def _decode(params, kv_k, kv_v, tokens, positions, page_tables, seq_lens, samp, key):
        lg, kv_k, kv_v = llama.decode_forward(
            params, cfg, tokens, positions, kv_k, kv_v, page_tables, seq_lens
        )
        return sample(lg, samp, key), kv_k, kv_v

    decode_step = jax.jit(_decode, donate_argnums=(1, 2))

    xs: List[float] = []
    ys: List[float] = []
    itl: List[float] = []
    thpt: List[float] = []
    for ctx in context_grid:
        pages_per_seq = (ctx + page - 1) // page
        for usage in kv_usage_grid:
            B = max(1, int(usage * max_kv_tokens / ctx))
            if B * pages_per_seq >= num_pages:
                B = (num_pages - 1) // pages_per_seq
                if B < 1:
                    continue
            pt = (
                1 + np.arange(B)[:, None] * pages_per_seq + np.arange(pages_per_seq)
            ) % num_pages
            page_tables = jnp.asarray(pt, jnp.int32)
            tokens = jnp.zeros((B,), jnp.int32)
            positions = jnp.full((B,), ctx - 1, jnp.int32)
            seq_lens = jnp.full((B,), ctx, jnp.int32)
            samp = SamplingParams.full(B, temperature=0.0)
            key = jax.random.PRNGKey(1)
            tokens, kv_k, kv_v = decode_step(
                params, kv_k, kv_v, tokens, positions, page_tables, seq_lens, samp, key
            )
            _fence(tokens)
            t0 = time.perf_counter()
            for i in range(decode_steps):
                key = jax.random.fold_in(key, i)
                tokens, kv_k, kv_v = decode_step(
                    params, kv_k, kv_v, tokens, positions, page_tables, seq_lens,
                    samp, key,
                )
            _fence(tokens)
            dt = (time.perf_counter() - t0) / decode_steps
            xs.append(usage)
            ys.append(float(ctx))
            itl.append(dt * 1000.0)
            thpt.append(B / dt / num_chips)

    return {
        "x_kv_usage": np.asarray(xs, np.float64),
        "y_context_length": np.asarray(ys, np.float64),
        "z_itl": np.asarray(itl, np.float64),
        "z_thpt_per_gpu": np.asarray(thpt, np.float64),
        "max_kv_tokens": np.asarray([max_kv_tokens], np.float64),
    }


def write_profiles(
    output_dir: str,
    prefill_raw: Dict[str, np.ndarray],
    decode_raw: Dict[str, np.ndarray],
) -> None:
    """Write both npz files in the directory layout the interpolators read."""
    for sub, raw in (
        ("selected_prefill_interpolation", prefill_raw),
        ("selected_decode_interpolation", decode_raw),
    ):
        d = os.path.join(output_dir, sub)
        os.makedirs(d, exist_ok=True)
        np.savez(os.path.join(d, "raw_data.npz"), **raw)


def main(argv: Optional[Sequence[str]] = None) -> None:
    import argparse

    ap = argparse.ArgumentParser(description="SLA profiler sweep (JAX engine)")
    ap.add_argument("--model", default="tiny")
    ap.add_argument("--output-dir", required=True)
    ap.add_argument("--isl-grid", type=int, nargs="+", default=[128, 512, 1024, 2048, 4096])
    ap.add_argument("--context-grid", type=int, nargs="+", default=[256, 1024, 4096])
    ap.add_argument(
        "--kv-usage-grid", type=float, nargs="+", default=[0.1, 0.25, 0.5, 0.75, 0.95]
    )
    ap.add_argument("--max-kv-tokens", type=int, default=1 << 16)
    ap.add_argument("--num-chips", type=int, default=1)
    args = ap.parse_args(argv)

    from ..models import llama

    cfgs = {
        "tiny": llama.LlamaConfig.tiny,
        "llama3-3b": llama.LlamaConfig.llama3_2_3b,
        "llama3-8b": llama.LlamaConfig.llama3_8b,
    }
    cfg = cfgs[args.model]()
    prefill_raw = profile_prefill(cfg, args.isl_grid, num_chips=args.num_chips)
    decode_raw = profile_decode(
        cfg, args.context_grid, args.kv_usage_grid, args.max_kv_tokens,
        num_chips=args.num_chips,
    )
    write_profiles(args.output_dir, prefill_raw, decode_raw)
    print(f"profiles written to {args.output_dir}")


if __name__ == "__main__":
    main()
