"""Planner connectors: turn a replica decision into actual scaling.

Reference equivalents: `KubernetesConnector` patches a
DynamoGraphDeployment CRD (components/planner/src/dynamo/planner/
kubernetes_connector.py) and `VirtualConnector` publishes the decision to
etcd for an external orchestrator (virtual_connector.py). Here:

* ``VirtualConnector`` writes the decision to the discovery service KV
  (``v1/planner/decision``) with a monotonically increasing revision —
  any orchestrator (k8s operator, slice manager) watches and acts.
* ``LocalProcessConnector`` scales real worker subprocesses on this host
  (the test/e2e orchestrator, reference's ManagedProcess-style role).
* ``NoopConnector`` records decisions (dryrun / unit tests).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import signal
import sys
import time
from typing import Awaitable, Callable, Dict, List, Optional, Sequence, Tuple

from ..runtime import faults
from ..runtime.backoff import Backoff, retry_async

logger = logging.getLogger(__name__)

PLANNER_DECISION_KEY = "v1/planner/decision"


class NoopConnector:
    def __init__(self):
        self.decisions: List[Tuple[int, int]] = []
        self.frontend_decisions: List[int] = []

    async def set_replicas(self, prefill: int, decode: int,
                           frontend: Optional[int] = None) -> None:
        self.decisions.append((prefill, decode))
        if frontend is not None:
            self.frontend_decisions.append(frontend)


class NoopMorphConnector(NoopConnector):
    """NoopConnector that also exposes the role-morph capability surface
    (morph_replicas / colocate), recording calls — the planner's re-role
    arm activates only on connectors with these attributes, so the plain
    Noop keeps legacy tests' decision logs byte-stable."""

    def __init__(self):
        super().__init__()
        self.morphs: List[Tuple[str, str, int]] = []
        self.colocations = 0

    async def morph_replicas(self, from_role: str, to_role: str, k: int) -> int:
        f = faults.FAULTS
        if f.enabled:
            await f.on("planner.connector")  # `error` raises; planner retries
        self.morphs.append((from_role, to_role, k))
        return k

    async def colocate(self) -> bool:
        self.colocations += 1
        return True


class VirtualConnector:
    """Publish {num_prefill, num_decode, revision} to discovery KV.
    Revisions continue from whatever is already stored, so they stay
    monotonic across planner restarts."""

    def __init__(self, discovery_client):
        self.client = discovery_client
        self.revision: Optional[int] = None
        # two concurrent publishers would both lazy-load, both increment,
        # and ship duplicate revision numbers — which the revision-gated
        # consumers (operator-lite) silently skip
        self._rev_lock = asyncio.Lock()

    async def _load_revision(self) -> int:
        raw = await self.client.get(PLANNER_DECISION_KEY)
        if raw:
            try:
                return int(json.loads(raw).get("revision", 0))
            except (ValueError, TypeError, AttributeError, json.JSONDecodeError):
                pass  # malformed stored doc: restart revisions from 0
        return 0

    async def set_replicas(self, prefill: int, decode: int,
                           frontend: Optional[int] = None) -> None:
        f = faults.FAULTS
        if f.enabled:
            await f.on("planner.connector")  # `error` raises; planner retries
        async with self._rev_lock:
            if self.revision is None:
                self.revision = await self._load_revision()
            self.revision += 1
            doc = {
                "num_prefill_workers": prefill,
                "num_decode_workers": decode,
                "revision": self.revision,
                "ts": time.time(),
            }
            if frontend is not None:
                # frontend role (docs/frontend_scaleout.md): stateless
                # replicas over shared discovery, scaled like workers.
                # Absent = orchestrators leave the frontend tier alone.
                doc["num_frontends"] = frontend
            await self.client.put(PLANNER_DECISION_KEY, json.dumps(doc).encode())
            logger.info("published planner decision rev=%d p=%d d=%d f=%s",
                        self.revision, prefill, decode, frontend)


class LocalProcessConnector:
    """Scale worker replicas as local subprocesses.

    `prefill_cmd` / `decode_cmd` are argv templates; each spawned replica
    gets the env of the parent plus DYN_WORKER_INDEX. Scaling down kills
    the newest replicas first (SIGTERM → the worker's graceful drain;
    SIGKILL after grace).

    Robustness contract (exercised by the `worker.spawn` fault point and
    the planner soak): a failed exec or a child that dies before reporting
    ready is retried with seeded backoff, bounded by `spawn_retries` per
    set_replicas call — and because the planner re-asserts its target via
    `reconcile()` every interval, even an exhausted budget never strands
    the replica count. With `ready_fn` set (an async `(role) -> int` of
    READY replicas, e.g. `DiscoveryWorkerCounts` — which excludes draining
    workers and, because workers register only after warmup, counts a
    fresh replica only once its warmup gate passed), scale-up additionally
    waits up to `ready_timeout` for the new capacity to actually appear,
    respawning children that died in the window.

    The replica counts given to set_replicas are the connector's OWN
    child-process counts. Workers of the same component managed outside
    the connector still count in `ready_fn`'s discovery-wide number —
    point the planner's `DiscoveryWorkerCounts` at a component only this
    connector manages, or the fleet runs `want + external` replicas.
    """

    def __init__(
        self,
        prefill_cmd: Sequence[str],
        decode_cmd: Sequence[str],
        env: Optional[Dict[str, str]] = None,
        grace_s: float = 5.0,
        spawn_retries: int = 3,
        ready_fn: Optional[Callable[[str], Awaitable[int]]] = None,
        ready_timeout: float = 30.0,
        frontend_cmd: Sequence[str] = (),
        morph_fn: Optional[Callable[[str, str], Awaitable[None]]] = None,
    ):
        self.prefill_cmd = list(prefill_cmd)
        self.decode_cmd = list(decode_cmd)
        # frontend role (docs/frontend_scaleout.md): stateless replicas of
        # `python -m dynamo_tpu.frontend` — each child's DYN_WORKER_INDEX
        # offsets its HTTP/gRPC/metrics ports, so one argv template serves
        # the whole tier. Readiness gating is skipped for this role:
        # frontends register no worker Instance records, so ready_fn's
        # discovery count cannot see them (their liveness check is the
        # alive-children reap + the next reconcile).
        self.frontend_cmd = list(frontend_cmd)
        self.env = env
        self.grace_s = grace_s
        self.spawn_retries = spawn_retries
        self.ready_fn = ready_fn
        self.ready_timeout = ready_timeout
        self.procs: Dict[str, List[asyncio.subprocess.Process]] = {
            "prefill": [],
            "decode": [],
            "frontend": [],
        }
        self._cmds = {
            "prefill": self.prefill_cmd,
            "decode": self.decode_cmd,
            "frontend": self.frontend_cmd,
        }
        # role morphing (docs/autoscaling.md "Role morphing"): an async
        # `(from_role, to_role)` hook that re-roles ONE live worker of
        # from_role (e.g. by calling the worker's morph control endpoint).
        # None = capability absent; the planner's getattr probe then keeps
        # its re-role arm dark and cold-spawns as before.
        self.morph_fn = morph_fn
        if morph_fn is None:
            # capability surface: the planner probes getattr(connector,
            # "morph_replicas") — shadow the method with None when no hook
            # exists, so a hookless connector keeps the re-role arm dark
            self.morph_replicas = None
        # last asked (p, d, f); f None = frontend tier never asked
        self._want: Optional[Tuple[int, int, Optional[int]]] = None

    def counts(self) -> Tuple[int, int]:
        self._reap()
        return len(self.procs["prefill"]), len(self.procs["decode"])

    def frontend_count(self) -> int:
        self._reap()
        return len(self.procs["frontend"])

    def _reap(self) -> None:
        for role in self.procs:
            self.procs[role] = [p for p in self.procs[role] if p.returncode is None]

    def _next_index(self, role: str) -> int:
        """Smallest index not held by a LIVE replica: a kill-then-respawn
        reuses the dead slot's index (ports/names derived from it stay
        stable), and never collides with a living replica's — `len(procs)`
        would hand a churn replacement a duplicate of the survivor's."""
        used = {getattr(p, "_dyn_worker_index", i)
                for i, p in enumerate(self.procs[role])}
        idx = 0
        while idx in used:
            idx += 1
        return idx

    async def _spawn(self, role: str) -> None:
        cmd = self._cmds[role]
        env = dict(os.environ if self.env is None else self.env)
        index = self._next_index(role)
        env["DYN_WORKER_INDEX"] = str(index)
        act = None
        f = faults.FAULTS
        if f.enabled:
            act = await f.on("worker.spawn")  # `error` raises FaultError
        proc = await asyncio.create_subprocess_exec(*cmd, env=env)
        if act == "crash":
            # the child dies before it ever reports ready — the readiness
            # wait (or the next reconcile) must replace it
            proc.kill()
        proc._dyn_worker_index = index
        self.procs[role].append(proc)
        logger.info("spawned %s worker pid=%d index=%d", role, proc.pid, index)

    async def _spawn_with_retry(self, role: str, backoff: Backoff) -> bool:
        try:
            await retry_async(
                lambda: self._spawn(role),
                attempts=self.spawn_retries, backoff=backoff,
                desc=f"spawn {role}", log=logger,
            )
            return True
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — exhausted; caller decides
            return False

    async def _wait_ready(self, role: str, want: int, backoff: Backoff) -> None:
        """Block until the asked capacity is actually up: all `want` managed
        children ALIVE (a child that died before ready is replaced in the
        window, bounded by the spawn-retry budget) AND `ready_fn(role)`
        reporting at least `want` registered replicas. Bounded by
        ready_timeout so a crash-looping worker can't wedge the loop.

        The alive check is authoritative for the connector's OWN children —
        `ready_fn` typically counts discovery-wide capacity, and in a mixed
        deployment (externally-managed workers of the same component) its
        count alone could mask a dead child; see the class docstring."""
        if self.ready_fn is None:
            return
        deadline = time.monotonic() + self.ready_timeout
        respawns = 0
        while time.monotonic() < deadline:
            self._reap()
            if len(self.procs[role]) < want:
                # a child died before reporting ready: replace it in the
                # window instead of waiting a whole adjustment interval
                if respawns >= max(1, self.spawn_retries):
                    logger.error(
                        "%s replica died before ready %d time(s); giving up "
                        "this interval (reconcile retries)", role, respawns,
                    )
                    return
                respawns += 1
                logger.warning(
                    "%s replica died before ready; respawning (%d/%d)",
                    role, respawns, self.spawn_retries,
                )
                await self._spawn_with_retry(role, backoff)
                continue
            try:
                ready = await self.ready_fn(role)
            except Exception as e:  # noqa: BLE001 — readiness probe is advisory
                logger.warning("ready_fn(%s) failed: %s", role, e)
                ready = 0
            if ready >= want:
                return
            await asyncio.sleep(0.1)
        logger.warning("%s capacity not ready within %.1fs", role, self.ready_timeout)

    async def _kill(self, role: str) -> None:
        proc = self.procs[role].pop()
        if proc.returncode is not None:
            return
        proc.send_signal(signal.SIGTERM)
        try:
            await asyncio.wait_for(proc.wait(), timeout=self.grace_s)
        except asyncio.TimeoutError:
            proc.kill()
            await proc.wait()
        logger.info("stopped %s worker pid=%d", role, proc.pid)

    async def kill_one(self, role: Optional[str] = None) -> Optional[int]:
        """SIGKILL one live managed replica with NO drain — hard worker
        death (the `worker.kill` fault point's action and the soak
        harness's crash arm). The corpse stays in `procs` until the next
        `_reap`/`reconcile`, exactly like a real crash: its lease lingers
        until TTL, in-flight streams sever, and migration must absorb it.
        Returns the killed pid, or None when no live replica exists."""
        roles = [role] if role else ["decode", "prefill"]
        for r in roles:
            for proc in reversed(self.procs.get(r, [])):
                if proc.returncode is None:
                    proc.kill()  # SIGKILL: no SIGTERM, no grace, no drain
                    logger.warning(
                        "worker.kill: SIGKILLed %s worker pid=%d (no drain)",
                        r, proc.pid,
                    )
                    await proc.wait()
                    return proc.pid
        return None

    async def morph_replicas(self, from_role: str, to_role: str,
                             k: int) -> int:
        """Re-role k live managed replicas via the morph hook (shadowed
        to None when no hook was configured). Each success moves
        the replica's bookkeeping between role lists and commits `_want`
        one worker at a time — a failure mid-batch raises with the
        completed morphs already committed, so reconcile re-asserts counts
        that match physical reality and the planner re-decides."""
        f = faults.FAULTS
        if f.enabled:
            await f.on("planner.connector")  # `error` raises; planner retries
        self._reap()
        done = 0
        for _ in range(k):
            if not self.procs[from_role]:
                break
            await self.morph_fn(from_role, to_role)
            proc = self.procs[from_role].pop()
            # re-slot under the new role: bookkeeping indexes are per-role
            # (the child's own DYN_WORKER_INDEX env is fixed at spawn; the
            # hook is responsible for any port/name re-derivation)
            proc._dyn_worker_index = self._next_index(to_role)
            self.procs[to_role].append(proc)
            if self._want is not None:
                p, d, fr = self._want
                p += (1 if to_role == "prefill" else 0) - (
                    1 if from_role == "prefill" else 0)
                d += (1 if to_role == "decode" else 0) - (
                    1 if from_role == "decode" else 0)
                self._want = (p, d, fr)
            done += 1
            logger.info("morphed %s worker pid=%d -> %s",
                        from_role, proc.pid, to_role)
        return done

    async def set_replicas(self, prefill: int, decode: int,
                           frontend: Optional[int] = None) -> None:
        f = faults.FAULTS
        if f.enabled:
            await f.on("planner.connector")  # `error` raises; planner retries
        self._reap()
        backoff = Backoff.seeded("worker.spawn", base=0.05, max_delay=1.0)
        roles = [("prefill", prefill), ("decode", decode)]
        if frontend is not None:
            roles.append(("frontend", frontend))
        for role, want in roles:
            if not self._cmds[role]:
                continue  # role not managed here (e.g. decode-only soak)
            grew = False
            while len(self.procs[role]) < want:
                if not await self._spawn_with_retry(role, backoff):
                    raise RuntimeError(
                        f"could not spawn {role} replica after "
                        f"{self.spawn_retries} attempts"
                    )
                grew = True
            while len(self.procs[role]) > want:
                await self._kill(role)
            if grew and role != "frontend":
                # frontends register no Instance records — ready_fn's
                # discovery count cannot gate them (class docstring)
                await self._wait_ready(role, want, backoff)
        # committed only on SUCCESS: the planner treats a raised
        # set_replicas as uncommitted and holds its own target, so
        # reconcile() must keep re-asserting the LAST SUCCESSFUL counts —
        # advancing _want on a failed apply would let reconcile grow the
        # fleet past what the planner believes exists (and any partial
        # spawns from the failed attempt are culled by the next
        # reconcile's kill-down to the old counts)
        if frontend is None and self._want is not None:
            frontend = self._want[2]  # an unasked tier keeps its target
        self._want = (prefill, decode, frontend)

    async def reconcile(self) -> None:
        """Re-assert the last committed replica counts: respawn replicas
        that died since (the planner calls this every interval)."""
        if self._want is None:
            return
        f = faults.FAULTS
        if f.enabled and f.check("worker.kill") == "kill":
            # dynochaos `worker.kill`: SIGKILL a live replica with no
            # drain on this tick — the respawn below is the recovery
            # path under test, migration absorbs the severed streams
            await self.kill_one()
        p, d, fr = self._want
        self._reap()
        dead = [
            (role, want, len(self.procs[role]))
            for role, want, cmd in (
                ("prefill", p, self.prefill_cmd),
                ("decode", d, self.decode_cmd),
                ("frontend", fr or 0, self.frontend_cmd),
            )
            # only roles this connector actually manages can "die" on it
            if cmd and want is not None and len(self.procs[role]) < want
        ]
        if dead:
            logger.warning(
                "reconcile: replica(s) died: %s",
                ", ".join(f"{r}: have {h}, want {w}" for r, w, h in dead),
            )
        await self.set_replicas(p, d, frontend=fr)

    async def shutdown(self) -> None:
        await self.set_replicas(
            0, 0, frontend=0 if self.frontend_cmd else None
        )


class DiscoveryWorkerCounts:
    """Count READY worker instances from discovery (reference
    get_workers_info, planner_core.py:180-219).

    Two gates make this the planner's capacity truth: workers register in
    discovery only AFTER their warmup/health gate passes (so a freshly
    spawned replica never counts early), and instances in any unroutable
    state — `draining` (scale-down in progress) or `morphing` (role flip
    in progress) — are excluded (so capacity being shed or mid-flip never
    counts in either role)."""

    def __init__(self, discovery_client, namespace: str = "dynamo",
                 prefill_component: str = "prefill", decode_component: str = "backend"):
        self.client = discovery_client
        self.namespace = namespace
        self.prefill_component = prefill_component
        self.decode_component = decode_component

    async def count(self) -> Tuple[int, int]:
        from ..runtime.component import INSTANCE_ROOT, UNROUTABLE_STATES

        items = await self.client.get_prefix(INSTANCE_ROOT + self.namespace + "/")
        n_p = n_d = 0
        for it in items:
            key = it["key"] if isinstance(it, dict) else it[0]
            value = it.get("value", b"") if isinstance(it, dict) else it[1]
            try:
                if json.loads(value).get("state") in UNROUTABLE_STATES:
                    continue
            except (ValueError, TypeError, AttributeError):
                pass  # unparseable record: count it (legacy writers)
            comp = key[len(INSTANCE_ROOT):].split("/")[1]
            if comp == self.prefill_component:
                n_p += 1
            elif comp == self.decode_component:
                n_d += 1
        return n_p, n_d

    def ready_fn(self) -> Callable[[str], Awaitable[int]]:
        """Adapter for LocalProcessConnector(ready_fn=...): per-role READY
        replica count."""

        async def ready(role: str) -> int:
            p, d = await self.count()
            return p if role == "prefill" else d

        return ready
