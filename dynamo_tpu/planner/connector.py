"""Planner connectors: turn a replica decision into actual scaling.

Reference equivalents: `KubernetesConnector` patches a
DynamoGraphDeployment CRD (components/planner/src/dynamo/planner/
kubernetes_connector.py) and `VirtualConnector` publishes the decision to
etcd for an external orchestrator (virtual_connector.py). Here:

* ``VirtualConnector`` writes the decision to the discovery service KV
  (``v1/planner/decision``) with a monotonically increasing revision —
  any orchestrator (k8s operator, slice manager) watches and acts.
* ``LocalProcessConnector`` scales real worker subprocesses on this host
  (the test/e2e orchestrator, reference's ManagedProcess-style role).
* ``NoopConnector`` records decisions (dryrun / unit tests).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import signal
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

PLANNER_DECISION_KEY = "v1/planner/decision"


class NoopConnector:
    def __init__(self):
        self.decisions: List[Tuple[int, int]] = []

    async def set_replicas(self, prefill: int, decode: int) -> None:
        self.decisions.append((prefill, decode))


class VirtualConnector:
    """Publish {num_prefill, num_decode, revision} to discovery KV.
    Revisions continue from whatever is already stored, so they stay
    monotonic across planner restarts."""

    def __init__(self, discovery_client):
        self.client = discovery_client
        self.revision: Optional[int] = None
        # two concurrent publishers would both lazy-load, both increment,
        # and ship duplicate revision numbers — which the revision-gated
        # consumers (operator-lite) silently skip
        self._rev_lock = asyncio.Lock()

    async def _load_revision(self) -> int:
        raw = await self.client.get(PLANNER_DECISION_KEY)
        if raw:
            try:
                return int(json.loads(raw).get("revision", 0))
            except (ValueError, TypeError, AttributeError, json.JSONDecodeError):
                pass  # malformed stored doc: restart revisions from 0
        return 0

    async def set_replicas(self, prefill: int, decode: int) -> None:
        async with self._rev_lock:
            if self.revision is None:
                self.revision = await self._load_revision()
            self.revision += 1
            doc = {
                "num_prefill_workers": prefill,
                "num_decode_workers": decode,
                "revision": self.revision,
                "ts": time.time(),
            }
            await self.client.put(PLANNER_DECISION_KEY, json.dumps(doc).encode())
            logger.info("published planner decision rev=%d p=%d d=%d",
                        self.revision, prefill, decode)


class LocalProcessConnector:
    """Scale worker replicas as local subprocesses.

    `prefill_cmd` / `decode_cmd` are argv templates; each spawned replica
    gets the env of the parent plus DYN_WORKER_INDEX. Scaling down kills
    the newest replicas first (SIGTERM, then SIGKILL after grace).
    """

    def __init__(
        self,
        prefill_cmd: Sequence[str],
        decode_cmd: Sequence[str],
        env: Optional[Dict[str, str]] = None,
        grace_s: float = 5.0,
    ):
        self.prefill_cmd = list(prefill_cmd)
        self.decode_cmd = list(decode_cmd)
        self.env = env
        self.grace_s = grace_s
        self.procs: Dict[str, List[asyncio.subprocess.Process]] = {
            "prefill": [],
            "decode": [],
        }

    def counts(self) -> Tuple[int, int]:
        self._reap()
        return len(self.procs["prefill"]), len(self.procs["decode"])

    def _reap(self) -> None:
        for role in self.procs:
            self.procs[role] = [p for p in self.procs[role] if p.returncode is None]

    async def _spawn(self, role: str) -> None:
        cmd = self.prefill_cmd if role == "prefill" else self.decode_cmd
        env = dict(os.environ if self.env is None else self.env)
        env["DYN_WORKER_INDEX"] = str(len(self.procs[role]))
        proc = await asyncio.create_subprocess_exec(*cmd, env=env)
        self.procs[role].append(proc)
        logger.info("spawned %s worker pid=%d", role, proc.pid)

    async def _kill(self, role: str) -> None:
        proc = self.procs[role].pop()
        if proc.returncode is not None:
            return
        proc.send_signal(signal.SIGTERM)
        try:
            await asyncio.wait_for(proc.wait(), timeout=self.grace_s)
        except asyncio.TimeoutError:
            proc.kill()
            await proc.wait()
        logger.info("stopped %s worker pid=%d", role, proc.pid)

    async def set_replicas(self, prefill: int, decode: int) -> None:
        self._reap()
        for role, want in (("prefill", prefill), ("decode", decode)):
            while len(self.procs[role]) < want:
                await self._spawn(role)
            while len(self.procs[role]) > want:
                await self._kill(role)

    async def shutdown(self) -> None:
        await self.set_replicas(0, 0)


class DiscoveryWorkerCounts:
    """Count live worker instances from discovery (reference
    get_workers_info, planner_core.py:180-219)."""

    def __init__(self, discovery_client, namespace: str = "dynamo",
                 prefill_component: str = "prefill", decode_component: str = "backend"):
        self.client = discovery_client
        self.namespace = namespace
        self.prefill_component = prefill_component
        self.decode_component = decode_component

    async def count(self) -> Tuple[int, int]:
        from ..runtime.component import INSTANCE_ROOT

        items = await self.client.get_prefix(INSTANCE_ROOT + self.namespace + "/")
        n_p = n_d = 0
        for it in items:
            key = it["key"] if isinstance(it, dict) else it[0]
            comp = key[len(INSTANCE_ROOT):].split("/")[1]
            if comp == self.prefill_component:
                n_p += 1
            elif comp == self.decode_component:
                n_d += 1
        return n_p, n_d
