"""Autoscaling soak harness: the planner loop's proving ground.

ROADMAP item 4 / docs/autoscaling.md: an in-proc cluster — real frontend
(HTTP service + model watcher + /metrics), real discovery, N mock workers
— with the real `Planner` scraping the frontend and scaling the worker set
while a seeded qps ramp runs and dynochaos fault plans fire. The pieces
here are reusable by tests (tests/test_planner_soak.py), the CI soak
smoke, and interactive debugging; none of them stub the serving plane —
streams ride the same request-plane/migration/drain machinery production
traffic does.

Two worker backends implement the `PlannerConnector` protocol:

* :class:`InProcWorkerPool` — workers are `DistributedRuntime`s inside
  this process (fast: tier-1 soak). Scale-down closes gracefully (the
  PR-3 drain: mark draining → revoke lease → finish in-flight);
  `kill_one()` tears a worker down crash-style for migration tests.
* `planner.connector.LocalProcessConnector` — real subprocess workers
  (`python -m dynamo_tpu.mocker`), SIGTERM-drained on scale-down; the
  slow soak + CI smoke use it via :func:`mocker_cmd`.
"""

from __future__ import annotations

import asyncio
import json
import logging
import math
import sys
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import aiohttp
import numpy as np

from ..runtime import DistributedRuntime, RouterMode, RuntimeConfig
from ..runtime.discovery import DiscoveryServer
from .perf_interpolation import DecodeInterpolator, PrefillInterpolator

logger = logging.getLogger(__name__)


# --------------------------------------------------------------------------- #
# synthetic interpolation profiles
# --------------------------------------------------------------------------- #


def synthetic_profiles(
    decode_tok_s_per_chip: float = 56.0,
    prefill_tok_s_per_chip: float = 5000.0,
    itl_grid_ms: float = 40.0,
    max_kv_tokens: int = 100_000,
) -> Tuple[dict, dict]:
    """(prefill_raw, decode_raw) interpolator inputs with CONSTANT
    throughput surfaces, so the planner's replica math reduces to
    `ceil(load_tok_s / per_chip)` — the soak can predict the correct
    replica count for a given ramp exactly."""
    isl = np.array([16.0, 256.0, 1024.0, 4096.0])
    prefill_raw = {
        "prefill_isl": isl,
        "prefill_ttft": np.full_like(isl, 5.0),  # ms; flat
        "prefill_thpt_per_gpu": np.full_like(isl, prefill_tok_s_per_chip),
    }
    xs, ys = np.meshgrid(
        np.array([0.1, 0.3, 0.5, 0.7, 0.9]), np.array([64.0, 512.0, 2048.0])
    )
    xs, ys = xs.ravel(), ys.ravel()
    decode_raw = {
        "x_kv_usage": xs,
        "y_context_length": ys,
        "z_itl": np.full_like(xs, itl_grid_ms),
        "z_thpt_per_gpu": np.full_like(xs, decode_tok_s_per_chip),
        "max_kv_tokens": np.array([max_kv_tokens]),
    }
    return prefill_raw, decode_raw


def make_interpolators(**kwargs) -> Tuple[PrefillInterpolator, DecodeInterpolator]:
    p_raw, d_raw = synthetic_profiles(**kwargs)
    return (
        PrefillInterpolator(raw_data=p_raw),
        DecodeInterpolator(raw_data=d_raw),
    )


# --------------------------------------------------------------------------- #
# in-proc cluster pieces
# --------------------------------------------------------------------------- #


class SoakFrontend:
    """Discovery server + frontend runtime + model watcher + HTTP service,
    all in-proc — the real serving plane the ramp drives and the planner
    scrapes."""

    def __init__(self, router_mode: RouterMode = RouterMode.ROUND_ROBIN,
                 lease_ttl_s: float = 3.0, graceful_timeout: float = 10.0):
        self.router_mode = router_mode
        self.lease_ttl_s = lease_ttl_s
        self.graceful_timeout = graceful_timeout
        self.disc: Optional[DiscoveryServer] = None
        self.drt: Optional[DistributedRuntime] = None
        self.http = None
        self.watcher = None
        self.gate = None  # dynogate (env-resolved; DYN_GATE=0 disables)
        self.port: int = 0

    @property
    def cfg(self) -> RuntimeConfig:
        cfg = RuntimeConfig()
        assert self.disc is not None
        cfg.discovery_endpoint = f"tcp://127.0.0.1:{self.disc.port}"
        cfg.lease_ttl_s = self.lease_ttl_s
        cfg.graceful_shutdown_timeout = self.graceful_timeout
        return cfg

    @property
    def metrics_url(self) -> str:
        return f"http://127.0.0.1:{self.port}/metrics"

    @property
    def base_url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    async def start(self) -> "SoakFrontend":
        from ..gate import AdmissionGate, GateConfig
        from ..llm.discovery import ModelManager, ModelWatcher
        from ..llm.http import HttpService

        self.disc = DiscoveryServer(port=0)
        await self.disc.start()
        self.drt = await DistributedRuntime.create(self.cfg)
        # same gate wiring as `python -m dynamo_tpu.frontend`: the soaks
        # exercise the production admission path, not a stub of it
        gate_cfg = GateConfig.from_env()
        if gate_cfg.enabled:
            self.gate = AdmissionGate(self.drt, gate_cfg)
            await self.gate.start()
        manager = ModelManager()
        self.watcher = ModelWatcher(
            self.drt, manager, self.router_mode, gate=self.gate
        )
        await self.watcher.start()
        self.http = HttpService(manager, host="127.0.0.1", port=0,
                                gate=self.gate)
        self.port = await self.http.start()
        return self

    async def wait_model(self, model: str, timeout: float = 30.0):
        deadline = time.monotonic() + timeout
        async with aiohttp.ClientSession() as s:
            while time.monotonic() < deadline:
                try:
                    async with s.get(f"{self.base_url}/v1/models") as r:
                        data = await r.json()
                    if any(m["id"] == model for m in data.get("data", [])):
                        return
                except (aiohttp.ClientError, OSError):
                    pass
                await asyncio.sleep(0.1)
        raise TimeoutError(f"model {model} never registered")

    async def stop(self):
        if self.watcher is not None:
            await self.watcher.stop()
        if self.http is not None:
            await self.http.stop()
        if self.gate is not None:
            await self.gate.close()
        if self.drt is not None:
            await self.drt.close()
        if self.disc is not None:
            await self.disc.stop()


#: which serving roles a worker role covers (soak-side mirror of the
#: engines' _ROLES table): a "both" worker counts as prefill AND decode.
ROLE_SERVES = {
    "prefill": frozenset({"prefill"}),
    "decode": frozenset({"decode"}),
    "both": frozenset({"prefill", "decode"}),
}


class InProcMockWorker:
    """One in-proc mock worker: mirrors `python -m dynamo_tpu.mocker` —
    warmup BEFORE registration (the capacity-readiness gate the planner
    counts on), MockEngine behind a served endpoint, model card under the
    primary lease.

    Role-aware (docs/autoscaling.md "Role morphing"): a decode-role worker
    registers under `component` with the model card (chat traffic routes
    here), a prefill-role worker registers under `prefill_component` with
    NO card (it is planner capacity + disagg remote-prefill target, never
    a chat destination), and a colocated "both" worker registers under
    both. `morph()` re-roles the live worker: mark every lane `morphing`
    (routers stop dialing immediately), drain via the engine's
    StreamSevered tail-migration, then flip the discovery lanes + card
    atomically with the drain's completion."""

    def __init__(self, cfg: RuntimeConfig, engine_args, *,
                 namespace: str = "dynamo", component: str = "mocker",
                 prefill_component: str = "prefill",
                 endpoint: str = "generate", migration_limit: int = 3):
        self.cfg = cfg
        self.engine_args = engine_args
        self.namespace, self.component, self.endpoint = namespace, component, endpoint
        self.prefill_component = prefill_component
        self.migration_limit = migration_limit
        self.role: str = getattr(engine_args, "role", "decode")
        self.drt: Optional[DistributedRuntime] = None
        self.engine = None
        self._metrics_pub = None
        self._served: dict = {}  # component name -> ServedEndpoint
        self._card_key: Optional[str] = None

    def _role_components(self, role: str) -> List[str]:
        return {
            "decode": [self.component],
            "prefill": [self.prefill_component],
            "both": [self.component, self.prefill_component],
        }[role]

    def _lane_endpoint(self, comp: str):
        assert self.drt is not None
        return (self.drt.namespace(self.namespace)
                .component(comp).endpoint(self.endpoint))

    async def _serve_lane(self, comp: str):
        engine = self.engine

        async def handler(request, context):
            async for item in engine.generate(request, context):
                yield item

        return await self._lane_endpoint(comp).serve_endpoint(handler)

    async def _register_card(self) -> None:
        from ..llm.model_card import ModelDeploymentCard, register_llm

        self._card_key = await register_llm(
            self._lane_endpoint(self.component),
            ModelDeploymentCard(
                name=self.engine_args.model_name,
                tokenizer="byte",
                kv_cache_block_size=self.engine_args.block_size,
                migration_limit=self.migration_limit,
            ))

    async def _drop_card(self) -> None:
        # mirror ServedEndpoint.remove for the leased card key: a worker
        # morphed away from decode must stop advertising the model NOW,
        # not at lease expiry
        assert self.drt is not None and self._card_key is not None
        self.drt._leased_keys.pop(self._card_key, None)
        if self.drt.discovery is not None:
            await self.drt.discovery.delete(self._card_key)
        self._card_key = None

    async def _start_metrics(self) -> None:
        from ..llm.kv_router.publisher import WorkerMetricsPublisher

        # swap-before-await: the attribute is cleared synchronously, so a
        # concurrent caller never double-closes the same publisher
        pub, self._metrics_pub = self._metrics_pub, None
        if pub is not None:
            await pub.close()
        if not self._served:
            return
        comp = (self.component if self.component in self._served
                else next(iter(self._served)))
        # same load-signal surface as `python -m dynamo_tpu.mocker`: the
        # admission gate and KV router read sched_est_ttft_ms/queue depth
        # off this topic (docs/overload.md); the planner's RoleEstimates
        # reads sched_est_{prefill,decode}_tok_s off the same stats dict
        self._metrics_pub = WorkerMetricsPublisher(
            self.drt, self._lane_endpoint(comp),
            self.drt.instance_id, self.engine.stats
        )
        await self._metrics_pub.start()

    async def _apply_lanes(self, role: str) -> None:
        """Reconcile discovery registrations to `role`'s lane set: remove
        lanes the role drops, serve lanes it gains (born `morphing` until
        the morph commits), and move the model card + metrics topic with
        the decode lane. Runs as the engine morph's on_flip hook, so the
        discovery flip is atomic with drain completion."""
        from ..runtime.component import STATE_MORPHING

        want = set(self._role_components(role))
        for comp in set(self._served) - want:
            await self._served.pop(comp).remove()
        for comp in want - set(self._served):
            served = await self._serve_lane(comp)
            await served.set_state(STATE_MORPHING)
            self._served[comp] = served
        if self.component in want and self._card_key is None:
            await self._register_card()
        elif self.component not in want and self._card_key is not None:
            await self._drop_card()
        await self._start_metrics()

    async def morph(self, target_role: str) -> dict:
        """Re-role this live worker. Unroutable window first (every lane
        flips to STATE_MORPHING before the drain starts, so new dials land
        on peers), then the engine state machine drains + flips + re-warms
        with `_apply_lanes` as the atomic discovery flip. On engine
        rollback the old lanes are restored routable; MorphCrash
        propagates for the pool's crash-style teardown."""
        from ..runtime import faults
        from ..runtime.component import STATE_MORPHING, STATE_READY

        assert self.engine is not None
        old_role = self.role
        if target_role == old_role:
            return {"from": old_role, "to": target_role, "drained": 0}
        await self._set_lane_states(STATE_MORPHING)
        try:
            summary = await self.engine.morph(
                target_role, on_flip=lambda: self._apply_lanes(target_role))
        except faults.MorphCrash:
            raise
        except BaseException:
            # engine rolled back to old_role (drained sessions already
            # migrating to peers); restore the old lane set routable
            await self._apply_lanes(old_role)
            await self._set_lane_states(STATE_READY)
            raise
        self.role = target_role
        await self._set_lane_states(STATE_READY)
        return summary

    async def _set_lane_states(self, state: str) -> None:
        for served in list(self._served.values()):
            await served.set_state(state)

    async def start(self) -> "InProcMockWorker":
        from ..llm.mocker import MockEngine

        self.drt = await DistributedRuntime.create(self.cfg)
        self.engine = MockEngine(self.engine_args)
        await self.engine.warmup()
        for comp in self._role_components(self.role):
            self._served[comp] = await self._serve_lane(comp)
        await self._start_metrics()
        if self.component in self._served:
            await self._register_card()
        return self

    @property
    def instance_id(self) -> int:
        assert self.drt is not None
        return self.drt.instance_id

    async def stop(self, graceful: bool = True):
        if self._metrics_pub is not None:
            await self._metrics_pub.close()
        if self.drt is not None:
            await self.drt.close(graceful=graceful)


class InProcWorkerPool:
    """PlannerConnector over in-proc mock workers, role-aware: decode
    workers serve `component` with the model card, prefill workers serve
    `prefill_component` without one, and a colocated "both" worker serves
    under both (docs/autoscaling.md "Role morphing"). Honors the same
    `planner.connector` / `worker.spawn` / `worker.kill` fault points as
    LocalProcessConnector so fault-plan soaks exercise one grammar, and
    exposes the native `morph_replicas`/`colocate` capability the
    planner's re-role arm probes for — a morph re-roles a LIVE worker via
    `InProcMockWorker.morph` instead of cold-spawning, which is exactly
    the time-to-SLA-recovery edge the soak measures (`spawn_delay_s`
    prices the cold spawn the morph avoids)."""

    def __init__(self, cfg: RuntimeConfig, engine_args, *,
                 component: str = "mocker",
                 prefill_component: str = "prefill",
                 spawn_retries: int = 3, spawn_delay_s: float = 0.0,
                 estimates=None):
        self.cfg = cfg
        self.engine_args = engine_args
        self.component = component
        self.prefill_component = prefill_component
        self.spawn_retries = spawn_retries
        self.spawn_delay_s = spawn_delay_s
        # planner.RoleEstimates (optional): reconcile() feeds it each
        # worker's stats so sched_est_{prefill,decode}_tok_s price the
        # planner's re-role decision without an HTTP scrape hop
        self.estimates = estimates
        self.workers: List[InProcMockWorker] = []
        self.scale_events: List[Tuple[float, int]] = []  # (t, decode_count)
        self.morph_events: List[Tuple[float, str, str]] = []  # (t, from, to)
        self._want: Optional[Tuple[int, int]] = None

    def count(self, role: str) -> int:
        """Workers currently covering `role` ("both" counts for each)."""
        return sum(1 for w in self.workers
                   if role in ROLE_SERVES.get(w.role, ()))

    async def _spawn(self, role: str = "decode") -> None:
        import dataclasses

        from ..runtime import faults
        from ..runtime.backoff import Backoff, retry_async

        async def start_one():
            args = (dataclasses.replace(self.engine_args, role=role)
                    if getattr(self.engine_args, "role", role) != role
                    else self.engine_args)
            w = InProcMockWorker(self.cfg, args, component=self.component,
                                 prefill_component=self.prefill_component)
            f = faults.FAULTS
            if f.enabled:
                act = await f.on("worker.spawn")  # `error` raises
                if act == "crash":
                    # worker dies before it reports ready: start, then
                    # tear down crash-style before registration counts
                    await w.start()
                    await w.stop(graceful=False)
                    raise ConnectionError("injected: worker crashed before ready")
            if self.spawn_delay_s > 0:
                # priced cold-spawn: the provisioning latency a morph of a
                # live worker does NOT pay
                await asyncio.sleep(self.spawn_delay_s)
            await w.start()
            return w

        self.workers.append(await retry_async(
            start_one, attempts=self.spawn_retries,
            backoff=Backoff.seeded("worker.spawn", base=0.05, max_delay=0.5),
            desc="in-proc worker spawn", log=logger,
        ))

    async def _stop_role(self, role: str) -> None:
        """Shed one unit of `role` capacity: retire the newest dedicated
        worker gracefully (the PR-3 drain sequence), or — if only a
        colocated worker covers the role — de-colocate by morphing it
        down to the remaining role."""
        exact = [w for w in self.workers if w.role == role]
        if exact:
            w = exact[-1]
            self.workers.remove(w)
            await w.stop(graceful=True)
            return
        colo = [w for w in self.workers if w.role == "both"]
        if colo:
            other = "decode" if role == "prefill" else "prefill"
            await self._morph_worker(colo[-1], other)
            return
        raise RuntimeError(f"no {role} worker to stop")

    async def set_replicas(self, prefill: int, decode: int,
                           frontend: Optional[int] = None) -> None:
        # `frontend` accepted and ignored: the in-proc soak runs one
        # SoakFrontend; frontend-tier scaling is exercised through
        # LocalProcessConnector(frontend_cmd=frontend_cmd(...))
        from ..runtime import faults

        f = faults.FAULTS
        if f.enabled:
            await f.on("planner.connector")  # `error` raises; planner retries
        while self.count("decode") < decode:
            await self._spawn("decode")
        while self.count("prefill") < prefill:
            await self._spawn("prefill")
        # retire colocated workers outright while BOTH roles are above
        # target (shutdown path); per-role shrink below de-colocates
        while (self.count("prefill") > prefill
               and self.count("decode") > decode):
            colo = [w for w in self.workers if w.role == "both"]
            if not colo:
                break
            w = colo[-1]
            self.workers.remove(w)
            await w.stop(graceful=True)
        while self.count("decode") > decode:
            await self._stop_role("decode")
        while self.count("prefill") > prefill:
            await self._stop_role("prefill")
        # committed only on success (same contract as LocalProcessConnector:
        # reconcile re-asserts the last SUCCESSFUL counts, never a target
        # the planner recorded as connector-error)
        self._want = (prefill, decode)
        self.scale_events.append((time.monotonic(), self.count("decode")))

    async def morph_replicas(self, from_role: str, to_role: str,
                             k: int) -> int:
        """Re-role up to k live workers from `from_role` to `to_role` —
        the planner's re-role arm. Only dedicated from_role workers are
        candidates (newest first, matching scale-down order). Commits the
        new role split to `_want` so reconcile re-asserts it."""
        from ..runtime import faults

        f = faults.FAULTS
        if f.enabled:
            await f.on("planner.connector")  # `error` raises; planner retries
        done = 0
        for _ in range(k):
            cands = [w for w in self.workers if w.role == from_role]
            if not cands:
                break
            await self._morph_worker(cands[-1], to_role)
            done += 1
        if done:
            self._want = (self.count("prefill"), self.count("decode"))
            self.scale_events.append((time.monotonic(), self.count("decode")))
        return done

    async def _morph_worker(self, w: InProcMockWorker, to_role: str) -> None:
        from ..runtime import faults

        from_role = w.role
        try:
            await w.morph(to_role)
        except faults.MorphCrash:
            # crashed mid-morph: crash-style teardown — the lease revoke
            # severs its streams onto peers through the same migration
            # machinery a SIGKILL exercises; reconcile respawns to the
            # last committed want. Surfaces to the planner as an
            # uncommitted connector error (PR-9 retry semantics).
            self.workers.remove(w)
            await w.stop(graceful=False)
            self.scale_events.append((time.monotonic(), self.count("decode")))
            raise ConnectionError("worker crashed mid-morph") from None
        self.morph_events.append((time.monotonic(), from_role, to_role))

    async def colocate(self) -> bool:
        """Fold to colocated serving at the traffic floor: morph the
        newest decode worker to "both", then gracefully retire dedicated
        prefill workers. Returns False when already colocated or nothing
        to fold."""
        if any(w.role == "both" for w in self.workers):
            return False
        decode = [w for w in self.workers if w.role == "decode"]
        if not decode:
            return False
        await self._morph_worker(decode[-1], "both")
        for w in [w for w in self.workers if w.role == "prefill"]:
            self.workers.remove(w)
            await w.stop(graceful=True)
        self._want = (self.count("prefill"), self.count("decode"))
        self.scale_events.append((time.monotonic(), self.count("decode")))
        return True

    async def reconcile(self) -> None:
        from ..runtime import faults

        f = faults.FAULTS
        if f.enabled and f.check("worker.kill") == "kill" and self.workers:
            # same `worker.kill` grammar as LocalProcessConnector: hard
            # worker death on the reconcile tick, no drain — migration
            # absorbs the severed streams, the respawn below heals
            await self.kill_one()
        if self._want is not None:
            p, d = self._want
            if self.count("prefill") < p or self.count("decode") < d:
                await self.set_replicas(p, d)
        if self.estimates is not None:
            for w in list(self.workers):
                if w.engine is not None:
                    self.estimates.observe(w.instance_id, w.engine.stats())

    async def kill_one(self, index: int = -1) -> int:
        """Crash-style teardown of one worker (no drain): the in-proc
        analog of SIGKILL, for mid-stream migration scenarios. Returns the
        killed instance id."""
        w = self.workers.pop(index)
        iid = w.instance_id
        await w.stop(graceful=False)
        self.scale_events.append((time.monotonic(), self.count("decode")))
        return iid

    async def shutdown(self) -> None:
        await self.set_replicas(0, 0)


def mocker_cmd(discovery: str, *, model_name: str = "mock-model",
               component: str = "mocker", block_size: int = 8,
               speedup_ratio: float = 2.0,
               extra: Sequence[str] = ()) -> List[str]:
    """argv template for LocalProcessConnector: a real mocker worker
    subprocess wired to the soak's discovery service."""
    return [
        sys.executable, "-m", "dynamo_tpu.mocker",
        "--model-name", model_name,
        "--component", component,
        "--discovery", discovery,
        "--block-size", str(block_size),
        "--speedup-ratio", str(speedup_ratio),
        *extra,
    ]


def frontend_cmd(discovery: str, *, http_port: int,
                 router_mode: str = "round-robin",
                 extra: Sequence[str] = ()) -> List[str]:
    """argv template for LocalProcessConnector(frontend_cmd=...): one
    stateless frontend replica on the shared discovery plane. Replica i
    listens on http_port + i (the frontend offsets by DYN_WORKER_INDEX,
    docs/frontend_scaleout.md)."""
    return [
        sys.executable, "-m", "dynamo_tpu.frontend",
        "--discovery", discovery,
        "--http-host", "127.0.0.1",
        "--http-port", str(http_port),
        "--router-mode", router_mode,
        *extra,
    ]


# --------------------------------------------------------------------------- #
# seeded qps ramp load
# --------------------------------------------------------------------------- #


@dataclass
class RampPhase:
    qps: float
    duration_s: float
    label: str = ""
    # per-phase shape overrides (None = RampLoad's defaults): a
    # prefill-heavy phase (big isl, small osl) flipping to a decode-heavy
    # one (small isl, big osl) is how the morph soak skews the planner's
    # per-role ask without changing total qps
    isl_chars: Optional[int] = None
    osl_tokens: Optional[int] = None


@dataclass
class StreamRecord:
    """One client stream's observation, sufficient for both SLA windows
    and the zero-lost/zero-duplicated contiguity check (the byte
    tokenizer maps one token to one character, so received characters
    count emitted stream items exactly — migration replays would inflate
    the count, drops would shrink it)."""

    phase: str
    t_send: float
    t_first: Optional[float] = None
    t_last: Optional[float] = None
    content_tokens: int = 0
    usage_completion: Optional[int] = None
    max_tokens: int = 0
    finish_reason: Optional[str] = None
    error: Optional[str] = None
    tenant: str = ""
    # dynogate rejection (docs/overload.md): a clean 429 BEFORE any
    # stream bytes — not an error, not a contiguity problem
    rejected: bool = False
    retry_after_s: Optional[float] = None

    @property
    def ok(self) -> bool:
        return (not self.rejected and self.error is None
                and self.finish_reason is not None)

    def ttft_ms(self) -> float:
        if self.t_first is None:
            return math.inf
        return (self.t_first - self.t_send) * 1000.0

    def contiguity_problems(self) -> List[str]:
        out = []
        if self.rejected:
            return out  # typed pre-stream rejection: nothing was promised
        if self.error is not None:
            out.append(f"error: {self.error}")
            return out
        if self.finish_reason is None:
            out.append("no finish_reason (truncated stream)")
        if self.content_tokens != self.max_tokens:
            out.append(
                f"{'lost' if self.content_tokens < self.max_tokens else 'duplicated'}"
                f" items: got {self.content_tokens}, asked {self.max_tokens}"
            )
        if self.usage_completion is not None and \
                self.usage_completion != self.content_tokens:
            out.append(
                f"usage mismatch: usage={self.usage_completion} "
                f"streamed={self.content_tokens}"
            )
        return out


async def drive_stream(session: aiohttp.ClientSession, base_url: str,
                       model: str, prompt: str, max_tokens: int,
                       phase: str = "", tenant: str = "",
                       priority: int = 0,
                       tenant_header: str = "x-dynamo-tenant") -> StreamRecord:
    """One streaming chat completion, recorded chunk by chunk. `tenant`
    rides the gate's tenant header and `priority` its nvext SLA class; a
    gate 429 is recorded as a clean rejection (Retry-After parsed), any
    other non-200 as an error."""
    rec = StreamRecord(phase=phase, t_send=time.monotonic(),
                       max_tokens=max_tokens, tenant=tenant)
    body = {
        "model": model,
        "messages": [{"role": "user", "content": prompt}],
        "max_tokens": max_tokens,
        "stream": True,
        "stream_options": {"include_usage": True},
    }
    if priority:
        body["nvext"] = {"priority": priority}
    headers = {tenant_header: tenant} if tenant else None
    try:
        async with session.post(
            f"{base_url}/v1/chat/completions",
            json=body,
            headers=headers,
            timeout=aiohttp.ClientTimeout(total=120),
        ) as resp:
            if resp.status == 429:
                rec.rejected = True
                try:
                    rec.retry_after_s = float(
                        resp.headers.get("Retry-After", "0"))
                except ValueError:
                    rec.retry_after_s = None
                await resp.read()
                return rec
            if resp.status != 200:
                rec.error = f"HTTP {resp.status}: {(await resp.text())[:200]}"
                return rec
            async for raw in resp.content:
                line = raw.decode("utf-8", "replace").strip()
                if not line.startswith("data:"):
                    continue
                payload = line[5:].strip()
                if payload == "[DONE]":
                    break
                chunk = json.loads(payload)
                if chunk.get("usage"):
                    rec.usage_completion = chunk["usage"]["completion_tokens"]
                for ch in chunk.get("choices", []):
                    content = (ch.get("delta") or {}).get("content")
                    if content:
                        if rec.t_first is None:
                            rec.t_first = time.monotonic()
                        rec.t_last = time.monotonic()
                        rec.content_tokens += len(content)
                    if ch.get("finish_reason"):
                        rec.finish_reason = ch["finish_reason"]
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
        rec.error = f"{type(e).__name__}: {e}"
    return rec


class RampLoad:
    """Seeded deterministic qps ramp: fixed inter-arrival 1/qps per phase,
    prompts varied per request index (prefix caching stays honest).
    `tenant_cycle`: optional [(tenant, priority), ...] assigned to
    requests round-robin — the deterministic multi-tenant mix the gate
    soak drives (docs/overload.md)."""

    def __init__(self, base_url: str, model: str, phases: Sequence[RampPhase],
                 *, isl_chars: int = 24, osl_tokens: int = 16, seed: int = 0,
                 tenant_cycle: Sequence[Tuple[str, int]] = ()):
        self.base_url = base_url
        self.model = model
        self.phases = list(phases)
        self.isl_chars = isl_chars
        self.osl_tokens = osl_tokens
        self.seed = seed
        self.tenant_cycle = list(tenant_cycle)
        self.records: List[StreamRecord] = []

    async def run(self) -> List[StreamRecord]:
        tasks: List[asyncio.Task] = []
        i = 0
        async with aiohttp.ClientSession() as session:
            for phase in self.phases:
                t_phase = time.monotonic()
                gap = 1.0 / max(phase.qps, 1e-9)
                n = max(1, int(round(phase.qps * phase.duration_s)))
                isl = phase.isl_chars if phase.isl_chars is not None \
                    else self.isl_chars
                osl = phase.osl_tokens if phase.osl_tokens is not None \
                    else self.osl_tokens
                for k in range(n):
                    at = t_phase + k * gap
                    delay = at - time.monotonic()
                    if delay > 0:
                        await asyncio.sleep(delay)
                    prompt = f"soak-{self.seed}-{i:05d} " + "x" * isl
                    tenant, priority = "", 0
                    if self.tenant_cycle:
                        tenant, priority = self.tenant_cycle[
                            i % len(self.tenant_cycle)]
                    tasks.append(asyncio.create_task(drive_stream(
                        session, self.base_url, self.model, prompt,
                        osl, phase=phase.label or f"qps{phase.qps}",
                        tenant=tenant, priority=priority,
                    )))
                    i += 1
                # hold the phase boundary even if requests lag
                tail = t_phase + phase.duration_s - time.monotonic()
                if tail > 0:
                    await asyncio.sleep(tail)
            self.records = list(await asyncio.gather(*tasks))
        return self.records


# --------------------------------------------------------------------------- #
# report helpers
# --------------------------------------------------------------------------- #


def attainment(records: Sequence[StreamRecord], ttft_slo_ms: float) -> float:
    """Fraction of records meeting the TTFT target (failures count as
    misses) — the bench_e2e `sla_fields` definition."""
    if not records:
        return 1.0
    met = [r for r in records if r.ok and r.ttft_ms() <= ttft_slo_ms]
    return len(met) / len(records)


def window_attainment(records: Sequence[StreamRecord], t0: float,
                      window_s: float, ttft_slo_ms: float
                      ) -> List[Tuple[float, float, int]]:
    """Per-window (offset_s, attainment, n) over send time — how the soak
    sees SLA degrade under the ramp and recover after scale-up."""
    if not records:
        return []
    t_end = max(r.t_send for r in records)
    out = []
    t = t0
    while t < t_end:
        win = [r for r in records if t <= r.t_send < t + window_s]
        if win:
            out.append((t - t0, attainment(win, ttft_slo_ms), len(win)))
        t += window_s
    return out


def goodput_tok_s(records: Sequence[StreamRecord], ttft_slo_ms: float,
                  window_s: Optional[float] = None) -> float:
    """SLA-attained tokens per second attributable to this offered-load
    window — the dynogate acceptance metric (docs/overload.md): tokens
    streamed by requests that finished AND met their TTFT target, over
    the window the load was OFFERED in (first to last send; pass
    `window_s` to pin it to the phase duration). Rejected/failed/late
    requests contribute zero tokens, so convoy collapse — everything
    admitted, everything late — reads as zero goodput, while clean
    shedding keeps the served slice's tokens counted."""
    if not records:
        return 0.0
    attained = [r for r in records if r.ok and r.ttft_ms() <= ttft_slo_ms]
    if window_s is None:
        t0 = min(r.t_send for r in records)
        t1 = max(r.t_send for r in records)
        window_s = max(t1 - t0, 1e-9)
    return sum(r.content_tokens for r in attained) / max(window_s, 1e-9)


def per_tenant_attainment(records: Sequence[StreamRecord],
                          ttft_slo_ms: float) -> dict:
    """TTFT attainment per tenant over SERVED streams (clean gate
    rejections are excluded: the fairness question is whether what each
    tenant WAS served met SLA, not how much of its flood was refused)."""
    served: dict = {}
    for r in records:
        if r.rejected:
            continue
        served.setdefault(r.tenant or "default", []).append(r)
    return {t: attainment(rs, ttft_slo_ms) for t, rs in served.items()}


def contiguity_report(records: Sequence[StreamRecord]) -> List[str]:
    """Flat list of per-stream contiguity violations (empty = zero lost,
    zero duplicated, every stream finished)."""
    problems = []
    for idx, r in enumerate(records):
        for p in r.contiguity_problems():
            problems.append(f"stream {idx} [{r.phase}]: {p}")
    return problems


def replica_trace(decisions) -> List[Tuple[int, int]]:
    """Applied (p, d) targets in order, deduplicated — the soak's
    scale-cycle assertion reads this."""
    out: List[Tuple[int, int]] = []
    for d in decisions:
        if d.applied and (not out or out[-1] != d.target):
            out.append(d.target)
    return out


def assert_no_flapping(decisions, cooldown_intervals: int,
                       adjustment_interval: float) -> None:
    """No A→B→A oscillation inside the cooldown window, and no two applied
    changes closer than the cooldown allows."""
    applied = [d for d in decisions if d.applied]
    for a, b in zip(applied, applied[1:]):
        gap = b.at - a.at
        min_gap = cooldown_intervals * adjustment_interval
        if gap < min_gap * 0.99:  # tolerance for loop-timing slop
            raise AssertionError(
                f"applied changes {a.target}→{b.target} only {gap:.2f}s apart "
                f"(cooldown {min_gap:.2f}s)"
            )
    for a, b, c in zip(applied, applied[1:], applied[2:]):
        if a.target == c.target and a.target != b.target and \
                c.at - a.at <= (cooldown_intervals + 1) * adjustment_interval:
            raise AssertionError(
                f"replica flap {a.target}→{b.target}→{c.target} within "
                f"the cooldown window"
            )
