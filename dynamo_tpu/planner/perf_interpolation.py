"""Perf interpolators over pre-deployment profiling sweeps.

Role of the reference's planner interpolators
(components/planner/src/dynamo/planner/utils/perf_interpolation.py:23-194):
PrefillInterpolator maps ISL -> TTFT and throughput/chip from a 1-D sweep;
DecodeInterpolator maps (kv_usage, context_length) -> ITL and
throughput/chip from a 2-D sweep, with reverse lookup ("best throughput
whose ITL meets the SLA"). npz field names match the reference's raw_data
format (prefill_isl/prefill_ttft/prefill_thpt_per_gpu; x_kv_usage/
y_context_length/z_itl/z_thpt_per_gpu/max_kv_tokens) so profiles are
interchangeable — "gpu" in those names reads "chip" here. The profiles
themselves come from planner/profiler.py sweeping the JAX engine.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np
import scipy.interpolate


class PrefillInterpolator:
    """ISL -> (TTFT seconds, prefill throughput tok/s/chip)."""

    def __init__(
        self,
        profile_results_dir: Optional[str] = None,
        raw_data: Optional[dict] = None,
    ):
        if profile_results_dir:
            fn = os.path.join(
                profile_results_dir, "selected_prefill_interpolation", "raw_data.npz"
            )
            with np.load(fn) as d:
                raw_data = {k: d[k] for k in d.files}
        if raw_data is None:
            raise ValueError("need profile_results_dir or raw_data")
        self.prefill_isl = np.asarray(raw_data["prefill_isl"], np.float64)
        self.prefill_ttft = np.asarray(raw_data["prefill_ttft"], np.float64) / 1000.0
        self.prefill_thpt_per_chip = np.asarray(
            raw_data["prefill_thpt_per_gpu"], np.float64
        )
        self.min_isl = float(self.prefill_isl.min())
        self.max_isl = float(self.prefill_isl.max())
        kind = "cubic" if len(self.prefill_isl) >= 4 else "linear"
        self._ttft = scipy.interpolate.interp1d(
            self.prefill_isl, self.prefill_ttft, kind=kind
        )
        self._thpt = scipy.interpolate.interp1d(
            self.prefill_isl, self.prefill_thpt_per_chip, kind=kind
        )

    def interpolate_ttft(self, isl: float) -> float:
        return float(self._ttft(np.clip(isl, self.min_isl, self.max_isl)))

    def interpolate_thpt_per_chip(self, isl: float) -> float:
        return float(self._thpt(np.clip(isl, self.min_isl, self.max_isl)))


class DecodeInterpolator:
    """(kv_usage in [0,1], context_length) -> (ITL seconds, decode
    throughput tok/s/chip) on a precomputed grid."""

    def __init__(
        self,
        profile_results_dir: Optional[str] = None,
        resolution: int = 100,
        raw_data: Optional[dict] = None,
    ):
        if profile_results_dir:
            fn = os.path.join(
                profile_results_dir, "selected_decode_interpolation", "raw_data.npz"
            )
            with np.load(fn) as d:
                raw_data = {k: d[k] for k in d.files}
        if raw_data is None:
            raise ValueError("need profile_results_dir or raw_data")
        self.x_kv_usage = np.asarray(raw_data["x_kv_usage"], np.float64)
        self.y_context_length = np.asarray(raw_data["y_context_length"], np.float64)
        self.z_itl = np.asarray(raw_data["z_itl"], np.float64)
        self.z_thpt_per_chip = np.asarray(raw_data["z_thpt_per_gpu"], np.float64)
        self.max_kv_tokens = float(np.asarray(raw_data["max_kv_tokens"]).reshape(-1)[0])

        self.resolution = resolution
        self.xi = np.linspace(0, 1, resolution)
        self.yi = np.linspace(0, float(self.y_context_length.max()), resolution)
        X, Y = np.meshgrid(self.xi, self.yi)
        pts = (self.x_kv_usage, self.y_context_length)
        self.itl_grid = self._grid(pts, self.z_itl, X, Y) / 1000.0  # ms -> s
        self.thpt_grid = self._grid(pts, self.z_thpt_per_chip, X, Y)

    @staticmethod
    def _grid(pts, z, X, Y) -> np.ndarray:
        method = "cubic" if len(z) >= 16 else "linear"
        g = scipy.interpolate.griddata(pts, z, (X, Y), method=method)
        nan = np.isnan(g)
        if np.any(nan):
            g[nan] = scipy.interpolate.griddata(pts, z, (X, Y), method="nearest")[nan]
        return g

    def _idx(self, concurrency: float, context_length: float) -> Tuple[int, int]:
        kv_usage = concurrency * context_length / self.max_kv_tokens
        ix = int(np.clip(round(kv_usage * (self.resolution - 1)), 0, self.resolution - 1))
        iy = int(
            np.clip(
                round((context_length - self.yi[0]) / (self.yi[1] - self.yi[0])),
                0,
                self.resolution - 1,
            )
        )
        return ix, iy

    def interpolate_itl(self, concurrency: float, context_length: float) -> float:
        ix, iy = self._idx(concurrency, context_length)
        return float(self.itl_grid[iy, ix])

    def interpolate_thpt_per_chip(
        self, concurrency: float, context_length: float
    ) -> float:
        ix, iy = self._idx(concurrency, context_length)
        return float(self.thpt_grid[iy, ix])

    def find_best_throughput_per_chip(
        self, itl: float, context_length: float
    ) -> Tuple[float, float, float]:
        """Highest-kv-load grid point whose ITL still meets the SLA; returns
        (thpt/chip, itl, kv_usage). Linear scan — interpolated ITL need not
        be monotonic in load."""
        iy = int(
            np.clip(
                round((context_length - self.yi[0]) / (self.yi[1] - self.yi[0])),
                0,
                self.resolution - 1,
            )
        )
        for ix in range(self.resolution - 1, -1, -1):
            if self.itl_grid[iy, ix] <= itl:
                return (
                    float(self.thpt_grid[iy, ix]),
                    float(self.itl_grid[iy, ix]),
                    float(self.xi[ix]),
                )
        return (
            float(self.thpt_grid[iy, 0]),
            float(self.itl_grid[iy, 0]),
            float(self.xi[0]),
        )
