"""SLA planner core: observe → predict → size → adjust.

Role of the reference's SLA planner
(components/planner/src/dynamo/planner/utils/planner_core.py:221-583):
every `adjustment_interval` seconds it observes frontend metrics (request
rate, ISL/OSL, TTFT/ITL), corrects its performance model against reality
(p/d correction factors), predicts next-interval load, computes how many
prefill and decode replicas meet the TTFT/ITL SLAs from profiled
interpolators, and asks a connector to scale. One deviation: the reference
queries a Prometheus server; here the planner scrapes the frontend's
/metrics endpoint directly and differences counters/histograms between
intervals (same averages, one less moving part).
"""

from __future__ import annotations

import asyncio
import logging
import math
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Protocol, Tuple

from ..runtime.backoff import Backoff, retry_async
from ..runtime.config import _env, env_bool
from ..runtime.metrics import SCHED_EST_DECODE_TOK_S, SCHED_EST_PREFILL_TOK_S
from .load_predictor import BasePredictor, make_predictor
from .perf_interpolation import DecodeInterpolator, PrefillInterpolator

logger = logging.getLogger(__name__)


@dataclass
class SlaArgs:
    ttft: float = 0.5  # target time-to-first-token, seconds
    itl: float = 0.05  # target inter-token latency, seconds
    adjustment_interval: float = 60.0  # seconds between scaling decisions
    prefill_engine_num_chips: int = 1
    decode_engine_num_chips: int = 1
    max_chip_budget: int = 64
    min_endpoint: int = 1
    load_predictor: str = "constant"
    no_correction: bool = False
    # -- loop robustness (docs/autoscaling.md) -------------------------- #
    # metrics scrape: bounded attempts, each under a timeout, backoff
    # between — a hung /metrics endpoint must cost one interval, not the
    # whole loop
    scrape_timeout: float = 5.0
    scrape_retries: int = 3
    # observations older than this never reach the scaling math: on scrape
    # failure the planner HOLDS rather than re-consuming a stale interval
    # average (0 = default of 2.5 × adjustment_interval)
    metrics_max_age: float = 0.0
    # decision governor: a noisy interval must not flap the fleet
    cooldown_intervals: int = 1    # intervals to hold after an applied change
    max_step: int = 2              # max replica delta per decision, per role
    scale_down_stable_intervals: int = 2  # consecutive below-target intervals
    #                                       required before stepping down
    # frontend role (docs/frontend_scaleout.md): with N > 0 every applied
    # worker target also sizes the frontend tier to ceil((p + d) / N)
    # stateless replicas — a monotone function of the governed worker
    # target, so it inherits the governor's cooldown/hysteresis and adds
    # no flapping mode of its own. 0 = frontends not planner-managed.
    workers_per_frontend: int = 0
    # role morphing (docs/autoscaling.md "Role morphing"): under load
    # skew (one role's ask up, the other's down) convert a live worker
    # (engine.morph: drain via tail-migration, flip discovery, re-warm)
    # instead of cold-spawning — effective only when the connector
    # exposes morph_replicas, and only while the priced morph cost beats
    # the cold-spawn cost on time-to-SLA-recovery.
    morph_enabled: bool = True
    morph_cost_s: float = 3.0   # seed morph wall-clock (drain+flip+rewarm)
    spawn_cost_s: float = 30.0  # seed cold-spawn wall-clock (boot+warmup)
    # colocate arm: at sustained floor-level traffic, morph the decode
    # worker to role `both` and retire the dedicated prefill worker.
    colocate: bool = False

    def effective_metrics_max_age(self) -> float:
        return self.metrics_max_age or 2.5 * self.adjustment_interval

    @classmethod
    def from_env(cls, **overrides) -> "SlaArgs":
        """Default args layered with the DYN_PLANNER_* env knobs (all in
        ENV_REGISTRY, rendered to docs/configuration.md); explicit
        keyword overrides win."""
        args = cls(
            scrape_timeout=_env("DYN_PLANNER_SCRAPE_TIMEOUT", cls.scrape_timeout, float),
            scrape_retries=_env("DYN_PLANNER_SCRAPE_RETRIES", cls.scrape_retries, int),
            metrics_max_age=_env(
                "DYN_PLANNER_METRICS_MAX_AGE_S", cls.metrics_max_age, float
            ),
            cooldown_intervals=_env(
                "DYN_PLANNER_COOLDOWN_INTERVALS", cls.cooldown_intervals, int
            ),
            max_step=_env("DYN_PLANNER_MAX_STEP", cls.max_step, int),
            scale_down_stable_intervals=_env(
                "DYN_PLANNER_SCALE_DOWN_STABLE_INTERVALS",
                cls.scale_down_stable_intervals, int,
            ),
            workers_per_frontend=_env(
                "DYN_PLANNER_WORKERS_PER_FRONTEND",
                cls.workers_per_frontend, int,
            ),
            morph_enabled=env_bool("DYN_PLANNER_MORPH", cls.morph_enabled),
            morph_cost_s=_env("DYN_PLANNER_MORPH_COST_S", cls.morph_cost_s, float),
            spawn_cost_s=_env("DYN_PLANNER_SPAWN_COST_S", cls.spawn_cost_s, float),
            colocate=env_bool("DYN_PLANNER_COLOCATE", cls.colocate),
        )
        for k, v in overrides.items():
            setattr(args, k, v)
        return args


@dataclass
class Metrics:
    """Averages observed over the last adjustment interval."""

    num_req: float = math.nan  # requests served in the interval
    isl: float = math.nan
    osl: float = math.nan
    ttft: float = math.nan  # seconds
    itl: float = math.nan  # seconds
    request_duration: float = math.nan  # seconds

    def is_valid(self) -> bool:
        return all(
            not math.isnan(v)
            for v in (self.num_req, self.isl, self.osl, self.ttft, self.itl)
        ) and self.num_req > 0


class MetricsSource(Protocol):
    async def read(self) -> Metrics: ...


class WorkerCounts(Protocol):
    async def count(self) -> tuple[int, int]:
        """(prefill_workers, decode_workers) currently live."""
        ...


class PlannerConnector(Protocol):
    async def set_replicas(self, prefill: int, decode: int,
                           frontend: Optional[int] = None) -> None:
        """`frontend` is only passed when the planner manages the frontend
        tier (SlaArgs.workers_per_frontend > 0); connectors that predate
        the role keep working in the default mode."""
        ...


class RoleEstimates:
    """Planner-side consumer of the per-role marginal-throughput gauges
    workers publish on their metrics topics (sched_est_prefill_tok_s /
    sched_est_decode_tok_s, runtime/metrics.py): folds the freshest
    per-worker values into fleet means so the re-role arm's pricing is
    grounded in observed throughput, not guessed. Advisory — while no
    worker has published, the planner prices from its static seed costs
    (SlaArgs.morph_cost_s / spawn_cost_s) alone."""

    def __init__(self):
        # worker_id -> (prefill_tok_s, decode_tok_s, observed_at)
        self._by_worker: Dict[int, Tuple[float, float, float]] = {}

    def observe(self, worker_id: int, stats: dict,
                now: Optional[float] = None) -> None:
        pf = stats.get(SCHED_EST_PREFILL_TOK_S)
        dc = stats.get(SCHED_EST_DECODE_TOK_S)
        if pf is None and dc is None:
            return
        now = time.monotonic() if now is None else now
        self._by_worker[int(worker_id)] = (
            float(pf or 0.0), float(dc or 0.0), now,
        )

    def fleet_tok_s(self, max_age_s: float = 120.0
                    ) -> Tuple[Optional[float], Optional[float]]:
        """(mean prefill tok/s, mean decode tok/s) over fresh publishes;
        None per side while no worker has reported a warm estimate."""
        now = time.monotonic()
        pfs = [p for p, _d, at in self._by_worker.values()
               if p > 0 and now - at <= max_age_s]
        dcs = [d for _p, d, at in self._by_worker.values()
               if d > 0 and now - at <= max_age_s]
        return (
            sum(pfs) / len(pfs) if pfs else None,
            sum(dcs) / len(dcs) if dcs else None,
        )


@dataclass
class ScaleDecision:
    """One governed planner decision, recorded every interval (including
    holds) — the soak's no-flapping assertion reads this log."""

    at: float  # time.monotonic() when decided
    raw: Optional[Tuple[int, int]]  # model-requested (p, d); None on a hold
    target: Tuple[int, int]  # governed target the connector is held to
    applied: bool  # connector called with a CHANGED target this interval
    reason: str  # scale-up | scale-down | steady | hold:* | connector-error


class Planner:
    def __init__(
        self,
        args: SlaArgs,
        prefill_interpolator: PrefillInterpolator,
        decode_interpolator: DecodeInterpolator,
        metrics_source: MetricsSource,
        workers: WorkerCounts,
        connector: PlannerConnector,
    ):
        self.args = args
        self.prefill_interpolator = prefill_interpolator
        self.decode_interpolator = decode_interpolator
        self.metrics_source = metrics_source
        self.workers = workers
        self.connector = connector

        self.num_req_predictor = make_predictor(args.load_predictor)
        self.isl_predictor = make_predictor(args.load_predictor)
        self.osl_predictor = make_predictor(args.load_predictor)
        self.p_correction_factor = 1.0
        self.d_correction_factor = 1.0
        self.last_metrics = Metrics()
        self._stop = asyncio.Event()
        # decision-governor state: all mutated only from the planner's own
        # loop task (run → make_adjustments), per GUARDED_STATE
        self._target: Optional[Tuple[int, int]] = None  # last applied target
        self._intervals_since_change = 10**9
        # PER-ROLE consecutive below-target ask counters: one role's noisy
        # interval must not pre-arm the other role's scale-down
        self._below_streak = [0, 0]  # [prefill, decode]
        self._observed_at: Optional[float] = None  # monotonic, last GOOD read
        self.decision_log: List[ScaleDecision] = []
        self.scrape_failures = 0  # consecutive; resets on a good read
        # role morphing (docs/autoscaling.md "Role morphing"): observed
        # per-role throughput (fed by the metrics consumer) prices the
        # re-role arm; the colocate streak counts consecutive floor-level
        # intervals before the colocate arm fires.
        self.role_estimates = RoleEstimates()
        self._colocate_streak = 0

    # -- observe -----------------------------------------------------------
    async def observe_metrics(self) -> bool:
        """Scrape the metrics source: bounded attempts under a per-attempt
        timeout, backoff between. Returns False when every attempt failed —
        last_metrics is left untouched and its age keeps growing, so the
        staleness gate (not a NaN average) is what the scaling math sees."""
        try:
            m = await retry_async(
                lambda: asyncio.wait_for(
                    self.metrics_source.read(), timeout=self.args.scrape_timeout
                ),
                attempts=self.args.scrape_retries,
                backoff=Backoff.seeded("planner.scrape", base=0.1, max_delay=1.0),
                desc="metrics scrape", log=logger,
            )
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — scrape must never kill the loop
            self.scrape_failures += 1
            logger.error("metrics scrape exhausted retries: %s", e)
            return False
        self.scrape_failures = 0
        self.last_metrics = m
        self._observed_at = time.monotonic()
        logger.info(
            "observed num_req=%.1f isl=%.1f osl=%.1f ttft=%.3fs itl=%.4fs",
            m.num_req, m.isl, m.osl, m.ttft, m.itl,
        )
        if m.is_valid():
            # an empty/invalid interval must not pollute the predictors
            # (a moving average dragged toward 0 by a quiet minute would
            # scale-to-min the moment traffic resumes)
            self.num_req_predictor.add_data_point(m.num_req)
            self.isl_predictor.add_data_point(m.isl)
            self.osl_predictor.add_data_point(m.osl)
        return True

    def observation_age(self) -> float:
        if self._observed_at is None:
            return math.inf
        return time.monotonic() - self._observed_at

    # -- correct (planner_core.py:383-441) ---------------------------------
    async def update_correction_factors(self) -> None:
        m = self.last_metrics
        if self.args.no_correction or not m.is_valid():
            return
        _, n_decode = await self.workers.count()
        expect_ttft = self.prefill_interpolator.interpolate_ttft(m.isl)
        if expect_ttft > 0:
            self.p_correction_factor = m.ttft / expect_ttft
        concurrency = (
            m.num_req / max(n_decode, 1)
            * m.request_duration / self.args.adjustment_interval
            if not math.isnan(m.request_duration)
            else 1.0
        )
        expect_itl = self.decode_interpolator.interpolate_itl(
            concurrency=concurrency, context_length=m.isl + m.osl / 2
        )
        if expect_itl > 0:
            self.d_correction_factor = m.itl / expect_itl
        logger.info(
            "correction factors: ttft=%.3f itl=%.3f",
            self.p_correction_factor, self.d_correction_factor,
        )

    # -- predict ------------------------------------------------------------
    def predict_load(self) -> tuple[Optional[float], Optional[float], Optional[float]]:
        return (
            self.num_req_predictor.predict_next(),
            self.isl_predictor.predict_next(),
            self.osl_predictor.predict_next(),
        )

    # -- size (planner_core.py:287-380) --------------------------------------
    def compute_replica_requirements(
        self, next_num_req: float, next_isl: float, next_osl: float
    ) -> tuple[int, int]:
        a = self.args
        # prefill: token throughput needed, derated by observed TTFT headroom
        # (queueing shows up as p_correction_factor > 1)
        pred_prefill_thpt = (
            next_num_req * next_isl / a.adjustment_interval
            * min(1.0, self.p_correction_factor)
        )
        per_p_replica = (
            self.prefill_interpolator.interpolate_thpt_per_chip(next_isl)
            * a.prefill_engine_num_chips
        )
        next_p = math.ceil(pred_prefill_thpt / max(per_p_replica, 1e-9))

        # decode: tighten the ITL target by the observed miss ratio, then find
        # the best per-chip throughput that still meets it at predicted context
        corrected_itl = (
            a.itl / self.d_correction_factor
            if self.d_correction_factor > 0
            else a.itl
        )
        thpt_per_chip, _, _ = self.decode_interpolator.find_best_throughput_per_chip(
            itl=corrected_itl, context_length=next_isl + next_osl / 2
        )
        pred_decode_thpt = next_num_req * next_osl / a.adjustment_interval
        next_d = math.ceil(
            pred_decode_thpt / max(thpt_per_chip * a.decode_engine_num_chips, 1e-9)
        )

        next_p = max(next_p, a.min_endpoint)
        next_d = max(next_d, a.min_endpoint)

        # chip budget: scale down proportionally (planner_core.py:358-380),
        # then walk down to the hard budget (round()/min_endpoint can leave
        # the proportional result one replica over)
        total = next_p * a.prefill_engine_num_chips + next_d * a.decode_engine_num_chips
        if total > a.max_chip_budget:
            scale = a.max_chip_budget / total
            next_p = max(a.min_endpoint, round(next_p * scale))
            next_d = max(
                a.min_endpoint,
                math.floor(
                    (a.max_chip_budget - next_p * a.prefill_engine_num_chips)
                    / a.decode_engine_num_chips
                ),
            )

            def chips() -> int:
                return (next_p * a.prefill_engine_num_chips
                        + next_d * a.decode_engine_num_chips)

            while chips() > a.max_chip_budget and next_p > a.min_endpoint:
                next_p -= 1
            while chips() > a.max_chip_budget and next_d > a.min_endpoint:
                next_d -= 1
            if chips() > a.max_chip_budget:
                logger.warning(
                    "min_endpoint floors alone exceed the chip budget "
                    "(%d chips > %d)", chips(), a.max_chip_budget,
                )
            logger.warning(
                "chip budget %d exceeded (%d); scaled to p=%d d=%d",
                a.max_chip_budget, total, next_p, next_d,
            )
        return next_p, next_d

    # -- govern (hysteresis / cooldown / bounded step) ------------------------
    def _record(self, raw, target, applied, reason) -> ScaleDecision:
        dec = ScaleDecision(time.monotonic(), raw, target, applied, reason)
        self.decision_log.append(dec)
        logger.info(
            "planner decision: raw=%s target=%s applied=%s (%s)",
            raw, target, applied, reason,
        )
        return dec

    def _govern(self, raw: Tuple[int, int], cur: Tuple[int, int]
                ) -> Tuple[Tuple[int, int], str]:
        """Turn the model's raw replica ask into a governed target:

        * bounded step — at most `max_step` replicas per role per decision;
        * scale-down hysteresis — the ask must sit below the current target
          for `scale_down_stable_intervals` CONSECUTIVE intervals before a
          step down (one quiet interval can't shed capacity);
        * cooldown — after any applied change, hold `cooldown_intervals`
          intervals before another (structurally rules out A→B→A flapping
          inside the window).

        Scale-up is only cooldown-gated (never hysteresis-gated): restoring
        SLA outranks fleet stability."""
        a = self.args
        step = max(1, a.max_step)
        govern = [
            max(cur[i] - step, min(cur[i] + step, raw[i])) for i in (0, 1)
        ]
        held_down = False
        for i in (0, 1):
            # per-role streaks: role i steps down only after ITS OWN ask
            # sat below target for scale_down_stable_intervals in a row
            self._below_streak[i] = (
                self._below_streak[i] + 1 if raw[i] < cur[i] else 0
            )
            if govern[i] < cur[i] and \
                    self._below_streak[i] < a.scale_down_stable_intervals:
                govern[i] = cur[i]
                held_down = True
        p, d = govern
        if (p, d) == cur:
            return cur, ("hold:hysteresis" if held_down else "steady")
        # `<=`, not `<`: _intervals_since_change was already incremented for
        # THIS interval, so cooldown_intervals=N must hold decisions on the
        # N intervals after a change (with `<` the default of 1 held none)
        if self._intervals_since_change <= a.cooldown_intervals:
            return cur, "hold:cooldown"
        if p > cur[0] or d > cur[1]:
            # mixed asks (one role up, one down) classify as scale-up; the
            # down half already passed its own hysteresis gate above
            return (p, d), "scale-up"
        return (p, d), "scale-down"

    async def _apply_target(self, target: Tuple[int, int]) -> bool:
        """Push a target through the connector with bounded retries: a
        transient connector failure (fault plan, spawn blip, discovery
        reset) must not strand the replica count — on final failure the
        target is NOT committed, so the next interval re-decides and
        re-asserts it."""
        kwargs = {}
        if self.args.workers_per_frontend > 0:
            # frontend tier rides every applied worker target: stateless
            # replicas sized to the fleet (docs/frontend_scaleout.md)
            kwargs["frontend"] = max(
                1, math.ceil(sum(target) / self.args.workers_per_frontend)
            )
        try:
            await retry_async(
                lambda: self.connector.set_replicas(*target, **kwargs),
                attempts=3,
                backoff=Backoff.seeded("planner.connector", base=0.1, max_delay=1.0),
                desc=f"connector set_replicas{target}", log=logger,
            )
            return True
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — surfaced in the log, retried next interval
            logger.error("connector failed after retries: %s", e)
            return False

    # -- re-role (docs/autoscaling.md "Role morphing") ------------------------
    def _plan_re_role(self, cur: Tuple[int, int], target: Tuple[int, int]
                      ) -> Tuple[int, Optional[str], Optional[str]]:
        """Under genuine load skew — the governed target moves one role UP
        and the other DOWN — convert live workers (morph) instead of
        cold-spawning, when the priced morph beats a spawn on
        time-to-SLA-recovery. Returns (k, from_role, to_role): k morphs to
        request, (0, None, None) when the spawn/kill path should run as
        usual. The governor already bounded and hysteresis-gated both
        deltas, so the morph count inherits every stability property."""
        a = self.args
        if not a.morph_enabled:
            return 0, None, None
        if getattr(self.connector, "morph_replicas", None) is None:
            return 0, None, None
        dp, dd = target[0] - cur[0], target[1] - cur[1]
        if dp == 0 or dd == 0 or (dp > 0) == (dd > 0):
            return 0, None, None  # not a skew: plain scale handles it
        if a.morph_cost_s >= a.spawn_cost_s:
            # priced out: a morph (drain + flip + re-warm) recovers SLA in
            # morph_cost_s vs spawn_cost_s for a cold replica — when that
            # inverts, spawning wins and the arm stands down
            return 0, None, None
        est_p, est_d = self.role_estimates.fleet_tok_s()
        logger.info(
            "re-role priced: morph=%.1fs beats spawn=%.1fs "
            "(observed prefill=%s decode=%s tok/s)",
            a.morph_cost_s, a.spawn_cost_s,
            f"{est_p:.0f}" if est_p else "cold",
            f"{est_d:.0f}" if est_d else "cold",
        )
        k = min(abs(dp), abs(dd))
        if dp > 0:
            return k, "decode", "prefill"
        return k, "prefill", "decode"

    async def _apply_morph(self, from_role: str, to_role: str, k: int) -> bool:
        """Push k re-roles through the connector with bounded retries —
        the same uncommitted-on-failure contract as _apply_target: on
        final failure nothing is committed and the next interval
        re-decides (the connector's own morph rollback restored any
        half-flipped worker to its original role)."""
        try:
            await retry_async(
                lambda: self.connector.morph_replicas(from_role, to_role, k),
                attempts=3,
                backoff=Backoff.seeded("planner.connector", base=0.1, max_delay=1.0),
                desc=f"connector morph_replicas {from_role}->{to_role} x{k}",
                log=logger,
            )
            return True
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — surfaced in the log, re-decided next interval
            logger.error("connector morph failed after retries: %s", e)
            return False

    async def _maybe_colocate(self, raw: Tuple[int, int],
                              cur: Tuple[int, int]) -> bool:
        """Colocate arm (DYN_PLANNER_COLOCATE): after the raw ask has sat
        at the min_endpoint floor for scale_down_stable_intervals
        consecutive intervals (outside cooldown), morph the decode worker
        to role `both` and retire the dedicated prefill worker — one
        worker serves both roles at low traffic. The connector's
        colocate() returns False when already colocated (no-op)."""
        a = self.args
        colocate = getattr(self.connector, "colocate", None)
        if not a.colocate or colocate is None:
            self._colocate_streak = 0
            return False
        if raw[0] > a.min_endpoint or raw[1] > a.min_endpoint:
            self._colocate_streak = 0
            return False
        self._colocate_streak += 1
        if self._colocate_streak < a.scale_down_stable_intervals:
            return False
        if self._intervals_since_change <= a.cooldown_intervals:
            return False
        try:
            did = await colocate()
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — retried next interval
            logger.warning("connector colocate failed: %s", e)
            return False
        if not did:
            return False
        # a colocation is a scale event on BOTH roles
        self._intervals_since_change = 0
        self._colocate_streak = 0
        self._record(raw, cur, True, "re-role:colocate")
        return True

    # -- adjust ---------------------------------------------------------------
    async def make_adjustments(self) -> Optional[tuple[int, int]]:
        if self._target is None:
            self._target = await self.workers.count()
        cur = self._target
        self._intervals_since_change += 1
        floor = self.args.min_endpoint
        if cur[0] < floor or cur[1] < floor:
            # cold start (or below-floor fleet): bring the fleet up to the
            # min_endpoint floor WITHOUT waiting for traffic — with zero
            # workers no model serves, so no request ever arrives and a
            # traffic-gated planner would deadlock at zero forever
            target = (max(cur[0], floor), max(cur[1], floor))
            if not await self._apply_target(target):
                self._record(None, cur, False, "connector-error")
                return None
            self._target = target
            self._intervals_since_change = 0
            self._record(None, target, True, "bootstrap:min-endpoint")
            return target
        if self.observation_age() > self.args.effective_metrics_max_age():
            # scrapes kept failing: the last averages are stale — hold the
            # current target rather than steer the fleet on old data
            self._record(None, cur, False, "hold:stale-metrics")
            return None
        if not self.last_metrics.is_valid():
            # first interval / zero-request interval: hold the last
            # decision (never scale-to-min on a quiet minute)
            self._record(None, cur, False, "hold:no-traffic")
            return None
        await self.update_correction_factors()
        num_req, isl, osl = self.predict_load()
        if num_req is None or isl is None or osl is None:
            self._record(None, cur, False, "hold:no-prediction")
            return None
        raw = self.compute_replica_requirements(num_req, isl, osl)
        target, reason = self._govern(raw, cur)
        if target == cur:
            if await self._maybe_colocate(raw, cur):
                return cur
            self._record(raw, cur, False, reason)
            return None
        self._colocate_streak = 0
        # re-role arm: under skew, morph live workers across roles instead
        # of cold-spawning; any residual delta beyond the morphed pairs
        # still rides the plain spawn/kill path. A failed step commits
        # nothing — the next interval re-decides and re-asserts.
        k, from_role, to_role = self._plan_re_role(cur, target)
        if k:
            if not await self._apply_morph(from_role, to_role, k):
                self._record(raw, cur, False, "connector-error")
                return None
            reason = f"re-role:{from_role}->{to_role}"
            if abs(target[0] - cur[0]) != k or abs(target[1] - cur[1]) != k:
                if not await self._apply_target(target):
                    self._record(raw, cur, False, "connector-error")
                    return None
                reason += "+scale"
        elif not await self._apply_target(target):
            self._record(raw, cur, False, "connector-error")
            return None
        self._target = target
        # an applied morph counts as a scale event on BOTH roles — the
        # shared cooldown window structurally rules out A→B→A re-role
        # flapping just as it does for plain scaling
        self._intervals_since_change = 0
        for i in (0, 1):
            if target[i] < cur[i]:
                # an applied step down re-arms that role's hysteresis:
                # further shedding needs fresh consecutive confirmation
                self._below_streak[i] = 0
        self._record(raw, target, True, reason)
        return target

    async def _reconcile_connector(self) -> None:
        """Connectors that manage real processes expose reconcile(): re-
        assert the committed target every interval so a replica that died
        (or a spawn that failed mid-apply) is replaced without waiting for
        the next load-driven decision."""
        reconcile = getattr(self.connector, "reconcile", None)
        if reconcile is None or self._target is None:
            return
        try:
            await reconcile()
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — retried next interval
            logger.warning("connector reconcile failed: %s", e)

    async def run(self) -> None:
        """Planner loop: sleep interval, observe, adjust — until stop()."""
        while not self._stop.is_set():
            try:
                await asyncio.wait_for(
                    self._stop.wait(), timeout=self.args.adjustment_interval
                )
                break
            except asyncio.TimeoutError:
                pass
            try:
                await self.observe_metrics()
                await self.make_adjustments()
                await self._reconcile_connector()
            except Exception:  # noqa: BLE001 — planner must survive blips
                logger.exception("planner iteration failed")

    def stop(self) -> None:
        self._stop.set()
