"""SLA planner core: observe → predict → size → adjust.

Role of the reference's SLA planner
(components/planner/src/dynamo/planner/utils/planner_core.py:221-583):
every `adjustment_interval` seconds it observes frontend metrics (request
rate, ISL/OSL, TTFT/ITL), corrects its performance model against reality
(p/d correction factors), predicts next-interval load, computes how many
prefill and decode replicas meet the TTFT/ITL SLAs from profiled
interpolators, and asks a connector to scale. One deviation: the reference
queries a Prometheus server; here the planner scrapes the frontend's
/metrics endpoint directly and differences counters/histograms between
intervals (same averages, one less moving part).
"""

from __future__ import annotations

import asyncio
import logging
import math
from dataclasses import dataclass, field
from typing import Optional, Protocol

from .load_predictor import BasePredictor, make_predictor
from .perf_interpolation import DecodeInterpolator, PrefillInterpolator

logger = logging.getLogger(__name__)


@dataclass
class SlaArgs:
    ttft: float = 0.5  # target time-to-first-token, seconds
    itl: float = 0.05  # target inter-token latency, seconds
    adjustment_interval: float = 60.0  # seconds between scaling decisions
    prefill_engine_num_chips: int = 1
    decode_engine_num_chips: int = 1
    max_chip_budget: int = 64
    min_endpoint: int = 1
    load_predictor: str = "constant"
    no_correction: bool = False


@dataclass
class Metrics:
    """Averages observed over the last adjustment interval."""

    num_req: float = math.nan  # requests served in the interval
    isl: float = math.nan
    osl: float = math.nan
    ttft: float = math.nan  # seconds
    itl: float = math.nan  # seconds
    request_duration: float = math.nan  # seconds

    def is_valid(self) -> bool:
        return all(
            not math.isnan(v)
            for v in (self.num_req, self.isl, self.osl, self.ttft, self.itl)
        ) and self.num_req > 0


class MetricsSource(Protocol):
    async def read(self) -> Metrics: ...


class WorkerCounts(Protocol):
    async def count(self) -> tuple[int, int]:
        """(prefill_workers, decode_workers) currently live."""
        ...


class PlannerConnector(Protocol):
    async def set_replicas(self, prefill: int, decode: int) -> None: ...


class Planner:
    def __init__(
        self,
        args: SlaArgs,
        prefill_interpolator: PrefillInterpolator,
        decode_interpolator: DecodeInterpolator,
        metrics_source: MetricsSource,
        workers: WorkerCounts,
        connector: PlannerConnector,
    ):
        self.args = args
        self.prefill_interpolator = prefill_interpolator
        self.decode_interpolator = decode_interpolator
        self.metrics_source = metrics_source
        self.workers = workers
        self.connector = connector

        self.num_req_predictor = make_predictor(args.load_predictor)
        self.isl_predictor = make_predictor(args.load_predictor)
        self.osl_predictor = make_predictor(args.load_predictor)
        self.p_correction_factor = 1.0
        self.d_correction_factor = 1.0
        self.last_metrics = Metrics()
        self._stop = asyncio.Event()

    # -- observe -----------------------------------------------------------
    async def observe_metrics(self) -> None:
        self.last_metrics = await self.metrics_source.read()
        m = self.last_metrics
        logger.info(
            "observed num_req=%.1f isl=%.1f osl=%.1f ttft=%.3fs itl=%.4fs",
            m.num_req, m.isl, m.osl, m.ttft, m.itl,
        )
        self.num_req_predictor.add_data_point(m.num_req)
        self.isl_predictor.add_data_point(m.isl)
        self.osl_predictor.add_data_point(m.osl)

    # -- correct (planner_core.py:383-441) ---------------------------------
    async def update_correction_factors(self) -> None:
        m = self.last_metrics
        if self.args.no_correction or not m.is_valid():
            return
        _, n_decode = await self.workers.count()
        expect_ttft = self.prefill_interpolator.interpolate_ttft(m.isl)
        if expect_ttft > 0:
            self.p_correction_factor = m.ttft / expect_ttft
        concurrency = (
            m.num_req / max(n_decode, 1)
            * m.request_duration / self.args.adjustment_interval
            if not math.isnan(m.request_duration)
            else 1.0
        )
        expect_itl = self.decode_interpolator.interpolate_itl(
            concurrency=concurrency, context_length=m.isl + m.osl / 2
        )
        if expect_itl > 0:
            self.d_correction_factor = m.itl / expect_itl
        logger.info(
            "correction factors: ttft=%.3f itl=%.3f",
            self.p_correction_factor, self.d_correction_factor,
        )

    # -- predict ------------------------------------------------------------
    def predict_load(self) -> tuple[Optional[float], Optional[float], Optional[float]]:
        return (
            self.num_req_predictor.predict_next(),
            self.isl_predictor.predict_next(),
            self.osl_predictor.predict_next(),
        )

    # -- size (planner_core.py:287-380) --------------------------------------
    def compute_replica_requirements(
        self, next_num_req: float, next_isl: float, next_osl: float
    ) -> tuple[int, int]:
        a = self.args
        # prefill: token throughput needed, derated by observed TTFT headroom
        # (queueing shows up as p_correction_factor > 1)
        pred_prefill_thpt = (
            next_num_req * next_isl / a.adjustment_interval
            * min(1.0, self.p_correction_factor)
        )
        per_p_replica = (
            self.prefill_interpolator.interpolate_thpt_per_chip(next_isl)
            * a.prefill_engine_num_chips
        )
        next_p = math.ceil(pred_prefill_thpt / max(per_p_replica, 1e-9))

        # decode: tighten the ITL target by the observed miss ratio, then find
        # the best per-chip throughput that still meets it at predicted context
        corrected_itl = (
            a.itl / self.d_correction_factor
            if self.d_correction_factor > 0
            else a.itl
        )
        thpt_per_chip, _, _ = self.decode_interpolator.find_best_throughput_per_chip(
            itl=corrected_itl, context_length=next_isl + next_osl / 2
        )
        pred_decode_thpt = next_num_req * next_osl / a.adjustment_interval
        next_d = math.ceil(
            pred_decode_thpt / max(thpt_per_chip * a.decode_engine_num_chips, 1e-9)
        )

        next_p = max(next_p, a.min_endpoint)
        next_d = max(next_d, a.min_endpoint)

        # chip budget: scale down proportionally (planner_core.py:358-380),
        # then walk down to the hard budget (round()/min_endpoint can leave
        # the proportional result one replica over)
        total = next_p * a.prefill_engine_num_chips + next_d * a.decode_engine_num_chips
        if total > a.max_chip_budget:
            scale = a.max_chip_budget / total
            next_p = max(a.min_endpoint, round(next_p * scale))
            next_d = max(
                a.min_endpoint,
                math.floor(
                    (a.max_chip_budget - next_p * a.prefill_engine_num_chips)
                    / a.decode_engine_num_chips
                ),
            )

            def chips() -> int:
                return (next_p * a.prefill_engine_num_chips
                        + next_d * a.decode_engine_num_chips)

            while chips() > a.max_chip_budget and next_p > a.min_endpoint:
                next_p -= 1
            while chips() > a.max_chip_budget and next_d > a.min_endpoint:
                next_d -= 1
            if chips() > a.max_chip_budget:
                logger.warning(
                    "min_endpoint floors alone exceed the chip budget "
                    "(%d chips > %d)", chips(), a.max_chip_budget,
                )
            logger.warning(
                "chip budget %d exceeded (%d); scaled to p=%d d=%d",
                a.max_chip_budget, total, next_p, next_d,
            )
        return next_p, next_d

    # -- adjust ---------------------------------------------------------------
    async def make_adjustments(self) -> Optional[tuple[int, int]]:
        if not self.last_metrics.is_valid():
            logger.info("no traffic in interval; skipping adjustment")
            return None
        await self.update_correction_factors()
        num_req, isl, osl = self.predict_load()
        if num_req is None or isl is None or osl is None:
            return None
        p, d = self.compute_replica_requirements(num_req, isl, osl)
        await self.connector.set_replicas(p, d)
        return p, d

    async def run(self) -> None:
        """Planner loop: sleep interval, observe, adjust — until stop()."""
        while not self._stop.is_set():
            try:
                await asyncio.wait_for(
                    self._stop.wait(), timeout=self.args.adjustment_interval
                )
                break
            except asyncio.TimeoutError:
                pass
            try:
                await self.observe_metrics()
                await self.make_adjustments()
            except Exception:  # noqa: BLE001 — planner must survive blips
                logger.exception("planner iteration failed")

    def stop(self) -> None:
        self._stop.set()
