"""Frontend metrics scraper for the planner.

Role of the reference's Prometheus client
(components/planner/src/dynamo/planner/utils/prometheus.py): supplies the
planner's per-interval averages. The reference queries a Prometheus server
with `avg_over_time`; here we scrape the frontend's /metrics endpoint and
difference counter/histogram samples between consecutive scrapes — the
same interval averages without a Prometheus deployment in the loop.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

import aiohttp

from ..runtime import faults
from .planner_core import Metrics

_NS = "dynamo_frontend"


def parse_prometheus_text(text: str) -> Dict[str, float]:
    """Sum samples per metric name (labels aggregated away — the planner
    sizes the whole deployment, not one model)."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            name_part, value = line.rsplit(None, 1)
        except ValueError:
            continue
        name = name_part.split("{", 1)[0]
        try:
            out[name] = out.get(name, 0.0) + float(value)
        except ValueError:
            continue
    return out


class FrontendMetricsSource:
    """Scrapes /metrics and returns deltas between consecutive reads."""

    def __init__(self, url: str):
        self.url = url if url.endswith("/metrics") else url.rstrip("/") + "/metrics"
        self._prev: Optional[Dict[str, float]] = None

    async def _scrape(self) -> Dict[str, float]:
        f = faults.FAULTS
        if f.enabled:
            # `error` raises FaultError, `hang` parks until the planner's
            # per-attempt timeout cuts it, `delay` slows the scrape — all
            # land on the retry/staleness path the planner must survive
            await f.on("planner.scrape")
        async with aiohttp.ClientSession() as s:
            async with s.get(self.url) as resp:
                resp.raise_for_status()
                return parse_prometheus_text(await resp.text())

    @staticmethod
    def _delta(cur: Dict[str, float], prev: Dict[str, float], name: str) -> float:
        return cur.get(name, 0.0) - prev.get(name, 0.0)

    @staticmethod
    def _avg(cur, prev, sum_name: str, count_name: str) -> float:
        dc = cur.get(count_name, 0.0) - prev.get(count_name, 0.0)
        if dc <= 0:
            return math.nan
        return (cur.get(sum_name, 0.0) - prev.get(sum_name, 0.0)) / dc

    async def read(self) -> Metrics:
        cur = await self._scrape()
        prev = self._prev
        self._prev = cur
        if prev is None:
            return Metrics()  # first scrape: no interval to difference yet

    # counter names per llm/http/metrics.py
        num_req = self._delta(cur, prev, f"{_NS}_requests_total")
        out_tok = self._delta(cur, prev, f"{_NS}_output_tokens_total")
        in_tok = self._delta(cur, prev, f"{_NS}_input_tokens_total")
        return Metrics(
            num_req=num_req,
            isl=in_tok / num_req if num_req > 0 else math.nan,
            osl=out_tok / num_req if num_req > 0 else math.nan,
            ttft=self._avg(
                cur, prev,
                f"{_NS}_time_to_first_token_seconds_sum",
                f"{_NS}_time_to_first_token_seconds_count",
            ),
            itl=self._avg(
                cur, prev,
                f"{_NS}_inter_token_latency_seconds_sum",
                f"{_NS}_inter_token_latency_seconds_count",
            ),
            request_duration=self._avg(
                cur, prev,
                f"{_NS}_request_duration_seconds_sum",
                f"{_NS}_request_duration_seconds_count",
            ),
        )
