"""`python -m dynamo_tpu.planner` — run the SLA planner against a frontend.

Reference CLI shape: components/planner/src/dynamo/planner/planner_sla.py
(+ planner_argparse.py). Scales via the virtual connector (decision in
discovery KV) or local worker subprocesses.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import shlex

logger = logging.getLogger(__name__)


def parse_args(argv=None) -> argparse.Namespace:
    ap = argparse.ArgumentParser(description="SLA planner")
    ap.add_argument("--frontend-url", default="http://127.0.0.1:8080")
    ap.add_argument("--profile-results-dir", required=True)
    ap.add_argument("--ttft", type=float, default=0.5, help="TTFT SLA seconds")
    ap.add_argument("--itl", type=float, default=0.05, help="ITL SLA seconds")
    ap.add_argument("--adjustment-interval", type=float, default=60.0)
    ap.add_argument("--prefill-engine-num-chips", type=int, default=1)
    ap.add_argument("--decode-engine-num-chips", type=int, default=1)
    ap.add_argument("--max-chip-budget", type=int, default=64)
    ap.add_argument("--min-endpoint", type=int, default=1)
    ap.add_argument(
        "--load-predictor", default="constant",
        choices=["constant", "moving-average", "ar", "arima", "prophet"],
    )
    ap.add_argument("--no-operation", action="store_true",
                    help="log decisions without scaling")
    ap.add_argument(
        "--connector", default="virtual", choices=["virtual", "local", "noop"]
    )
    ap.add_argument("--prefill-cmd", default="", help="argv for a prefill worker (local connector)")
    ap.add_argument("--decode-cmd", default="", help="argv for a decode worker (local connector)")
    ap.add_argument("--frontend-cmd", default="",
                    help="argv for a frontend replica (local connector); "
                    "each replica's DYN_WORKER_INDEX offsets its ports "
                    "(docs/frontend_scaleout.md)")
    ap.add_argument("--workers-per-frontend", type=int, default=None,
                    help="size the frontend tier to ceil(workers / N) "
                    "replicas alongside every applied worker target "
                    "(default: DYN_PLANNER_WORKERS_PER_FRONTEND; 0 = "
                    "frontends not planner-managed)")
    ap.add_argument("--namespace", default="dynamo")
    ap.add_argument("--prefill-component", default="prefill",
                    help="discovery component name counted as prefill capacity")
    ap.add_argument("--decode-component", default="backend",
                    help="discovery component name counted as decode capacity "
                    "(mocker workers default to 'mocker')")
    ap.add_argument("--log-level", default="INFO")
    return ap.parse_args(argv)


async def amain(args: argparse.Namespace) -> None:
    from ..runtime.config import discovery_address
    from ..runtime.discovery import DiscoveryClient
    from .connector import (
        DiscoveryWorkerCounts,
        LocalProcessConnector,
        NoopConnector,
        VirtualConnector,
    )
    from .metrics_source import FrontendMetricsSource
    from .perf_interpolation import DecodeInterpolator, PrefillInterpolator
    from .planner_core import Planner, SlaArgs

    host, port = discovery_address()
    # NB: connect is a classmethod factory — `DiscoveryClient(host, port)`
    # followed by an instance .connect() was a TypeError waiting for the
    # first real deployment of this entrypoint
    disc = await DiscoveryClient.connect(host, port)

    counts = DiscoveryWorkerCounts(
        disc, namespace=args.namespace,
        prefill_component=args.prefill_component,
        decode_component=args.decode_component,
    )
    if args.no_operation or args.connector == "noop":
        connector = NoopConnector()
    elif args.connector == "local":
        connector = LocalProcessConnector(
            shlex.split(args.prefill_cmd), shlex.split(args.decode_cmd),
            ready_fn=counts.ready_fn(),
            frontend_cmd=shlex.split(args.frontend_cmd),
        )
    else:
        connector = VirtualConnector(disc)

    planner = Planner(
        SlaArgs.from_env(
            ttft=args.ttft,
            itl=args.itl,
            adjustment_interval=args.adjustment_interval,
            prefill_engine_num_chips=args.prefill_engine_num_chips,
            decode_engine_num_chips=args.decode_engine_num_chips,
            max_chip_budget=args.max_chip_budget,
            min_endpoint=args.min_endpoint,
            load_predictor=args.load_predictor,
            **({"workers_per_frontend": args.workers_per_frontend}
               if args.workers_per_frontend is not None else {}),
        ),
        PrefillInterpolator(profile_results_dir=args.profile_results_dir),
        DecodeInterpolator(profile_results_dir=args.profile_results_dir),
        FrontendMetricsSource(args.frontend_url),
        counts,
        connector,
    )
    # SIGTERM/SIGINT stop the loop cleanly so the finally below actually
    # runs — the interpreter's default SIGTERM exit would orphan every
    # connector-managed worker subprocess
    import signal

    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, planner.stop)
        except (NotImplementedError, RuntimeError):
            break
    try:
        await planner.run()
    finally:
        # shielded: a cancellation (Ctrl-C) landing mid-close must not
        # abandon the teardown. Connector-managed children die with the
        # planner (SIGTERM → their own graceful drain) — otherwise a
        # planner restart would spawn a duplicate fleet beside orphans.
        async def _teardown():
            shutdown = getattr(connector, "shutdown", None)
            if shutdown is not None:
                try:
                    await shutdown()
                except Exception:  # noqa: BLE001 — teardown is best-effort
                    logger.exception("connector shutdown failed")
            await disc.close()

        await asyncio.shield(_teardown())


def main(argv=None) -> None:
    args = parse_args(argv)
    logging.basicConfig(
        level=args.log_level.upper(),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
