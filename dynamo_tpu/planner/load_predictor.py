"""Load predictors for the SLA planner.

Role of the reference's planner load predictors
(components/planner/src/dynamo/planner/utils/load_predictor.py:36-177):
each wraps a sliding window of observed per-interval load (request rate,
ISL, OSL) and predicts the next interval. The reference offers
Constant/ARIMA/Prophet; here the ARIMA/Prophet roles are played by a
dependency-free least-squares AR(p) model (statsmodels/prophet are not in
the image, and an AR fit captures the same short-horizon trend the planner
actually consumes).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import List, Optional

import numpy as np


class BasePredictor(ABC):
    def __init__(self, minimum_data_points: int = 5):
        self.minimum_data_points = minimum_data_points
        self.data_buffer: List[float] = []

    def add_data_point(self, value: float) -> None:
        if value is None or (isinstance(value, float) and np.isnan(value)):
            return
        self.data_buffer.append(float(value))

    def get_last_value(self) -> Optional[float]:
        return self.data_buffer[-1] if self.data_buffer else None

    @abstractmethod
    def predict_next(self) -> Optional[float]: ...


class ConstantPredictor(BasePredictor):
    """Next load = last observed load."""

    def predict_next(self) -> Optional[float]:
        return self.get_last_value()


class MovingAveragePredictor(BasePredictor):
    """Next load = mean of the last `window_size` observations."""

    def __init__(self, window_size: int = 10, minimum_data_points: int = 1):
        super().__init__(minimum_data_points)
        self.window_size = window_size

    def predict_next(self) -> Optional[float]:
        if not self.data_buffer:
            return None
        w = self.data_buffer[-self.window_size :]
        return float(np.mean(w))


class ARPredictor(BasePredictor):
    """Least-squares AR(p) one-step-ahead forecast over a sliding window
    (the ARIMA role, load_predictor.py:79-117, without statsmodels)."""

    def __init__(
        self, order: int = 3, window_size: int = 100, minimum_data_points: int = 5
    ):
        super().__init__(minimum_data_points)
        self.order = order
        self.window_size = window_size

    def add_data_point(self, value: float) -> None:
        super().add_data_point(value)
        if len(self.data_buffer) > self.window_size:
            self.data_buffer = self.data_buffer[-self.window_size :]

    def predict_next(self) -> Optional[float]:
        n = len(self.data_buffer)
        if n == 0:
            return None
        if n < max(self.minimum_data_points, self.order + 1):
            return self.get_last_value()
        x = np.asarray(self.data_buffer, np.float64)
        p = self.order
        # design matrix of lagged values + intercept
        rows = n - p
        X = np.ones((rows, p + 1))
        for i in range(p):
            X[:, i + 1] = x[i : i + rows]
        y = x[p:]
        coef, *_ = np.linalg.lstsq(X, y, rcond=None)
        pred = coef[0] + float(np.dot(coef[1:], x[-p:]))
        # an AR fit on a short noisy window can extrapolate wildly; clamp to
        # a sane band around the observed range (planner safety)
        lo, hi = float(x.min()), float(x.max())
        span = max(hi - lo, abs(hi) * 0.1, 1e-9)
        return float(np.clip(pred, lo - span, hi + span))


PREDICTORS = {
    "constant": ConstantPredictor,
    "moving-average": MovingAveragePredictor,
    "ar": ARPredictor,
    # reference names, mapped to the closest native predictor
    "arima": ARPredictor,
    "prophet": ARPredictor,
}


def make_predictor(kind: str, **kwargs) -> BasePredictor:
    if kind not in PREDICTORS:
        raise ValueError(f"unknown predictor {kind!r}; choose from {sorted(PREDICTORS)}")
    return PREDICTORS[kind](**kwargs)
