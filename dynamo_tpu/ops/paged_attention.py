"""Paged attention ops: XLA reference implementations.

The role of the reference's engine attention kernels + block_copy.cu, done
the TPU way: static-shaped gathers + einsums that XLA fuses well on the MXU,
with a Pallas decode kernel (ops/pallas_paged_attention.py) swapped in on
TPU for the HBM-bound gather.

Layouts:
  kv_k / kv_v (per layer): [num_pages, page_size, kv_heads, head_dim]
  page_table: logical page index -> physical page id
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def prefill_attention(
    q: jax.Array,  # [T, H, D] (current chunk, rope applied)
    k_chunk: jax.Array,  # [T, KH, D] (unused: already written to pages)
    v_chunk: jax.Array,
    kv_k_layer: jax.Array,  # [pages, page_size, KH, D]
    kv_v_layer: jax.Array,
    positions: jax.Array,  # [T] absolute positions of the chunk
    page_table: jax.Array,  # [max_pages]
    context_len: jax.Array,  # scalar (history before this chunk)
) -> jax.Array:
    """Chunk attends to all earlier positions (history pages + itself,
    causal). Returns [T, H, D]."""
    page_size = kv_k_layer.shape[1]
    S = page_table.shape[0] * page_size
    ctx_k = kv_k_layer[page_table].reshape(S, *kv_k_layer.shape[2:])  # [S, KH, D]
    ctx_v = kv_v_layer[page_table].reshape(S, *kv_v_layer.shape[2:])

    T, H, D = q.shape
    KH = ctx_k.shape[1]
    G = H // KH
    qg = q.reshape(T, KH, G, D)
    scores = jnp.einsum(
        "tkgd,skd->tkgs", qg, ctx_k, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.asarray(D, jnp.float32))
    # causal over absolute positions: key j valid iff j <= pos_t
    key_pos = jnp.arange(S)
    mask = key_pos[None, :] <= positions[:, None]  # [T, S]
    scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("tkgs,skd->tkgd", probs.astype(ctx_v.dtype), ctx_v)
    return out.reshape(T, H, D)


def _use_pallas_decode() -> bool:
    import os

    mode = os.environ.get("DYNAMO_TPU_PAGED_ATTN", "auto")
    if mode == "pallas":
        return True
    if mode == "xla":
        return False
    try:
        # auto: single-chip TPU only. Under a tp>1 GSPMD mesh the KV cache is
        # sharded over heads and a bare pallas_call has no partitioning rule —
        # the XLA path partitions cleanly there. (shard_map-wrapped kernel is
        # the multi-chip follow-up.)
        return jax.default_backend() == "tpu" and jax.device_count() == 1
    except Exception:
        return False


def paged_attention_decode(
    q: jax.Array,  # [B, H, D]
    kv_k_layer: jax.Array,  # [pages, page_size, KH, D]
    kv_v_layer: jax.Array,
    page_tables: jax.Array,  # [B, max_pages]
    seq_lens: jax.Array,  # [B] (including current token)
) -> jax.Array:
    """One-token decode attention over paged KV. Returns [B, H, D].

    Dispatch: on TPU (or DYNAMO_TPU_PAGED_ATTN=pallas) the Pallas flash
    kernel (ops/pallas_paged_attention.py) streams pages HBM→VMEM without
    materializing the gather; elsewhere the XLA reference path below runs.
    """
    if _use_pallas_decode():
        from .pallas_paged_attention import paged_attention_decode_pallas

        return paged_attention_decode_pallas(
            q, kv_k_layer, kv_v_layer, page_tables, seq_lens
        )
    B, H, D = q.shape
    page_size = kv_k_layer.shape[1]
    KH = kv_k_layer.shape[2]
    S = page_tables.shape[1] * page_size
    ctx_k = kv_k_layer[page_tables].reshape(B, S, KH, D)
    ctx_v = kv_v_layer[page_tables].reshape(B, S, KH, D)

    G = H // KH
    qg = q.reshape(B, KH, G, D)
    scores = jnp.einsum(
        "bkgd,bskd->bkgs", qg, ctx_k, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.asarray(D, jnp.float32))
    key_pos = jnp.arange(S)
    mask = key_pos[None, :] < seq_lens[:, None]  # [B, S]
    scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgs,bskd->bkgd", probs.astype(ctx_v.dtype), ctx_v)
    return out.reshape(B, H, D)
