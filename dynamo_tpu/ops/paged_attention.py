"""Paged attention ops: XLA reference implementations.

The role of the reference's engine attention kernels + block_copy.cu, done
the TPU way: static-shaped gathers + einsums that XLA fuses well on the MXU,
with a Pallas decode kernel (ops/pallas_paged_attention.py) swapped in on
TPU for the HBM-bound gather.

Layouts:
  kv_k / kv_v (per layer): [num_pages, page_size, kv_heads, head_dim]
  page_table: logical page index -> physical page id
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from .kv_quant import QuantKV, gather_dequant, is_quant_kv

NEG_INF = -1e30


def _layer_dims(layer) -> tuple:
    """(num_pages, page_size, KH, D) for a per-layer KV operand — plain
    array or QuantKV (whose q axis 1 is packed for int4)."""
    if is_quant_kv(layer):
        P, _, KH, D = layer.q.shape
        return P, layer.page_size, KH, D
    return layer.shape


def prefill_attention(
    q: jax.Array,  # [T, H, D] (current chunk, rope applied)
    k_chunk: jax.Array,  # [T, KH, D] (unused: already written to pages)
    v_chunk: jax.Array,
    kv_k_layer: jax.Array,  # [pages, page_size, KH, D]
    kv_v_layer: jax.Array,
    positions: jax.Array,  # [T] absolute positions of the chunk
    page_table: jax.Array,  # [max_pages]
    context_len: jax.Array,  # scalar (history before this chunk)
    total_len: Optional[jax.Array] = None,  # scalar: history + real chunk len
) -> jax.Array:
    """Chunk attends to all earlier positions (history pages + itself,
    causal). Returns [T, H, D].

    Dispatch: on TPU the Pallas flash kernel
    (ops/pallas_prefill_attention.py) streams only the pages that hold real
    context; elsewhere the XLA reference path below gathers the page table
    (the engine bounds the table length to the context bucket, so the
    gather is context-sized, not max-context-sized).
    """
    if (
        total_len is not None and _pallas_eligible(q.shape[-1])
        and not is_quant_kv(kv_k_layer)
    ):
        # quantized pages ride the XLA reference here: prefill is
        # compute-bound, and the in-kernel dequant investment went to the
        # ragged + decode kernels (the HBM-bound paths)
        from .pallas_prefill_attention import paged_prefill_attention_pallas

        return paged_prefill_attention_pallas(
            q, kv_k_layer, kv_v_layer, page_table, context_len, total_len
        )
    _, page_size, KH_l, D_l = _layer_dims(kv_k_layer)
    S = page_table.shape[0] * page_size
    ctx_k = gather_dequant(kv_k_layer, page_table, q.dtype).reshape(S, KH_l, D_l)
    ctx_v = gather_dequant(kv_v_layer, page_table, q.dtype).reshape(S, KH_l, D_l)

    T, H, D = q.shape
    KH = ctx_k.shape[1]
    G = H // KH
    qg = q.reshape(T, KH, G, D)
    scores = jnp.einsum(
        "tkgd,skd->tkgs", qg, ctx_k, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.asarray(D, jnp.float32))
    # causal over absolute positions: key j valid iff j <= pos_t
    key_pos = jnp.arange(S)
    mask = key_pos[None, :] <= positions[:, None]  # [T, S]
    scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("tkgs,skd->tkgd", probs.astype(ctx_v.dtype), ctx_v)
    return out.reshape(T, H, D)


def prefill_attention_batched(
    q: jax.Array,  # [B, T, H, D] (chunks, rope applied)
    kv_k_layer: jax.Array,  # [pages, page_size, KH, D]
    kv_v_layer: jax.Array,
    positions: jax.Array,  # [B, T] absolute positions
    page_tables: jax.Array,  # [B, max_pages]
    total_lens: jax.Array,  # [B] valid context per seq (history + real chunk)
    starts: jax.Array,  # [B] absolute position of each chunk's row 0
) -> jax.Array:
    """Batched chunked prefill: each sequence's chunk attends to its own
    history pages + itself (causal). Returns [B, T, H, D].

    Dispatch: on TPU the batched Pallas flash kernel streams only real
    context pages; elsewhere the XLA path gathers each (engine-bounded)
    page table.
    """
    if _pallas_eligible(q.shape[-1]) and not is_quant_kv(kv_k_layer):
        from .pallas_prefill_attention import paged_prefill_attention_pallas_batched

        return paged_prefill_attention_pallas_batched(
            q, kv_k_layer, kv_v_layer, page_tables, starts, total_lens
        )
    B, T, H, D = q.shape
    _, page_size, KH, _ = _layer_dims(kv_k_layer)
    S = page_tables.shape[1] * page_size
    ctx_k = gather_dequant(kv_k_layer, page_tables, q.dtype).reshape(B, S, KH, D)
    ctx_v = gather_dequant(kv_v_layer, page_tables, q.dtype).reshape(B, S, KH, D)
    G = H // KH
    qg = q.reshape(B, T, KH, G, D)
    scores = jnp.einsum(
        "btkgd,bskd->btkgs", qg, ctx_k, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.asarray(D, jnp.float32))
    key_pos = jnp.arange(S)
    mask = (key_pos[None, None, :] <= positions[:, :, None]) & (
        key_pos[None, None, :] < total_lens[:, None, None]
    )  # [B, T, S]
    scores = jnp.where(mask[:, :, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("btkgs,bskd->btkgd", probs.astype(ctx_v.dtype), ctx_v)
    return out.reshape(B, T, H, D)


def _use_pallas_decode() -> bool:
    import os

    mode = os.environ.get("DYNAMO_TPU_PAGED_ATTN", "auto")
    if mode == "pallas":
        return True
    if mode == "xla":
        return False
    try:
        # auto: single-chip TPU only. Under a tp>1 GSPMD mesh the KV cache is
        # sharded over heads and a bare pallas_call has no partitioning rule —
        # the XLA path partitions cleanly there. (shard_map-wrapped kernel is
        # the multi-chip follow-up.)
        return jax.default_backend() == "tpu" and jax.device_count() == 1
    except Exception:
        return False


def _pallas_eligible(lane_dim: int) -> bool:
    """THE Pallas dispatch gate, shared by every attention op in this
    module: the DYNAMO_TPU_PAGED_ATTN env/platform knob (auto = single-chip
    TPU) plus the Mosaic 128-lane DMA alignment on the kernel's lane
    dimension. `lane_dim` is whatever the kernel's page DMA slices —
    head_dim for the per-head-column prefill/ragged kernels, KH*D for the
    whole-page decode kernels; smaller (tiny/test) models fall back to the
    bounded XLA reference paths."""
    return lane_dim % 128 == 0 and _use_pallas_decode()


def paged_attention_decode_mixed(
    q: jax.Array,  # [B, H, D]
    kv_k_layer: jax.Array,  # [pages, page_size, KH, D] — READ-ONLY pool
    kv_v_layer: jax.Array,
    page_tables: jax.Array,  # [B, max_pages]
    pool_lens: jax.Array,  # [B] positions valid IN THE POOL (block-start len)
    loc_k: jax.Array,  # [B, K, KH, D] block-local new keys (this layer)
    loc_v: jax.Array,
    step_idx: jax.Array,  # scalar i32: local entries 0..step_idx are valid
) -> jax.Array:
    """Decode attention over paged pool + block-local buffer.

    The fused-decode-block design (engine/engine.py) keeps the KV pool
    READ-ONLY inside the K-step lax.scan — per-step scatters into a
    multi-GB pool force XLA to materialize carry copies that scale with
    pool size, not with bytes written (the reference never meets this: CUDA
    writes KV in place, lib/llm/src/kernels/block_copy.cu). New tokens
    accumulate in a [K]-entry local buffer carried through the scan and are
    scattered into the pool ONCE per block. Attention therefore reads pool
    pages (frozen at block start) plus the valid local prefix, merged with
    a log-sum-exp combine on the Pallas path or a single concatenated
    softmax on the XLA path.
    """
    B, H, D = q.shape
    _, page_size, KH, D_ = _layer_dims(kv_k_layer)
    G = H // KH
    K = loc_k.shape[1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
    if _pallas_eligible(KH * D_):
        # pool chunks AND the local buffer flash-merge inside ONE kernel
        # launch — an XLA-level lse combine costs ~8 extra op launches per
        # layer-step, which dominates a 28-layer x 16-step fused block.
        # Quantized pools dequantize inside the VMEM window (the scales
        # ride scalar prefetch beside the page tables); the block-local
        # buffer stays full precision — quantization happens on POOL
        # writes only (the once-per-block carry patch).
        from .pallas_paged_attention import paged_attention_decode_pallas_local

        return paged_attention_decode_pallas_local(
            q, kv_k_layer, kv_v_layer, page_tables, pool_lens,
            loc_k, loc_v, step_idx,
        )

    # XLA reference path: gather pool pages, concatenate the local buffer,
    # one softmax over both
    S = page_tables.shape[1] * page_size
    ctx_k = gather_dequant(kv_k_layer, page_tables, q.dtype).reshape(B, S, KH, D)
    ctx_v = gather_dequant(kv_v_layer, page_tables, q.dtype).reshape(B, S, KH, D)
    cat_k = jnp.concatenate([ctx_k, loc_k.astype(ctx_k.dtype)], axis=1)
    cat_v = jnp.concatenate([ctx_v, loc_v.astype(ctx_v.dtype)], axis=1)
    qg = q.reshape(B, KH, G, D)
    scores = jnp.einsum(
        "bkgd,bskd->bkgs", qg, cat_k, preferred_element_type=jnp.float32
    ) * scale
    pool_valid = jnp.arange(S)[None, :] < pool_lens[:, None]  # [B, S]
    loc_valid = jnp.broadcast_to(
        jnp.arange(K)[None, :] <= step_idx, (B, K)
    )
    mask = jnp.concatenate([pool_valid, loc_valid], axis=1)  # [B, S+K]
    scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgs,bskd->bkgd", probs.astype(cat_v.dtype), cat_v)
    return out.reshape(B, H, D)


def paged_attention_decode(
    q: jax.Array,  # [B, H, D]
    kv_k_layer: jax.Array,  # [pages, page_size, KH, D]
    kv_v_layer: jax.Array,
    page_tables: jax.Array,  # [B, max_pages]
    seq_lens: jax.Array,  # [B] (including current token)
) -> jax.Array:
    """One-token decode attention over paged KV. Returns [B, H, D].

    Dispatch: on TPU (or DYNAMO_TPU_PAGED_ATTN=pallas) the Pallas flash
    kernel (ops/pallas_paged_attention.py) streams pages HBM→VMEM without
    materializing the gather; elsewhere the XLA reference path below runs.
    """
    _, page_size, KH_, D_ = _layer_dims(kv_k_layer)
    # the decode kernel's page window has lane dim KH*D (whole-page
    # copies), so that is what must be 128-aligned here (int4 packs along
    # the page_size/sublane axis, so the lane dim is unchanged)
    if _pallas_eligible(KH_ * D_):
        from .pallas_paged_attention import paged_attention_decode_pallas

        return paged_attention_decode_pallas(
            q, kv_k_layer, kv_v_layer, page_tables, seq_lens
        )
    B, H, D = q.shape
    KH = KH_
    S = page_tables.shape[1] * page_size
    ctx_k = gather_dequant(kv_k_layer, page_tables, q.dtype).reshape(B, S, KH, D)
    ctx_v = gather_dequant(kv_v_layer, page_tables, q.dtype).reshape(B, S, KH, D)

    G = H // KH
    qg = q.reshape(B, KH, G, D)
    scores = jnp.einsum(
        "bkgd,bskd->bkgs", qg, ctx_k, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.asarray(D, jnp.float32))
    key_pos = jnp.arange(S)
    mask = key_pos[None, :] < seq_lens[:, None]  # [B, S]
    scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgs,bskd->bkgd", probs.astype(ctx_v.dtype), ctx_v)
    return out.reshape(B, H, D)


def ragged_attention_reference(
    q: jax.Array,  # [N, H, D] flat packed tokens (rope applied)
    kv_k_layer: jax.Array,  # [pages, page_size, KH, D]
    kv_v_layer: jax.Array,
    page_tables: jax.Array,  # [R, max_pages]
    row_starts: jax.Array,  # [R] flat index of row r's token 0 (ascending;
    # padding rows sit at N)
    row_lens: jax.Array,  # [R] real tokens per row (0 for padding rows)
    ctx_lens: jax.Array,  # [R] history length before each row's chunk
) -> jax.Array:
    """XLA reference for the ragged mixed prefill+decode attention: every
    flat token attends to its OWN row's pages (history + chunk, causal).
    Returns [N, H, D]. The CPU/non-aligned fallback of the Pallas ragged
    kernel (ops/pallas_ragged_attention.py) and the fuzz-parity oracle
    (tests/test_ragged_attention.py). Tokens outside every row span
    (alignment/tail padding) return finite garbage — callers only read
    real rows."""
    N, H, D = q.shape
    R, P = page_tables.shape
    _, page_size, KH, _ = _layer_dims(kv_k_layer)
    S = P * page_size
    idx = jnp.arange(N)
    # owning row per token: the last row whose start <= idx (padding
    # tokens fold into the nearest preceding row and mask to nothing)
    row_ids = jnp.clip(
        jnp.sum(idx[:, None] >= row_starts[None, :], axis=1) - 1, 0, R - 1
    )
    local = idx - row_starts[row_ids]
    positions = ctx_lens[row_ids] + local
    totals = ctx_lens[row_ids] + row_lens[row_ids]
    ctx_k = gather_dequant(
        kv_k_layer, page_tables, q.dtype
    ).reshape(R, S, KH, D)[row_ids]  # [N, S, KH, D]
    ctx_v = gather_dequant(
        kv_v_layer, page_tables, q.dtype
    ).reshape(R, S, KH, D)[row_ids]
    G = H // KH
    qg = q.reshape(N, KH, G, D)
    scores = jnp.einsum(
        "nkgd,nskd->nkgs", qg, ctx_k, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.asarray(D, jnp.float32))
    key_pos = jnp.arange(S)
    mask = (
        (key_pos[None, :] <= positions[:, None])
        & (key_pos[None, :] < totals[:, None])
        & (local < row_lens[row_ids])[:, None]
    )  # [N, S]
    scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("nkgs,nskd->nkgd", probs.astype(ctx_v.dtype), ctx_v)
    return out.reshape(N, H, D)


def ragged_attention(
    q: jax.Array,  # [N, H, D]
    kv_k_layer: jax.Array,  # [pages, page_size, KH, D]
    kv_v_layer: jax.Array,
    page_tables: jax.Array,  # [R, max_pages]
    row_starts: jax.Array,  # [R]
    row_lens: jax.Array,  # [R]
    ctx_lens: jax.Array,  # [R]
) -> jax.Array:
    """Ragged mixed prefill+decode attention over paged KV: one call for a
    flat buffer packing prefill chunks (T>1) and decode slots (T=1).
    Returns [N, H, D].

    Dispatch: on TPU the Pallas ragged kernel streams only each row's real
    context pages; elsewhere the XLA reference path gathers the (engine-
    bounded) tables. The Pallas path additionally requires row starts
    aligned to `ragged_tile_q(q.dtype)` — the engine's mixed packer aligns
    exactly when this gate says the kernel will run
    (engine/engine.py:_dispatch_mixed)."""
    if _pallas_eligible(q.shape[-1]):
        from .pallas_ragged_attention import ragged_paged_attention_pallas

        return ragged_paged_attention_pallas(
            q, kv_k_layer, kv_v_layer, page_tables,
            row_starts, row_lens, ctx_lens,
        )
    return ragged_attention_reference(
        q, kv_k_layer, kv_v_layer, page_tables, row_starts, row_lens, ctx_lens
    )
