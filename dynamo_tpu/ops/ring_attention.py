"""Ring attention: sequence-parallel exact attention over a mesh axis.

Long-context capability the reference lacks natively (SURVEY.md §2.5 row
"Sequence/context parallel" — absent upstream, listed as the TPU-native
extension): the sequence is sharded over the ``sp`` mesh axis, each device
holds one contiguous chunk of Q/K/V, and K/V blocks rotate around the ring
via ``ppermute`` while every device accumulates flash-style (running max /
running sum) partial attention for its local queries. Peak memory per device
is O(T/n) and the K/V transfer rides the ICI ring — the canonical TPU
sequence-parallel layout (Ring Attention, Liu et al. 2023; see PAPERS.md).

All shapes are static; the rotation loop is a ``lax.fori_loop`` so the whole
ring compiles to a single XLA while-loop with collective-permute inside.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.mesh import SP_AXIS, TP_AXIS

NEG_INF = -1e30


def _ring_attention_local(
    q: jax.Array,  # [Tl, H, D] local query chunk (rope applied)
    k: jax.Array,  # [Tl, KH, D] local key chunk
    v: jax.Array,  # [Tl, KH, D]
    *,
    axis_name: str,
    num_chunks: int,
    causal: bool,
) -> jax.Array:
    """Per-device body (runs under shard_map). Device r holds sequence chunk
    r; K/V blocks travel r -> r+1 each step so after `num_chunks` steps every
    device has seen every block."""
    rank = jax.lax.axis_index(axis_name)
    Tl, H, D = q.shape
    KH = k.shape[1]
    G = H // KH
    qg = q.reshape(Tl, KH, G, D)
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
    local_pos = jnp.arange(Tl)

    perm = [(i, (i + 1) % num_chunks) for i in range(num_chunks)]

    o0 = jnp.zeros((Tl, KH, G, D), jnp.float32)
    m0 = jnp.full((Tl, KH, G), NEG_INF, jnp.float32)
    l0 = jnp.zeros((Tl, KH, G), jnp.float32)

    def step(i, carry):
        o, m, l, k_blk, v_blk = carry
        # which sequence chunk this K/V block is: blocks rotate forward, so at
        # step i device `rank` holds the block that started at rank - i
        src = (rank - i) % num_chunks
        scores = (
            jnp.einsum("tkgd,skd->tkgs", qg, k_blk, preferred_element_type=jnp.float32)
            * scale
        )
        if causal:
            q_pos = rank * Tl + local_pos
            k_pos = src * Tl + local_pos
            mask = k_pos[None, :] <= q_pos[:, None]  # [Tl, Tl]
            scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
        blk_max = jnp.max(scores, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        # keep fully-masked blocks from poisoning the running max correction
        safe_m = jnp.where(new_m == NEG_INF, 0.0, new_m)
        corr = jnp.exp(jnp.where(m == NEG_INF, NEG_INF, m - safe_m))
        p = jnp.exp(scores - safe_m[..., None])
        p = jnp.where(scores == NEG_INF, 0.0, p)
        o = o * corr[..., None] + jnp.einsum(
            "tkgs,skd->tkgd", p, v_blk.astype(jnp.float32)
        )
        l = l * corr + jnp.sum(p, axis=-1)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (o, new_m, l, k_blk, v_blk)

    o, m, l, _, _ = jax.lax.fori_loop(0, num_chunks, step, (o0, m0, l0, k, v))
    out = o / jnp.where(l == 0.0, 1.0, l)[..., None]
    return out.reshape(Tl, H, D).astype(q.dtype)


def ring_attention(
    q: jax.Array,  # [T, H, D] full sequence (sharded or to-be-sharded over sp)
    k: jax.Array,  # [T, KH, D]
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = SP_AXIS,
    causal: bool = True,
) -> jax.Array:
    """Exact (ring) attention with the sequence dim sharded over
    ``axis_name``. T must divide evenly by the axis size. Returns [T, H, D]
    with the same sharding as q."""
    num_chunks = mesh.shape[axis_name]
    if q.shape[0] % num_chunks:
        raise ValueError(
            f"seq len {q.shape[0]} not divisible by {axis_name}={num_chunks}"
        )
    # co-shard heads over tp when the mesh has a populated tp axis, so the
    # ring composes with tensor parallelism (q arrives tp-sharded from the
    # projections; kv heads must split evenly for GQA grouping)
    head_axis = None
    if TP_AXIS in mesh.shape and mesh.shape[TP_AXIS] > 1:
        tp = mesh.shape[TP_AXIS]
        if q.shape[1] % tp == 0 and k.shape[1] % tp == 0:
            head_axis = TP_AXIS
    spec = P(axis_name, head_axis, None)
    fn = jax.shard_map(
        partial(
            _ring_attention_local,
            axis_name=axis_name,
            num_chunks=num_chunks,
            causal=causal,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
