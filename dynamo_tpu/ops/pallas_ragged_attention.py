"""Pallas TPU kernel: ragged paged-attention over mixed prefill+decode rows.

One kernel for what used to be two dispatches: the engine's mixed step
(engine/engine.py:_dispatch_mixed) packs the StepPlanner's chosen prefill
chunks (T > 1) and the active decode lanes (T = 1) into ONE flat token
buffer, and this kernel runs attention for every row in one grid — the
"Ragged Paged Attention" shape (PAPERS.md) folding the roles of
ops/pallas_prefill_attention.py and ops/pallas_paged_attention.py.

Layouts (match ops/paged_attention.py and engine/kv_cache.py):
    q:           [N, H, D]  flat packed tokens (rope applied, chunk KV
                            already written into pages by the model)
    kv_{k,v}:    [num_pages, page_size, KH, D]   (one layer)
    page_tables: [R, max_pages] int32 (per-row logical -> physical)
    row_starts:  [R] int32 — flat index of row r's first token, ascending,
                 ALIGNED to the q tile (ragged_tile_q); padding rows sit
                 at N (they own no tiles)
    row_lens:    [R] int32 — real tokens in row r (1 for decode rows;
                 0 for padding rows)
    ctx_lens:    [R] int32 — history length before the row's chunk (the
                 absolute position of its token 0)

Design notes:
  * grid = (num_tiles, KH): the flat buffer is cut into TQ-token q tiles
    and a scalar-prefetched `tile_rows` map (built by the wrapper from
    row_starts) names each tile's owning row — tiles never straddle rows
    because the packer aligns row starts to TQ. Per (tile, kv-head) step
    the kernel streams ONLY that row's real context pages (history +
    chunk, causally bounded per tile) through a double-buffered VMEM
    window and flash-accumulates, exactly like the prefill kernel; a
    decode row is simply a one-tile row with ctx = seq_len - 1 and
    row_len = 1.
  * per-head DMA: each step fetches only kv-head k0's D-wide column slice
    of a page, so total HBM bytes equal one pass over the real context.
  * q tiles are pre-arranged [num_tiles, KH, TQ, G*D] by the wrapper; the
    G query heads of the group are static column slices (no Mosaic
    reshapes of minor dims).
  * masking: a q row is real iff its in-row offset < row_len; keys are
    valid iff key_pos <= q_pos and key_pos < ctx + row_len. Rows that are
    pure padding produce finite garbage (discarded by the caller).
  * REQUIRES head_dim % 128 == 0 (the per-head DMA slices the flattened
    KH*D lane dim in head_dim-wide columns) — the dispatcher
    (ops/paged_attention.py:_pallas_eligible) falls back to
    ragged_attention_reference otherwise, and on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG = -1e30


def ragged_tile_q(dtype) -> int:
    """Q-tile height (and the row-start alignment the packer must honor):
    the Mosaic second-minor register tile — 16 for bf16, 8 for f32."""
    return 16 if jnp.dtype(dtype).itemsize < 4 else 8


def _ragged_kernel(
    # positional refs — scalar prefetch first: tile_rows [num_tiles],
    # row_starts [R], row_lens [R], ctx_lens [R], page_tables
    # [R, max_pages] (all int32 SMEM) and, under kv_bits > 0, the
    # per-page-per-head K and V scales [num_pages, KH] f32 riding the
    # SAME scalar-prefetch channel beside the page tables; then
    # q [1, 1, TQ, G*D] VMEM, kv_k/kv_v [num_pages, rows, KH*D] ANY/HBM
    # (rows = page_size, or page_size//2 int4-packed along the sublane
    # axis), the output block, and the double-buffered VMEM window +
    # DMA semaphores.
    *refs,
    page_size: int,
    chunk_pages: int,
    max_pages: int,
    group: int,
    head_dim: int,
    tile_q: int,
    kv_bits: int = 0,
):
    if kv_bits:
        (tr_ref, rs_ref, rl_ref, ctx_ref, pt_ref, ks_ref, vs_ref,
         q_ref, kv_k_hbm, kv_v_hbm, out_ref, k_buf, v_buf, k_sem,
         v_sem) = refs
    else:
        (tr_ref, rs_ref, rl_ref, ctx_ref, pt_ref,
         q_ref, kv_k_hbm, kv_v_hbm, out_ref, k_buf, v_buf, k_sem,
         v_sem) = refs
        ks_ref = vs_ref = None
    t = pl.program_id(0)
    k0 = pl.program_id(1)
    g, d, tq = group, head_dim, tile_q
    chunk = chunk_pages * page_size
    num_phys = kv_k_hbm.shape[0]
    # rows each page occupies in HBM/VMEM (int4 packs 2 tokens per byte
    # along this axis; positions unpack back in order, so the causal
    # key_pos math below is untouched)
    page_rows = kv_k_hbm.shape[1]

    r = tr_ref[t]
    ctx = ctx_ref[r]
    row_len = rl_ref[r]
    local0 = t * tq - rs_ref[r]  # this tile's first in-row offset
    total_len = ctx + row_len
    # causal limit for this tile: its last row is position ctx+local0+tq-1
    limit = jnp.minimum(total_len, ctx + local0 + tq)
    n_chunks = pl.cdiv(jnp.maximum(limit, 1), chunk)

    def start_chunk(ci, slot):
        for p in range(chunk_pages):
            lp = jnp.minimum(ci * chunk_pages + p, max_pages - 1)
            phys = jnp.minimum(pt_ref[r, lp], num_phys - 1)
            pltpu.make_async_copy(
                kv_k_hbm.at[phys, :, pl.ds(k0 * d, d)],
                k_buf.at[slot, pl.ds(p * page_rows, page_rows)],
                k_sem.at[slot, p],
            ).start()
            pltpu.make_async_copy(
                kv_v_hbm.at[phys, :, pl.ds(k0 * d, d)],
                v_buf.at[slot, pl.ds(p * page_rows, page_rows)],
                v_sem.at[slot, p],
            ).start()

    def wait_chunk(ci, slot):
        for p in range(chunk_pages):
            lp = jnp.minimum(ci * chunk_pages + p, max_pages - 1)
            phys = jnp.minimum(pt_ref[r, lp], num_phys - 1)
            pltpu.make_async_copy(
                kv_k_hbm.at[phys, :, pl.ds(k0 * d, d)],
                k_buf.at[slot, pl.ds(p * page_rows, page_rows)],
                k_sem.at[slot, p],
            ).wait()
            pltpu.make_async_copy(
                kv_v_hbm.at[phys, :, pl.ds(k0 * d, d)],
                v_buf.at[slot, pl.ds(p * page_rows, page_rows)],
                v_sem.at[slot, p],
            ).wait()

    def dequant_window(ci, slot, compute_dtype):
        """Quantized window -> [chunk, D] full-precision K and V: per page,
        unpack (int4) and multiply by that page's per-head scale read from
        the scalar-prefetched scales — the in-kernel dequant the DMA
        overlap pays for (RTP-LLM shape, PAPERS.md)."""
        from ..models.quant import unpack_int4

        k_segs, v_segs = [], []
        for p in range(chunk_pages):
            lp = jnp.minimum(ci * chunk_pages + p, max_pages - 1)
            phys = jnp.minimum(pt_ref[r, lp], num_phys - 1)
            kseg = k_buf[slot, pl.ds(p * page_rows, page_rows)]  # int8 [rows, D]
            vseg = v_buf[slot, pl.ds(p * page_rows, page_rows)]
            if kv_bits == 4:
                kseg = unpack_int4(kseg, axis=0)  # [page_size, D]
                vseg = unpack_int4(vseg, axis=0)
            ks = ks_ref[phys, k0]
            vs = vs_ref[phys, k0]
            k_segs.append((kseg.astype(jnp.float32) * ks).astype(compute_dtype))
            v_segs.append((vseg.astype(jnp.float32) * vs).astype(compute_dtype))
        return (
            jnp.concatenate(k_segs, axis=0),
            jnp.concatenate(v_segs, axis=0),
        )

    start_chunk(0, 0)

    q_tile = q_ref[0, 0]  # [TQ, G*D], pre-scaled by 1/sqrt(D)
    local = local0 + jax.lax.broadcasted_iota(jnp.int32, (tq, 1), 0)
    q_pos = ctx + local
    q_real = local < row_len  # [TQ, 1]

    m0 = tuple(jnp.full((tq, 1), NEG, jnp.float32) for _ in range(g))
    l0 = tuple(jnp.zeros((tq, 1), jnp.float32) for _ in range(g))
    acc0 = tuple(jnp.zeros((tq, d), jnp.float32) for _ in range(g))

    def body(ci, carry):
        m, l, acc = carry
        slot = jax.lax.rem(ci, 2)

        @pl.when(ci + 1 < n_chunks)
        def _():
            start_chunk(ci + 1, jax.lax.rem(ci + 1, 2))

        wait_chunk(ci, slot)
        if kv_bits:
            k, v = dequant_window(ci, slot, q_ref.dtype)  # [C, D]
        else:
            k = k_buf[slot]  # [C, D]
            v = v_buf[slot]

        key_pos = ci * chunk + jax.lax.broadcasted_iota(jnp.int32, (1, chunk), 1)
        valid = q_real & (key_pos <= q_pos) & (key_pos < total_len)  # [TQ, C]

        m_n, l_n, acc_n = [], [], []
        for gi in range(g):
            qg = q_tile[:, gi * d : (gi + 1) * d]  # [TQ, D] static slice
            s = jax.lax.dot_general(
                qg.astype(k.dtype),
                k,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [TQ, C]
            s = jnp.where(valid, s, NEG)
            mg = jnp.maximum(m[gi], jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m[gi] - mg)
            p = jnp.exp(s - mg)
            lg = l[gi] * alpha + jnp.sum(p, axis=-1, keepdims=True)
            pv = jax.lax.dot_general(
                p.astype(v.dtype),
                v,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [TQ, D]
            m_n.append(mg)
            l_n.append(lg)
            acc_n.append(acc[gi] * alpha + pv)
        return tuple(m_n), tuple(l_n), tuple(acc_n)

    m, l, acc = jax.lax.fori_loop(0, n_chunks, body, (m0, l0, acc0))
    for gi in range(g):
        out = acc[gi] / jnp.maximum(l[gi], 1e-30)
        out_ref[0, 0, :, gi * d : (gi + 1) * d] = out.astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def ragged_paged_attention_pallas(
    q: jax.Array,  # [N, H, D] flat packed tokens (rope applied)
    kv_k_layer: jax.Array,  # [num_pages, page_size, KH, D]
    kv_v_layer: jax.Array,
    page_tables: jax.Array,  # [R, max_pages] int32
    row_starts: jax.Array,  # [R] int32, ascending, TQ-aligned
    row_lens: jax.Array,  # [R] int32
    ctx_lens: jax.Array,  # [R] int32
    *,
    interpret: bool = False,
) -> jax.Array:
    """Ragged flash attention over paged KV; returns [N, H, D] (q.dtype).
    Rows outside every [row_start, row_start+row_len) span return finite
    garbage — the caller only reads real rows. `kv_k_layer`/`kv_v_layer`
    may be per-layer QuantKV stores (ops/kv_quant.py): the int8/int4 pages
    DMA at their packed width and dequantize inside the VMEM window, with
    the per-page-per-head scales scalar-prefetched beside the page
    tables."""
    from .kv_quant import kernel_operands

    N, H, D = q.shape
    kv_k_raw, kv_v_raw, rows, page_size, kv_bits, scale_prefetch = (
        kernel_operands(kv_k_layer, kv_v_layer)
    )
    num_pages, _, KH, _ = kv_k_raw.shape
    G = H // KH
    max_pages = page_tables.shape[1]
    tile_q = ragged_tile_q(q.dtype)
    assert N % tile_q == 0, (
        f"flat buffer {N} must be a multiple of the q tile {tile_q} "
        "(the mixed packer pads to ragged_tile_q)"
    )
    num_tiles = N // tile_q
    # KV streamed in ~512-position chunks: full 128-lane score tiles, and
    # 2 slots x (K+V) x [C, D] comfortably inside VMEM
    chunk_pages = max(1, 512 // page_size)
    chunk_pages = min(chunk_pages, max_pages)

    # each tile's owning row: rows are TQ-aligned and packed ascending, so
    # the owner of tile t is the last row whose start <= t*TQ (tail-padding
    # tiles fold into the last real row and mask to nothing)
    t0s = jnp.arange(num_tiles, dtype=jnp.int32) * tile_q
    tile_rows = jnp.maximum(
        jnp.sum(
            t0s[:, None] >= row_starts.astype(jnp.int32)[None, :], axis=1
        ).astype(jnp.int32)
        - 1,
        0,
    )

    scale = 1.0 / (D**0.5)
    # [N, H, D] -> [num_tiles, KH, TQ, G*D]: group g of kv-head k0 in
    # column block g (same pre-arrangement as the prefill kernel)
    q_g = (
        (q * scale)
        .reshape(num_tiles, tile_q, KH, G, D)
        .transpose(0, 2, 1, 3, 4)
        .reshape(num_tiles, KH, tile_q, G * D)
    )
    # flatten pages' minor dims in XLA (contiguous bitcast) — Mosaic cannot
    # merge minor dims in-register. Quantized stores DMA their PACKED q
    # bytes (int4: half the sublane rows); the f32 scales join the scalar
    # prefetch operands right after the page tables (kernel_operands is
    # the one spelling of this contract across all three kernels).
    kv_k_flat = kv_k_raw.reshape(num_pages, rows, KH * D)
    kv_v_flat = kv_v_raw.reshape(num_pages, rows, KH * D)
    prefetch = [
        tile_rows,
        row_starts.astype(jnp.int32),
        row_lens.astype(jnp.int32),
        ctx_lens.astype(jnp.int32),
        page_tables.astype(jnp.int32),
        *scale_prefetch,
    ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(prefetch),
        grid=(num_tiles, KH),
        in_specs=[
            pl.BlockSpec((1, 1, tile_q, G * D), lambda t, k0, *_: (t, k0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, tile_q, G * D), lambda t, k0, *_: (t, k0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((2, chunk_pages * rows, D), kv_k_flat.dtype),
            pltpu.VMEM((2, chunk_pages * rows, D), kv_v_flat.dtype),
            pltpu.SemaphoreType.DMA((2, chunk_pages)),
            pltpu.SemaphoreType.DMA((2, chunk_pages)),
        ],
    )
    kernel = functools.partial(
        _ragged_kernel,
        page_size=page_size,
        chunk_pages=chunk_pages,
        max_pages=max_pages,
        group=G,
        head_dim=D,
        tile_q=tile_q,
        kv_bits=kv_bits,
    )
    cost = pl.CostEstimate(
        flops=4 * N * H * D * max_pages * page_size // 2,
        bytes_accessed=2 * num_tiles * max_pages * page_size * KH * D * 2,
        transcendentals=N * H * max_pages * page_size // 2,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_tiles, KH, tile_q, G * D), q.dtype),
        cost_estimate=cost,
        interpret=interpret,
    )(
        *prefetch,
        q_g,
        kv_k_flat,
        kv_v_flat,
    )
    # [num_tiles, KH, TQ, G*D] -> [N, H, D]
    return (
        out.reshape(num_tiles, KH, tile_q, G, D)
        .transpose(0, 2, 1, 3, 4)
        .reshape(N, H, D)
    )
