"""Pallas TPU kernel: batched chunked-prefill flash attention over paged KV.

The prefill-side counterpart of ops/pallas_paged_attention.py (decode).
Role of the reference engines' prefill attention kernels (vLLM flash-attn
over paged KV), done the TPU way: each sequence's chunk KV has already been
scattered into HBM pages by the model; this kernel streams ONLY the pages
that hold real context (history + the chunk itself) through a
double-buffered VMEM window and flash-accumulates — instead of the XLA
fallback's materialized full max-context gather, which reads
`max_pages * page_size` positions per layer regardless of actual context
(the round-1 TTFT killer: 493 ms at isl 128 came almost entirely from that
gather traffic).

Batching: the engine packs prefill chunks from SEVERAL sequences into one
dispatch (grid dim 0), so concurrent short prompts prefill together instead
of serializing one chunk per engine-loop iteration.

Layouts (match ops/paged_attention.py and engine/kv_cache.py):
    q:           [B, T, H, D]     (chunks, rope applied; T = bucket)
    kv_{k,v}:    [num_pages, page_size, KH, D]   (one layer)
    page_tables: [B, max_pages] int32 (per-seq logical -> physical)
    starts:      [B] int32 — absolute position of each seq's q row 0
    total_lens:  [B] int32 — valid context = start + real chunk len

Design notes:
  * grid = (B, KH, T // TQ): one kv-head per middle step so each DMA
    fetches only that head's D-wide column slice of a page — total HBM
    bytes equal one pass over the real context, never duplicated across
    heads.
  * q is pre-arranged [B, KH, T, G*D] by the wrapper (XLA transpose);
    inside the kernel the G query heads of the group are static column
    slices, so every matmul is a clean 2D [TQ, D] x [D, C] MXU op (no
    Mosaic reshapes of minor dims — unsupported shape casts).
  * causal masking by absolute position: tile t's rows are positions
    start + t*TQ + i, keys are ci*C + j; a tile only loops over chunks up
    to its own causal limit, so early tiles do less work.
  * tail chunks may DMA a stale/garbage page (clamped ids); additive NEG
    masking keeps them out of the softmax.
  * REQUIRES head_dim % 128 == 0: the per-head DMA slices the flattened
    KH*D minor (lane) dim in head_dim-wide columns, and Mosaic rejects
    lane slices not aligned to the 128-lane tiling. The dispatcher
    (ops/paged_attention.py) falls back to the bounded XLA path for
    smaller head dims (tiny/test models); flagship llama-family configs
    all use head_dim 128.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG = -1e30


def _prefill_kernel(
    # scalar prefetch
    pt_ref,  # [B, max_pages] int32 (SMEM)
    start_ref,  # [B] int32 (SMEM)
    total_ref,  # [B] int32 (SMEM)
    # inputs
    q_ref,  # [1, 1, TQ, G*D] VMEM block (one seq, one kv-head's query group)
    kv_k_hbm,  # [num_pages, page_size, KH*D] (ANY/HBM; flattened by wrapper)
    kv_v_hbm,
    # outputs
    out_ref,  # [1, 1, TQ, G*D] VMEM block
    # scratch
    k_buf,  # [2, C, D] VMEM — this head's column slice of the chunk pages
    v_buf,
    k_sem,  # DMA sems [2, chunk_pages]
    v_sem,
    *,
    page_size: int,
    chunk_pages: int,
    max_pages: int,
    group: int,
    head_dim: int,
    tile_q: int,
):
    b = pl.program_id(0)
    k0 = pl.program_id(1)
    t = pl.program_id(2)
    g, d, tq = group, head_dim, tile_q
    chunk = chunk_pages * page_size
    num_phys = kv_k_hbm.shape[0]

    start = start_ref[b]
    total_len = total_ref[b]
    # causal limit for this q tile: its last row is position start+(t+1)*TQ-1
    limit = jnp.minimum(total_len, start + (t + 1) * tq)
    n_chunks = pl.cdiv(jnp.maximum(limit, 1), chunk)

    def start_chunk(ci, slot):
        for p in range(chunk_pages):
            lp = jnp.minimum(ci * chunk_pages + p, max_pages - 1)
            phys = jnp.minimum(pt_ref[b, lp], num_phys - 1)
            pltpu.make_async_copy(
                kv_k_hbm.at[phys, :, pl.ds(k0 * d, d)],
                k_buf.at[slot, pl.ds(p * page_size, page_size)],
                k_sem.at[slot, p],
            ).start()
            pltpu.make_async_copy(
                kv_v_hbm.at[phys, :, pl.ds(k0 * d, d)],
                v_buf.at[slot, pl.ds(p * page_size, page_size)],
                v_sem.at[slot, p],
            ).start()

    def wait_chunk(ci, slot):
        for p in range(chunk_pages):
            lp = jnp.minimum(ci * chunk_pages + p, max_pages - 1)
            phys = jnp.minimum(pt_ref[b, lp], num_phys - 1)
            pltpu.make_async_copy(
                kv_k_hbm.at[phys, :, pl.ds(k0 * d, d)],
                k_buf.at[slot, pl.ds(p * page_size, page_size)],
                k_sem.at[slot, p],
            ).wait()
            pltpu.make_async_copy(
                kv_v_hbm.at[phys, :, pl.ds(k0 * d, d)],
                v_buf.at[slot, pl.ds(p * page_size, page_size)],
                v_sem.at[slot, p],
            ).wait()

    start_chunk(0, 0)

    q_tile = q_ref[0, 0]  # [TQ, G*D], pre-scaled by 1/sqrt(D)
    q_pos = start + t * tq + jax.lax.broadcasted_iota(jnp.int32, (tq, 1), 0)

    m0 = tuple(jnp.full((tq, 1), NEG, jnp.float32) for _ in range(g))
    l0 = tuple(jnp.zeros((tq, 1), jnp.float32) for _ in range(g))
    acc0 = tuple(jnp.zeros((tq, d), jnp.float32) for _ in range(g))

    def body(ci, carry):
        m, l, acc = carry
        slot = jax.lax.rem(ci, 2)

        @pl.when(ci + 1 < n_chunks)
        def _():
            start_chunk(ci + 1, jax.lax.rem(ci + 1, 2))

        wait_chunk(ci, slot)
        k = k_buf[slot]  # [C, D]
        v = v_buf[slot]

        key_pos = ci * chunk + jax.lax.broadcasted_iota(jnp.int32, (1, chunk), 1)
        valid = (key_pos <= q_pos) & (key_pos < total_len)  # [TQ, C]

        m_n, l_n, acc_n = [], [], []
        for gi in range(g):
            qg = q_tile[:, gi * d : (gi + 1) * d]  # [TQ, D] static slice
            s = jax.lax.dot_general(
                qg.astype(k.dtype),
                k,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [TQ, C]
            s = jnp.where(valid, s, NEG)
            mg = jnp.maximum(m[gi], jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m[gi] - mg)
            p = jnp.exp(s - mg)
            lg = l[gi] * alpha + jnp.sum(p, axis=-1, keepdims=True)
            pv = jax.lax.dot_general(
                p.astype(v.dtype),
                v,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [TQ, D]
            m_n.append(mg)
            l_n.append(lg)
            acc_n.append(acc[gi] * alpha + pv)
        return tuple(m_n), tuple(l_n), tuple(acc_n)

    m, l, acc = jax.lax.fori_loop(0, n_chunks, body, (m0, l0, acc0))
    for gi in range(g):
        out = acc[gi] / jnp.maximum(l[gi], 1e-30)
        out_ref[0, 0, :, gi * d : (gi + 1) * d] = out.astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_prefill_attention_pallas_batched(
    q: jax.Array,  # [B, T, H, D] (rope applied)
    kv_k_layer: jax.Array,  # [num_pages, page_size, KH, D]
    kv_v_layer: jax.Array,
    page_tables: jax.Array,  # [B, max_pages] int32
    starts: jax.Array,  # [B] int32
    total_lens: jax.Array,  # [B] int32
    *,
    interpret: bool = False,
) -> jax.Array:
    """Batched flash chunked-prefill over paged KV; returns [B, T, H, D]."""
    B, T, H, D = q.shape
    num_pages, page_size, KH, _ = kv_k_layer.shape
    G = H // KH
    max_pages = page_tables.shape[1]
    tile_q = min(256, T)
    assert T % tile_q == 0, f"chunk bucket {T} must be a multiple of {tile_q}"
    num_tiles = T // tile_q
    # KV streamed in ~512-position chunks: full 128-lane score tiles, and
    # 2 slots x (K+V) x [C, D] comfortably inside VMEM
    chunk_pages = max(1, 512 // page_size)
    chunk_pages = min(chunk_pages, max_pages)

    scale = 1.0 / (D**0.5)
    # [B, T, H, D] -> [B, KH, T, G*D]: group g of kv-head k0 in column block g
    q_g = (
        (q * scale)
        .reshape(B, T, KH, G, D)
        .transpose(0, 2, 1, 3, 4)
        .reshape(B, KH, T, G * D)
    )
    # flatten pages' minor dims in XLA (contiguous bitcast) — Mosaic cannot
    # merge minor dims in-register
    kv_k_flat = kv_k_layer.reshape(num_pages, page_size, KH * D)
    kv_v_flat = kv_v_layer.reshape(num_pages, page_size, KH * D)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, KH, num_tiles),
        in_specs=[
            pl.BlockSpec((1, 1, tile_q, G * D), lambda b, k0, t, *_: (b, k0, t, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, tile_q, G * D), lambda b, k0, t, *_: (b, k0, t, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((2, chunk_pages * page_size, D), kv_k_layer.dtype),
            pltpu.VMEM((2, chunk_pages * page_size, D), kv_v_layer.dtype),
            pltpu.SemaphoreType.DMA((2, chunk_pages)),
            pltpu.SemaphoreType.DMA((2, chunk_pages)),
        ],
    )
    kernel = functools.partial(
        _prefill_kernel,
        page_size=page_size,
        chunk_pages=chunk_pages,
        max_pages=max_pages,
        group=G,
        head_dim=D,
        tile_q=tile_q,
    )
    cost = pl.CostEstimate(
        flops=4 * B * T * H * D * max_pages * page_size // 2,
        bytes_accessed=2 * B * max_pages * page_size * KH * D * 2,
        transcendentals=B * T * H * max_pages * page_size // 2,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KH, T, G * D), q.dtype),
        cost_estimate=cost,
        interpret=interpret,
    )(
        page_tables.astype(jnp.int32),
        starts.astype(jnp.int32),
        total_lens.astype(jnp.int32),
        q_g,
        kv_k_flat,
        kv_v_flat,
    )
    # [B, KH, T, G*D] -> [B, T, H, D]
    return out.reshape(B, KH, T, G, D).transpose(0, 2, 1, 3, 4).reshape(B, T, H, D)


def paged_prefill_attention_pallas(
    q: jax.Array,  # [T, H, D]
    kv_k_layer: jax.Array,
    kv_v_layer: jax.Array,
    page_table: jax.Array,  # [max_pages]
    start: jax.Array,  # scalar
    total_len: jax.Array,  # scalar
    *,
    interpret: bool = False,
) -> jax.Array:
    """Single-sequence wrapper over the batched kernel; returns [T, H, D]."""
    out = paged_prefill_attention_pallas_batched(
        q[None],
        kv_k_layer,
        kv_v_layer,
        page_table[None],
        jnp.asarray(start, jnp.int32)[None],
        jnp.asarray(total_len, jnp.int32)[None],
        interpret=interpret,
    )
    return out[0]
