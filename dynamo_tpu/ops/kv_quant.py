"""Quantized paged KV cache: int8/int4 page storage with per-page-per-head
scales (DYN_KV_QUANT / EngineConfig.kv_quant; docs/kvbm.md "Quantized KV
format", docs/ragged_attention.md "Quantized pages").

The KV cache is the HBM bound on BOTH raw speed and resident-session count
(ROADMAP item 5): halving (int8) or quartering (int4) the bytes per page
roughly doubles/quadruples the sessions a chip holds AND shrinks every
byte the KVBM tiers, the peer fabric, and the disagg handoff move. The
production shape is RTP-LLM's (PAPERS.md): pages quantized ON WRITE,
dequantized INSIDE the attention kernel's VMEM window, scales riding the
scalar-prefetch operands beside the page tables.

Representation — `QuantKV`, a registered pytree replacing the raw
[L, pages, page_size, KH, D] kv_k/kv_v arrays:

    q: int8  [L, pages, ps_eff, KH, D]   quantized values; int4 packs two
                                         tokens per byte ALONG THE
                                         page_size axis (ps_eff = ps//2),
                                         pairing token o with o + ps/2 so
                                         unpack is concat(lo, hi) — no
                                         minor-dim interleave, which the
                                         Pallas VMEM window cannot do
    s: f32   [L, pages, KH]              per-page-per-head scale

(bits, page_size) are STATIC pytree aux data: jit specializes per format,
donation/tree_map/jax.device transfers all work leaf-wise, and
extract/inject gathers ride the same `[:, page_ids]` slice on both leaves.

Scale discipline (quantize-on-write, `kv_write`):
  * a page's scale is the running max over the amax of every write into
    it; when a write GROWS the scale, the page's existing ints are
    requantized (q' = round(q * old/new)) so dequantization stays exact
    under one scale per page.
  * a write at in-page offset 0 STARTS the page (offset 0 is the earliest
    slot a position can occupy, so any prior content belongs to a dead
    sequence): the stale scale is dropped first, which also zero-scrubs
    the stale ints — page reuse cannot inflate quantization error.
  * fp mode ("none") is the exact original scatter — jaxprs are identical,
    so quant off == seed behavior byte-for-byte.

Host/wire boundary (`host_pack_pages`/`host_unpack_pages`): a page
serializes as q-bytes ‖ scale-bytes in one uint8 row `[L, n, PAGE_BYTES]`
— KVBM G2/G3 tiers store these rows natively (block_shape (L, PB) uint8),
and the kv_transfer peer-pull / disagg payloads ship them unchanged, so
tier capacity at fixed host/disk bytes and the fabric's wire bytes shrink
by the same 2x/4x. The format name travels in block descriptors and the
kvbm pull handshake; a mixed-precision fleet fails TYPED
(llm.kv_transfer.KvFormatError), never silently misreads bytes.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.quant import QMAX, pack_int4, unpack_int4

KV_QUANT_MODES = ("none", "int8", "int4")


def resolve_kv_quant(mode: Optional[str]) -> str:
    """EngineConfig.kv_quant (explicit) else DYN_KV_QUANT else "none"."""
    if mode is None:
        mode = os.environ.get("DYN_KV_QUANT") or "none"
    mode = str(mode).strip().lower() or "none"
    if mode not in KV_QUANT_MODES:
        raise ValueError(
            f"unknown KV quant mode {mode!r} (DYN_KV_QUANT / kv_quant); "
            f"expected one of {KV_QUANT_MODES}"
        )
    return mode


def kv_quant_bits(mode: str) -> int:
    """Bits per stored KV value; 0 = full precision."""
    return {"none": 0, "int8": 8, "int4": 4}[mode]


@jax.tree_util.register_pytree_node_class
class QuantKV:
    """Quantized KV store (see module docstring). Leaves: (q, s); static
    aux: (bits, page_size)."""

    def __init__(self, q, s, bits: int, page_size: int):
        self.q = q
        self.s = s
        self.bits = int(bits)
        self.page_size = int(page_size)

    def tree_flatten(self):
        return (self.q, self.s), (self.bits, self.page_size)

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, s = children
        return cls(q, s, aux[0], aux[1])

    @property
    def mode(self) -> str:
        return {8: "int8", 4: "int4"}[self.bits]

    @property
    def nbytes(self) -> int:
        return int(self.q.nbytes) + int(self.s.nbytes)

    def __repr__(self):  # debugging aid, never in a hot path
        return (
            f"QuantKV(bits={self.bits}, q={getattr(self.q, 'shape', None)}, "
            f"s={getattr(self.s, 'shape', None)})"
        )


def is_quant_kv(x: Any) -> bool:
    return isinstance(x, QuantKV)


def _ps_eff(page_size: int, bits: int) -> int:
    if bits == 4:
        if page_size % 2:
            raise ValueError("int4 KV quant requires an even page_size")
        return page_size // 2
    return page_size


def kv_page_bytes(page_size: int, num_kv_heads: int, head_dim: int,
                  dtype, mode: str) -> int:
    """Bytes ONE K or V page occupies in HBM (and, packed, on the wire):
    quantized = q bytes + 4-byte f32 scale per kv head. Pool sizing
    (engine._auto_num_pages) and the bench's sessions-per-HBM math both
    read this."""
    bits = kv_quant_bits(mode)
    if bits == 0:
        itemsize = jnp.zeros((), dtype).dtype.itemsize
        return page_size * num_kv_heads * head_dim * itemsize
    return _ps_eff(page_size, bits) * num_kv_heads * head_dim + 4 * num_kv_heads


def alloc_kv_store(num_layers: int, num_pages: int, page_size: int,
                   num_kv_heads: int, head_dim: int, dtype, mode: str,
                   sharding=None):
    """One KV store (K or V): a plain fp array for mode "none", else a
    QuantKV with zeroed ints and zeroed scales (scale 0 marks a fresh
    page: kv_write's page-start reset plus requantize-by-ratio scrub it
    before first use)."""
    bits = kv_quant_bits(mode)
    if bits == 0:
        arr = jnp.zeros(
            (num_layers, num_pages, page_size, num_kv_heads, head_dim), dtype
        )
        if sharding is not None:
            arr = jax.device_put(arr, sharding)
        return arr
    if sharding is not None:
        raise ValueError(
            "kv_quant with a sharded KV pool is unsupported (per-head scale "
            "sharding is the multi-chip follow-up); run tp_size=1 or "
            "DYN_KV_QUANT=none"
        )
    q = jnp.zeros(
        (num_layers, num_pages, _ps_eff(page_size, bits), num_kv_heads,
         head_dim),
        jnp.int8,
    )
    s = jnp.zeros((num_layers, num_pages, num_kv_heads), jnp.float32)
    return QuantKV(q, s, bits, page_size)


def kv_page_size(store) -> int:
    """Tokens per page of a KV store (QuantKV carries it statically; a
    plain array reads its page axis)."""
    if isinstance(store, QuantKV):
        return store.page_size
    return store.shape[2]


def kv_layer(store, li: int):
    """Per-layer view for the attention ops: kv[li] for fp arrays, a
    per-layer QuantKV (q [pages, ps_eff, KH, D], s [pages, KH]) else."""
    if not isinstance(store, QuantKV):
        return store[li]
    return QuantKV(store.q[li], store.s[li], store.bits, store.page_size)


def kernel_operands(kv_k_layer, kv_v_layer):
    """Destructure per-layer KV operands for the Pallas wrappers — the ONE
    spelling of the packed-layout contract (pallas_ragged_attention +
    both decode kernels): returns (k_raw, v_raw, rows, page_size,
    kv_bits, scale_prefetch) where k_raw/v_raw are the arrays to flatten
    and DMA ([pages, rows, KH, D]; rows = page_size, or page_size//2
    int4-packed along the sublane axis), kv_bits selects the in-kernel
    dequant path (0 = fp), and scale_prefetch is the list of f32 scale
    operands to append to the scalar-prefetch refs (empty for fp)."""
    if isinstance(kv_k_layer, QuantKV):
        return (
            kv_k_layer.q,
            kv_v_layer.q,
            kv_k_layer.q.shape[1],
            kv_k_layer.page_size,
            kv_k_layer.bits,
            [
                kv_k_layer.s.astype(jnp.float32),
                kv_v_layer.s.astype(jnp.float32),
            ],
        )
    return (
        kv_k_layer, kv_v_layer, kv_k_layer.shape[1], kv_k_layer.shape[1],
        0, [],
    )


# ---------------------------------------------------------------------- #
# quantize-on-write
# ---------------------------------------------------------------------- #


def _write_one_layer(q, s, phys, offs, vals, bits: int, page_size: int):
    """Core scatter-write of `vals` [T, KH, D] (f-dtype) at (phys[t],
    offs[t]) into one layer's (q [P, ps_eff, KH, D], s [P, KH]).

    Duplicate pages within one write are handled exactly: scale combines
    via scatter-max, the requantize pass writes identical whole-page
    content per duplicate, and the new values land via a scatter-ADD of
    per-copy deltas (int8 wraparound is linear, so concurrent nibble/row
    deltas into one byte compose exactly)."""
    qmax = QMAX[bits]
    T = phys.shape[0]
    vals32 = vals.astype(jnp.float32)
    amax = jnp.max(jnp.abs(vals32), axis=-1)  # [T, KH]
    # page-start reset: offset 0 is a page's earliest slot, so a write
    # there means any existing content/scale belongs to a dead sequence
    starts = jnp.where(
        (offs == 0)[:, None], jnp.float32(0), jnp.float32(jnp.inf)
    )  # [T, KH] broadcast over heads
    s = s.at[phys].min(jnp.broadcast_to(starts, amax.shape))
    old_s = s[phys]  # [T, KH] (post-reset, pre-grow)
    s = s.at[phys].max(amax / qmax)
    eff_s = s[phys]  # [T, KH] final per-page scales (duplicates agree)
    # requantize the touched pages for grown scales (ratio 0 scrubs
    # freshly-started pages' stale ints to 0)
    pages_q = q[phys]  # [T, ps_eff, KH, D] (pre-write content, dup-consistent)
    nib = unpack_int4(pages_q, axis=1) if bits == 4 else pages_q  # [T, ps, KH, D]
    ratio = jnp.where(eff_s > 0, old_s / jnp.maximum(eff_s, 1e-30), 0.0)
    nib = jnp.clip(
        jnp.round(nib.astype(jnp.float32) * ratio[:, None, :, None]),
        -qmax, qmax,
    ).astype(jnp.int8)
    repacked = pack_int4(nib, axis=1) if bits == 4 else nib
    q = q.at[phys].set(repacked)  # duplicates write identical content
    # quantize the new values at the final page scale and write each
    # copy's own row; the delta-add merges duplicate pages exactly
    qv = jnp.clip(
        jnp.round(vals32 / jnp.maximum(eff_s, 1e-30)[:, :, None]),
        -qmax, qmax,
    ).astype(jnp.int8)
    written = nib.at[jnp.arange(T), offs].set(qv)
    wpacked = pack_int4(written, axis=1) if bits == 4 else written
    # int8 subtraction/addition wrap (two's complement); the FINAL value
    # per byte is the in-range written one, so wraparound cancels exactly
    q = q.at[phys].add(wpacked - repacked)
    return q, s


def kv_write(store, li, phys, offs, vals):
    """Write `vals` [..., KH, D] at (li, phys[...], offs[...]) — the ONE
    KV page-write spelling for every model forward (prefill chunk store,
    ragged mixed store, decode). fp mode is the exact original scatter."""
    if not isinstance(store, QuantKV):
        return store.at[li, phys, offs].set(vals)
    lead = phys.shape
    T = int(np.prod(lead)) if lead else 1
    phys_f = phys.reshape(T)
    offs_f = offs.reshape(T)
    vals_f = vals.reshape(T, *vals.shape[len(lead):])
    q, s = _write_one_layer(
        store.q[li], store.s[li], phys_f, offs_f, vals_f,
        store.bits, store.page_size,
    )
    return QuantKV(
        store.q.at[li].set(q), store.s.at[li].set(s),
        store.bits, store.page_size,
    )


def kv_write_all_layers(store, phys, offs, vals):
    """All-layer write (the fused decode block's once-per-block carry
    patch): vals [L, ...lead, KH, D] at (phys[...lead], offs[...lead]).
    fp mode keeps the seed's single fused scatter."""
    if not isinstance(store, QuantKV):
        return store.at[:, phys, offs].set(vals)
    lead = phys.shape
    T = int(np.prod(lead)) if lead else 1
    phys_f = phys.reshape(T)
    offs_f = offs.reshape(T)
    L = vals.shape[0]
    vals_f = vals.reshape(L, T, *vals.shape[1 + len(lead):])
    write = jax.vmap(
        lambda ql, sl, vl: _write_one_layer(
            ql, sl, phys_f, offs_f, vl, store.bits, store.page_size
        )
    )
    q, s = write(store.q, store.s, vals_f)
    return QuantKV(q, s, store.bits, store.page_size)


# ---------------------------------------------------------------------- #
# dequantizing gathers (the XLA reference attention paths / fuzz oracle)
# ---------------------------------------------------------------------- #


def gather_dequant(layer, tables, dtype=jnp.float32):
    """Gather pages for a per-layer KV operand and return FULL-PRECISION
    context [..., n_pages, page_size, KH, D] in `dtype`. `layer` is a
    plain [pages, ps, KH, D] array (plain gather, any dtype) or a
    per-layer QuantKV (unpack + dequantize). `tables` may have any
    leading shape ([max_pages] or [B, max_pages])."""
    if not isinstance(layer, QuantKV):
        return layer[tables]
    q = layer.q[tables]  # [..., P, ps_eff, KH, D]
    if layer.bits == 4:
        q = unpack_int4(q, axis=-3)  # page_size axis
    s = layer.s[tables]  # [..., P, KH]
    return (q.astype(jnp.float32) * s[..., None, :, None]).astype(dtype)


# ---------------------------------------------------------------------- #
# host/wire packing (KVBM tiers, peer pulls, disagg payloads)
# ---------------------------------------------------------------------- #


def host_pack_pages(x) -> np.ndarray:
    """Device->host for extracted pages in the `[L, n, ...]` layout:
    fp -> np.asarray (unchanged seed behavior); QuantKV -> one uint8 row
    per (layer, page): q bytes ‖ f32 scale bytes, shape [L, n, PB]."""
    if not isinstance(x, QuantKV):
        return np.asarray(x)
    q = np.asarray(x.q)  # [L, n, ps_eff, KH, D] int8
    s = np.ascontiguousarray(np.asarray(x.s, dtype=np.float32))  # [L, n, KH]
    L, n = q.shape[0], q.shape[1]
    qb = np.ascontiguousarray(q).view(np.uint8).reshape(L, n, -1)
    sb = s.view(np.uint8).reshape(L, n, -1)
    return np.concatenate([qb, sb], axis=-1)


def host_unpack_pages(arr: np.ndarray, mode: str, page_size: int,
                      num_kv_heads: int, head_dim: int
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Inverse of host_pack_pages for a packed [..., PB] uint8 array:
    returns (q [..., ps_eff, KH, D] int8, s [..., KH] f32)."""
    bits = kv_quant_bits(mode)
    ps_eff = _ps_eff(page_size, bits)
    qbytes = ps_eff * num_kv_heads * head_dim
    lead = arr.shape[:-1]
    if arr.shape[-1] != qbytes + 4 * num_kv_heads:
        raise ValueError(
            f"packed KV page has {arr.shape[-1]} bytes; {mode} layout "
            f"expects {qbytes + 4 * num_kv_heads}"
        )
    q = (
        np.ascontiguousarray(arr[..., :qbytes])
        .view(np.int8)
        .reshape(*lead, ps_eff, num_kv_heads, head_dim)
    )
    s = (
        np.ascontiguousarray(arr[..., qbytes:])
        .view(np.float32)
        .reshape(*lead, num_kv_heads)
    )
    return q, s


def device_pages(arr, mode: str, page_size: int, num_kv_heads: int,
                 head_dim: int):
    """Host payload -> inject operand: fp passthrough (jnp.asarray at the
    call site keeps seed behavior), packed uint8 -> a QuantKV of device
    arrays in the same [L, n, ...] layout extract produced."""
    bits = kv_quant_bits(mode)
    if bits == 0:
        return jnp.asarray(arr)
    q, s = host_unpack_pages(
        np.asarray(arr), mode, page_size, num_kv_heads, head_dim
    )
    return QuantKV(jnp.asarray(q), jnp.asarray(s), bits, page_size)
