"""Pallas TPU kernel: ragged paged-attention for single-token decode.

Role of the reference's paged-attention CUDA kernels (inside vLLM) and of
`block_copy.cu` (lib/llm/src/kernels/block_copy.cu:41) — done the TPU way:
the KV cache stays in HBM, each grid step streams ONE slot's pages through
a double-buffered VMEM window with async DMA, and a flash-style running
softmax accumulates the output. This avoids the XLA fallback's materialized
[B, S, KH, D] gather (which costs an extra HBM round-trip for the whole
context).

Layouts (match ops/paged_attention.py and engine/kv_cache.py):
    q:           [B, H, D]
    kv_{k,v}:    [num_pages, page_size, KH, D]   (one layer)
    page_tables: [B, max_pages] int32  (logical -> physical page)
    seq_lens:    [B] int32             (valid positions incl. current token)

Design notes:
  * grid = (B,); page_tables/seq_lens ride scalar-prefetch (SMEM) so DMA
    source indices are known ahead of the body.
  * pages are streamed in chunks of CHUNK = max(128, page_size) positions so
    the score lane dimension is a full 128-lane register tile.
  * physical page ids are clamped to the valid range: tail chunks may DMA a
    garbage page, but masking (additive NEG) keeps them out of the softmax.
  * all softmax state is f32; QK^T and PV ride the MXU in bf16 with f32
    accumulation (preferred_element_type).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG = -1e30


def _window_dequant(b, ci, slot, k_buf, v_buf, pt_ref, ks_ref, vs_ref,
                    compute_dtype, *, chunk_pages, page_rows, max_pages,
                    num_phys, num_kv_heads, head_dim, kv_bits):
    """Quantized decode window -> full-precision ([chunk, KH*D] K, V):
    per page, unpack (int4 packs two tokens per byte along the sublane
    axis) and multiply each kv head's D-wide column block by that page's
    scalar-prefetched per-head scale."""
    from ..models.quant import unpack_int4

    k_segs, v_segs = [], []
    for p in range(chunk_pages):
        lp_safe = jnp.minimum(ci * chunk_pages + p, max_pages - 1)
        phys = jnp.minimum(pt_ref[b, lp_safe], num_phys - 1)
        kseg = k_buf[slot, pl.ds(p * page_rows, page_rows)]  # int8 [rows, KH*D]
        vseg = v_buf[slot, pl.ds(p * page_rows, page_rows)]
        if kv_bits == 4:
            kseg = unpack_int4(kseg, axis=0)  # [page_size, KH*D]
            vseg = unpack_int4(vseg, axis=0)
        # per-head scale over the head's D-wide column block
        ks_row = jnp.concatenate(
            [jnp.full((1, head_dim), ks_ref[phys, h], jnp.float32)
             for h in range(num_kv_heads)], axis=1,
        )  # [1, KH*D]
        vs_row = jnp.concatenate(
            [jnp.full((1, head_dim), vs_ref[phys, h], jnp.float32)
             for h in range(num_kv_heads)], axis=1,
        )
        k_segs.append((kseg.astype(jnp.float32) * ks_row).astype(compute_dtype))
        v_segs.append((vseg.astype(jnp.float32) * vs_row).astype(compute_dtype))
    return jnp.concatenate(k_segs, axis=0), jnp.concatenate(v_segs, axis=0)


def _decode_kernel(
    # positional refs: page_tables [B, max_pages] + seq_lens [B] int32
    # scalar prefetch (+ per-page-per-head K/V scales [num_pages, KH] f32
    # when kv_bits > 0), then q [1, H, D] VMEM, kv_k/kv_v
    # [num_pages, rows, KH*D] ANY/HBM (rows = page_size, or page_size//2
    # int4-packed along the sublane axis), the out block, and the
    # double-buffered VMEM window + DMA semaphores.
    *refs,
    page_size: int,
    chunk_pages: int,
    max_pages: int,
    num_heads: int,
    num_kv_heads: int,
    head_dim: int,
    kv_bits: int = 0,
):
    if kv_bits:
        (pt_ref, sl_ref, ks_ref, vs_ref, q_ref, kv_k_hbm, kv_v_hbm,
         out_ref, k_buf, v_buf, k_sem, v_sem) = refs
    else:
        (pt_ref, sl_ref, q_ref, kv_k_hbm, kv_v_hbm,
         out_ref, k_buf, v_buf, k_sem, v_sem) = refs
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    chunk = chunk_pages * page_size
    num_phys = kv_k_hbm.shape[0]
    page_rows = kv_k_hbm.shape[1]
    kh, g, d = num_kv_heads, num_heads // num_kv_heads, head_dim

    seq_len = jnp.maximum(sl_ref[b], 1)  # empty slots behave as len-1
    n_chunks = pl.cdiv(seq_len, chunk)
    max_chunks = pl.cdiv(max_pages, chunk_pages)

    def start_chunk(ci, slot):
        """Kick off DMAs for all pages of chunk ci into buffer `slot`."""
        for p in range(chunk_pages):
            lp = ci * chunk_pages + p
            lp_safe = jnp.minimum(lp, max_pages - 1)
            phys = jnp.minimum(pt_ref[b, lp_safe], num_phys - 1)
            pltpu.make_async_copy(
                kv_k_hbm.at[phys],
                k_buf.at[slot, pl.ds(p * page_rows, page_rows)],
                k_sem.at[slot, p],
            ).start()
            pltpu.make_async_copy(
                kv_v_hbm.at[phys],
                v_buf.at[slot, pl.ds(p * page_rows, page_rows)],
                v_sem.at[slot, p],
            ).start()

    def wait_chunk(ci, slot):
        for p in range(chunk_pages):
            lp_safe = jnp.minimum(ci * chunk_pages + p, max_pages - 1)
            phys = jnp.minimum(pt_ref[b, lp_safe], num_phys - 1)
            pltpu.make_async_copy(
                kv_k_hbm.at[phys],
                k_buf.at[slot, pl.ds(p * page_rows, page_rows)],
                k_sem.at[slot, p],
            ).wait()
            pltpu.make_async_copy(
                kv_v_hbm.at[phys],
                v_buf.at[slot, pl.ds(p * page_rows, page_rows)],
                v_sem.at[slot, p],
            ).wait()

    start_chunk(0, 0)

    # GQA as ONE matmul pair per chunk: q arrives pre-packed block-diagonal
    # [KH*G, KH*D] (head h's G queries in column block h, built by XLA in
    # the wrapper) so s = q_bd @ k_flat^T and pv = p @ v_flat each hit the
    # MXU once instead of KH tiny per-head matmuls. acc accumulates the full
    # [HG, KH*D] pv; the diagonal blocks are extracted once after the loop.
    hg = kh * g
    q_bd = q_ref[0]  # [HG, KH*D]

    m0 = jnp.full((hg, 1), NEG, jnp.float32)
    l0 = jnp.zeros((hg, 1), jnp.float32)
    acc0 = jnp.zeros((hg, kh * d), jnp.float32)

    def body(ci, carry):
        m, l, acc = carry
        slot = jax.lax.rem(ci, 2)

        @pl.when(ci + 1 < n_chunks)
        def _():
            start_chunk(ci + 1, jax.lax.rem(ci + 1, 2))

        wait_chunk(ci, slot)
        if kv_bits:
            k, v = _window_dequant(
                b, ci, slot, k_buf, v_buf, pt_ref, ks_ref, vs_ref,
                q_ref.dtype, chunk_pages=chunk_pages, page_rows=page_rows,
                max_pages=max_pages, num_phys=num_phys,
                num_kv_heads=kh, head_dim=d, kv_bits=kv_bits,
            )
        else:
            k = k_buf[slot]  # [CHUNK, KH*D]
            v = v_buf[slot]

        pos = ci * chunk + jax.lax.broadcasted_iota(jnp.int32, (1, chunk), 1)
        valid = pos < seq_len  # [1, CHUNK]

        s = jax.lax.dot_general(
            q_bd.astype(k.dtype),
            k,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [HG, CHUNK]
        s = jnp.where(valid, s, NEG)
        m_n = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_n)  # [HG, 1]
        p = jnp.exp(s - m_n)  # [HG, CHUNK]
        l_n = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv_all = jax.lax.dot_general(
            p.astype(v.dtype),
            v,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [HG, KH*D]
        return m_n, l_n, acc * alpha + pv_all

    m, l, acc = jax.lax.fori_loop(0, n_chunks, body, (m0, l0, acc0))
    # extract head h's D-block from row block h of acc: static slices per kv
    # head (no [HG,KH*D]->[HG,KH,D] reshape — unsupported Mosaic shape cast)
    row_head = jax.lax.broadcasted_iota(jnp.int32, (hg, 1), 0) // g
    out = jnp.zeros((hg, d), jnp.float32)
    for k0 in range(kh):
        blk = jax.lax.slice(acc, (0, k0 * d), (hg, (k0 + 1) * d))
        out = out + jnp.where(row_head == k0, blk, 0.0)
    out = out / jnp.maximum(l, 1e-30)
    out_ref[0] = out.astype(out_ref.dtype)


def _decode_local_kernel(
    # positional refs: page_tables [B, max_pages], POOL lens [B], step [1]
    # int32 scalar prefetch (+ per-page-per-head K/V scales
    # [num_pages, KH] f32 when kv_bits > 0), then q [1, HG, KH*D] VMEM
    # (block-diagonal packed), the block-local loc_k/loc_v [1, K, KH*D]
    # (ALWAYS full precision — quantization happens on pool writes only),
    # kv_k/kv_v [num_pages, rows, KH*D] ANY/HBM, out, window scratch.
    *refs,
    page_size: int,
    chunk_pages: int,
    max_pages: int,
    num_heads: int,
    num_kv_heads: int,
    head_dim: int,
    kv_bits: int = 0,
):
    """Decode flash attention over pool pages PLUS a block-local KV buffer,
    all in one kernel launch. The local part is what lets the engine keep
    the KV pool read-only inside its fused K-step scan (engine/engine.py
    decode_block): per-step XLA-level combines cost ~8 extra op launches
    per layer-step, which dominated the block at 28 layers x 16 steps."""
    if kv_bits:
        (pt_ref, sl_ref, step_ref, ks_ref, vs_ref, q_ref, loc_k_ref,
         loc_v_ref, kv_k_hbm, kv_v_hbm, out_ref, k_buf, v_buf, k_sem,
         v_sem) = refs
    else:
        (pt_ref, sl_ref, step_ref, q_ref, loc_k_ref, loc_v_ref,
         kv_k_hbm, kv_v_hbm, out_ref, k_buf, v_buf, k_sem, v_sem) = refs
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    chunk = chunk_pages * page_size
    num_phys = kv_k_hbm.shape[0]
    page_rows = kv_k_hbm.shape[1]
    kh, g, d = num_kv_heads, num_heads // num_kv_heads, head_dim

    seq_len = jnp.maximum(sl_ref[b], 1)
    n_chunks = pl.cdiv(seq_len, chunk)

    def start_chunk(ci, slot):
        for p in range(chunk_pages):
            lp = ci * chunk_pages + p
            lp_safe = jnp.minimum(lp, max_pages - 1)
            phys = jnp.minimum(pt_ref[b, lp_safe], num_phys - 1)
            pltpu.make_async_copy(
                kv_k_hbm.at[phys],
                k_buf.at[slot, pl.ds(p * page_rows, page_rows)],
                k_sem.at[slot, p],
            ).start()
            pltpu.make_async_copy(
                kv_v_hbm.at[phys],
                v_buf.at[slot, pl.ds(p * page_rows, page_rows)],
                v_sem.at[slot, p],
            ).start()

    def wait_chunk(ci, slot):
        for p in range(chunk_pages):
            lp_safe = jnp.minimum(ci * chunk_pages + p, max_pages - 1)
            phys = jnp.minimum(pt_ref[b, lp_safe], num_phys - 1)
            pltpu.make_async_copy(
                kv_k_hbm.at[phys],
                k_buf.at[slot, pl.ds(p * page_rows, page_rows)],
                k_sem.at[slot, p],
            ).wait()
            pltpu.make_async_copy(
                kv_v_hbm.at[phys],
                v_buf.at[slot, pl.ds(p * page_rows, page_rows)],
                v_sem.at[slot, p],
            ).wait()

    start_chunk(0, 0)
    hg = kh * g
    q_bd = q_ref[0]

    m0 = jnp.full((hg, 1), NEG, jnp.float32)
    l0 = jnp.zeros((hg, 1), jnp.float32)
    acc0 = jnp.zeros((hg, kh * d), jnp.float32)

    def flash_update(s, valid, v, carry):
        m, l, acc = carry
        s = jnp.where(valid, s, NEG)
        m_n = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_n)
        p = jnp.exp(s - m_n)
        l_n = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_n, l_n, acc * alpha + pv

    def body(ci, carry):
        slot = jax.lax.rem(ci, 2)

        @pl.when(ci + 1 < n_chunks)
        def _():
            start_chunk(ci + 1, jax.lax.rem(ci + 1, 2))

        wait_chunk(ci, slot)
        if kv_bits:
            k, v = _window_dequant(
                b, ci, slot, k_buf, v_buf, pt_ref, ks_ref, vs_ref,
                q_ref.dtype, chunk_pages=chunk_pages, page_rows=page_rows,
                max_pages=max_pages, num_phys=num_phys,
                num_kv_heads=kh, head_dim=d, kv_bits=kv_bits,
            )
        else:
            k = k_buf[slot]
            v = v_buf[slot]
        pos = ci * chunk + jax.lax.broadcasted_iota(jnp.int32, (1, chunk), 1)
        s = jax.lax.dot_general(
            q_bd.astype(k.dtype), k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return flash_update(s, pos < seq_len, v, carry)

    m, l, acc = jax.lax.fori_loop(0, n_chunks, body, (m0, l0, acc0))

    # local buffer: one more flash iteration over the K in-block entries
    k_loc = loc_k_ref[0]  # [K, KH*D]
    v_loc = loc_v_ref[0]
    K_loc = k_loc.shape[0]
    j = jax.lax.broadcasted_iota(jnp.int32, (1, K_loc), 1)
    s_loc = jax.lax.dot_general(
        q_bd.astype(k_loc.dtype), k_loc, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m, l, acc = flash_update(s_loc, j <= step_ref[0], v_loc, (m, l, acc))

    row_head = jax.lax.broadcasted_iota(jnp.int32, (hg, 1), 0) // g
    out = jnp.zeros((hg, d), jnp.float32)
    for k0 in range(kh):
        blk = jax.lax.slice(acc, (0, k0 * d), (hg, (k0 + 1) * d))
        out = out + jnp.where(row_head == k0, blk, 0.0)
    out = out / jnp.maximum(l, 1e-30)
    out_ref[0] = out.astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_attention_decode_pallas_local(
    q: jax.Array,  # [B, H, D]
    kv_k_layer: jax.Array,  # [num_pages, page_size, KH, D] (READ-ONLY pool)
    kv_v_layer: jax.Array,
    page_tables: jax.Array,  # [B, max_pages] int32
    pool_lens: jax.Array,  # [B] int32 — positions valid in the pool
    loc_k: jax.Array,  # [B, K, KH, D] block-local new keys
    loc_v: jax.Array,
    step_idx: jax.Array,  # scalar i32 — local entries 0..step_idx valid
    *,
    interpret: bool = False,
) -> jax.Array:
    """Fused pool+local decode attention; returns [B, H, D] (q.dtype).
    The pool may be a per-layer QuantKV (ops/kv_quant.py): packed pages
    dequantize inside the VMEM window off scalar-prefetched scales; the
    block-local buffer is always full precision."""
    from .kv_quant import kernel_operands

    B, H, D = q.shape
    kv_k_raw, kv_v_raw, rows, page_size, kv_bits, scale_prefetch = (
        kernel_operands(kv_k_layer, kv_v_layer)
    )
    num_pages, _, KH, _ = kv_k_raw.shape
    max_pages = page_tables.shape[1]
    K_loc = loc_k.shape[1]
    target = 512 if KH * D * page_size <= 131072 else 256
    chunk_pages = max(1, target // page_size)
    chunk_pages = min(chunk_pages, max_pages)

    KHG = KH * (H // KH)
    scale = 1.0 / (D**0.5)
    q_r = (q * scale).reshape(B, KH, H // KH, D)
    eye = jnp.eye(KH, dtype=q.dtype)
    q_bd = jnp.einsum("bkgd,kj->bkgjd", q_r, eye).reshape(B, KHG, KH * D)

    kv_k_flat = kv_k_raw.reshape(num_pages, rows, KH * D)
    kv_v_flat = kv_v_raw.reshape(num_pages, rows, KH * D)
    loc_k_flat = loc_k.reshape(B, K_loc, KH * D)
    loc_v_flat = loc_v.reshape(B, K_loc, KH * D)
    prefetch = [
        page_tables.astype(jnp.int32),
        pool_lens.astype(jnp.int32),
        jnp.reshape(step_idx, (1,)).astype(jnp.int32),
        *scale_prefetch,
    ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(prefetch),
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, KHG, KH * D), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec((1, K_loc, KH * D), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec((1, K_loc, KH * D), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda b, *_: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, chunk_pages * rows, KH * D), kv_k_flat.dtype),
            pltpu.VMEM((2, chunk_pages * rows, KH * D), kv_v_flat.dtype),
            pltpu.SemaphoreType.DMA((2, chunk_pages)),
            pltpu.SemaphoreType.DMA((2, chunk_pages)),
        ],
    )
    kernel = functools.partial(
        _decode_local_kernel,
        page_size=page_size,
        chunk_pages=chunk_pages,
        max_pages=max_pages,
        num_heads=H,
        num_kv_heads=KH,
        head_dim=D,
        kv_bits=kv_bits,
    )
    cost = pl.CostEstimate(
        flops=4 * B * H * D * (max_pages * page_size + K_loc),
        bytes_accessed=2 * B * max_pages * page_size * KH * D * 2,
        transcendentals=B * H * (max_pages * page_size + K_loc),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        cost_estimate=cost,
        interpret=interpret,
    )(
        *prefetch,
        q_bd,
        loc_k_flat,
        loc_v_flat,
        kv_k_flat,
        kv_v_flat,
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_attention_decode_pallas(
    q: jax.Array,  # [B, H, D]
    kv_k_layer: jax.Array,  # [num_pages, page_size, KH, D]
    kv_v_layer: jax.Array,
    page_tables: jax.Array,  # [B, max_pages] int32
    seq_lens: jax.Array,  # [B] int32
    *,
    interpret: bool = False,
) -> jax.Array:
    """Flash decode attention over paged KV; returns [B, H, D] (q.dtype).
    (Block-local merging lives in _decode_local_kernel — the fused variant —
    so this hot path writes exactly one output.) The pool may be a
    per-layer QuantKV: packed pages dequantize in the VMEM window."""
    from .kv_quant import kernel_operands

    B, H, D = q.shape
    kv_k_raw, kv_v_raw, rows, page_size, kv_bits, scale_prefetch = (
        kernel_operands(kv_k_layer, kv_v_layer)
    )
    num_pages, _, KH, _ = kv_k_raw.shape
    max_pages = page_tables.shape[1]
    # chunk target: big enough to amortize per-iteration overhead, small
    # enough that 2 double-buffered K+V chunks fit comfortably in VMEM
    target = 512 if KH * D * page_size <= 131072 else 256
    chunk_pages = max(1, target // page_size)
    chunk_pages = min(chunk_pages, max_pages)

    KHG = KH * (H // KH)
    # pre-pack block-diagonal queries in XLA: q_bd[b, h*G+g, h*D:(h+1)*D] = q
    scale = 1.0 / (D**0.5)
    q_r = (q * scale).reshape(B, KH, H // KH, D)
    eye = jnp.eye(KH, dtype=q.dtype)
    q_bd = jnp.einsum("bkgd,kj->bkgjd", q_r, eye).reshape(B, KHG, KH * D)

    # flatten [pages, rows, KH, D] -> [pages, rows, KH*D] in XLA
    # (contiguous bitcast) — Mosaic cannot merge minor dims in-register
    kv_k_flat = kv_k_raw.reshape(num_pages, rows, KH * D)
    kv_v_flat = kv_v_raw.reshape(num_pages, rows, KH * D)
    prefetch = [
        page_tables.astype(jnp.int32),
        seq_lens.astype(jnp.int32),
        *scale_prefetch,
    ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(prefetch),
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, KHG, KH * D), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda b, *_: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, chunk_pages * rows, KH * D), kv_k_flat.dtype),
            pltpu.VMEM((2, chunk_pages * rows, KH * D), kv_v_flat.dtype),
            pltpu.SemaphoreType.DMA((2, chunk_pages)),
            pltpu.SemaphoreType.DMA((2, chunk_pages)),
        ],
    )
    kernel = functools.partial(
        _decode_kernel,
        page_size=page_size,
        chunk_pages=chunk_pages,
        max_pages=max_pages,
        num_heads=H,
        num_kv_heads=KH,
        head_dim=D,
        kv_bits=kv_bits,
    )
    cost = pl.CostEstimate(
        flops=4 * B * H * D * max_pages * page_size,
        bytes_accessed=2 * B * max_pages * page_size * KH * D * 2,
        transcendentals=B * H * max_pages * page_size,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        cost_estimate=cost,
        interpret=interpret,
    )(*prefetch, q_bd, kv_k_flat, kv_v_flat)
