"""JAX engine worker: `python -m dynamo_tpu.jax_worker`.

Mirrors the reference vLLM worker wiring (components/backends/vllm main.py:
64,209 — create service, build engine, publish KV events + metrics,
register_llm, serve_endpoint) with the native JAX engine underneath.
"""

import argparse
import asyncio
import logging
import time

from dynamo_tpu.engine import EngineConfig, JaxEngine
from dynamo_tpu.llm.kv_router.publisher import KvEventPublisher, WorkerMetricsPublisher
from dynamo_tpu.llm.model_card import ModelDeploymentCard, register_llm
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig, init_logging
from dynamo_tpu.runtime.metrics import (
    NUM_RUNNING_REQS,
    NUM_WAITING_REQS,
    worker_exported_stats,
)

logger = logging.getLogger("dynamo_tpu.jax_worker")


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="dynamo-tpu JAX engine worker")
    ap.add_argument("--model", default="tiny", help="model registry key (tiny/llama3-8b/llama3-70b)")
    ap.add_argument("--model-name", default=None, help="served model name (defaults to --model)")
    ap.add_argument("--model-path", default=None,
                    help="HF safetensors checkpoint dir; random init if omitted")
    ap.add_argument("--namespace", default="dynamo")
    ap.add_argument("--component", default="backend")
    ap.add_argument("--endpoint", default="generate")
    ap.add_argument("--discovery", default=None)
    ap.add_argument("--page-size", type=int, default=64)
    ap.add_argument("--num-pages", type=int, default=0,
                    help="KV page pool; 0 = auto-size from free device HBM "
                    "(DYN_HBM_UTILIZATION; CPU falls back to a fixed 2048)")
    ap.add_argument("--max-num-seqs", type=int, default=64)
    ap.add_argument("--max-model-len", type=int, default=8192)
    ap.add_argument("--decode-pool-mode", choices=["scatter", "local"],
                    default=None,
                    help="KV-write strategy in the fused decode block "
                    "(default: auto — local on TPU, scatter on CPU; "
                    "see EngineConfig.decode_pool_mode)")
    ap.add_argument("--decode-block-unroll", type=int, default=0,
                    help="0 = auto (4 under local, 1 under scatter)")
    ap.add_argument("--lora", action="append", default=[],
                    metavar="NAME=PATH",
                    help="serve a LoRA adapter (HF PEFT export dir); "
                         "repeatable. NAME=random:<seed> makes a random "
                         "adapter (tests/demos). Select per request via "
                         "nvext.lora_name.")
    ap.add_argument("--spec", choices=["ngram"], default=None,
                    help="speculative decoding: self-drafting prompt-lookup "
                    "verified in one pass (engine/spec.py)")
    ap.add_argument("--spec-draft-len", type=int, default=4)
    ap.add_argument("--spec-ngram", type=int, default=2)
    ap.add_argument("--spec-rounds", type=int, default=4)
    ap.add_argument("--kv-quant", choices=["none", "int8", "int4"],
                    default=None,
                    help="quantized KV cache page format (default: resolve "
                    "from DYN_KV_QUANT, none): pages quantize on write and "
                    "dequantize in-kernel; ~2x/4x resident sessions at "
                    "fixed HBM and the same shrink on every KVBM/peer/"
                    "disagg transfer. All workers of a fleet must match "
                    "(mismatches fail typed).")
    ap.add_argument("--quantize", choices=["int8"], default=None,
                    help="weight-only quantization (models/quant.py): int8 "
                    "projections/embed/head, per-channel scales")
    ap.add_argument("--tp-size", type=int, default=1)
    ap.add_argument("--ep-size", type=int, default=1,
                    help="expert-parallel axis size (MoE models)")
    ap.add_argument("--pp-size", type=int, default=1,
                    help="pipeline stages (layers over the pp mesh axis)")
    ap.add_argument("--sp-size", type=int, default=1,
                    help="sequence-parallel axis (ring-attention prefill)")
    ap.add_argument("--ring-prefill-threshold", type=int, default=512,
                    help="fresh prompts at least this long ride the sp ring")
    ap.add_argument("--dp-attention", action="store_true",
                    help="MoE: attention/batch data-parallel over the ep axis "
                    "(DeepSeek-style wide-EP layout)")
    ap.add_argument("--kv-events", action="store_true")
    # KVBM tiers (kvbm/): host-RAM + disk KV block offload
    ap.add_argument("--kvbm-host-blocks", type=int, default=0)
    ap.add_argument("--kvbm-disk-blocks", type=int, default=0)
    ap.add_argument("--kvbm-disk-path", default=None)
    ap.add_argument("--migration-limit", type=int, default=3)
    # SLA-aware step scheduling (engine/scheduler/, docs/scheduler.md);
    # defaults resolve from DYN_SCHED_POLICY / DYN_SLA_TTFT_MS /
    # DYN_SLA_ITL_MS so fleet-wide rollout needs no CLI change
    ap.add_argument("--sched-policy", choices=["fifo", "sla"], default=None,
                    help="step-scheduling policy: fifo = legacy admit-order "
                    "dispatch (bit-for-bit, modulo the batch-kind "
                    "anti-starvation fix), sla = EDF + ITL-budget planner "
                    "(default: DYN_SCHED_POLICY, fifo)")
    ap.add_argument("--ttft-target-ms", type=float, default=None,
                    help="TTFT target under sla policy (default: "
                    "DYN_SLA_TTFT_MS)")
    ap.add_argument("--itl-target-ms", type=float, default=None,
                    help="decode ITL budget under sla policy; 0 disables "
                    "(default: DYN_SLA_ITL_MS)")
    ap.add_argument("--warmup", choices=["auto", "full", "none"],
                    default="auto",
                    help="compile all engine dispatch variants before "
                    "joining the control plane (auto: on for accelerators, "
                    "off for CPU test runs)")
    ap.add_argument("--context-length", type=int, default=None)
    # disaggregation (reference: --disaggregation-mode prefill|decode)
    ap.add_argument(
        "--role", choices=["aggregated", "prefill", "decode"], default="aggregated"
    )
    ap.add_argument("--prefill-component", default="prefill")
    ap.add_argument("--disagg-threshold", type=int, default=64,
                    help="remote prefill iff uncached prompt tokens exceed this")
    # multi-host slice (reference: vLLM node orchestration, main.py:64-296).
    # All hosts run this same module; host 0 owns the control plane and
    # broadcasts step descriptors; hosts >0 replay them (SPMD).
    ap.add_argument("--num-hosts", type=int, default=1)
    ap.add_argument("--host-id", type=int, default=0)
    ap.add_argument("--coordinator", default=None,
                    help="jax.distributed coordinator host:port (required for --num-hosts > 1)")
    ap.add_argument("--spmd-port", type=int, default=17300,
                    help="host-0 step-descriptor fan-out port")
    # KV data plane (llm/kv_transfer.py — the NIXL-replacement fast path):
    # prefill-capable workers stage finished prompts here for pulling
    ap.add_argument("--kv-data-plane-port", type=int, default=0,
                    help="KV data plane listen port (0 = ephemeral)")
    ap.add_argument("--kv-data-plane-host", default=None,
                    help="advertised data plane host (defaults to local)")
    ap.add_argument("--no-kv-data-plane", action="store_true",
                    help="disable the pull data plane (inline KV payloads)")
    return ap.parse_args(argv)


async def main():
    init_logging()
    args = parse_args()

    multihost = args.num_hosts > 1
    spmd = None
    if multihost:
        if not args.coordinator:
            raise SystemExit("--coordinator is required with --num-hosts > 1")
        from dynamo_tpu.parallel.multihost import (
            StepBroadcaster,
            StepReceiver,
            init_multihost,
        )

        # must run before ANY other jax call on every host
        init_multihost(args.coordinator, args.num_hosts, args.host_id)
        if args.host_id == 0:
            spmd = StepBroadcaster("0.0.0.0", args.spmd_port, args.num_hosts - 1)
            await spmd.start()

    engine_cfg = EngineConfig(
        model=args.model,
        page_size=args.page_size,
        num_pages=args.num_pages,
        max_num_seqs=args.max_num_seqs,
        max_model_len=args.max_model_len,
        decode_pool_mode=args.decode_pool_mode,
        decode_block_unroll=args.decode_block_unroll,
        quantize=args.quantize,
        kv_quant=args.kv_quant,
        spec_mode=args.spec,
        spec_draft_len=args.spec_draft_len,
        spec_ngram=args.spec_ngram,
        spec_rounds=args.spec_rounds,
        tp_size=args.tp_size,
        pp_size=args.pp_size,
        sp_size=args.sp_size,
        ring_prefill_threshold=args.ring_prefill_threshold,
        kvbm_host_blocks=args.kvbm_host_blocks,
        kvbm_disk_blocks=args.kvbm_disk_blocks,
        kvbm_disk_path=args.kvbm_disk_path,
        sched_policy=args.sched_policy,
        ttft_target_ms=args.ttft_target_ms,
        itl_target_ms=args.itl_target_ms,
        # aggregated serving warms both surfaces, same as decode
        role=args.role if args.role in ("prefill", "decode") else "decode",
    )

    kv_sharding = None
    params = None
    model_config = None
    gguf_path = None
    mesh = None
    any_parallel = (
        args.tp_size > 1 or args.ep_size > 1 or args.pp_size > 1
        or args.sp_size > 1
    )
    if any_parallel or args.model_path or multihost:
        from dynamo_tpu.models import llama, moe
        from dynamo_tpu.parallel.mesh import (
            DpAttentionShardings,
            LlamaShardings,
            MoeShardings,
            ParallelConfig,
            build_mesh,
            shard_params,
        )
        import jax

        from dynamo_tpu.engine.engine import _resolve_model

        from dynamo_tpu.models.loader import _find_gguf, config_from_gguf

        gguf_path = _find_gguf(args.model_path) if args.model_path else None
        if gguf_path is not None:
            # the checkpoint is authoritative: shapes come from the .gguf
            # metadata/tensors, no registry entry needed
            model_config = config_from_gguf(gguf_path)
        else:
            model_config = _resolve_model(args.model)
        is_moe = isinstance(model_config, moe.MoeConfig)
        model_mod = moe if is_moe else llama
        shardings = None
        if any_parallel or multihost:
            mesh = build_mesh(
                ParallelConfig(
                    tp_size=args.tp_size, ep_size=args.ep_size,
                    pp_size=args.pp_size, sp_size=args.sp_size,
                )
            )
            if is_moe and args.dp_attention:
                shardings = DpAttentionShardings(mesh)
            elif is_moe:
                shardings = MoeShardings(mesh)
            else:
                shardings = LlamaShardings(mesh)
            kv_sharding = shardings.kv_sharding()
        if args.model_path:
            from dynamo_tpu.models.loader import load_llama_params, load_moe_params

            load = load_moe_params if is_moe else load_llama_params
            params = load(
                args.model_path,
                model_config,
                shardings.param_shardings() if shardings else None,
                quantize=args.quantize,
            )
        else:
            params = model_mod.init_params(
                model_config, jax.random.PRNGKey(engine_cfg.seed)
            )
            if args.quantize == "int8":
                from dynamo_tpu.models.quant import quantize_tree

                params = quantize_tree(params, consume=True)
            if shardings is not None:
                params = shard_params(params, shardings)

    # build the engine BEFORE joining the control plane: param init can take
    # tens of seconds and must not eat into the discovery lease
    pending_events = []
    engine = JaxEngine(
        engine_cfg,
        model_config=model_config,
        params=params,
        kv_sharding=kv_sharding,
        event_sink=pending_events.append if args.host_id == 0 else None,
        mesh=mesh,
        spmd=spmd,
        multihost=multihost,
    )
    # guided decoding compiles token FSMs against the SERVED vocabulary:
    # GGUF checkpoints carry their own; everything else uses the byte
    # tokenizer the model card advertises (llm/guided.py)
    from dynamo_tpu.llm.tokenizers import load_tokenizer

    engine.tokenizer = load_tokenizer(
        f"gguf:{gguf_path}" if gguf_path is not None
        else f"byte:{engine.model_config.vocab_size}"
    )
    if args.lora:
        import jax as _jax_lora

        from dynamo_tpu.models import lora as lora_mod

        adapters = []
        for spec in args.lora:
            name, _, src = spec.partition("=")
            if not src:
                raise SystemExit(f"--lora expects NAME=PATH, got {spec!r}")
            if src.startswith("random:"):
                adapters.append(lora_mod.init_adapter(
                    engine.model_config, name,
                    _jax_lora.random.PRNGKey(int(src.split(":", 1)[1])),
                ))
            else:
                adapters.append(lora_mod.load_peft_adapter(
                    src, engine.model_config, name=name
                ))
        engine.register_adapters(adapters)
        logger.info("LoRA adapters registered: %s", engine.lora_names())

    # KV data plane: prefill-capable workers stage finished prompts here;
    # under multi-host EVERY host (followers too) runs one, serving only its
    # own KV shard — the per-shard point-to-point transfer path
    data_plane = None
    kvbm_enabled = args.kvbm_host_blocks > 0 or args.kvbm_disk_blocks > 0
    if not args.no_kv_data_plane and (
        multihost or kvbm_enabled or args.role in ("prefill", "aggregated")
    ):
        # kvbm_enabled: decode-role workers join the distributed KVBM mesh
        # too — they both pull peers' offloaded blocks and serve their own
        from dynamo_tpu.llm.kv_transfer import KvDataPlaneServer

        data_plane = KvDataPlaneServer(
            advertise_host=args.kv_data_plane_host, port=args.kv_data_plane_port
        )
        await data_plane.start()
        engine.data_plane = data_plane
        engine.host_id = args.host_id
        logger.info("kv data plane listening on %s", data_plane.addr)

    if multihost and args.host_id != 0:
        # follower host: no discovery, no endpoint, no KV events (host-0
        # ownership) — replay the leader's dispatch stream until shutdown
        leader_host = args.coordinator.rsplit(":", 1)[0]
        receiver = StepReceiver(
            leader_host, args.spmd_port,
            host_id=args.host_id,
            data_plane_addr=data_plane.addr if data_plane is not None else "",
        )
        await receiver.connect()
        logger.info(
            "jax follower host %d/%d connected to leader %s:%d",
            args.host_id, args.num_hosts, leader_host, args.spmd_port,
        )
        await engine.run_follower(receiver)
        return

    if spmd is not None:
        logger.info("waiting for %d follower host(s)", args.num_hosts - 1)
        await spmd.wait_for_followers()
        follower_planes = spmd.follower_data_planes
        if data_plane is not None and len(follower_planes) == args.num_hosts - 1 \
                and all(follower_planes.get(h) for h in range(1, args.num_hosts)):
            engine.shard_addrs = [data_plane.addr] + [
                follower_planes[h] for h in range(1, args.num_hosts)
            ]
            logger.info("kv shard rendezvous: %s", engine.shard_addrs)
        # a dead follower wedges every future collective: fail all in-flight
        # requests (so callers migrate, llm/migration.py) and shut the
        # worker down — the lease expires and the frontend drops us
        # (reference analogue: engine-death watchdog -> runtime shutdown,
        # vllm handlers.py:268-273)
        loop = asyncio.get_running_loop()
        shutdown_holder = {}

        def _follower_lost(host_id, why):
            logger.error(
                "follower %d lost (%s): failing active requests and shutting down",
                host_id, why,
            )
            engine._fail_all(f"follower host {host_id} lost: {why}")
            if "shutdown" in shutdown_holder:
                shutdown_holder["shutdown"]()
            # the device thread may be wedged inside a dead collective and
            # block interpreter exit: force it after a drain grace period
            import os
            import threading

            threading.Timer(5.0, lambda: os._exit(1)).start()

        spmd.on_follower_lost = lambda hid, why: loop.call_soon(_follower_lost, hid, why)

    # compile every engine program variant BEFORE joining the control
    # plane: a first-request compile (20-40s/program through the axon
    # remote-compile tunnel) after registration starves lease renewal and
    # the frontend drops the worker mid-stream (round-4 e2e failure mode)
    import jax as _jax

    do_warmup = args.warmup == "full" or (
        args.warmup == "auto" and _jax.local_devices()[0].platform != "cpu"
    )
    if do_warmup:
        t0 = time.monotonic()
        n_warm = await engine.warmup()
        logger.info(
            "engine warmup: %d requests, all dispatch variants compiled "
            "in %.1fs", n_warm, time.monotonic() - t0,
        )

    cfg = RuntimeConfig.from_settings()
    if args.discovery:
        cfg.discovery_endpoint = args.discovery
    drt = await DistributedRuntime.create(cfg)
    # SIGTERM (planner scale-down) walks the graceful drain, not a hard exit
    drt.install_signal_handlers()
    if spmd is not None:
        shutdown_holder["shutdown"] = drt.shutdown
    if data_plane is not None:
        await data_plane.register(drt)

    kvbm_dist = None
    if engine.kvbm is not None and data_plane is not None:
        # distributed KVBM (reference KvbmLeader/Worker role): announce our
        # tiered blocks namespace-wide so ANY worker (prefill or decode
        # pool) can onboard blocks we offloaded, via the data plane
        from dynamo_tpu.kvbm.distributed import KvbmDistributed

        kvbm_dist = KvbmDistributed(
            drt, engine.kvbm, data_plane, args.namespace, "kvbm",
            drt.instance_id,
        )
        await kvbm_dist.start()
        logger.info("distributed KVBM mesh joined (namespace %s)", args.namespace)
    def role_component(role: str) -> str:
        return args.prefill_component if role == "prefill" else args.component

    # live role state: `morph` (below) re-roles the worker without a
    # restart, so everything role-dependent reads this box, not args.role
    state = {"role": args.role, "card_key": None}
    component = role_component(args.role)
    endpoint = drt.namespace(args.namespace).component(component).endpoint(args.endpoint)

    publisher = None
    if args.kv_events:
        publisher = KvEventPublisher(drt, endpoint, drt.instance_id)
        await publisher.start()
        for ev in pending_events:
            publisher.publish(ev)
        engine.allocator.event_sink = publisher.publish
    else:
        engine.allocator.event_sink = None
    pending_events.clear()

    metrics_pub = WorkerMetricsPublisher(drt, endpoint, drt.instance_id, engine.stats)
    await metrics_pub.start()

    # prometheus surface for the engine counters (system-status /metrics
    # when DYN_SYSTEM_PORT is set — the deploy/metrics grafana dashboard
    # reads these; the discovery metrics topic above feeds router/planner)
    _stats_snap = {"t": 0.0, "v": {}}

    def _snap_stat(k):
        # one engine.stats() per scrape, shared across the gauges (each
        # gauge callback fires within the same render pass)
        now = time.monotonic()
        if now - _stats_snap["t"] > 0.5:
            _stats_snap["v"] = engine.stats()
            _stats_snap["t"] = now
        return float(_stats_snap["v"].get(k, 0) or 0)

    # registry-driven export (runtime/metrics.py METRICS export=True):
    # a stat added to the registry with export=True becomes a
    # dynamo_worker_<name> gauge here without touching this file, and
    # the met-registry dynolint rule retires the 'published on the
    # metrics topic but never exported to prometheus' drift class
    for _stat in worker_exported_stats():
        # registry prepends the "dynamo" prefix -> dynamo_worker_<stat>
        drt.metrics.callback_gauge(
            f"worker_{_stat}", f"engine stat {_stat}",
            (lambda k=_stat: _snap_stat(k)),
        )

    model_name = args.model_name or args.model

    def make_card() -> ModelDeploymentCard:
        # only decode/aggregated workers front the model (reference: the
        # prefill pool is internal, reached by decode orchestration).
        # Publication is deferred until AFTER serve_endpoint below: the
        # card is what makes frontends build a pipeline, so the instance
        # must already be live (and warmup done) when it appears.
        return ModelDeploymentCard(
            name=model_name,
            # the card's tokenizer is the SERVING contract: frontend
            # tokenization and the engine's guided-decoding FSM must agree
            # on the id↔text mapping, so GGUF checkpoints advertise their
            # embedded vocab
            tokenizer=f"gguf:{gguf_path}" if gguf_path is not None else "byte",
            kv_cache_block_size=args.page_size,
            context_length=args.context_length or args.max_model_len,
            migration_limit=args.migration_limit,
            lora_adapters=engine.lora_names(),
        )

    prefill_client = None
    disagg_router = None
    _queue_watch_task = None
    _set_watch_task = None
    if args.role in ("prefill", "decode"):
        # built for BOTH disagg roles: a prefill worker can be morphed
        # into a decode worker at runtime, and then needs the conditional-
        # disagg wiring live (the handler gates on state["role"])
        from dynamo_tpu.llm.disagg import DisaggConfig, DisaggregatedRouter

        prefill_ep = (
            drt.namespace(args.namespace)
            .component(args.prefill_component)
            .endpoint(args.endpoint)
        )
        prefill_client = await prefill_ep.client()
        disagg_router = DisaggregatedRouter(
            DisaggConfig(remote_prefill_threshold_tokens=args.disagg_threshold)
        )

        # conditional-disagg queue guard (reference disagg_router.rs:230
        # "prefill queue below limit"): watch the prefill pool's published
        # engine stats and feed the LEAST-loaded live worker's queue depth
        # into the router — remote prefill stops when the whole pool is
        # backed up
        async def _watch_prefill_queue():
            from dynamo_tpu.llm.kv_router.publisher import METRICS_TOPIC_FMT
            from dynamo_tpu.runtime import codec

            if drt.discovery is None:
                return
            sub = await drt.discovery.subscribe(
                METRICS_TOPIC_FMT.format(
                    namespace=args.namespace, component=args.prefill_component
                )
            )
            depths: dict[int, int] = {}
            announced = False
            async for payload in sub:
                try:
                    msg = codec.unpack(payload)
                    stats = msg.get("stats", {})
                    depths[int(msg["worker_id"])] = int(
                        stats.get(NUM_WAITING_REQS, 0)
                    ) + int(stats.get(NUM_RUNNING_REQS, 0))
                    live = set(prefill_client.instance_ids())
                    for w in list(depths):
                        if w not in live:
                            del depths[w]
                    if depths:
                        disagg_router.update_queue_depth(
                            min(depths[w] for w in depths)
                        )
                    else:
                        # no live publisher left: UNKNOWN, not "empty" —
                        # a fresh depth=0 would green-light remote prefill
                        # into a pool that just vanished
                        disagg_router.invalidate("no live prefill publishers")
                    if not announced:
                        announced = True
                        logger.info(
                            "prefill queue watcher active (%d worker(s), depth=%d)",
                            len(depths), disagg_router.prefill_queue_depth,
                        )
                except Exception:  # noqa: BLE001 — stats are advisory
                    logger.debug("bad prefill metrics message", exc_info=True)

        async def _watch_prefill_set():
            # role-flip staleness guard (docs/disagg_serving.md "Role
            # morphing"): the metrics loop above only wakes on PUBLISHED
            # messages, so when the prefill instance set changes shape —
            # a worker drained, died, or role-morphed in or out — the
            # last depth would otherwise hold sway until the TTL aged it
            # out. Watch the set itself and invalidate immediately.
            prev = set(prefill_client.instance_ids())
            while True:
                await asyncio.sleep(0.25)
                live = set(prefill_client.instance_ids())
                if live != prev:
                    disagg_router.invalidate(
                        f"prefill set changed {len(prev)}->{len(live)}"
                    )
                prev = live

        # owned by main(): strong refs (the event loop keeps only weak
        # refs), cancelled after wait_for_shutdown
        _queue_watch_task = asyncio.get_running_loop().create_task(
            _watch_prefill_queue()
        )
        _set_watch_task = asyncio.get_running_loop().create_task(
            _watch_prefill_set()
        )

    async def handler(request, context):
        if "worker_instance_id" in (request.get("annotations") or []):
            yield {"event": "worker_instance_id", "comment": [f"{drt.instance_id:x}"]}
        if "clear_kv_blocks" in (request.get("annotations") or []):
            # admin flush (reference service_v2.rs:319-339 clear-kv-blocks):
            # drop every unreferenced prefix-cache page (+ KVBM tiers)
            cleared = engine.clear_kv_blocks()
            yield {"event": "clear_kv_blocks", "comment": [str(cleared)]}
            return
        if state["role"] == "decode" and disagg_router is not None:
            from dynamo_tpu.jax_worker.disagg_handler import maybe_remote_prefill

            stream = maybe_remote_prefill(
                engine, prefill_client, disagg_router, request, context
            )
            async for item in stream:
                yield item
            return
        async for item in engine.generate(request, context):
            yield item

    # ---------------------------------------------------------------- #
    # live role morphing (docs/autoscaling.md "Role morphing"): a
    # `morph` control endpoint rides beside `generate`; the planner's
    # re-role arm calls it to convert this worker prefill<->decode
    # in-place — drain via StreamSevered tail-migration, flip the
    # discovery component + model card atomically with the drain, then
    # re-warm the incoming role's compile surfaces.
    # ---------------------------------------------------------------- #
    lanes: dict = {"component": component, "generate": None, "morph": None}

    async def _drop_card():
        if state["card_key"] is None:
            return
        drt._leased_keys.pop(state["card_key"], None)
        if drt.discovery is not None:
            await drt.discovery.delete(state["card_key"])
        state["card_key"] = None

    async def _apply_lanes(role: str):
        """Reconcile discovery registrations to `role`: move generate +
        morph endpoints to the role's component (new lanes born
        `morphing` until the morph commits), move the model card and the
        metrics/KV-events topics with them. Runs as the engine morph's
        on_flip hook — atomic with drain completion — and again (toward
        the OLD role) on rollback."""
        nonlocal metrics_pub, publisher
        from dynamo_tpu.runtime.component import STATE_MORPHING

        new_comp = role_component(role)
        if new_comp != lanes["component"]:
            gen_ep = (drt.namespace(args.namespace)
                      .component(new_comp).endpoint(args.endpoint))
            morph_ep = (drt.namespace(args.namespace)
                        .component(new_comp).endpoint("morph"))
            for name in ("generate", "morph"):
                if lanes[name] is not None:
                    await lanes[name].remove()
            lanes["generate"] = await gen_ep.serve_endpoint(handler)
            await lanes["generate"].set_state(STATE_MORPHING)
            lanes["morph"] = await morph_ep.serve_endpoint(morph_handler)
            await lanes["morph"].set_state(STATE_MORPHING)
            lanes["component"] = new_comp
            # load-signal + KV-event topics are per-component: re-home
            await metrics_pub.close()
            metrics_pub = WorkerMetricsPublisher(
                drt, gen_ep, drt.instance_id, engine.stats)
            await metrics_pub.start()
            if publisher is not None:
                await publisher.close()
                publisher = KvEventPublisher(drt, gen_ep, drt.instance_id)
                await publisher.start()
                engine.allocator.event_sink = publisher.publish
        if role != "prefill" and state["card_key"] is None:
            state["card_key"] = await register_llm(
                (drt.namespace(args.namespace)
                 .component(lanes["component"]).endpoint(args.endpoint)),
                make_card())
        elif role == "prefill":
            await _drop_card()

    async def _set_lane_states(st: str):
        for name in ("generate", "morph"):
            if lanes[name] is not None:
                await lanes[name].set_state(st)

    async def morph_handler(request, context):
        from dynamo_tpu.runtime import faults
        from dynamo_tpu.runtime.component import STATE_MORPHING, STATE_READY

        target = (request or {}).get("target_role", "")
        if target not in ("prefill", "decode"):
            yield {"error": f"bad target_role {target!r}"}
            return
        if args.role == "aggregated":
            yield {"error": "aggregated worker has no role to morph"}
            return
        if state["role"] == target:
            yield {"ok": True, "noop": True, "role": target}
            return
        old_role = state["role"]
        await _set_lane_states(STATE_MORPHING)
        try:
            summary = await engine.morph(
                target, on_flip=lambda: _apply_lanes(target))
        except faults.MorphCrash:
            raise
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — typed result for the planner
            # engine rolled back to old_role (drained sessions already
            # migrating to peers); restore the old lane set routable
            await _apply_lanes(old_role)
            await _set_lane_states(STATE_READY)
            yield {"error": f"morph rolled back: {type(e).__name__}: {e}"}
            return
        state["role"] = target
        await _set_lane_states(STATE_READY)
        yield {"ok": True, **summary}

    lanes["generate"] = await endpoint.serve_endpoint(handler)
    if args.role in ("prefill", "decode"):
        morph_ep = (drt.namespace(args.namespace)
                    .component(component).endpoint("morph"))
        lanes["morph"] = await morph_ep.serve_endpoint(morph_handler)
    if args.role != "prefill":
        state["card_key"] = await register_llm(endpoint, make_card())
    logger.info(
        "jax worker up: model=%s tp=%d role=%s instance=%x",
        model_name,
        args.tp_size,
        state["role"],
        drt.instance_id,
    )
    await drt.wait_for_shutdown()
    for t in (_queue_watch_task, _set_watch_task):
        if t is not None:
            t.cancel()
    # graceful drain: lease revoked first (routers stop picking us), then
    # in-flight streams finish within DYN_RUNTIME_GRACEFUL_SHUTDOWN_TIMEOUT,
    # then survivors are force-cancelled (runtime/component.py close())
    await drt.close()


if __name__ == "__main__":
    asyncio.run(main())
