"""Decode-side disaggregation orchestration.

Mirrors reference DecodeWorkerHandler.generate (vllm/handlers.py:164-270):
the decode worker decides (conditional disagg), calls the prefill pool with
max_tokens=1 + return_kv, receives the first token AND the prompt KV on the
same response stream (direct prefill→decode TCP hop — our NIXL), injects,
and continues decoding locally. Any prefill-path failure falls back to
local prefill, so disagg is strictly an optimization.
"""

from __future__ import annotations

import logging
from typing import Any, AsyncIterator

from dynamo_tpu.llm.disagg import DisaggregatedRouter, unpack_kv_payload
from dynamo_tpu.llm.protocols import Annotated, LLMEngineOutput
from dynamo_tpu.llm.tokens import compute_seq_hashes, salt_hash
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.push_router import PushRouter, RouterMode
from dynamo_tpu.runtime.request_plane import EngineError, StreamLost

logger = logging.getLogger(__name__)


async def maybe_remote_prefill(
    engine,
    prefill_client,
    disagg_router: DisaggregatedRouter,
    request: dict,
    context: Context,
) -> AsyncIterator[Any]:
    prompt = request.get("token_ids") or []
    page_size = engine.config.page_size
    # LoRA requests live on an adapter-salted hash chain (llm/tokens.py):
    # the cached-prefix probe must consult the SAME chain the engine's
    # prefix cache keys on, or the local/remote decision is wrong in both
    # directions for adapter traffic
    salt = (
        salt_hash(request["lora_name"].encode())
        if request.get("lora_name") else 0
    )
    hashes = compute_seq_hashes(prompt, page_size, salt)
    n_cached = len(engine.allocator.cached_prefix(hashes))
    if engine.kvbm is not None and n_cached < len(hashes):
        # blocks held in KVBM tiers — local, OR announced by a peer (G4
        # mesh) — onboard at admission; recomputing them remotely would
        # waste the prefill pool (reference G4 reuse flow,
        # block_manager/distributed/leader.rs:126)
        n_cached += len(engine.kvbm.probe(hashes[n_cached:]))
    cached_tokens = n_cached * page_size
    have_workers = bool(prefill_client and prefill_client.instance_ids())

    want_annotation = "remote_prefill" in (request.get("annotations") or [])
    # the scheduler's estimated local TTFT (queue depth x cost model)
    # augments the static token threshold once the cost model is warm —
    # a below-threshold prompt still offloads when the LOCAL queue would
    # spend the TTFT budget (sla policy only; fifo keeps the reference
    # threshold rule alone)
    est_ms = target_ms = None
    if engine.scheduler.policy == "sla":
        est_ms = engine.estimated_prefill_wait_ms(len(prompt) - cached_tokens)
        target_ms = engine.scheduler.sla.ttft_target_ms
    if not disagg_router.prefill_remote(
        len(prompt), cached_tokens, have_workers,
        local_ttft_est_ms=est_ms, ttft_target_ms=target_ms,
    ):
        if want_annotation:
            yield {"event": "remote_prefill", "comment": ["false"]}
        async for item in engine.generate(request, context):
            yield item
        return

    # --- remote prefill (reference handlers.py:192-246) ---
    from dynamo_tpu.runtime.config import env_bool

    want_stream = env_bool("DYN_DISAGG_STREAM", True)
    prefill_req = dict(request)
    stop = dict(prefill_req.get("stop_conditions") or {})
    orig_max_tokens = int(stop.get("max_tokens") or 128)
    stop["max_tokens"] = 1
    prefill_req["stop_conditions"] = stop
    # kv_pull: we can pull from the prefill worker's data plane (descriptor
    # rendezvous instead of an inline payload); workers without a data plane
    # answer inline anyway, so this is a capability hint, not a demand.
    # kv_stream: we can ALSO consume the early-staged streamed handoff —
    # the prefill worker ships the descriptor at admission and publishes
    # chunks as prefill commits pages, so our pull overlaps its compute
    # (docs/disagg_serving.md)
    prefill_req["disagg_params"] = {
        "return_kv": True, "kv_pull": True, "kv_stream": want_stream,
    }

    first_token = None
    first_lp = None
    first_top = None
    kv_payload = None
    early = None  # StreamedPullHandle once the early descriptor arrives
    try:
        try:
            router = PushRouter(prefill_client, RouterMode.ROUND_ROBIN)
            stream = await router.generate(prefill_req, context.child())
            async for item in stream:
                data = item.get("data") if isinstance(item, dict) else None
                if not data:
                    continue
                kvp = data.get("kv_transfer_params")
                if not kvp:
                    continue
                if not data.get("token_ids"):
                    # EARLY streamed descriptor (no token yet): start
                    # pulling the prefill worker's committed chunks now,
                    # while it is still computing
                    pull = kvp.get("pull") or {}
                    if want_stream and early is None and pull.get("streamed"):
                        try:
                            early = engine.begin_streamed_pull(
                                request, context, pull
                            )
                        except Exception:  # noqa: BLE001 — early start is
                            # an optimization; the final descriptor covers
                            logger.exception("early streamed pull not started")
                            early = None
                    continue
                kv_payload = kvp
                first_token = data["token_ids"][0]
                first_lp = (data.get("log_probs") or [None])[0]
                first_top = (data.get("top_logprobs") or [None])[0]
        except (StreamLost, EngineError) as e:
            logger.warning("remote prefill failed (%s); falling back to local", e)

        if kv_payload is None or first_token is None:
            if early is not None:
                early.abort()
                early = None
            if want_annotation:
                yield {"event": "remote_prefill", "comment": ["false"]}
            async for item in engine.generate(request, context):
                yield item
            return

        if want_annotation:
            yield {"event": "remote_prefill", "comment": ["true"]}
        # emit the prefill-produced first token to the caller (with its
        # logprob when the request asked — the lists must stay aligned)
        yield Annotated(data=LLMEngineOutput(
            token_ids=[first_token],
            log_probs=[first_lp] if first_lp is not None else None,
            top_logprobs=[first_top] if first_top else None,
        ).to_dict()).to_dict()
        pull = kv_payload.get("pull") or {}
        if early is not None and pull.get("transfer_id") == early.transfer_id:
            # streamed handoff: the early pull has been injecting chunks
            # since admission — hand it the token and continue decoding
            early.set_first_token(first_token)
            handle, early = early, None
            stream = handle.stream()
        elif "pull" in kv_payload:
            # the transfer was (re)staged serially (early stage died or
            # was preempted): the early pull is stale — abandon it
            if early is not None:
                early.abort()
                early = None
            stream = engine.generate_decode_from_pull(
                request, context, first_token, kv_payload["pull"]
            )
        else:
            if early is not None:
                early.abort()
                early = None
            my_fmt = getattr(engine.config, "kv_quant", "none") or "none"
            if str(kv_payload.get("fmt", "none")) != my_fmt:
                # mixed-precision fleet (typed, not silent): the prefill
                # worker ships a different quantized page layout — refuse
                # the payload, count it, and prefill locally from the
                # already-emitted first token instead of injecting
                # misread bytes
                engine.kv_format_mismatches += 1
                logger.warning(
                    "disagg kv payload fmt=%r != local kv_quant=%r; "
                    "prefilling locally", kv_payload.get("fmt"), my_fmt,
                )
                stream = engine.generate_decode_resume(
                    request, context, first_token
                )
            else:
                kv_k, kv_v, n_tokens = unpack_kv_payload(kv_payload)
                stream = engine.generate_decode_from_kv(
                    request, context, first_token, kv_k, kv_v, n_tokens
                )
        async for item in stream:
            yield item
    finally:
        # handler cancelled (client vanished) with an unresolved early
        # pull: the slot must not wait on a first token that never comes
        if early is not None:
            early.abort()
