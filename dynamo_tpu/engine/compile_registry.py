"""COMPILE_SURFACES: the compile contract, one entry per staged surface.

Every jit/pjit/shard_map/pallas_call-staged computation in engine/, ops/,
models/, llm/, and planner/ is named here with the contract the
`comp-*` dynolint rules enforce:

  module   repo-relative file the staged callsite lives in
  kind     "jit" | "pjit" | "shard_map" | "pallas_call"
  donate   donate_argnums the callsite must declare, () for none.
           Donation is the TPU memory-headroom lever (a decode block
           donates the KV pool so XLA aliases instead of copying ~GBs),
           and also the sharp edge comp-donation-safety guards: reading
           a donated buffer in the caller after the call returns is
           silent wrong data.
  static   static_argnames/static_argnums the callsite must declare.
  axes     operand-shape dimensions that select the compile variant,
           mapped to the bound that keeps the variant space finite.
           Purely documentary (rendered into docs/compilation.md); the
           enforcement lives in comp-shape-bucketing's taint analysis
           against bucketing.BUCKETING_HELPERS.
  warmup   True when the surface serves the request path and must be
           reachable from JaxEngine.warmup's compile drive — a
           serving-reachable variant missing from warmup is a 20-40s
           cold-compile TTFT spike on a live fleet (comp-warmup-coverage).
           False for offline tools (planner profiler) and surfaces only
           reached by KV-transfer RPCs, which compile on first use by
           design.
  dispatch optional alternate caller-side names (the engine stores
           `spec_block` as `self._spec_block_fn`); `_<key>` is always
           accepted without being spelled.
  help     one line for the generated docs table.

Parsed from the AST, NEVER imported (the ENV_REGISTRY / KNOWN_FAULT_POINTS
/ GUARDED_STATE / METRICS discipline: the checker runs on hosts without
jax importable), so every value must stay a pure literal. The runtime
reads its own copy of the surface names through
`JaxEngine._compiled_surfaces` (engine.py) — the comp-surface-registry
rule is what keeps this table and the code from drifting apart.
"""

COMPILE_SURFACES = {
    # ----------------------------------------------------------------- #
    # engine/engine.py — the serving dispatch closures built in _compile()
    # ----------------------------------------------------------------- #
    "decode_block": {
        "module": "dynamo_tpu/engine/engine.py",
        "kind": "jit",
        "donate": (1, 2, 8, 9),
        "static": (),
        "axes": {
            "B": "config.max_num_seqs (fixed lane count)",
            "K": "config.decode_block_steps (fused steps)",
        },
        "warmup": True,
        "help": "K fused decode steps over all lanes; one variant total "
                "(two bodies: pool-local vs per-step scatter, picked by "
                "decode_pool_mode at compile time)",
    },
    "spec_block": {
        "module": "dynamo_tpu/engine/engine.py",
        "kind": "jit",
        "donate": (1, 2, 8, 9),
        "static": (),
        "axes": {
            "B": "config.max_num_seqs",
            "S": "config.spec_rounds (draft-verify rounds)",
        },
        "warmup": True,
        "dispatch": ("_spec_block_fn",),
        "help": "speculative decode: S n-gram draft-verify rounds per "
                "dispatch",
    },
    "prefill_batch": {
        "module": "dynamo_tpu/engine/engine.py",
        "kind": "jit",
        "donate": (1, 2, 9),
        "static": (),
        "axes": {
            "lanes": "plan_prefill (1 or per-bucket lane cap)",
            "bucket": "plan_prefill (config.prefill_buckets ladder)",
            "P": "min(next_pow2(pages), config.max_pages_per_seq) + 1",
        },
        "warmup": True,
        "help": "chunked batched prefill; variant per (bucket, lanes, "
                "page-table bucket)",
    },
    "mixed_step": {
        "module": "dynamo_tpu/engine/engine.py",
        "kind": "jit",
        "donate": (1, 2, 12),
        "static": (),
        "axes": {
            "N": "plan_mixed / min(next_pow2(tokens), aligned "
                 "config.mixed_max_tokens)",
            "R": "next_pow2(config.max_num_seqs * (1 + spec_draft_len if "
                 "spec_mode else 1) + config.max_prefill_batch) — spec "
                 "verify rows share the lane row budget",
            "P": "min(next_pow2(pages), config.max_pages_per_seq) + 1",
        },
        "warmup": True,
        "help": "ragged prefill+decode fusion over the token dimension "
                "(plain and pure-spec packs; spec lanes pack 1+d verify "
                "rows)",
    },
    "mixed_step_variant": {
        "module": "dynamo_tpu/engine/engine.py",
        "kind": "jit",
        "donate": (1, 2, 12),
        "static": (),
        "axes": {
            "N": "plan_mixed / min(next_pow2(tokens), aligned "
                 "config.mixed_max_tokens)",
            "R": "next_pow2(config.max_num_seqs * (1 + spec_draft_len if "
                 "spec_mode else 1) + config.max_prefill_batch)",
            "P": "min(next_pow2(pages), config.max_pages_per_seq) + 1",
            "V8": "(vocab_size + 7) // 8 (packed per-row grammar mask; "
                  "all-ones rows are exact no-ops)",
            "rank": "pool r_max (fixed device adapter stack; operand "
                    "present only when adapters are registered)",
        },
        "warmup": True,
        "help": "fused mixed step with per-row FSM mask and adapter-index "
                "operands — guided/lora rows ride the same flat buffer",
    },
    "prefill_batch_mm": {
        "module": "dynamo_tpu/engine/engine.py",
        "kind": "jit",
        "donate": (1, 2, 9),
        "static": (),
        "axes": {
            "lanes": "plan_prefill",
            "bucket": "plan_prefill",
            "E": "vit config.n_patches (fixed embed count)",
        },
        "warmup": True,
        "help": "prefill with multimodal embedding scatter into the token "
                "stream",
    },
    "decode_step_guided": {
        "module": "dynamo_tpu/engine/engine.py",
        "kind": "jit",
        "donate": (1, 2, 8, 10),
        "static": (),
        "axes": {
            "B": "config.max_num_seqs",
            "V8": "(vocab_size + 7) // 8 (packed grammar mask)",
        },
        "warmup": True,
        "help": "single guided-decoding step with grammar-mask logit "
                "filtering",
    },
    "decode_step_guided_lora": {
        "module": "dynamo_tpu/engine/engine.py",
        "kind": "jit",
        "donate": (1, 2, 8, 10),
        "static": (),
        "axes": {
            "B": "config.max_num_seqs",
            "V8": "(vocab_size + 7) // 8",
            "rank": "config.lora_rank (fixed)",
        },
        "warmup": True,
        "help": "guided step through per-lane LoRA deltas",
    },
    "prefill_batch_guided": {
        "module": "dynamo_tpu/engine/engine.py",
        "kind": "jit",
        "donate": (1, 2, 9),
        "static": (),
        "axes": {
            "lanes": "plan_prefill",
            "bucket": "plan_prefill",
            "V8": "(vocab_size + 7) // 8",
        },
        "warmup": True,
        "help": "batched prefill whose last-token logits pass the grammar "
                "mask",
    },
    "decode_block_lora": {
        "module": "dynamo_tpu/engine/engine.py",
        "kind": "jit",
        "donate": (1, 2, 8, 9),
        "static": (),
        "axes": {
            "B": "config.max_num_seqs",
            "K": "config.decode_block_steps",
            "rank": "config.lora_rank (fixed)",
        },
        "warmup": True,
        "help": "K fused decode steps through per-lane LoRA deltas",
    },
    "prefill_batch_lora": {
        "module": "dynamo_tpu/engine/engine.py",
        "kind": "jit",
        "donate": (1, 2, 9),
        "static": (),
        "axes": {
            "lanes": "plan_prefill",
            "bucket": "plan_prefill",
            "rank": "config.lora_rank (fixed)",
        },
        "warmup": True,
        "help": "batched prefill through per-lane LoRA deltas",
    },
    "prefill_single": {
        "module": "dynamo_tpu/engine/engine.py",
        "kind": "jit",
        "donate": (1, 2, 7),
        "static": (),
        "axes": {
            "T": "next_pow2(chunk) rounded to sp/pp unit "
                 "(admission-bounded prompt)",
            "P": "min(next_pow2(pages), config.max_pages_per_seq) + 1",
        },
        "warmup": True,
        "help": "whole-prompt single-sequence prefill through the ring/"
                "pipeline parallel path (compiled only when sp/pp > 1)",
    },
    "patch_lanes": {
        "module": "dynamo_tpu/engine/engine.py",
        "kind": "jit",
        "donate": (),
        "static": (),
        "axes": {"B": "config.max_num_seqs"},
        "warmup": True,
        "help": "masked on-device swap of per-lane decode state at slot "
                "turnover (no donation: old carry is the fallback for "
                "unmasked lanes)",
    },
    "extract_pages": {
        "module": "dynamo_tpu/engine/engine.py",
        "kind": "jit",
        "donate": (),
        "static": (),
        "axes": {"n": "gather width = len(page_ids) (pow2-bucketed by "
                      "the KV-transfer batcher)"},
        "warmup": False,
        "help": "KV page gather for migration/offload export; reached "
                "only by KV-transfer RPCs, compiles on first transfer",
    },
    "inject_pages": {
        "module": "dynamo_tpu/engine/engine.py",
        "kind": "jit",
        "donate": (0, 1),
        "static": (),
        "axes": {"n": "scatter width = len(page_ids)"},
        "warmup": False,
        "help": "KV page scatter for migration/onboard import; donates "
                "the pool (aliased in-place update)",
    },
    # ----------------------------------------------------------------- #
    # ops/ — attention kernels (jit wrappers staging pallas_call bodies)
    # ----------------------------------------------------------------- #
    "paged_attention_decode_pallas_local": {
        "module": "dynamo_tpu/ops/pallas_paged_attention.py",
        "kind": "jit",
        "donate": (),
        "static": ("interpret",),
        "axes": {
            "B": "caller lane count (engine: config.max_num_seqs)",
            "pages": "caller page-table bucket",
        },
        "warmup": True,
        "help": "fused decode attention merging block-local K/V with the "
                "paged pool (decode_pool_mode=local)",
    },
    "paged_attention_decode_pallas": {
        "module": "dynamo_tpu/ops/pallas_paged_attention.py",
        "kind": "jit",
        "donate": (),
        "static": ("interpret",),
        "axes": {
            "B": "caller lane count",
            "pages": "caller page-table bucket",
        },
        "warmup": True,
        "help": "paged flash decode attention over the scattered pool",
    },
    "ragged_paged_attention_pallas": {
        "module": "dynamo_tpu/ops/pallas_ragged_attention.py",
        "kind": "jit",
        "donate": (),
        "static": ("interpret",),
        "axes": {
            "N": "caller token bucket (mixed_step N)",
            "tiles": "N / ragged_tile_q(dtype)",
        },
        "warmup": True,
        "help": "ragged paged attention over mixed prefill+decode token "
                "rows",
    },
    "paged_prefill_attention_pallas_batched": {
        "module": "dynamo_tpu/ops/pallas_prefill_attention.py",
        "kind": "jit",
        "donate": (),
        "static": ("interpret",),
        "axes": {
            "B": "caller lane count",
            "T": "caller chunk bucket",
        },
        "warmup": True,
        "help": "batched causal prefill attention against the paged pool",
    },
    "ring_attention_local": {
        "module": "dynamo_tpu/ops/ring_attention.py",
        "kind": "shard_map",
        "donate": (),
        "static": (),
        "axes": {
            "T/sp": "sequence shard = caller T / config.sp_size",
        },
        "warmup": True,
        "dispatch": ("_ring_attention_local",),
        "help": "sequence-parallel ring attention shard program "
                "(prefill_single path, sp > 1)",
    },
    # ----------------------------------------------------------------- #
    # llm/ — multimodal encoder
    # ----------------------------------------------------------------- #
    "vit_encode": {
        "module": "dynamo_tpu/llm/multimodal.py",
        "kind": "jit",
        "donate": (),
        "static": (),
        "axes": {
            "px": "(num_channels, image_size, image_size) — config-fixed, "
                  "one variant",
        },
        "warmup": True,
        "dispatch": ("_fwd",),
        "help": "ViT image-to-embedding forward; single config-fixed "
                "pixel shape",
    },
    # ----------------------------------------------------------------- #
    # planner/ — offline profiler (not serving-path; no warmup claim)
    # ----------------------------------------------------------------- #
    "profiler_prefill": {
        "module": "dynamo_tpu/planner/profiler.py",
        "kind": "jit",
        "donate": (1, 2),
        "static": (),
        "axes": {"isl": "isl_grid sweep points (offline, one compile per "
                        "grid point by design)"},
        "warmup": False,
        "dispatch": ("prefill",),
        "help": "offline prefill timing probe for the planner's "
                "interpolation tables",
    },
    "profiler_decode_step": {
        "module": "dynamo_tpu/planner/profiler.py",
        "kind": "jit",
        "donate": (1, 2),
        "static": (),
        "axes": {"B": "derived batch per (context, kv_usage) grid point "
                      "(offline sweep)"},
        "warmup": False,
        "dispatch": ("decode_step",),
        "help": "offline batched-decode timing probe",
    },
}
