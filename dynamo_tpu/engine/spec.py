"""Self-drafting speculative decoding, fully on-device (TPU-first).

Reference contract: speculative decoding is a first-class engine
capability with SpecDecodeStats metrics
(/root/reference/lib/bindings/python/src/dynamo/_core.pyi:269-301); the
reference delegates the mechanism to vLLM/TRT-LLM (EAGLE / draft models).
Here the TPU engine implements n-gram **prompt-lookup** drafting natively:
no draft model, pure win for repetition/prefix-heavy serving, and the
verify pass turns d+1 weight streams into ONE — exactly what a
weight-bandwidth-bound TPU decode wants.

Design (engine.py `_dev_spec_block` wires these into the fused block):
  * a [B, H] ring buffer of recent token ids lives ON DEVICE (position p
    at slot p % H), appended as the block decodes — drafting never causes
    a host round-trip, matching the engine's one-fetch-per-block design.
  * `ngram_draft`: for each lane, find the most recent occurrence of the
    current n-gram in the ring, propose the d tokens that followed it.
  * verify = the existing batched-prefill program over a [B, 1+d] chunk
    (computes logits AND writes KV for every position in one pass);
    rejected positions' KV is invisible (seq_len bounds attention) and is
    overwritten by the next round's chunk at the same positions.
  * `verify_accept`: longest-accepted-prefix + replacement/bonus token.
    Greedy lanes accept on argmax match — output is TOKEN-IDENTICAL to
    the non-speculative engine (tests assert this). Sampled lanes use
    point-mass-draft rejection sampling (accept draft t with prob
    p_target(t); on rejection sample from p with the draft's mass
    removed), which preserves the target distribution exactly — both
    evaluated on the same top-K candidate set the normal sampler uses,
    so the spec path samples from the *same* distribution.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .sampling import TOPK_CAP, SamplingParams


def hist_write(hist: jax.Array, positions: jax.Array, tokens: jax.Array) -> jax.Array:
    """Write per-lane `tokens` at ring slot `positions % H`."""
    B, H = hist.shape
    return hist.at[jnp.arange(B), positions % H].set(tokens)


def ngram_draft(
    hist: jax.Array,  # [B, H] ring: token at position p lives at p % H
    tokens: jax.Array,  # [B] current token (position pos_cur, already in hist)
    pos_cur: jax.Array,  # [B] absolute position of the current token
    n: int,  # n-gram length to match (query = last n tokens incl. current)
    d: int,  # draft length
) -> jax.Array:
    """[B, d] drafted continuation token ids.

    The query n-gram ends at pos_cur. In ring space an n-gram ending at
    slot j occupies slots j-n+1..j (mod H) regardless of absolute
    position, so matching is a position-independent AND over n rolled
    views — O(B*H*n) comparisons, trivial on the VPU. The most recent
    match wins; lanes with no match draft the current token repeated
    (cheap, and rejection costs nothing extra)."""
    B, H = hist.shape
    # match mask over ring slots: m[b, j] = hist n-gram ending at slot j
    # equals the query n-gram ending at pos_cur
    m = jnp.ones((B, H), bool)
    for k in range(n):
        q_k = hist[jnp.arange(B), (pos_cur - k) % H]  # [B]
        rolled = jnp.roll(hist, k, axis=1)  # rolled[:, j] = hist[:, j-k mod H]
        m &= rolled == q_k[:, None]
    # absolute position mapped to slot j: largest p <= pos_cur with p%H==j
    j_grid = jnp.arange(H)[None, :]
    p_j = pos_cur[:, None] - jnp.mod(pos_cur[:, None] - j_grid, H)  # [B, H]
    # need a full n-gram (p >= n-1) and at least one continuation token
    # strictly before the current position (exclude the trivial self-match)
    valid = (p_j >= n - 1) & (p_j < pos_cur[:, None])
    score = jnp.where(m & valid, p_j, -1)
    p_star = jnp.max(score, axis=1)  # [B]; -1 = no match
    # no match: point at pos_cur-1 so every draft token gathers the
    # current token (guaranteed present in the ring)
    p_star = jnp.where(p_star < 0, pos_cur - 1, p_star)
    # continuation tokens at positions p*+1 .. p*+d, clamped to pos_cur
    # (tokens past the known history repeat the latest known token)
    cont = p_star[:, None] + 1 + jnp.arange(d)[None, :]  # [B, d]
    cont = jnp.minimum(cont, pos_cur[:, None])
    return hist[jnp.arange(B)[:, None], cont % H]


def _candidate_probs(logits: jax.Array, samp: SamplingParams):
    """Shared candidate-set filtering, matching sampling.sample() exactly:
    per row, top-K candidates -> temperature -> top-k mask -> top-p mask.
    logits: [B, T, V]. Returns (cand_idx [B,T,K], probs [B,T,K] — filtered
    + renormalized, greedy rows get a one-hot on candidate 0)."""
    B, T, V = logits.shape
    flat = logits.reshape(B * T, V)
    if V > 4096:
        cand_logits, cand_idx = jax.lax.approx_max_k(flat, min(TOPK_CAP, V))
    else:
        cand_logits, cand_idx = jax.lax.top_k(flat, min(TOPK_CAP, V))
    K = cand_logits.shape[1]
    cand_logits = cand_logits.reshape(B, T, K)
    cand_idx = cand_idx.reshape(B, T, K)

    temp = jnp.maximum(samp.temperature, 1e-6)[:, None, None]
    scaled = cand_logits / temp
    k_eff = jnp.where(
        (samp.top_k <= 0) | (samp.top_k > K), K, samp.top_k
    )[:, None, None]
    rank = jnp.arange(K)[None, None, :]
    scaled = jnp.where(rank < k_eff, scaled, -jnp.inf)
    probs = jax.nn.softmax(scaled, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < samp.top_p[:, None, None]
    probs = jnp.where(keep, probs, 0.0)
    probs = probs / jnp.maximum(probs.sum(-1, keepdims=True), 1e-20)
    greedy = samp.temperature[:, None, None] <= 0.0
    onehot0 = (rank == 0).astype(probs.dtype) * jnp.ones_like(probs)
    probs = jnp.where(greedy, onehot0, probs)
    return cand_idx, probs


def verify_accept(
    logits: jax.Array,  # [B, d+1, V] chunk logits (index t predicts pos+t+1)
    draft: jax.Array,  # [B, d] drafted token ids
    samp: SamplingParams,
    key: jax.Array,
):
    """Longest-accepted-prefix acceptance.

    Returns (out_toks [B, d+1], n_emit [B]): out_toks[:, :n_emit] are the
    tokens to emit this round — accepted drafts followed by one
    replacement (sampled from the leftover distribution at the first
    rejection) or one bonus token (all drafts accepted). 1 <= n_emit <=
    d+1 always: rejection never emits fewer tokens than plain decode."""
    B, T, V = logits.shape
    d = T - 1
    cand_idx, probs = _candidate_probs(logits, samp)  # [B, T, K]
    K = probs.shape[-1]

    # draft token's target probability (0 when outside the candidate set —
    # the non-spec sampler can't produce it either, so rejecting is exact)
    in_cand = cand_idx[:, :d, :] == draft[:, :, None]  # [B, d, K]
    p_draft = jnp.sum(jnp.where(in_cand, probs[:, :d, :], 0.0), axis=-1)

    key, k_u, k_repl = jax.random.split(key, 3)
    u = jax.random.uniform(k_u, (B, d))
    # point-mass draft: accept w.p. p_target(draft). Strict < so p=0 never
    # accepts (u==0.0 exists in [0,1)) and p=1 always does (u<1 surely) —
    # greedy lanes' one-hot probs make this an exact argmax match test.
    accept = u < p_draft
    acc_prefix = jnp.cumprod(accept.astype(jnp.int32), axis=1)  # [B, d]
    n_acc = jnp.sum(acc_prefix, axis=1)  # [B]

    # replacement/bonus sampling per chunk index: leftover distribution =
    # probs with the draft token's mass removed (renormalized); the bonus
    # index d has no draft -> plain probs
    probs_left = jnp.where(
        jnp.pad(in_cand, ((0, 0), (0, 1), (0, 0))), 0.0, probs
    )
    probs_left = probs_left / jnp.maximum(
        probs_left.sum(-1, keepdims=True), 1e-20
    )
    # greedy lanes: leftover must still argmax the ORIGINAL candidates
    # (greedy "rejection" means argmax != draft; the replacement is that
    # argmax, which removal could have zeroed). Restore plain probs.
    greedy = samp.temperature[:, None, None] <= 0.0
    probs_left = jnp.where(greedy, probs, probs_left)
    gumbel = -jnp.log(-jnp.log(
        jax.random.uniform(k_repl, probs_left.shape, minval=1e-20, maxval=1.0)
    ))
    masked = jnp.where(probs_left > 0, jnp.log(probs_left) + gumbel, -jnp.inf)
    repl_pos = jnp.argmax(masked, axis=-1)  # [B, T]
    repl = jnp.take_along_axis(cand_idx, repl_pos[..., None], axis=-1)[..., 0]

    t_grid = jnp.arange(T)[None, :]
    out_toks = jnp.where(
        t_grid < n_acc[:, None],
        jnp.pad(draft, ((0, 0), (0, 1))),  # accepted drafts
        repl,  # replacement at the first rejection / bonus at index d
    )
    n_emit = n_acc + 1
    return out_toks, n_emit, key
