"""JaxEngine: the TPU-native inference engine.

The role vLLM plays under the reference (SURVEY.md §7 step 4), built the XLA
way: everything on the token hot path is a pre-compiled static-shape program.

  * decode: ONE jitted step for the whole slot batch [max_num_seqs] — paged
    attention + on-device sampling; KV buffers donated so XLA updates in
    place. Inactive slots write to a reserved scratch page and are masked.
  * prefill: chunked + bucketed (compile once per bucket size); a chunk
    attends to its own causal block plus already-written pages, enabling
    prefix-cache hits and bounded step latency (the reference gets this from
    vLLM's chunked prefill; here it is native).
  * prefix cache: PageAllocator keys pages by the SAME chained block hashes
    the KV router indexes (llm/tokens.py), and emits stored/removed events.
  * host scheduler: admission by free pages + slots; continuous batching —
    each loop iteration runs at most one prefill chunk, then one decode step
    for all active slots.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, AsyncIterator, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..llm.mocker.kv_manager import KvEvent
from ..llm.protocols import Annotated, LLMEngineOutput, PreprocessedRequest
from ..llm.tokens import TokenBlockSequence, compute_seq_hashes
from ..models import llama
from ..runtime.engine import Context
from .config import EngineConfig
from .kv_cache import PageAllocator, alloc_kv_arrays
from .sampling import SamplingParams, sample

logger = logging.getLogger(__name__)

SCRATCH_PAGE = 0  # physical page 0 is the dump target for masked lanes


@dataclass
class _Slot:
    """One decode slot (host bookkeeping)."""

    request_id: str
    queue: asyncio.Queue
    context: Context
    prompt: List[int]
    max_tokens: int
    min_tokens: int
    eos_ids: List[int]
    ignore_eos: bool
    stop_token_ids: List[int]
    seq: TokenBlockSequence
    pages: List[int] = field(default_factory=list)
    committed_hashes: List[int] = field(default_factory=list)
    prefill_pos: int = 0
    generated: int = 0
    last_token: int = 0
    slot_idx: int = -1
    done: bool = False
    return_kv: bool = False  # prefill role: ship KV pages with the 1st token
    preloaded: Optional[tuple] = None  # decode role: (first_tok, k, v, n_tokens)
    onboard: Optional[tuple] = None  # KVBM tier hit: (alloc_pages, hashes)


class JaxEngine:
    """Continuous-batching JAX engine with the MockEngine-compatible
    `generate(request, context)` interface."""

    def __init__(
        self,
        config: EngineConfig,
        model_config: Optional[llama.LlamaConfig] = None,
        params: Optional[dict] = None,
        kv_sharding=None,
        event_sink: Optional[Callable[[KvEvent], None]] = None,
    ):
        self.config = config
        self.model_config = model_config or _resolve_model(config.model)
        c = self.model_config
        # family dispatch: MoeConfig subclasses LlamaConfig, and models/moe.py
        # exposes the same init/decode/prefill signatures
        from ..models import moe

        self._model = moe if isinstance(c, moe.MoeConfig) else llama
        key = jax.random.PRNGKey(config.seed)
        self.params = params if params is not None else self._model.init_params(c, key)
        # +1: physical page 0 is scratch
        self.kv_k, self.kv_v = alloc_kv_arrays(
            c.num_layers,
            config.num_pages + 1,
            config.page_size,
            c.num_kv_heads,
            c.head_dim,
            dtype=c.dtype,
            sharding=kv_sharding,
        )
        self.allocator = PageAllocator(
            config.num_pages, config.page_size, event_sink=event_sink
        )
        # KVBM host/disk tiers (kvbm/): write-through offload of committed
        # blocks, onboard at admission when the device prefix cache misses
        self.kvbm = None
        if config.kvbm_host_blocks > 0 or config.kvbm_disk_blocks > 0:
            from ..kvbm import KvBlockManager, KvbmConfig, KvbmConnector

            block_shape = (c.num_layers, config.page_size, c.num_kv_heads, c.head_dim)
            np_dtype = np.dtype(jnp.zeros((), c.dtype).dtype)
            manager = KvBlockManager(
                KvbmConfig(
                    host_blocks=config.kvbm_host_blocks,
                    disk_blocks=config.kvbm_disk_blocks,
                    disk_path=config.kvbm_disk_path,
                ),
                block_shape,
                np_dtype,
            )
            self.kvbm = KvbmConnector(self, manager)
        # shift page ids by +1 so allocator page 0 -> physical page 1
        B, P = config.max_num_seqs, config.max_pages_per_seq
        self.page_tables = np.zeros((B, P), np.int32)
        self.seq_lens = np.zeros((B,), np.int32)
        self.tokens = np.zeros((B,), np.int32)
        self.temps = np.zeros((B,), np.float32)
        self.top_ks = np.zeros((B,), np.int32)
        self.top_ps = np.ones((B,), np.float32)
        self.slots: List[Optional[_Slot]] = [None] * B
        self._free_slots = list(range(B - 1, -1, -1))
        self._waiting: List[_Slot] = []
        self._step_task: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()
        self._closed = False
        self._rng = jax.random.PRNGKey(config.seed + 1)
        self._step_counter = 0
        self.num_requests = 0
        # all device calls run on this single thread so XLA compiles (which
        # can take tens of seconds) never stall the asyncio event loop —
        # heartbeats/leases/streams stay live during compilation
        import concurrent.futures

        self._device_exec = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="jax-step"
        )
        self._compile()

    # ------------------------------------------------------------------ #
    # compiled programs
    # ------------------------------------------------------------------ #

    def _compile(self):
        c = self.model_config
        cfg = self.config

        @partial(jax.jit, donate_argnums=(1, 2))
        def decode_step(params, kv_k, kv_v, tokens, positions, page_tables, seq_lens, samp, key):
            logits, kv_k, kv_v = self._model.decode_forward(
                params, c, tokens, positions, kv_k, kv_v, page_tables, seq_lens
            )
            next_tokens = sample(logits, samp, key)
            return next_tokens, kv_k, kv_v

        self._decode_step = decode_step

        @partial(jax.jit, donate_argnums=(1, 2), static_argnums=(8,))
        def prefill_step(params, kv_k, kv_v, tokens, positions, page_table, ctx_len, last_idx, _bucket):
            logits, kv_k, kv_v = self._model.prefill_forward(
                params, c, tokens, positions, kv_k, kv_v, page_table, ctx_len, last_idx
            )
            return logits, kv_k, kv_v

        self._prefill_step = prefill_step

        @jax.jit
        def sample_one(logits, samp, key):
            return sample(logits[None, :], samp, key)[0]

        self._sample_one = sample_one

        # disagg KV movement (host-staged; llm/disagg.py wire format)
        @jax.jit
        def extract_pages(kv_k, kv_v, page_ids):
            return kv_k[:, page_ids], kv_v[:, page_ids]

        self._extract_pages = extract_pages

        @partial(jax.jit, donate_argnums=(0, 1))
        def inject_pages(kv_k, kv_v, page_ids, data_k, data_v):
            return (
                kv_k.at[:, page_ids].set(data_k),
                kv_v.at[:, page_ids].set(data_v),
            )

        self._inject_pages = inject_pages

    # ------------------------------------------------------------------ #
    # lifecycle / interface (MockEngine-compatible)
    # ------------------------------------------------------------------ #

    def start(self):
        if self._step_task is None:
            self._step_task = asyncio.create_task(self._step_loop())

    async def close(self):
        self._closed = True
        self._wake.set()
        if self._step_task:
            self._step_task.cancel()
        if self.kvbm is not None:
            # drain in-flight write-through offloads, then persist G3 index
            for _ in range(500):
                if self.kvbm._pending == 0:
                    break
                await asyncio.sleep(0.01)
            self.kvbm.manager.flush()

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        self.start()
        req = (
            request
            if isinstance(request, PreprocessedRequest)
            else PreprocessedRequest.from_dict(request)
        )
        stop = req.stop_conditions or {}
        sampling = req.sampling_options or {}
        slot = _Slot(
            request_id=req.request_id or f"jax-{self.num_requests}",
            queue=asyncio.Queue(),
            context=context,
            prompt=list(req.token_ids),
            max_tokens=int(stop.get("max_tokens") or 128),
            min_tokens=int(stop.get("min_tokens") or 0),
            eos_ids=list(req.eos_token_ids or []),
            ignore_eos=bool(stop.get("ignore_eos")),
            stop_token_ids=list(stop.get("stop_token_ids") or []),
            seq=TokenBlockSequence(req.token_ids, self.config.page_size),
        )
        slot.temperature = float(sampling.get("temperature", self.config.default_temperature) or 0.0)
        slot.top_k = int(sampling.get("top_k") or 0)
        slot.top_p = float(sampling.get("top_p") or 1.0)
        disagg = req.disagg_params or {}
        slot.return_kv = bool(disagg.get("return_kv"))
        if len(slot.prompt) + slot.max_tokens > self.config.max_model_len:
            slot.max_tokens = max(self.config.max_model_len - len(slot.prompt), 1)
        self.num_requests += 1
        self._waiting.append(slot)
        self._wake.set()
        try:
            while True:
                item = await slot.queue.get()
                if item is None:
                    return
                yield item
        finally:
            slot.done = True
            self._wake.set()

    async def generate_decode_from_kv(
        self,
        request: Any,
        context: Context,
        first_token: int,
        kv_k_pages,
        kv_v_pages,
        n_tokens: int,
    ) -> AsyncIterator[dict]:
        """Disagg decode entry: continue decoding from remotely-prefilled KV
        (reference decode-with-kv_transfer_params, handlers.py:258-270).
        The first token was already produced by the prefill worker and is
        NOT re-emitted here."""
        self.start()
        req = (
            request
            if isinstance(request, PreprocessedRequest)
            else PreprocessedRequest.from_dict(request)
        )
        stop = req.stop_conditions or {}
        sampling = req.sampling_options or {}
        slot = _Slot(
            request_id=(req.request_id or f"jax-{self.num_requests}") + "-d",
            queue=asyncio.Queue(),
            context=context,
            prompt=list(req.token_ids),
            max_tokens=int(stop.get("max_tokens") or 128),
            min_tokens=int(stop.get("min_tokens") or 0),
            eos_ids=list(req.eos_token_ids or []),
            ignore_eos=bool(stop.get("ignore_eos")),
            stop_token_ids=list(stop.get("stop_token_ids") or []),
            seq=TokenBlockSequence(req.token_ids, self.config.page_size),
        )
        slot.temperature = float(sampling.get("temperature", self.config.default_temperature) or 0.0)
        slot.top_k = int(sampling.get("top_k") or 0)
        slot.top_p = float(sampling.get("top_p") or 1.0)
        slot.preloaded = (first_token, kv_k_pages, kv_v_pages, n_tokens)
        self.num_requests += 1
        self._waiting.append(slot)
        self._wake.set()
        try:
            while True:
                item = await slot.queue.get()
                if item is None:
                    return
                yield item
        finally:
            slot.done = True
            self._wake.set()

    def stats(self) -> dict:
        alloc_stats = self.allocator.stats()
        running = sum(1 for s in self.slots if s is not None)
        out = {
            "num_waiting_reqs": len(self._waiting),
            "num_running_reqs": running,
            "gpu_cache_usage_perc": self.allocator.active_pages / self.allocator.num_pages,
            "request_total_slots": self.config.max_num_seqs,
            **alloc_stats,
        }
        if self.kvbm is not None:
            out.update(self.kvbm.stats())
        return out

    # ------------------------------------------------------------------ #
    # step loop
    # ------------------------------------------------------------------ #

    async def _step_loop(self):
        while not self._closed:
            has_active = any(s is not None for s in self.slots)
            if not self._waiting and not has_active:
                self._wake.clear()
                await self._wake.wait()
                continue
            try:
                did_prefill = await self._admit_and_prefill()
                did_decode = await self._decode_all()
            except Exception as e:  # noqa: BLE001 — engine loop must not die silently
                logger.exception("engine step failed; failing active requests")
                self._fail_all(f"engine step failed: {type(e).__name__}: {e}")
                await asyncio.sleep(0.1)
                continue
            # yield to the event loop so streams flush between steps
            await asyncio.sleep(0)

    # -- admission + chunked prefill ------------------------------------ #

    async def _run_on_device(self, fn, *args):
        return await asyncio.get_running_loop().run_in_executor(
            self._device_exec, fn, *args
        )

    async def _admit_and_prefill(self) -> bool:
        cfg = self.config
        # admit waiting requests into free slots
        still: List[_Slot] = []
        for slot in self._waiting:
            if slot.done or slot.context.is_stopped():
                self._emit_finish(slot, "cancelled")
                continue
            if not self._free_slots:
                still.append(slot)
                continue
            if not self._try_admit(slot):
                still.append(slot)
                continue
        self._waiting = still

        # inject one preloaded (disagg-transferred) slot per iteration
        for slot in self.slots:
            if slot is not None and slot.preloaded is not None:
                await self._inject_preloaded(slot)
                return True
        # inject one KVBM onboard (G2/G3 tier hit) per iteration
        for slot in self.slots:
            if slot is not None and slot.onboard is not None:
                await self._inject_onboard(slot)
                return True
        # run ONE prefill chunk for the first slot still prefilling
        for slot in self.slots:
            if slot is None or slot.prefill_pos >= len(slot.prompt):
                continue
            await self._prefill_chunk(slot)
            return True
        return False

    def _try_admit(self, slot: _Slot) -> bool:
        cfg = self.config
        if slot.preloaded is not None:
            # disagg decode role: all prompt pages fresh; KV arrives by
            # injection, not prefill
            n_pages = (len(slot.prompt) + cfg.page_size - 1) // cfg.page_size
            if not self.allocator.can_allocate(n_pages + 1):
                return False
            fresh = self.allocator.alloc_fresh(n_pages)
            if fresh is None:
                return False
            idx = self._free_slots.pop()
            slot.slot_idx = idx
            slot.pages = fresh
            slot.committed_hashes = []
            slot.prefill_pos = len(slot.prompt)
            self.slots[idx] = slot
            self.page_tables[idx, :] = SCRATCH_PAGE
            self.page_tables[idx, : len(fresh)] = [p + 1 for p in fresh]
            self.seq_lens[idx] = 0
            self.temps[idx] = slot.temperature
            self.top_ks[idx] = slot.top_k
            self.top_ps[idx] = slot.top_p
            return True
        hashes = slot.seq.block_hashes()
        cached_pages = (
            self.allocator.acquire_cached(hashes) if cfg.enable_prefix_caching else []
        )
        n_cached = len(cached_pages)
        # KVBM: probe G2/G3 for the hashes the device cache missed; tier hits
        # are injected before prefill (onboard), extending the cached prefix
        onboard_hashes: List[int] = []
        if self.kvbm is not None and cfg.enable_prefix_caching:
            prompt_full_blocks = len(slot.prompt) // cfg.page_size
            onboard_hashes = self.kvbm.probe(hashes[n_cached:prompt_full_blocks])
        n_onboard = len(onboard_hashes)
        # allocate the prompt's remaining pages now; generation pages grow later
        prompt_pages = (len(slot.prompt) + cfg.page_size - 1) // cfg.page_size
        fresh_prompt = max(prompt_pages - n_cached, 0)
        if not self.allocator.can_allocate(fresh_prompt + 1):
            self.allocator.release(cached_pages, hashes[:n_cached])
            return False
        fresh = self.allocator.alloc_fresh(fresh_prompt)
        if fresh is None:
            self.allocator.release(cached_pages, hashes[:n_cached])
            return False
        idx = self._free_slots.pop()
        slot.slot_idx = idx
        slot.pages = cached_pages + fresh
        slot.committed_hashes = hashes[:n_cached]
        slot.prefill_pos = (n_cached + n_onboard) * cfg.page_size
        if n_onboard:
            slot.onboard = (fresh[:n_onboard], onboard_hashes)
        # skip-ahead: if the whole prompt is cached, recompute the last token
        # (need its logits) — back off one position
        if slot.prefill_pos >= len(slot.prompt):
            slot.prefill_pos = len(slot.prompt) - 1
        self.slots[idx] = slot
        # host state
        self.page_tables[idx, :] = SCRATCH_PAGE
        phys = [p + 1 for p in slot.pages]  # +1: scratch shift
        self.page_tables[idx, : len(phys)] = phys
        self.seq_lens[idx] = 0
        self.temps[idx] = slot.temperature
        self.top_ks[idx] = slot.top_k
        self.top_ps[idx] = slot.top_p
        return True

    def _bucket_for(self, n: int) -> int:
        for b in self.config.prefill_buckets:
            if n <= b:
                return b
        return self.config.prefill_buckets[-1]

    async def _prefill_chunk(self, slot: _Slot):
        cfg = self.config
        c = self.model_config
        remaining = len(slot.prompt) - slot.prefill_pos
        chunk = min(remaining, cfg.max_prefill_chunk)
        bucket = self._bucket_for(chunk)
        start = slot.prefill_pos
        toks = slot.prompt[start : start + chunk]
        positions = list(range(start, start + chunk))
        # pad to bucket; pads write to the tail logical page -> scratch
        pad = bucket - chunk
        pad_pos = cfg.max_pages_per_seq * cfg.page_size - 1
        toks = toks + [0] * pad
        positions = positions + [pad_pos] * pad

        def run_prefill():
            table = jnp.asarray(self.page_tables[slot.slot_idx])
            return self._prefill_step(
                self.params,
                self.kv_k,
                self.kv_v,
                jnp.asarray(np.array(toks, np.int32)),
                jnp.asarray(np.array(positions, np.int32)),
                table,
                jnp.asarray(start, jnp.int32),
                chunk - 1,
                bucket,
            )

        logits, self.kv_k, self.kv_v = await self._run_on_device(run_prefill)
        slot.prefill_pos += chunk
        if slot.prefill_pos >= len(slot.prompt):
            # prompt done: commit full prompt blocks to the prefix cache
            self._commit_blocks(slot)
            # sample the first token from the last real position's logits
            self._rng, sub = jax.random.split(self._rng)
            samp = SamplingParams(
                temperature=jnp.asarray([slot.temperature], jnp.float32),
                top_k=jnp.asarray([slot.top_k], jnp.int32),
                top_p=jnp.asarray([slot.top_p], jnp.float32),
            )
            first = int(
                await self._run_on_device(self._sample_one, logits, samp, sub)
            )
            if slot.return_kv:
                # prefill role: ship the prompt KV with the first token and
                # finish (reference: prefill returns kv_transfer_params,
                # handlers.py:297-306; here the payload IS the transfer)
                await self._emit_prefill_result(slot, first)
                return
            self._emit_token(slot, first)
            if not slot.done:
                slot.last_token = first
                slot.generated = 1
                slot.seq.append(first)
                self.tokens[slot.slot_idx] = first
                self.seq_lens[slot.slot_idx] = len(slot.prompt) + 1
                self._maybe_finish(slot, first)

    async def _emit_prefill_result(self, slot: _Slot, first_token: int):
        from ..llm.disagg import pack_kv_payload

        cfg = self.config
        n_prompt_pages = (len(slot.prompt) + cfg.page_size - 1) // cfg.page_size
        page_ids = np.array(
            [p + 1 for p in slot.pages[:n_prompt_pages]], np.int32
        )  # +1 scratch shift

        def run_extract():
            k, v = self._extract_pages(self.kv_k, self.kv_v, jnp.asarray(page_ids))
            return np.asarray(k), np.asarray(v)

        k_np, v_np = await self._run_on_device(run_extract)
        payload = pack_kv_payload(k_np, v_np, len(slot.prompt), cfg.page_size)
        if not slot.done:
            out = LLMEngineOutput(
                token_ids=[first_token],
                finish_reason="remote_prefill_done",
                kv_transfer_params=payload,
            ).to_dict()
            slot.queue.put_nowait(Annotated(data=out).to_dict())
            slot.queue.put_nowait(None)
            slot.done = True
        self._release_slot(slot)

    async def _inject_preloaded(self, slot: _Slot):
        """Decode role: write transferred KV pages into our cache and enter
        the decode batch as if we had prefilled locally."""
        first_token, k_np, v_np, n_tokens = slot.preloaded
        slot.preloaded = None
        cfg = self.config
        page_ids = np.array([p + 1 for p in slot.pages], np.int32)

        def run_inject():
            kv_k, kv_v = self._inject_pages(
                self.kv_k,
                self.kv_v,
                jnp.asarray(page_ids),
                jnp.asarray(k_np),
                jnp.asarray(v_np),
            )
            return kv_k, kv_v

        self.kv_k, self.kv_v = await self._run_on_device(run_inject)
        # transferred prompt KV is now reusable: publish it to the prefix cache
        self._commit_blocks(slot)
        slot.prefill_pos = len(slot.prompt)
        slot.generated = 1
        slot.last_token = first_token
        slot.seq.append(first_token)
        self.tokens[slot.slot_idx] = first_token
        self.seq_lens[slot.slot_idx] = len(slot.prompt) + 1
        self._maybe_finish(slot, first_token)

    async def _inject_onboard(self, slot: _Slot):
        """KVBM onboard: scatter G2/G3 blocks into the freshly allocated
        device pages, then register them in the device prefix cache so
        concurrent sequences share them."""
        alloc_pages, hashes = slot.onboard
        slot.onboard = None
        try:
            # tier reads (host memcpy / disk memmap) run off the event loop,
            # serialized with offload stores on the same executor
            k_np, v_np = await self._run_on_device(self.kvbm.load, hashes)
        except KeyError as e:
            # block evicted between probe and load: fall back to computing
            # that part of the prompt (pages are already allocated)
            logger.warning("KVBM onboard miss: %s; prefilling instead", e)
            n_known = len(slot.committed_hashes)
            slot.prefill_pos = n_known * self.config.page_size
            return
        # [n, layers, page, heads, dim] -> [layers, n, page, heads, dim]
        k_np = k_np.swapaxes(0, 1)
        v_np = v_np.swapaxes(0, 1)
        phys = np.array([p + 1 for p in alloc_pages], np.int32)  # scratch shift

        def run_inject():
            kv_k, kv_v = self._inject_pages(
                self.kv_k,
                self.kv_v,
                jnp.asarray(phys),
                jnp.asarray(k_np),
                jnp.asarray(v_np),
            )
            return kv_k, kv_v

        self.kv_k, self.kv_v = await self._run_on_device(run_inject)
        n_known = len(slot.committed_hashes)
        token_blocks = [
            b.tokens for b in slot.seq.blocks[n_known : n_known + len(hashes)]
        ]
        parent = slot.committed_hashes[-1] if slot.committed_hashes else None
        self.allocator.commit_hashes(alloc_pages, hashes, token_blocks, parent)
        slot.committed_hashes.extend(hashes)
        # (whole-prompt clamp already applied at admission, _try_admit)

    def _commit_blocks(self, slot: _Slot):
        """Bind filled prompt pages to their hashes -> prefix cache + events."""
        hashes = slot.seq.block_hashes()
        n_known = len(slot.committed_hashes)
        prompt_full_blocks = len(slot.prompt) // self.config.page_size
        new_hashes = hashes[n_known:prompt_full_blocks]
        if new_hashes:
            pages = slot.pages[n_known : n_known + len(new_hashes)]
            token_blocks = [
                b.tokens for b in slot.seq.blocks[n_known : n_known + len(new_hashes)]
            ]
            parent = slot.committed_hashes[-1] if slot.committed_hashes else None
            self.allocator.commit_hashes(pages, new_hashes, token_blocks, parent)
            slot.committed_hashes.extend(new_hashes)
            if self.kvbm is not None:
                self.kvbm.offload_commit(new_hashes, [p + 1 for p in pages])

    # -- decode ---------------------------------------------------------- #

    def _active_decode_indices(self) -> List[int]:
        out = []
        for i, slot in enumerate(self.slots):
            if slot is None:
                continue
            if slot.prefill_pos >= len(slot.prompt) and slot.generated > 0:
                out.append(i)
        return out

    async def _decode_all(self) -> bool:
        active = self._active_decode_indices()
        if not active:
            return False
        cfg = self.config
        # grow pages for slots whose next write crosses a page boundary.
        # seq_lens counts tokens INCLUDING the pending (last-sampled) token,
        # whose KV is written this step at position seq_len - 1.
        for i in active:
            slot = self.slots[i]
            pos = int(self.seq_lens[i]) - 1  # write position this step
            needed_pages = pos // cfg.page_size + 1
            while len(slot.pages) < needed_pages:
                fresh = self.allocator.alloc_fresh(1)
                if fresh is None:
                    # out of pages: finish with length (simplest backpressure;
                    # real preemption lands with the KVBM tiers)
                    self._emit_finish(slot, "length")
                    self._release_slot(slot)
                    break
                slot.pages.extend(fresh)
                self.page_tables[i, len(slot.pages) - 1] = fresh[0] + 1

        active = self._active_decode_indices()
        if not active:
            return False

        B = cfg.max_num_seqs
        positions = np.zeros((B,), np.int32)
        mask = np.zeros((B,), bool)
        for i in active:
            positions[i] = self.seq_lens[i] - 1  # pending token's position
            mask[i] = True
        seq_lens_step = np.where(mask, self.seq_lens, 0).astype(np.int32)

        self._rng, sub = jax.random.split(self._rng)

        def run_decode():
            samp = SamplingParams(
                temperature=jnp.asarray(self.temps),
                top_k=jnp.asarray(self.top_ks),
                top_p=jnp.asarray(self.top_ps),
            )
            next_tokens, kv_k, kv_v = self._decode_step(
                self.params,
                self.kv_k,
                self.kv_v,
                jnp.asarray(self.tokens),
                jnp.asarray(positions),
                jnp.asarray(self.page_tables),
                jnp.asarray(seq_lens_step),
                samp,
                sub,
            )
            return np.asarray(next_tokens), kv_k, kv_v

        next_np, self.kv_k, self.kv_v = await self._run_on_device(run_decode)
        self._step_counter += 1

        for i in active:
            slot = self.slots[i]
            if slot is None:
                continue
            if slot.done or slot.context.is_stopped():
                self._emit_finish(slot, "cancelled")
                self._release_slot(slot)
                continue
            tok = int(next_np[i])
            slot.seq.append(tok)
            slot.generated += 1
            slot.last_token = tok
            self.tokens[i] = tok
            self.seq_lens[i] += 1
            self._emit_token(slot, tok)
            self._maybe_finish(slot, tok)
        return True

    def _fail_all(self, message: str):
        """A step raised: the batch state is unreliable. Error every live
        request so callers can migrate/retry rather than hang."""
        for slot in list(self.slots):
            if slot is not None:
                if not slot.done:
                    slot.queue.put_nowait(Annotated.from_error(message).to_dict())
                    slot.queue.put_nowait(None)
                    slot.done = True
                self._release_slot(slot)
        for slot in self._waiting:
            if not slot.done:
                slot.queue.put_nowait(Annotated.from_error(message).to_dict())
                slot.queue.put_nowait(None)
                slot.done = True
        self._waiting = []

    # -- emission / teardown --------------------------------------------- #

    def _emit_token(self, slot: _Slot, token: int):
        if slot.done:
            return
        out = LLMEngineOutput(token_ids=[token]).to_dict()
        slot.queue.put_nowait(Annotated(data=out).to_dict())

    def _maybe_finish(self, slot: _Slot, token: int):
        finish = None
        if (
            not slot.ignore_eos
            and slot.generated >= slot.min_tokens
            and (token in slot.eos_ids or token in slot.stop_token_ids)
        ):
            finish = "eos"
        elif slot.generated >= slot.max_tokens:
            finish = "length"
        if finish:
            self._emit_finish(slot, finish)
            self._release_slot(slot)

    def _emit_finish(self, slot: _Slot, reason: str):
        if not slot.done:
            out = LLMEngineOutput(token_ids=[], finish_reason=reason).to_dict()
            slot.queue.put_nowait(Annotated(data=out).to_dict())
            slot.queue.put_nowait(None)
            slot.done = True

    def _release_slot(self, slot: _Slot):
        if slot.slot_idx >= 0 and self.slots[slot.slot_idx] is slot:
            # commit any full generated blocks before release so decode KV is
            # reusable (conversation prefix reuse)
            self._commit_generated_blocks(slot)
            self.allocator.release(slot.pages, slot.committed_hashes)
            self.slots[slot.slot_idx] = None
            self._free_slots.append(slot.slot_idx)
            self.page_tables[slot.slot_idx, :] = SCRATCH_PAGE
            self.seq_lens[slot.slot_idx] = 0
            slot.slot_idx = -1

    def _commit_generated_blocks(self, slot: _Slot):
        hashes = slot.seq.block_hashes()
        n_known = len(slot.committed_hashes)
        full_blocks = len(slot.seq.blocks)
        # only blocks whose pages exist
        max_by_pages = min(full_blocks, len(slot.pages))
        new_hashes = hashes[n_known:max_by_pages]
        if new_hashes:
            pages = slot.pages[n_known : n_known + len(new_hashes)]
            token_blocks = [
                b.tokens for b in slot.seq.blocks[n_known : n_known + len(new_hashes)]
            ]
            parent = slot.committed_hashes[-1] if slot.committed_hashes else None
            self.allocator.commit_hashes(pages, new_hashes, token_blocks, parent)
            slot.committed_hashes.extend(new_hashes)
            if self.kvbm is not None:
                self.kvbm.offload_commit(new_hashes, [p + 1 for p in pages])


def _resolve_model(name: str) -> llama.LlamaConfig:
    from ..models import moe

    registry = {
        "tiny": llama.LlamaConfig.tiny,
        "llama3-3b": llama.LlamaConfig.llama3_2_3b,
        "llama3-8b": llama.LlamaConfig.llama3_8b,
        "llama3-70b": llama.LlamaConfig.llama3_70b,
        "tiny-moe": moe.MoeConfig.tiny_moe,
        "mixtral-8x7b": moe.MoeConfig.mixtral_8x7b,
    }
    if name in registry:
        return registry[name]()
    raise ValueError(f"unknown model {name!r}; known: {sorted(registry)}")
