"""JaxEngine: the TPU-native inference engine.

The role vLLM plays under the reference (SURVEY.md §7 step 4), built the XLA
way: everything on the token hot path is a pre-compiled static-shape program,
and the host loop is designed around the observation that a synchronous
device round-trip costs ~10-100x an async dispatch (dispatches are cheap and
pipelined; host reads are the expensive unit):

  * decode: ONE jitted BLOCK of K steps for the whole slot batch
    [max_num_seqs] — paged attention + on-device sampling, the sampled token
    feeding the next step inside `lax.scan`. KV buffers are donated so XLA
    updates in place. Up to two blocks are kept in flight (the fetch of
    block i overlaps block i+1's compute), so steady-state decode costs ONE
    host read per K*B tokens.
  * prefill: chunked + bucketed + BATCHED — chunks from several waiting
    sequences are packed into one [B_pf, bucket] dispatch (compile variants
    are bounded: B_pf = budget/bucket), with the first token sampled
    on-device inside the same program. Chunks that do not complete a prompt
    need no host read at all.
  * all host reads of an iteration ride a single `jax.device_get` (one RTT).
  * prefix cache: PageAllocator keys pages by the SAME chained block hashes
    the KV router indexes (llm/tokens.py), and emits stored/removed events.
  * preemption: on page exhaustion the newest-admitted sequence is preempted
    — its full blocks are committed (cheap resume via prefix cache), pages
    released, and the request requeued; it resumes decoding from its pending
    token without re-emitting (reference semantics: vLLM preempt/requeue,
    lib/llm/src/mocker/scheduler.rs:240 watermark eviction).
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Any, AsyncIterator, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..llm.mocker.kv_manager import KvEvent
from ..llm.protocols import Annotated, LLMEngineOutput, PreprocessedRequest
from ..llm.tokens import TokenBlockSequence, compute_seq_hashes, salt_hash
from ..models import llama
from ..runtime import faults
from ..runtime.engine import Context
from ..runtime.request_plane import StreamSevered
from ..runtime.metrics import (
    NUM_RUNNING_REQS,
    NUM_WAITING_REQS,
    SCHED_EST_DECODE_TOK_S,
    SCHED_EST_PREFILL_TOK_S,
    SCHED_EST_REQ_MS,
    SCHED_EST_TTFT_MS,
)
from .bucketing import next_pow2 as _next_pow2
from .config import EngineConfig
from .kv_cache import PageAllocator, alloc_kv_arrays
from .sampling import SamplingParams, penalized, sample, sample_lp, unpack_mask
from .scheduler import SlaConfig, StepPlanner

logger = logging.getLogger(__name__)

SCRATCH_PAGE = 0  # physical page 0 is the dump target for masked lanes


def _enable_compile_cache():
    """Persistent XLA compilation cache: the engine compiles one variant per
    (prefill batch x bucket x table-length bucket) — cached across process
    restarts so only the first-ever run pays the 20-40s Mosaic compiles."""
    import os

    path = os.environ.get("DYNAMO_TPU_COMPILE_CACHE", "~/.cache/dynamo_tpu_xla")
    if not path or path.lower() == "off":
        return
    try:
        path = os.path.expanduser(path)
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001 — cache is an optimization, never fatal
        logger.warning("could not enable XLA compilation cache", exc_info=True)


def _kv_shard_div(kv_sharding) -> int:
    """How many devices each KV page is SPLIT across (1 when replicated).

    Derived from the sharding spec, not len(jax.devices()): a replicated
    pool puts the full page on every device, so free-memory math must not
    scale with device count (round-4 advisor medium #1)."""
    if kv_sharding is None:
        return 1
    div = 1
    for axes in kv_sharding.spec:
        if not axes:
            continue
        names = axes if isinstance(axes, tuple) else (axes,)
        for a in names:
            div *= int(kv_sharding.mesh.shape[a])
    return max(div, 1)


def _auto_num_pages(params, model_cfg, config: EngineConfig,
                    kv_sharding=None, multihost: bool = False) -> int:
    """Size the KV page pool from free device memory (the role vLLM's
    gpu_memory_utilization plays). Called with the weights already resident,
    so free = bytes_limit * DYN_HBM_UTILIZATION - bytes_in_use. Platforms
    without memory_stats (CPU, some tunneled runtimes) fall back to
    DYN_HBM_BYTES, then a platform guess (TPU), then a fixed test pool.

    All math is PER-DEVICE: free bytes on one device divided by this
    device's share of a page (the page axis may be sharded — see
    _kv_shard_div). `DYN_WORKERS_PER_DEVICE` > 1 splits the free pool
    between co-located workers sharing one chip (single-chip disagg);
    `DYN_HBM_RESERVE_MB` (default 512) holds back compile/activation
    workspace the post-weights snapshot can't see. In multihost mode the
    leader's result is broadcast so every process allocates identical KV
    shapes (dispatch replay requires it).

    The "scatter" decode KV-write strategy materializes pool-sized copies
    inside the fused block (see EngineConfig.decode_pool_mode), so it needs
    headroom for a second pool; "local" writes in place.
    """
    import os

    dev = jax.local_devices()[0]
    util = float(os.environ.get("DYN_HBM_UTILIZATION", "0.85"))
    reserve = int(float(os.environ.get("DYN_HBM_RESERVE_MB", "512")) * 2**20)
    workers = max(int(os.environ.get("DYN_WORKERS_PER_DEVICE", "1")), 1)
    limit = in_use = None
    try:
        ms = dev.memory_stats() or {}
        limit = ms.get("bytes_limit")
        in_use = ms.get("bytes_in_use")
    except Exception:  # noqa: BLE001 — stats are best-effort on any backend
        pass
    if limit is None and os.environ.get("DYN_HBM_BYTES"):
        limit = int(float(os.environ["DYN_HBM_BYTES"]))
    if limit is None and dev.platform == "tpu":
        limit = 16 * 1024**3  # v5e/v5lite HBM; override via DYN_HBM_BYTES
    if limit is None:
        n = 2048  # CPU/test fallback: the legacy fixed pool
    else:
        if in_use is None:
            # per-device resident weight bytes: sum THIS device's shards,
            # not global nbytes (a TP-sharded leaf holds 1/tp of its bytes
            # here; a replicated leaf holds all of them)
            in_use = 0
            for x in jax.tree_util.tree_leaves(params):
                try:
                    in_use += sum(
                        s.data.nbytes for s in x.addressable_shards
                        if s.device == dev
                    )
                except Exception:  # noqa: BLE001 — non-Array leaves
                    in_use += getattr(x, "nbytes", 0)
        from ..ops.kv_quant import kv_page_bytes, resolve_kv_quant

        # quantized pages shrink the per-page bytes (int8 ~2x, int4 ~4x
        # incl. the f32 per-head scales), so the SAME free-HBM budget
        # yields ~2x/4x the pages — the resident-session density win
        page_bytes = (
            2  # K and V
            * model_cfg.num_layers
            * kv_page_bytes(
                config.page_size, model_cfg.num_kv_heads,
                model_cfg.head_dim, model_cfg.dtype,
                resolve_kv_quant(config.kv_quant),
            )
        )
        page_bytes_dev = page_bytes // _kv_shard_div(kv_sharding)
        alloc_bytes_dev = page_bytes_dev
        if config.decode_pool_mode == "scatter":
            alloc_bytes_dev *= 2  # transient pool copy inside the fused block
        free = (int(limit * util) - int(in_use) - reserve) // workers
        n = free // alloc_bytes_dev
        logger.info(
            "auto-sized KV pool: %d pages (%.2f GiB resident of %.2f GiB free"
            " per device, mode=%s, workers/dev=%d)",
            n, n * page_bytes_dev / 2**30, free / 2**30,
            config.decode_pool_mode, workers,
        )
    if multihost:
        # every process must allocate identical KV shapes for dispatch
        # replay; the leader's sizing wins (followers may see different
        # free-memory snapshots — round-4 advisor medium #1). The floor
        # check comes AFTER the rendezvous: a process raising before it
        # would leave the others hung inside the collective.
        from jax.experimental import multihost_utils

        n = int(multihost_utils.broadcast_one_to_all(np.int32(n)))
    floor = config.max_num_seqs + 2  # at least one page per decode slot
    if n < floor:
        raise RuntimeError(
            f"KV pool auto-sizing found room for only {n} pages; reduce "
            "model size, quantize weights (--quantize int8), quantize the "
            "KV cache (DYN_KV_QUANT=int8/int4 — halves/quarters bytes per "
            "page), or lower max_num_seqs"
        )
    return int(n)


@dataclass
class _Slot:
    """One decode slot (host bookkeeping)."""

    request_id: str
    queue: asyncio.Queue
    context: Context
    prompt: List[int]
    max_tokens: int
    min_tokens: int
    eos_ids: List[int]
    ignore_eos: bool
    stop_token_ids: List[int]
    seq: TokenBlockSequence
    kv_prompt: List[int] = field(default_factory=list)  # tokens whose KV
    # prefill computes; == prompt for fresh slots, prompt+generated-minus-
    # pending for preempted slots
    pages: List[int] = field(default_factory=list)
    committed_hashes: List[int] = field(default_factory=list)
    prefill_pos: int = 0
    generated: int = 0
    last_token: int = 0
    slot_idx: int = -1
    admit_seq: int = 0  # admission order; preemption victims = newest
    done: bool = False
    resume_token: Optional[int] = None  # preempted: continue with this token
    return_kv: bool = False  # prefill role: ship KV pages with the 1st token
    kv_pull: bool = False  # prefill role: caller can pull via the data plane
    kv_stream: bool = False  # prefill role: caller wants the EARLY-staged
    # streamed handoff (descriptor ships at admission, chunks publish as
    # prefill commits pages — docs/disagg_serving.md)
    kv_stream_tid: Optional[str] = None  # live streamed stage's transfer id
    kv_stream_desc: Optional[dict] = None  # its descriptor (resent at emit)
    kv_holder: Optional[dict] = None  # router holder hint for peer onboard
    preloaded: Optional[tuple] = None  # decode role: (first_tok, k, v, n_tokens)
    pull_desc: Optional[dict] = None  # decode role: pull-path descriptor
    first_token_fut: Optional[asyncio.Future] = None  # decode role, streamed
    # handoff: resolves to the prefill-produced first token (None = abort)
    onboard: Optional[tuple] = None  # KVBM tier hit: (alloc_pages, hashes)
    mm: Optional[List[tuple]] = None  # multimodal splices: (position, emb [n, H])
    guided_fsm: Optional[Any] = None  # llm/guided.TokenFsm (structured output)
    guided_state: int = 0  # current FSM state; advanced per emitted token
    lora_idx: int = 0  # adapter slot in the engine's LoRA stack (0 = base)
    lora_name: str = ""  # adapter pinned in the LoraPool ("" = no pin);
    # the pin releases exactly once (finish/release clears the name)
    want_logprobs: bool = False  # attach sampled-token logprobs to emissions
    sample_seed: int = 0  # per-request sampling seed (SamplingParams.seed)
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    repetition_penalty: float = 1.0
    want_top_logprobs: int = 0  # top-k alternatives per token (max 5)
    # dynosched (engine/scheduler/): SLA bookkeeping. priority scales the
    # TTFT target (each +1 halves it); sched_deadline is the EDF key;
    # sched_skips counts dispatches this candidate was passed over (the
    # starvation guard's aging signal) and resets on every granted chunk.
    priority: int = 0
    arrival_s: float = 0.0
    sched_deadline: float = 0.0
    sched_skips: int = 0
    # dynogate tenant key (docs/overload.md): feeds the StepPlanner's
    # per-tenant fairness tiebreak; "" = the default tenant
    tenant: str = ""
    # migration retry ordinal (llm/migration.py RetryManager): > 0 means
    # this request resumes a stream a dead worker lost — the prompt is
    # the original prompt plus the already-emitted tokens. Admission
    # classifies the resume source (checkpoint/peer/local/recompute)
    # exactly once (a later preemption re-admit must not re-count).
    migration: int = 0
    migration_counted: bool = False


class StreamedPullHandle:
    """Decode-side handle for an early (streamed) disagg KV pull
    (docs/disagg_serving.md): the pull starts while the PREFILL worker is
    still computing, off its early-shipped descriptor. The disagg handler
    resolves the handle with the prefill's first token once it arrives
    (`set_first_token`), or abandons it (`abort`) when the prefill stream
    fails or the transfer was re-staged under a different id (preempt)."""

    def __init__(self, engine: "JaxEngine", slot: _Slot, transfer_id: str):
        self._engine = engine
        self._slot = slot
        # the handle owns its OWN reference to the future: the pull task
        # detaches slot.first_token_fut before awaiting it, and a
        # set_first_token/abort arriving after that detach (last chunk
        # landed before the handler processed the final event — the
        # exact overlap the feature maximizes) must still resolve it, or
        # the pull task awaits forever with the slot pinned
        self._fut = slot.first_token_fut
        self.transfer_id = transfer_id

    def set_first_token(self, token: int):
        if self._fut is not None and not self._fut.done():
            self._fut.set_result(int(token))

    def abort(self):
        """Abandon the early pull: the slot releases, any in-flight chunk
        injection unwinds, and the handler falls back to the serial /
        local path."""
        eng, slot = self._engine, self._slot
        if self._fut is not None and not self._fut.done():
            self._fut.set_result(None)
        slot.done = True
        if slot.slot_idx >= 0 and eng.slots[slot.slot_idx] is slot:
            eng._release_slot(slot)
        eng._wake.set()

    async def stream(self):
        """Consume the decode stream (same contract as engine.generate)."""
        slot = self._slot
        try:
            while True:
                item = await slot.queue.get()
                if item is None:
                    return
                yield item
        finally:
            slot.done = True
            self._engine._wake.set()


class JaxEngine:
    """Continuous-batching JAX engine with the MockEngine-compatible
    `generate(request, context)` interface."""

    def __init__(
        self,
        config: EngineConfig,
        model_config: Optional[llama.LlamaConfig] = None,
        params: Optional[dict] = None,
        kv_sharding=None,
        event_sink: Optional[Callable[[KvEvent], None]] = None,
        mesh=None,
        spmd=None,
        multihost: bool = False,
    ):
        """`mesh`+`kv_sharding`: jit programs with explicit out_shardings
        (host-fetched outputs replicated so host 0 can read them on a
        multi-host mesh). `spmd`: a parallel.multihost.StepBroadcaster —
        every device dispatch is mirrored to follower hosts, which replay
        it via `run_follower`. `multihost`: True when jax.distributed is
        active (disagg KV extraction then rides process_allgather)."""
        from ..ops.kv_quant import resolve_kv_quant

        kvq = resolve_kv_quant(config.kv_quant)
        if (
            config.decode_pool_mode is None or not config.decode_block_unroll
            or config.kv_quant != kvq
        ):
            # platform auto (EngineConfig docstring): local's once-per-block
            # pool write wins on TPU at production pool sizes; scatter
            # keeps CPU (tests/smoke) compile time sane. Resolve into a
            # COPY — the caller's config keeps its auto sentinels. The KV
            # quant mode (DYN_KV_QUANT) resolves here too so every later
            # consumer (pool sizing, KVBM block layout, wire descriptors)
            # reads one explicit spelling.
            import dataclasses as _dc

            mode = config.decode_pool_mode or (
                "local" if jax.devices()[0].platform == "tpu" else "scatter"
            )
            config = _dc.replace(
                config,
                decode_pool_mode=mode,
                decode_block_unroll=config.decode_block_unroll
                or (4 if mode == "local" else 1),
                kv_quant=kvq,
            )
        if kvq != "none":
            if config.pp_size > 1 or config.sp_size > 1 or config.tp_size > 1:
                raise ValueError(
                    "kv_quant requires tp_size == pp_size == sp_size == 1 "
                    "(per-page-per-head scale sharding is the multi-chip "
                    "follow-up); set DYN_KV_QUANT=none for parallel layouts"
                )
            if kv_sharding is not None or multihost:
                raise ValueError(
                    "kv_quant is incompatible with a sharded/multi-host KV "
                    "pool; set DYN_KV_QUANT=none"
                )
        self.config = config
        self._mesh = mesh
        self._spmd = spmd
        self._multihost = multihost
        self._kv_sharding = kv_sharding
        _enable_compile_cache()
        self.model_config = model_config or _resolve_model(config.model)
        c = self.model_config
        # family dispatch: MoeConfig subclasses LlamaConfig, and models/moe.py
        # exposes the same init/decode/prefill signatures
        from ..models import moe

        self._model = moe if isinstance(c, moe.MoeConfig) else llama
        key = jax.random.PRNGKey(config.seed)
        if params is None:
            params = self._model.init_params(c, key)
            if config.quantize == "int8":
                from ..models.quant import quantize_tree

                params = quantize_tree(params, consume=True)
            elif config.quantize:
                raise ValueError(f"unknown quantize mode {config.quantize!r}")
        self.params = params
        if config.num_pages <= 0:
            config.num_pages = _auto_num_pages(
                params, c, config, kv_sharding=kv_sharding, multihost=multihost
            )
        # +1: physical page 0 is scratch. If the layout shards the PAGE axis
        # (dp-attention: pages over ep), round the pool up to a shardable
        # size — the allocator still manages only num_pages, spares idle.
        total_pages = config.num_pages + 1
        if kv_sharding is not None and len(kv_sharding.spec) > 1 and kv_sharding.spec[1]:
            axes = kv_sharding.spec[1]
            names = axes if isinstance(axes, tuple) else (axes,)
            div = int(np.prod([kv_sharding.mesh.shape[a] for a in names]))
            total_pages = -(-total_pages // div) * div
        self.kv_k, self.kv_v = alloc_kv_arrays(
            c.num_layers,
            total_pages,
            config.page_size,
            c.num_kv_heads,
            c.head_dim,
            dtype=c.dtype,
            sharding=kv_sharding,
            kv_quant=config.kv_quant,
        )
        self.allocator = PageAllocator(
            config.num_pages, config.page_size, event_sink=event_sink
        )
        # KVBM host/disk tiers (kvbm/): write-through offload of committed
        # blocks, onboard at admission when the device prefix cache misses
        self.kvbm = None
        if config.kvbm_host_blocks > 0 or config.kvbm_disk_blocks > 0:
            from ..kvbm import KvBlockManager, KvbmConfig, KvbmConnector
            from ..ops.kv_quant import kv_page_bytes

            if config.kv_quant != "none":
                # quantized blocks tier NATIVELY as packed uint8 rows
                # (q bytes + per-page-per-head scales, ops/kv_quant.py):
                # G2/G3 capacity at fixed host/disk bytes and peer-pull
                # payloads shrink by the same 2x/4x as the device pool
                block_shape = (
                    c.num_layers,
                    kv_page_bytes(config.page_size, c.num_kv_heads,
                                  c.head_dim, c.dtype, config.kv_quant),
                )
                np_dtype = np.dtype(np.uint8)
            else:
                block_shape = (c.num_layers, config.page_size, c.num_kv_heads, c.head_dim)
                np_dtype = np.dtype(jnp.zeros((), c.dtype).dtype)
            manager = KvBlockManager(
                KvbmConfig(
                    host_blocks=config.kvbm_host_blocks,
                    disk_blocks=config.kvbm_disk_blocks,
                    disk_path=config.kvbm_disk_path,
                ),
                block_shape,
                np_dtype,
                kv_format=config.kv_quant,
            )
            self.kvbm = KvbmConnector(self, manager)
        # shift page ids by +1 so allocator page 0 -> physical page 1
        B, P = config.max_num_seqs, config.max_pages_per_seq
        self.page_tables = np.zeros((B, P), np.int32)
        self.seq_lens = np.zeros((B,), np.int32)
        self.tokens = np.zeros((B,), np.int32)
        self.temps = np.zeros((B,), np.float32)
        self.top_ks = np.zeros((B,), np.int32)
        self.top_ps = np.ones((B,), np.float32)
        self.seeds = np.zeros((B,), np.uint32)  # per-lane sampling seeds
        self.presence = np.zeros((B,), np.float32)
        self.frequency = np.zeros((B,), np.float32)
        self.repetition = np.ones((B,), np.float32)
        # recent-token ring per lane (penalties window; pad = -1). Host
        # mirror for reset/patch; the device copy rides the decode carry.
        self.recent = np.full((B, config.penalty_window), -1, np.int32)
        self.slots: List[Optional[_Slot]] = [None] * B
        self._free_slots = list(range(B - 1, -1, -1))
        self._waiting: List[_Slot] = []
        self._step_task: Optional[asyncio.Task] = None
        # strong refs to in-flight background pulls: the event loop only
        # keeps weak refs, and a GC'd pull task would strand its slot
        self._bg_tasks: set = set()
        self._wake = asyncio.Event()
        # optional llm.kv_transfer.KvDataPlaneServer (worker attaches it):
        # enables the descriptor/pull disagg path instead of inline payloads
        self.data_plane = None
        # multi-host shard rendezvous (worker wires these after the SPMD
        # followers connect): this host's id and the per-host data-plane
        # addresses [host0, host1, ...]. With these set, disagg KV moves
        # per-shard point-to-point — no process_allgather of the full pages,
        # no leader re-broadcast of KV bytes (reference scaling property:
        # NIXL point-to-point descriptors, block_manager/storage/nixl.rs)
        self.host_id = 0
        self.shard_addrs: Optional[List[str]] = None
        self._closed = False
        self._rng = jax.random.PRNGKey(config.seed + 1)
        self._step_counter = 0
        self.num_requests = 0
        self.num_preemptions = 0
        # decode-side data-plane counters (the serving side counts on the
        # KvDataPlaneServer): how many remote-prefill KV pulls actually
        # landed, and how many pages moved — the disagg tests assert on
        # these instead of grepping logs
        self.kv_pulls_completed = 0
        self.kv_pages_pulled = 0
        # typed mixed-precision rejections (kv_quant): a peer staging a
        # different KV page format is refused BEFORE any byte moves and
        # the request recomputes locally — counted so a misconfigured
        # fleet is visible, never silent (docs/kvbm.md mixed-fleet rules)
        self.kv_format_mismatches = 0
        # streamed disagg handoff (docs/disagg_serving.md): decode-side
        # evidence that KV transfer overlapped prefill — chunks that landed
        # BEFORE the prefill's first-token event, and handoffs where the
        # first token was already client-bound while the tail chunks were
        # still in flight (the serial path is structurally 0 on both)
        self.disagg_streamed_handoffs = 0
        self.disagg_chunks_before_first_token = 0
        self.disagg_first_token_before_last_chunk = 0
        # prefill-side: early-staged streamed transfers, and the ones that
        # died mid-stream and fell back to a fresh serial stage at emit
        self.kv_streamed_stages = 0
        self.kv_streamed_fallbacks = 0
        # blocks reused MID-prefix from concurrent same-prefix requests
        # (_try_skip_ahead; admission-time hits count in the allocator)
        self.prefix_skip_ahead_blocks = 0
        # KVBM tier-chain effectiveness (docs/kvbm.md): G1 = device prefix
        # cache hits at admission; misses = prompt blocks the device cache
        # could not serve (onboarded from G2/G3 or prefilled). Tier-level
        # G2/G3 hit counters live on the tiers themselves.
        self.kvbm_g1_hit_blocks = 0
        self.kvbm_g1_miss_blocks = 0
        # onboard latency histogram (ms buckets) + recompute comparison
        # inputs: the bench and planner read these to judge whether tier
        # onboarding actually beats recompute
        self._onboard_hist_bounds = (1.0, 5.0, 20.0, 100.0, 500.0)
        self.kvbm_onboard_hist = [0] * (len(self._onboard_hist_bounds) + 1)
        self.kvbm_onboard_ms_sum = 0.0
        self.kvbm_onboard_count = 0
        self._admit_counter = 0
        # dynosched (engine/scheduler/): the StepPlanner owns prefill
        # ordering and chunk budgeting; policy "fifo" (the default)
        # reproduces the legacy admit-order dispatch bit-for-bit (modulo
        # the batch-kind anti-starvation fairness fix, active under both
        # policies), "sla" spends explicit TTFT/ITL targets
        # (docs/scheduler.md). Its cost
        # model is fed by the _timed dispatch instrumentation below.
        self.scheduler = StepPlanner(
            config,
            SlaConfig.from_env(
                policy=config.sched_policy,
                ttft_target_ms=config.ttft_target_ms,
                itl_target_ms=config.itl_target_ms,
            ),
        )
        # ragged unified mixed dispatch (docs/ragged_attention.md): when
        # the planner has BOTH runnable prefill chunks and active decode
        # lanes, ONE flat ragged buffer + ONE device call replaces the
        # split prefill-batch + decode-block pair. Guided, multi-LoRA and
        # speculative rows fuse too (mask / adapter-index operands on the
        # variant program, spec lanes as 1+d one-token verify rows);
        # pp/sp configs keep the split path outright.
        from ..ops.paged_attention import _pallas_eligible
        from ..ops.pallas_ragged_attention import ragged_tile_q
        from ..runtime.config import env_bool

        self._mixed_enabled = (
            config.mixed_dispatch
            if config.mixed_dispatch is not None
            else env_bool("DYN_MIXED_DISPATCH", True)
        ) and config.pp_size == 1 and config.sp_size == 1
        # durable decode sessions (docs/fault_tolerance.md "Request
        # migration"): commit newly-FULL generated blocks during the step
        # loop rather than only at _release_slot, so a live session's
        # prefix is continuously visible to the prefix cache, the KVBM
        # offload pipeline, the announcement mesh and (when enabled) the
        # session-checkpoint replicator. The commit logic is the same
        # _commit_generated_blocks call release uses — byte-identical
        # blocks either way, incremental just runs it earlier.
        self._incremental_commit = (
            config.incremental_commit
            if config.incremental_commit is not None
            else env_bool("DYN_KV_INCREMENTAL_COMMIT", True)
        )
        # migration observability (ISSUE 15): what a worker death actually
        # cost. A resumed (migrated) request arrives with req.migration > 0;
        # at admission we classify the session-prefix source — checkpoint
        # (peer-replicated session blocks), peer (plain fabric pull),
        # local (own G1/G2/G3 copies), recompute (full prefill) — and count
        # the tokens that really had to be re-prefilled.
        self.migrations_resumed = 0
        self.migration_replayed_tokens = 0
        self.resume_source_checkpoint = 0
        self.resume_source_peer = 0
        self.resume_source_local = 0
        self.resume_source_recompute = 0
        # live role morphing (docs/autoscaling.md "Role morphing"): the
        # serving role + state machine position, mutated only inside
        # morph() (GUARDED_STATE "JaxEngine._role"/"._morph_state")
        self._role = config.role
        self._morph_state = "serving"
        self._severed_queues: List[asyncio.Queue] = []
        self.morphs_completed = 0
        self.morphs_rolled_back = 0
        self.morph_drained_sessions = 0
        self.morph_last_duration_s = 0.0
        # row-start alignment of the flat packer: the Pallas ragged kernel
        # needs q-tile-aligned rows; the XLA reference packs dense
        self._mixed_align = (
            ragged_tile_q(c.dtype) if _pallas_eligible(c.head_dim) else 1
        )
        # ONE fixed row bucket: the row axis only sizes scalar operands
        # (tables, sampling state), so a single padded variant is free —
        # compile variants stay (token bucket x table bucket). Under spec
        # every decode lane may pack 1 + spec_draft_len verify rows.
        rows_per_lane = 1 + (config.spec_draft_len if config.spec_mode else 0)
        self._mixed_row_bucket = _next_pow2(
            config.max_num_seqs * rows_per_lane + config.max_prefill_batch
        )
        # fused-vs-split visibility (stats() + jax_worker gauges): is the
        # fused path actually taken in production, and what padding does
        # each path pay per step
        self.mixed_steps = 0
        self.split_steps = 0
        self.mixed_padded_tokens = 0
        self.mixed_real_tokens = 0
        self.split_padded_tokens = 0
        self.split_real_tokens = 0
        # per-kind fused coverage (docs/observability.md): which row
        # classes actually ride the fused buffer, and what fraction of
        # fused-ELIGIBLE steps (mixed-shaped traffic) fused — the CI
        # blended smoke gates mixed_coverage_frac >= 0.9
        self.mixed_rows_plain = 0
        self.mixed_rows_guided = 0
        self.mixed_rows_spec = 0
        self.mixed_rows_lora = 0
        self._last_prefill_shape = None  # (padded, real) of the latest dispatch
        self._last_decode_shape = None
        # set by _dispatch_mixed when only the in-flight decode pipeline
        # blocks fusing: the step loop holds the split prefill one step so
        # the drained pipeline fuses next step instead
        self._mixed_wait_drain = False
        # speculative decoding (engine/spec.py): host mirror of the device
        # history ring + SpecDecodeStats counters (_core.pyi:269-301 role)
        self.hist = (
            np.zeros((config.max_num_seqs, config.spec_hist), np.int32)
            if config.spec_mode else None
        )
        self._hist_dev = None
        self.spec_num_drafts = 0
        self.spec_num_draft_tokens = 0
        self.spec_num_accepted_tokens = 0
        # guided decoding (llm/guided.py): tokenizer for vocab→FSM lift
        # (workers set this to the served model's tokenizer; defaults to
        # ByteTokenizer over the model vocab), lazily-built compiler, and
        # a requests counter for stats()
        self.tokenizer = None
        self._guided = None
        self.guided_requests = 0
        # multi-LoRA (models/lora.py): stacked adapters in HBM + per-lane
        # adapter index mirror (rides lora dispatch variants as an operand).
        # At fleet scale the stack is a FIXED-slot paging tier
        # (models/lora_pool.LoraPool) — adapter weights page HBM<->host on
        # demand, so "names" maps only the RESIDENT roster
        self._lora = None  # {"a": {...}, "b": {...}, "scale", "names"}
        self._lora_pool = None  # models/lora_pool.LoraPool when registered
        self.lora_idx = np.zeros((config.max_num_seqs,), np.int32)
        self.lora_requests = 0
        # per-dispatch-type device occupancy: {tag: (count, seconds)} —
        # dispatches run serialized on the single device thread, so these
        # sum to device-stream busy time (the serving-gap diagnostic)
        self._dev_time: Dict[str, tuple] = {}
        # emit batching (tokens-per-delta-batch): mean > 1 in steady decode
        # means the serving plane is getting whole blocks, not singletons —
        # the self-diagnosing coalescing signal on hardware e2e rows
        self.emit_batches = 0
        self.emit_tokens = 0
        # decode pipeline: device-resident carry (tokens/positions/seq_lens)
        # + up to two in-flight K-step blocks
        self._carry = None  # (tokens_dev, positions_dev, seq_lens_dev)
        self._carry_valid = False
        # per-lane dirt: admissions/finishes/page-growth touch only their
        # lanes via the patch program — a full invalidation would drain the
        # block pipeline and re-upload everything (the round-2 ITL gap)
        self._dirty_lanes: set = set()  # full lane state from host
        self._dirty_tables: set = set()  # page-table row only (lane carry
        # on device is NEWER than host and must not be overwritten)
        self._tables_dev = None
        self._samp_dev = None
        self._pen_dev = None  # [B, W] recent-token ring (penalties)
        self._inflight: deque = deque()  # [{"active": [...], "toks": dev[K,B]}]
        # pending prefill completions awaiting their first-token fetch
        self._pending_prefill: List[dict] = []
        # all device dispatches run on this single thread so XLA compiles
        # (which can take tens of seconds) never stall the asyncio event
        # loop; host reads run on a separate fetch thread so a blocking
        # device_get (~1 RTT) never delays the next dispatch
        import concurrent.futures

        self._device_exec = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="jax-step"
        )
        self._fetch_exec = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="jax-fetch"
        )
        self._compile()

    # ------------------------------------------------------------------ #
    # compiled programs
    # ------------------------------------------------------------------ #

    def _compile(self):
        c = self.model_config
        cfg = self.config
        K = cfg.decode_block_steps

        # under a (possibly multi-host) mesh, pin host-fetched outputs to
        # fully-replicated shardings so every host can read them locally;
        # the KV cache keeps its tp sharding
        decode_out_sh = prefill_out_sh = None
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            repl = NamedSharding(self._mesh, PartitionSpec())
            kvs = self._kv_sharding or repl
            decode_out_sh = (repl, repl, repl, repl, kvs, kvs, repl, repl)
            prefill_out_sh = (repl, kvs, kvs, repl)

        # the RNG key lives ON DEVICE and is threaded through every program
        # (split inside jit, advanced key returned): an eager
        # jax.random.split per dispatch costs a host round-trip — measured
        # ~9 ms/step through the axon tunnel, the round-1 ITL killer
        if cfg.decode_pool_mode == "local":

            @partial(jax.jit, donate_argnums=(1, 2, 8, 9), out_shardings=decode_out_sh)
            def decode_block(params, kv_k, kv_v, tokens, positions, seq_lens, page_tables, samp, rng, pen):
                """K fused decode steps, pool READ-ONLY inside the scan.

                A per-step scatter into the pool makes XLA materialize
                pool-sized copies (941 ms/block at 1024 pages vs 215 at 161
                on v5e). Here new K/V accumulate in per-layer [B, K, KH, D]
                local buffers — the fused pallas kernel merges them into the
                flash softmax — and the pool is written ONCE per block.
                Requires decode_block_unroll > 1 to dodge lax.scan's
                per-iteration re-copy of closed-over HBM arrays."""
                rng, sub = jax.random.split(rng)
                keys = jax.random.split(sub, K)
                B = tokens.shape[0]
                pool_lens = jnp.maximum(seq_lens - 1, 0)
                start_pos = positions
                # local accumulators stay FULL precision even under a
                # quantized pool (c.dtype == pool dtype in fp mode):
                # quantization happens once, at the per-block pool commit
                loc_shape = (B, K, c.num_kv_heads, c.head_dim)
                loc_k0 = tuple(
                    jnp.zeros(loc_shape, c.dtype) for _ in range(c.num_layers)
                )
                loc_v0 = tuple(
                    jnp.zeros(loc_shape, c.dtype) for _ in range(c.num_layers)
                )

                W = pen.shape[1]

                def step(carry, inp):
                    key_j, j = inp
                    tokens, positions, seq_lens, loc_k, loc_v, pen = carry
                    logits, loc_k, loc_v = self._model.decode_forward_local(
                        params, c, tokens, positions, loc_k, loc_v, j,
                        kv_k, kv_v, page_tables, pool_lens,
                    )
                    plogits = penalized(logits, samp, pen)
                    nxt, lp, tid, tlp = sample_lp(
                        plogits, samp, key_j, positions=positions, raw=logits
                    )
                    pen = pen.at[jnp.arange(B), (positions + 1) % W].set(nxt)
                    return (
                        (nxt, positions + 1, seq_lens + 1, loc_k, loc_v, pen),
                        (nxt, lp, tid, tlp),
                    )

                (tokens, positions, seq_lens, loc_k, loc_v, pen), toks = jax.lax.scan(
                    step,
                    (tokens, positions, seq_lens, loc_k0, loc_v0, pen),
                    (keys, jnp.arange(K)),
                    unroll=min(max(cfg.decode_block_unroll, 1), K),
                )
                # one pool scatter for the whole block. Inactive lanes write
                # via their SCRATCH table rows (the host keeps non-active
                # lanes' device table rows at scratch), positions past the
                # table route to physical page 0.
                page_size = cfg.page_size
                P = page_tables.shape[1]
                pos = start_pos[:, None] + jnp.arange(K)[None, :]  # [B, K]
                logical = jnp.minimum(pos // page_size, P - 1)
                phys = jnp.take_along_axis(page_tables, logical, axis=1)
                phys = jnp.where(pos < P * page_size, phys, 0)
                offs = pos % page_size
                from ..ops.kv_quant import kv_write_all_layers

                # the decode carry patch: ONE pool write per block —
                # quantize-on-write under DYN_KV_QUANT, the seed's fused
                # scatter otherwise (byte-identical jaxpr)
                kv_k = kv_write_all_layers(kv_k, phys, offs, jnp.stack(loc_k))
                kv_v = kv_write_all_layers(kv_v, phys, offs, jnp.stack(loc_v))
                return toks, tokens, positions, seq_lens, kv_k, kv_v, rng, pen

        else:

            @partial(jax.jit, donate_argnums=(1, 2, 8, 9), out_shardings=decode_out_sh)
            def decode_block(params, kv_k, kv_v, tokens, positions, seq_lens, page_tables, samp, rng, pen):
                """K fused decode steps: sampled tokens feed the next step on
                device — one host read per K*B tokens instead of per token.
                Per-step pool scatter (best at small/medium pools; see
                EngineConfig.decode_pool_mode for the trade-off)."""
                rng, sub = jax.random.split(rng)
                keys = jax.random.split(sub, K)
                W = pen.shape[1]
                B = tokens.shape[0]

                def step(carry, k):
                    tokens, positions, seq_lens, kv_k, kv_v, pen = carry
                    if cfg.pp_size > 1:
                        # layers pipelined over pp: each step is a full
                        # microbatch schedule (parallel/pipeline.py)
                        logits, kv_k, kv_v = self._model.decode_forward_pp(
                            params, c, tokens, positions, kv_k, kv_v,
                            page_tables, seq_lens, self._mesh,
                        )
                    else:
                        logits, kv_k, kv_v = self._model.decode_forward(
                            params, c, tokens, positions, kv_k, kv_v, page_tables, seq_lens
                        )
                    plogits = penalized(logits, samp, pen)
                    nxt, lp, tid, tlp = sample_lp(
                        plogits, samp, k, positions=positions, raw=logits
                    )
                    pen = pen.at[jnp.arange(B), (positions + 1) % W].set(nxt)
                    return (
                        (nxt, positions + 1, seq_lens + 1, kv_k, kv_v, pen),
                        (nxt, lp, tid, tlp),
                    )

                (tokens, positions, seq_lens, kv_k, kv_v, pen), toks = jax.lax.scan(
                    step, (tokens, positions, seq_lens, kv_k, kv_v, pen), keys
                )
                return toks, tokens, positions, seq_lens, kv_k, kv_v, rng, pen

        self._decode_block = decode_block

        self._spec_block_fn = None
        if cfg.spec_mode == "ngram":
            from .spec import hist_write, ngram_draft, verify_accept

            S = cfg.spec_rounds
            d_len = cfg.spec_draft_len
            ng = cfg.spec_ngram
            Hc = cfg.spec_hist
            Tc = d_len + 1

            spec_out_sh = None
            if self._mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                repl = NamedSharding(self._mesh, PartitionSpec())
                kvs = self._kv_sharding or repl
                spec_out_sh = (
                    repl, repl, repl, repl, repl, kvs, kvs, repl, repl,
                )

            @partial(jax.jit, donate_argnums=(1, 2, 8, 9),
                     out_shardings=spec_out_sh)
            def spec_block(params, kv_k, kv_v, tokens, positions, seq_lens,
                           page_tables, samp, rng, hist):
                """S draft-verify rounds (engine/spec.py). Each round: write
                the current token into the history ring, n-gram-draft d
                continuations, verify all 1+d in ONE batched-prefill pass
                (one weight stream instead of 1+d), accept the longest
                matching prefix. Emits 1..1+d tokens per lane per round —
                never fewer than plain decode."""
                B = tokens.shape[0]

                def round_fn(carry, key):
                    tokens, positions, seq_lens, kv_k, kv_v, hist = carry
                    hist = hist_write(hist, positions, tokens)
                    draft = ngram_draft(hist, tokens, positions, ng, d_len)
                    chunk = jnp.concatenate([tokens[:, None], draft], axis=1)
                    cpos = positions[:, None] + jnp.arange(Tc)[None, :]
                    logits, kv_k, kv_v = self._model.prefill_forward_batched(
                        params, c, chunk, cpos, kv_k, kv_v, page_tables,
                        positions,  # context_lens: tokens already in KV
                        jnp.full((B,), d_len, jnp.int32),
                        all_logits=True,
                    )
                    out_toks, n_emit, key = verify_accept(
                        logits.astype(jnp.float32), draft, samp, key
                    )
                    new_tokens = out_toks[jnp.arange(B), n_emit - 1]
                    # ring-append the emitted tokens (pos+1 .. pos+n_emit);
                    # invalid tail indices point out of bounds -> dropped
                    wpos = positions[:, None] + 1 + jnp.arange(Tc)[None, :]
                    slot_i = jnp.where(
                        jnp.arange(Tc)[None, :] < n_emit[:, None],
                        wpos % Hc, Hc,
                    )
                    hist = hist.at[
                        jnp.arange(B)[:, None], slot_i
                    ].set(out_toks, mode="drop")
                    positions = positions + n_emit
                    seq_lens = seq_lens + n_emit
                    return (
                        (new_tokens, positions, seq_lens, kv_k, kv_v, hist),
                        (out_toks, n_emit),
                    )

                rng, sub = jax.random.split(rng)
                keys = jax.random.split(sub, S)
                (tokens, positions, seq_lens, kv_k, kv_v, hist), (toks_s, n_emit_s) = jax.lax.scan(
                    round_fn, (tokens, positions, seq_lens, kv_k, kv_v, hist), keys
                )
                return (
                    toks_s, n_emit_s, tokens, positions, seq_lens,
                    kv_k, kv_v, rng, hist,
                )

            self._spec_block_fn = spec_block

        @partial(jax.jit, donate_argnums=(1, 2, 9), out_shardings=prefill_out_sh)
        def prefill_batch(params, kv_k, kv_v, tokens, positions, page_tables, ctx_lens, last_idx, samp, rng, pen):
            """Batched chunked prefill + on-device first-token sampling."""
            rng, sub = jax.random.split(rng)
            logits, kv_k, kv_v = self._model.prefill_forward_batched(
                params, c, tokens, positions, kv_k, kv_v, page_tables, ctx_lens, last_idx
            )
            plogits = penalized(logits, samp, pen)
            first = sample_lp(
                plogits, samp, sub, positions=ctx_lens + last_idx, raw=logits
            )
            return first, kv_k, kv_v, rng

        self._prefill_batch = prefill_batch

        @partial(jax.jit, donate_argnums=(1, 2, 12), out_shardings=prefill_out_sh)
        def mixed_step(params, kv_k, kv_v, tokens, positions, row_ids,
                       page_tables, row_starts, row_lens, ctx_lens, last_flat,
                       samp, rng, pen_rows):
            """Unified mixed step: ONE ragged forward over a flat buffer
            packing prefill chunks (row_len > 1) and decode lanes
            (row_len == 1), with each row's last-token logits sampled on
            device — the fused replacement for a prefill_batch dispatch
            followed by a decode dispatch (docs/ragged_attention.md).
            Attention rides ops/pallas_ragged_attention on TPU, the XLA
            ragged reference elsewhere."""
            rng, sub = jax.random.split(rng)
            logits, kv_k, kv_v = self._model.ragged_forward(
                params, c, tokens, positions, row_ids, kv_k, kv_v,
                page_tables, row_starts, row_lens, ctx_lens, last_flat,
            )
            plogits = penalized(logits, samp, pen_rows)
            # the sampled token's position counter: the row's last real
            # token (= ctx + last_idx for prefill rows, seq_len - 1 for
            # decode rows) — identical to what the split dispatches use,
            # so seeded streams don't depend on the dispatch shape
            first = sample_lp(
                plogits, samp, sub, positions=ctx_lens + row_lens - 1,
                raw=logits,
            )
            return first, kv_k, kv_v, rng

        self._mixed_step = mixed_step

        @partial(jax.jit, donate_argnums=(1, 2, 12), out_shardings=prefill_out_sh)
        def mixed_step_variant(params, kv_k, kv_v, tokens, positions, row_ids,
                               page_tables, row_starts, row_lens, ctx_lens,
                               last_flat, samp, rng, pen_rows, mask_packed,
                               lora):
            """Mixed step for VARIANT row classes (guided / multi-LoRA /
            speculative): same ragged forward + per-row sampling as
            mixed_step plus a bitpacked per-row FSM admissibility mask
            (all-ones rows are an exact no-op — the invariant the split
            guided variants already rely on) and, when adapters are
            registered, the LoRA stack with per-row adapter indices
            (index 0 = the all-zero base adapter, an exact no-op).
            Speculative verify rows need no extra operand: they are
            ordinary one-token rows whose ctx includes their sibling
            draft rows' KV (written before attention each layer).
            A separate lazy jit so plain blended-free traffic never
            carries the mask/adapter operands."""
            rng, sub = jax.random.split(rng)
            logits, kv_k, kv_v = self._model.ragged_forward(
                params, c, tokens, positions, row_ids, kv_k, kv_v,
                page_tables, row_starts, row_lens, ctx_lens, last_flat,
                lora=lora,
            )
            plogits = penalized(logits, samp, pen_rows)
            mask = unpack_mask(mask_packed, c.vocab_size)
            first = sample_lp(
                plogits, samp, sub, mask=mask,
                positions=ctx_lens + row_lens - 1, raw=logits,
            )
            return first, kv_k, kv_v, rng

        self._mixed_step_variant = mixed_step_variant

        @partial(jax.jit, donate_argnums=(1, 2, 9), out_shardings=prefill_out_sh)
        def prefill_batch_mm(params, kv_k, kv_v, tokens, positions, page_tables,
                             ctx_lens, last_idx, samp, rng, pen, emb, emb_mask):
            """Batched prefill with the multimodal embedding splice: encoder
            rows replace placeholder-token embeddings (E/P/D flow). A
            separate program so text-only dispatches never carry the
            [B, T, H] override operand. jax.jit is lazy — this compiles
            only when a multimodal request actually arrives."""
            rng, sub = jax.random.split(rng)
            logits, kv_k, kv_v = self._model.prefill_forward_batched(
                params, c, tokens, positions, kv_k, kv_v, page_tables,
                ctx_lens, last_idx, emb_override=emb, emb_mask=emb_mask,
            )
            plogits = penalized(logits, samp, pen)
            first = sample_lp(
                plogits, samp, sub, positions=ctx_lens + last_idx, raw=logits
            )
            return first, kv_k, kv_v, rng

        self._prefill_batch_mm = prefill_batch_mm

        # guided-decoding variants (llm/guided.py): same programs with a
        # [B, V] admissibility mask applied inside the sampler. Separate
        # jits so unguided dispatches never carry the mask operand —
        # jax.jit is lazy, these compile only when a guided request
        # actually arrives. The decode variant is a SINGLE step: the mask
        # for step t+1 depends host-side on the token emitted at step t,
        # so guided decode cannot ride the K-step fused block.
        @partial(jax.jit, donate_argnums=(1, 2, 8, 10), out_shardings=decode_out_sh)
        def decode_step_guided(params, kv_k, kv_v, tokens, positions, seq_lens,
                               page_tables, samp, rng, mask_packed, pen):
            rng, sub = jax.random.split(rng)
            if cfg.pp_size > 1:
                logits, kv_k, kv_v = self._model.decode_forward_pp(
                    params, c, tokens, positions, kv_k, kv_v,
                    page_tables, seq_lens, self._mesh,
                )
            else:
                logits, kv_k, kv_v = self._model.decode_forward(
                    params, c, tokens, positions, kv_k, kv_v, page_tables, seq_lens
                )
            plogits = penalized(logits, samp, pen)
            mask = unpack_mask(mask_packed, c.vocab_size)
            nxt, lp, tid, tlp = sample_lp(
                plogits, samp, sub, mask=mask, positions=positions, raw=logits
            )
            pen = pen.at[
                jnp.arange(pen.shape[0]), (positions + 1) % pen.shape[1]
            ].set(nxt)
            return (
                (nxt[None], lp[None], tid[None], tlp[None]),
                nxt, positions + 1, seq_lens + 1, kv_k, kv_v, rng, pen,
            )

        self._decode_step_guided = decode_step_guided

        # guided + LoRA lanes decode-active TOGETHER: the masked single
        # step must still apply the LoRA deltas, or the LoRA lane would
        # silently generate (and write KV!) with the base model while a
        # guided request is in flight
        @partial(jax.jit, donate_argnums=(1, 2, 8, 10), out_shardings=decode_out_sh)
        def decode_step_guided_lora(params, kv_k, kv_v, tokens, positions,
                                    seq_lens, page_tables, samp, rng,
                                    mask_packed, pen, lora):
            rng, sub = jax.random.split(rng)
            logits, kv_k, kv_v = self._model.decode_forward(
                params, c, tokens, positions, kv_k, kv_v, page_tables,
                seq_lens, lora=lora,
            )
            plogits = penalized(logits, samp, pen)
            mask = unpack_mask(mask_packed, c.vocab_size)
            nxt, lp, tid, tlp = sample_lp(
                plogits, samp, sub, mask=mask, positions=positions, raw=logits
            )
            pen = pen.at[
                jnp.arange(pen.shape[0]), (positions + 1) % pen.shape[1]
            ].set(nxt)
            return (
                (nxt[None], lp[None], tid[None], tlp[None]),
                nxt, positions + 1, seq_lens + 1, kv_k, kv_v, rng, pen,
            )

        self._decode_step_guided_lora = decode_step_guided_lora

        @partial(jax.jit, donate_argnums=(1, 2, 9), out_shardings=prefill_out_sh)
        def prefill_batch_guided(params, kv_k, kv_v, tokens, positions,
                                 page_tables, ctx_lens, last_idx, samp, rng,
                                 pen, mask_packed):
            rng, sub = jax.random.split(rng)
            logits, kv_k, kv_v = self._model.prefill_forward_batched(
                params, c, tokens, positions, kv_k, kv_v, page_tables,
                ctx_lens, last_idx
            )
            plogits = penalized(logits, samp, pen)
            mask = unpack_mask(mask_packed, c.vocab_size)
            first = sample_lp(
                plogits, samp, sub, mask=mask,
                positions=ctx_lens + last_idx, raw=logits
            )
            return first, kv_k, kv_v, rng

        self._prefill_batch_guided = prefill_batch_guided

        # multi-LoRA variants (models/lora.py): the adapter stack + per-lane
        # index ride as operands; base-model lanes carry index 0 (the
        # all-zero adapter — an exact no-op), so mixed batches need no
        # masking. Lazy jits: compile only when adapters are registered and
        # a LoRA request arrives. K-step fused blocks work unchanged —
        # adapters are static per lane, unlike guided masks.
        @partial(jax.jit, donate_argnums=(1, 2, 8, 9), out_shardings=decode_out_sh)
        def decode_block_lora(params, kv_k, kv_v, tokens, positions, seq_lens,
                              page_tables, samp, rng, pen, lora):
            rng, sub = jax.random.split(rng)
            keys = jax.random.split(sub, K)
            W = pen.shape[1]
            B = tokens.shape[0]

            def step(carry, key_j):
                tokens, positions, seq_lens, kv_k, kv_v, pen = carry
                logits, kv_k, kv_v = self._model.decode_forward(
                    params, c, tokens, positions, kv_k, kv_v, page_tables,
                    seq_lens, lora=lora,
                )
                plogits = penalized(logits, samp, pen)
                nxt, lp, tid, tlp = sample_lp(
                    plogits, samp, key_j, positions=positions, raw=logits
                )
                pen = pen.at[jnp.arange(B), (positions + 1) % W].set(nxt)
                return (
                    (nxt, positions + 1, seq_lens + 1, kv_k, kv_v, pen),
                    (nxt, lp, tid, tlp),
                )

            (tokens, positions, seq_lens, kv_k, kv_v, pen), toks = jax.lax.scan(
                step, (tokens, positions, seq_lens, kv_k, kv_v, pen), keys
            )
            return toks, tokens, positions, seq_lens, kv_k, kv_v, rng, pen

        self._decode_block_lora = decode_block_lora

        @partial(jax.jit, donate_argnums=(1, 2, 9), out_shardings=prefill_out_sh)
        def prefill_batch_lora(params, kv_k, kv_v, tokens, positions,
                               page_tables, ctx_lens, last_idx, samp, rng,
                               pen, lora):
            rng, sub = jax.random.split(rng)
            logits, kv_k, kv_v = self._model.prefill_forward_batched(
                params, c, tokens, positions, kv_k, kv_v, page_tables,
                ctx_lens, last_idx, lora=lora,
            )
            plogits = penalized(logits, samp, pen)
            first = sample_lp(
                plogits, samp, sub, positions=ctx_lens + last_idx, raw=logits
            )
            return first, kv_k, kv_v, rng

        self._prefill_batch_lora = prefill_batch_lora

        # single-sequence prefill variants for the native parallel layouts
        # (SURVEY.md §2.5): ring attention over sp (long-context), layer
        # pipeline over pp. Both sample the first token on device.
        self._prefill_single = None
        if self._mesh is not None and (cfg.sp_size > 1 or cfg.pp_size > 1):
            mode = "pp" if cfg.pp_size > 1 else "ring"
            from jax.sharding import NamedSharding, PartitionSpec

            repl = NamedSharding(self._mesh, PartitionSpec())
            kvs = self._kv_sharding or repl
            single_out_sh = (repl, kvs, kvs, repl)

            @partial(jax.jit, donate_argnums=(1, 2, 7), out_shardings=single_out_sh)
            def prefill_single(params, kv_k, kv_v, toks, table, ctx_len, real_len, rng, samp, pen):
                rng, sub = jax.random.split(rng)
                if mode == "pp":
                    logits, kv_k, kv_v = self._model.prefill_forward_pp(
                        params, c, toks, kv_k, kv_v, table, ctx_len, real_len,
                        self._mesh,
                    )
                else:
                    logits, kv_k, kv_v = self._model.prefill_forward_ring(
                        params, c, toks, kv_k, kv_v, table, real_len, self._mesh
                    )
                first = sample_lp(
                    penalized(logits[None], samp, pen), samp, sub,
                    positions=(ctx_len + real_len - 1)[None],
                    raw=logits[None],
                )
                return first, kv_k, kv_v, rng

            self._prefill_single = prefill_single

        # per-lane carry patch: admissions/finishes update ONLY their lanes
        # on device instead of invalidating the whole carry (a full reset
        # forces a pipeline drain + re-upload — the round-2 ITL gap under
        # churn). lane_mask patches carry+sampling+table; table_mask extends
        # to lanes whose page table grew mid-decode (their carry values on
        # device are NEWER than host state and must not be overwritten).
        patch_out_sh = None
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            repl = NamedSharding(self._mesh, PartitionSpec())
            patch_out_sh = (repl,) * 10

        @partial(jax.jit, out_shardings=patch_out_sh)
        def patch_lanes(
            tokens, positions, seq_lens, tables, temps, top_ks, top_ps, seeds,
            pens, recent,
            lane_mask, table_mask,
            n_tokens, n_positions, n_seq_lens, n_tables, n_temps, n_top_ks,
            n_top_ps, n_seeds, n_pens, n_recent,
        ):
            tokens = jnp.where(lane_mask, n_tokens, tokens)
            positions = jnp.where(lane_mask, n_positions, positions)
            seq_lens = jnp.where(lane_mask, n_seq_lens, seq_lens)
            temps = jnp.where(lane_mask, n_temps, temps)
            top_ks = jnp.where(lane_mask, n_top_ks, top_ks)
            top_ps = jnp.where(lane_mask, n_top_ps, top_ps)
            seeds = jnp.where(lane_mask, n_seeds, seeds)
            pens = jnp.where(lane_mask[:, None], n_pens, pens)
            recent = jnp.where(lane_mask[:, None], n_recent, recent)
            tables = jnp.where(table_mask[:, None], n_tables, tables)
            return (
                tokens, positions, seq_lens, tables, temps, top_ks, top_ps,
                seeds, pens, recent,
            )

        self._patch_lanes = patch_lanes

        # disagg KV movement (host-staged; llm/disagg.py wire format).
        # tree_map covers both store shapes: a plain fp array, or a
        # QuantKV whose q pages AND per-page scales gather/scatter on the
        # same `[:, page_ids]` slice — scales travel with their pages
        # through every tier/wire hop.
        @jax.jit
        def extract_pages(kv_k, kv_v, page_ids):
            ex = lambda a: a[:, page_ids]  # noqa: E731
            return jax.tree.map(ex, kv_k), jax.tree.map(ex, kv_v)

        self._extract_pages = extract_pages

        @partial(jax.jit, donate_argnums=(0, 1))
        def inject_pages(kv_k, kv_v, page_ids, data_k, data_v):
            inj = lambda a, d: a.at[:, page_ids].set(d)  # noqa: E731
            return (
                jax.tree.map(inj, kv_k, data_k),
                jax.tree.map(inj, kv_v, data_v),
            )

        self._inject_pages = inject_pages

        # per-surface compile telemetry (docs/compilation.md): every
        # staged callable keyed by its COMPILE_SURFACES registry name, so
        # stats() can report XLA cache growth per surface and the replay
        # compile smoke can gate on zero post-warmup recompiles. Keys
        # MUST match engine/compile_registry.py — dynocomp's registry
        # rule anchors the static contract, this map closes it at runtime
        self._compiled_surfaces = {
            "decode_block": self._decode_block,
            "spec_block": self._spec_block_fn,
            "prefill_batch": self._prefill_batch,
            "mixed_step": self._mixed_step,
            "mixed_step_variant": self._mixed_step_variant,
            "prefill_batch_mm": self._prefill_batch_mm,
            "decode_step_guided": self._decode_step_guided,
            "decode_step_guided_lora": self._decode_step_guided_lora,
            "prefill_batch_guided": self._prefill_batch_guided,
            "decode_block_lora": self._decode_block_lora,
            "prefill_batch_lora": self._prefill_batch_lora,
            "prefill_single": self._prefill_single,
            "patch_lanes": self._patch_lanes,
            "extract_pages": self._extract_pages,
            "inject_pages": self._inject_pages,
        }
        # snapshot of per-surface cache sizes taken when warmup finishes;
        # None until then (pre-warmup compiles are expected, not debt)
        self._warmup_compile_baseline = None

    def _surface_cache_sizes(self) -> dict:
        """Per-surface XLA executable counts from jit's compilation
        cache (PjitFunction._cache_size — private but stable across the
        jax versions we pin; 0 when a surface is disabled for this
        config or the probe is absent in a future jax)."""
        out = {}
        for name, fn in self._compiled_surfaces.items():
            size = 0
            probe = getattr(fn, "_cache_size", None)
            if probe is not None:
                try:
                    size = int(probe())
                except Exception:
                    size = 0
            out[name] = size
        return out

    # ------------------------------------------------------------------ #
    # lifecycle / interface (MockEngine-compatible)
    # ------------------------------------------------------------------ #

    def start(self):
        if self._step_task is None:
            self._step_task = asyncio.create_task(self._step_loop())

    async def close(self):
        self._closed = True
        self._wake.set()
        if self._step_task:
            self._step_task.cancel()
        # in-flight KV pulls: their slots are dead with the engine, and a
        # pull left running would keep injecting into reused pages
        for t in list(self._bg_tasks):
            t.cancel()
        if self.kvbm is not None:
            # flush any staged commits, drain in-flight write-through
            # offloads (staged + queued + legacy inline), stop the tier
            # thread, then persist the G3 index
            self.kvbm.flush_step()
            for _ in range(500):
                if self.kvbm.pending_offloads() == 0:
                    break
                await asyncio.sleep(0.01)
            self.kvbm.shutdown()
            self.kvbm.manager.flush()

    async def warmup(self) -> int:
        """Compile every dispatch variant BEFORE serving traffic.

        First-request compiles are 20-40s per program through the axon
        remote-compile tunnel; paying them on-path once the worker is
        registered starves discovery-lease renewal and breaks in-flight
        streams (the round-4 e2e ladder failure: worker dropped from the
        control plane mid-compile, 96/96 requests "no instances
        available"). Driving the real `generate` path pre-registration
        compiles the bounded variant space — per-bucket {1, cap}-lane
        batched prefill, decode reset/patch/block — into the persistent
        XLA cache, so restarts are cheap. Returns the number of warmup
        requests served. vLLM analogue: GPU-worker profile/warmup runs
        before the engine reports ready."""
        import numpy as _np

        rng = _np.random.RandomState(0xD74A)
        vocab = self.model_config.vocab_size
        K = self.config.decode_block_steps

        async def _drain(isl: int):
            req = PreprocessedRequest(
                token_ids=rng.randint(5, max(vocab - 1, 6), size=isl).tolist(),
                stop_conditions={"max_tokens": K + 2, "ignore_eos": True},
                sampling_options={"temperature": 1.0},
            ).to_dict()
            async for _ in self.generate(req, Context()):
                pass

        n = 0
        buckets = [
            b for b in self.config.prefill_buckets
            if b <= self.config.max_model_len
        ] or [self.config.prefill_buckets[0]]
        prev = 0
        for b in buckets:
            # both ends of this bucket's first-chunk range: the prefill
            # page-table axis (P = next_pow2(pages) + 1) changes rung
            # WITHIN a bucket, so a single isl per bucket leaves page
            # variants to compile on-path (the --compile-smoke replay
            # gate caught exactly that)
            isls = sorted({max(prev + 1, 4), max(b - 8, 4), b})
            for isl in isls:
                # lone arrival: the 1-lane prefill variant (+ decode
                # block/reset on the first pass)
                await _drain(isl)
                n += 1
            cap = max(1, min(
                self.config.prefill_batch_tokens // b,
                self.config.max_prefill_batch,
            ))
            if cap > 1:
                # concurrent arrivals batch into the padded cap-lane
                # variant; admissions mid-decode also exercise _dev_patch.
                # Burst at both page rungs — the P axis is orthogonal to
                # the lane axis
                burst = min(cap, 3)
                for isl in (isls[0], isls[-1]):
                    await asyncio.gather(*[_drain(isl) for _ in range(burst)])
                    n += burst
            prev = b
        long_isl = self.config.max_model_len - K - 4
        if long_isl > buckets[-1]:
            # one long prompt walks the chunked-prefill path: successive
            # chunks carry more context pages, compiling the upper
            # page-table rungs no single-chunk prompt reaches
            await _drain(long_isl)
            n += 1
        if (
            self.config.pp_size == 1 and self.config.sp_size == 1
            and (not self.config.spec_mode or self._mixed_enabled)
        ):
            # compile the guided prefill/decode variants too (a first
            # guided request on-path would otherwise pay the compile) —
            # at both bucket ends, matching the plain coverage. Under
            # spec_mode guided is admittable only via the fused path, so
            # the gate relaxes exactly with _mixed_enabled.
            for isl in sorted({
                max(buckets[0] - 8, 4), max(buckets[-1] - 8, 4)
            }):
                req = PreprocessedRequest(
                    token_ids=rng.randint(
                        5, max(vocab - 1, 6), size=isl
                    ).tolist(),
                    stop_conditions={"max_tokens": 3},
                    sampling_options={"temperature": 1.0},
                    guided={"kind": "regex", "regex": "[ab]*"},
                ).to_dict()
                async for _ in self.generate(req, Context()):
                    pass
                n += 1
        if self._mixed_enabled:
            # compile the unified mixed-step variant: a staggered pair puts
            # one request in decode while the other's prefill chunk is
            # runnable, so the fused ragged program (ragged_forward +
            # sampling) compiles before serving traffic instead of on-path
            isl = max(buckets[0] - 8, 4)
            t1 = asyncio.create_task(_drain(isl))
            await asyncio.sleep(0.05)
            t2 = asyncio.create_task(_drain(isl))
            await asyncio.gather(t1, t2)
            n += 2
        if self._lora is not None and self._lora["names"]:
            # compile the LoRA prefill/decode variants with a registered
            # adapter (same on-path-compile hazard as the guided
            # variants), again at both bucket ends
            for isl in sorted({
                max(buckets[0] - 8, 4), max(buckets[-1] - 8, 4)
            }):
                req = PreprocessedRequest(
                    token_ids=rng.randint(
                        5, max(vocab - 1, 6), size=isl
                    ).tolist(),
                    stop_conditions={"max_tokens": K + 2, "ignore_eos": True},
                    sampling_options={"temperature": 1.0},
                    lora_name=next(iter(self._lora["names"])),
                ).to_dict()
                async for _ in self.generate(req, Context()):
                    pass
                n += 1
        if self._mixed_enabled and (
            self.config.pp_size == 1 and self.config.sp_size == 1
        ):
            # fused-dispatch variants (lean + mask/adapter operand
            # program): a fused step's page-table axis rides the DECODE
            # rows' context, so blended traffic arriving mid-decode of a
            # long generation lands on table rungs the short staggered
            # pair never reaches. Anchor one long-prompt decode per pow2
            # table rung and admit plain (lean), guided and lora
            # (variant) arrivals beside it — at both chunk-bucket ends —
            # so every (token bucket, table rung) pair steady blended
            # traffic hits is compiled pre-serving
            # (post_warmup_compiles == 0 must hold on blended traffic).
            page = self.config.page_size
            anchor_osl = 8 * K
            anchor_isls = []
            pages = 2
            while pages * page + anchor_osl + 8 <= self.config.max_model_len:
                anchor_isls.append(max(pages * page - 4, 4))
                pages *= 2

            async def _drain_long(isl: int, started: asyncio.Event):
                req = PreprocessedRequest(
                    token_ids=rng.randint(
                        5, max(vocab - 1, 6), size=isl
                    ).tolist(),
                    stop_conditions={"max_tokens": anchor_osl,
                                     "ignore_eos": True},
                    sampling_options={"temperature": 1.0},
                ).to_dict()
                async for _ in self.generate(req, Context()):
                    started.set()

            async def _drain_req(r):
                async for _ in self.generate(dict(r), Context()):
                    pass

            def _mk_variant_reqs(isl: int) -> list:
                reqs = [PreprocessedRequest(
                    token_ids=rng.randint(
                        5, max(vocab - 1, 6), size=isl
                    ).tolist(),
                    stop_conditions={"max_tokens": 4, "ignore_eos": True},
                    sampling_options={"temperature": 1.0},
                ).to_dict(), PreprocessedRequest(
                    token_ids=rng.randint(
                        5, max(vocab - 1, 6), size=isl
                    ).tolist(),
                    stop_conditions={"max_tokens": 4},
                    sampling_options={"temperature": 1.0},
                    guided={"kind": "regex", "regex": "[ab]*"},
                ).to_dict()]
                if self._lora is not None and self._lora["names"]:
                    reqs.append(PreprocessedRequest(
                        token_ids=rng.randint(
                            5, max(vocab - 1, 6), size=isl
                        ).tolist(),
                        stop_conditions={"max_tokens": 4,
                                         "ignore_eos": True},
                        sampling_options={"temperature": 1.0},
                        lora_name=next(iter(self._lora["names"])),
                    ).to_dict())
                return reqs

            chunk_isls = sorted({
                max(buckets[0] - 8, 4), max(buckets[-1] - 8, 4)
            })
            for a_isl in anchor_isls:
                # sequential arrivals: each fuses ALONE beside the anchor,
                # pinning the token bucket to its own chunk. The anchor is
                # (re)started on demand and each admission gates on the
                # anchor having just emitted (not wall time — post-compile
                # step cadence is far faster than any fixed sleep)
                anchor = None
                for isl in chunk_isls:
                    for vreq in _mk_variant_reqs(isl):
                        if anchor is None or anchor.done():
                            started = asyncio.Event()
                            anchor = asyncio.create_task(
                                _drain_long(a_isl, started)
                            )
                            await started.wait()
                            n += 1
                        await _drain_req(vreq)
                        n += 1
                await anchor
            if self._lora is not None and self._lora["names"]:
                # guided + lora lanes decoding in the SAME split decode
                # block: the combined-kind decode program no single-kind
                # warmup request reaches
                _, g_req, l_req = _mk_variant_reqs(chunk_isls[0])
                g_req["stop_conditions"]["max_tokens"] = K + 2
                l_req["stop_conditions"]["max_tokens"] = K + 2
                await asyncio.gather(_drain_req(g_req), _drain_req(l_req))
                n += 2
        # steady-state contract line: every XLA program compiled from
        # here on counts as a post-warmup recompile
        # (stats()['post_warmup_compiles']); the replay compile smoke
        # (bench_serving_overhead --compile-smoke) gates on it staying 0
        self._warmup_compile_baseline = self._surface_cache_sizes()
        return n

    # ------------------------------------------------------------------ #
    # live role morphing (docs/autoscaling.md "Role morphing")
    # ------------------------------------------------------------------ #

    _ROLES = {
        "prefill": {"prefill"},
        "decode": {"decode"},
        "both": {"prefill", "decode"},
    }

    async def warmup_role(self, role: str) -> int:
        """Trimmed re-warm for the INCOMING role of a morph: drive the
        role's hot compile surfaces (per-bucket short-output prefill for
        a prefill worker; short-prompt decode blocks for a decode worker)
        through the real generate path, then refresh the post-warmup
        compile baseline so morph-time compiles never count as
        steady-state recompile debt (stats()['post_warmup_compiles']).
        Cheap by construction: the full warmup() already populated the
        persistent XLA cache at boot, so these replays hit it — the point
        is paying any residual first-dispatch cost BEFORE the flipped
        worker takes traffic, the same contract warmup() holds at boot."""
        import numpy as _np

        rng = _np.random.RandomState(0xD74B)
        vocab = self.model_config.vocab_size
        K = self.config.decode_block_steps

        async def _drain(isl: int, max_tokens: int):
            req = PreprocessedRequest(
                token_ids=rng.randint(5, max(vocab - 1, 6), size=isl).tolist(),
                stop_conditions={"max_tokens": max_tokens, "ignore_eos": True},
                sampling_options={"temperature": 1.0},
            ).to_dict()
            async for _ in self.generate(req, Context()):
                pass

        buckets = [
            b for b in self.config.prefill_buckets
            if b <= self.config.max_model_len
        ] or [self.config.prefill_buckets[0]]
        n = 0
        if "prefill" in self._ROLES[role]:
            for b in buckets:
                await _drain(max(b - 8, 4), 1)
                n += 1
        if "decode" in self._ROLES[role]:
            for _ in range(2):
                await _drain(max(buckets[0] - 8, 4), K + 2)
                n += 1
        self._warmup_compile_baseline = self._surface_cache_sizes()
        return n

    async def _await_sever_consumed(self, timeout_s: float):
        """Hold the flip until every severed stream's sentinel has been
        picked up by its consumer (the caller is now migrating) — the
        drain budget DYN_MORPH_DRAIN_TIMEOUT_S bounds the wait; expiry
        fails the morph and rolls back."""
        t0 = time.monotonic()
        while any(not q.empty() for q in self._severed_queues):
            if time.monotonic() - t0 > timeout_s:
                raise TimeoutError(
                    f"morph drain exceeded {timeout_s}s budget "
                    f"(severed stream consumer never woke)"
                )
            await asyncio.sleep(0.01)
        self._severed_queues = []

    async def morph(
        self,
        target_role: str,
        *,
        on_flip: Optional[Callable[[], Any]] = None,
    ) -> dict:
        """Re-role this live engine: serving → draining-role → flipped →
        warm → serving. In-flight streams of the outgoing role are
        severed so their sessions resume on peers from durable
        checkpoints (zero lost items, a tail of latency); `on_flip` is
        awaited between the role flip and re-warm so the worker harness
        can atomically move the discovery registration; warmup_role then
        re-warms the incoming role's compile surfaces before the worker
        takes traffic again.

        Failure semantics: any exception mid-morph rolls the engine back
        to its original role (drained sessions already resumed on peers —
        nothing to restore) EXCEPT faults.MorphCrash, which propagates so
        the harness tears the worker down crash-style."""
        from ..runtime.config import env_float

        if target_role not in self._ROLES:
            raise ValueError(f"unknown role {target_role!r}")
        if self._morph_state != "serving":
            raise RuntimeError(f"morph re-entered while {self._morph_state!r}")
        old_role = self._role
        if target_role == old_role:
            return {"from": old_role, "to": target_role,
                    "drained": 0, "duration_s": 0.0}
        t0 = time.monotonic()
        self._morph_state = "draining-role"
        try:
            f = faults.FAULTS
            if f.enabled:
                # dynochaos `worker.morph` (mid-drain): `error` exercises
                # rollback, `crash` the corpse path
                act = await f.on("worker.morph")
                if act == "crash":
                    raise faults.MorphCrash("injected crash mid-drain")
            drained = 0
            # sever when ANY previously-served lane is going away; "both"
            # keeps every lane, so growing into it drains nothing
            if self._ROLES[old_role] - self._ROLES[target_role]:
                drained = self._sever_all(
                    f"worker morphing {old_role}->{target_role}; "
                    "stream re-routed"
                )
                if drained:
                    await self._await_sever_consumed(
                        env_float("DYN_MORPH_DRAIN_TIMEOUT_S", 10.0)
                    )
            self.morph_drained_sessions += drained
            self._morph_state = "flipped"
            if f.enabled:
                # dynochaos `worker.morph` (mid-flip): same actions, after
                # the drain — rollback here proves sessions already moved
                act = await f.on("worker.morph")
                if act == "crash":
                    raise faults.MorphCrash("injected crash mid-flip")
            self._role = target_role
            if on_flip is not None:
                await on_flip()
            self._morph_state = "warm"
            await self.warmup_role(target_role)
        except asyncio.CancelledError:
            raise
        except faults.MorphCrash:
            raise  # harness tears the worker down mid-morph, no rollback
        except Exception:
            self._role = old_role
            self._morph_state = "serving"
            self.morphs_rolled_back += 1
            raise
        self._morph_state = "serving"
        self.morphs_completed += 1
        self.morph_last_duration_s = time.monotonic() - t0
        return {"from": old_role, "to": target_role,
                "drained": drained,
                "duration_s": self.morph_last_duration_s}

    def estimated_role_tok_s(self) -> Dict[str, float]:
        """Marginal per-role throughput from the cost model's observed
        EWMAs (s/token for prefill dispatches and decode blocks) — the
        numbers that price the planner's morph-vs-spawn decision. 0.0
        while the model is cold on a kind (the planner then falls back to
        its static seed costs)."""
        pf = self.scheduler.cost.per_token("prefill")
        dc = self.scheduler.cost.per_token("block")
        return {
            "prefill": 1.0 / pf if pf else 0.0,
            "decode": 1.0 / dc if dc else 0.0,
        }

    def _check_multimodal(self, req: PreprocessedRequest) -> Optional[str]:
        """None when the request is serveable; else the rejection reason.
        Serveable = text-only, OR every part carries encoder embeddings +
        a placeholder position (the encode hop ran; llm/multimodal.py)."""
        if not req.multimodal:
            return None
        H = self.model_config.hidden_size
        for p in req.multimodal:
            if p.get("embedding") is None or p.get("position") is None:
                return (
                    f"model {self.config.model!r} needs encoder embeddings "
                    f"for multimodal parts (type={p.get('type')!r}); "
                    f"deploy an encode worker (dynamo_tpu.encode_worker)"
                )
            # a malformed embedding must fail THIS request at admission —
            # inside the shared prefill dispatch it would _fail_all
            # co-active requests
            try:
                arr = np.asarray(p["embedding"], np.float32)
            except (ValueError, TypeError):
                return "multimodal embedding is not a numeric [n, hidden] array"
            if arr.ndim != 2 or arr.shape[1] != H or arr.shape[0] == 0:
                return (
                    f"multimodal embedding shape {arr.shape} does not match "
                    f"[n>0, hidden={H}] — encode worker configured for a "
                    f"different model?"
                )
            # keep the converted array: real encoders are MBs of nested
            # lists off the wire; _slot_mm must not convert again
            p["embedding"] = arr
        if self.config.pp_size > 1 or self.config.sp_size > 1:
            return "multimodal splice is not supported on pp/sp layouts yet"
        return None

    @staticmethod
    def _slot_mm(req: PreprocessedRequest) -> Optional[List[tuple]]:
        if not req.multimodal:
            return None
        return [
            (int(p["position"]), np.asarray(p["embedding"], np.float32))
            for p in req.multimodal
        ]

    def _guided_compiler(self):
        if self._guided is None:
            from ..llm.guided import GuidedCompiler

            tok = self.tokenizer
            if tok is None:
                from ..llm.tokenizers import ByteTokenizer

                tok = ByteTokenizer(self.model_config.vocab_size)
            self._guided = GuidedCompiler(tok)
        return self._guided

    def register_adapters(self, adapters) -> None:
        """Install LoRA adapters (models/lora.LoraAdapter list) behind the
        fixed-slot paging tier (models/lora_pool.LoraPool): the engine's
        stack reference stays live across onboard/evict, so registration
        is append-only and fleet rosters larger than the device slot count
        page on demand. In-flight LoRA requests keep their indices (their
        slots are pinned)."""
        from ..models import moe
        from ..models.lora_pool import LoraPool
        from ..runtime.config import env_int

        if isinstance(self.model_config, moe.MoeConfig):
            raise ValueError("LoRA serving is not supported on MoE models yet")
        if self._lora_pool is None:
            slots = self.config.lora_pool_slots
            if slots is None:
                slots = env_int("DYN_LORA_POOL_SLOTS", 8)
            self._lora_pool = LoraPool(
                self.model_config, list(adapters), slots=slots,
            )
        else:
            self._lora_pool.register(list(adapters))
        self._lora = self._lora_pool.stack

    def lora_names(self) -> List[str]:
        if self._lora_pool is not None:
            return self._lora_pool.known_names()
        return list(self._lora["names"]) if self._lora else []

    def _check_lora(self, req: PreprocessedRequest) -> Optional[str]:
        if not req.lora_name:
            return None
        cfg = self.config
        if self._lora is None or req.lora_name not in self.lora_names():
            return (
                f"unknown LoRA adapter {req.lora_name!r}; available: "
                f"{sorted(self.lora_names())}"
            )
        if cfg.spec_mode and not self._mixed_enabled:
            # fused spec verify rows carry the adapter index per row; the
            # split spec block has no adapter operand
            return "LoRA is incompatible with speculative decoding (spec_mode)"
        if cfg.pp_size > 1 or cfg.sp_size > 1:
            return "LoRA is not supported on pp/sp layouts yet"
        # (decode_pool_mode == "local" needs no rejection: the lora block
        # variant uses per-step pool scatter regardless of pool mode)
        if req.guided:
            return "guided decoding with a LoRA adapter is not supported yet"
        if req.multimodal:
            return "LoRA with multimodal content parts is not supported yet"
        return None

    def _acquire_lora(self, req: PreprocessedRequest) -> Optional[str]:
        """Resolve + PIN the request's adapter in the paging tier
        (models/lora_pool.py). Hot adapters are a dict lookup; cold ones
        onboard here (bounded, EWMA-priced). A full-and-pinned pool or an
        injected `lora.onboard` fault refuses TYPED — a counted refusal
        the caller can retry/route, never a silent base-model answer.
        Must run LAST in the admission check chain: a later rejection
        would leak the pin."""
        if not req.lora_name or self._lora_pool is None:
            return None
        from ..models.lora_pool import LoraPoolError

        try:
            req._lora_slot = self._lora_pool.acquire(req.lora_name)
        except LoraPoolError as e:
            return str(e)
        return None

    def _release_lora_pin(self, slot: "_Slot") -> None:
        """Idempotent per-stream unpin (clears the name, so double release
        on the finish->release path is a no-op)."""
        if slot.lora_name and self._lora_pool is not None:
            self._lora_pool.release(slot.lora_name)
            slot.lora_name = ""

    def _check_logprobs(self, req: PreprocessedRequest) -> Optional[str]:
        s = req.sampling_options or {}
        if self.config.spec_mode and (
            s.get("presence_penalty") or s.get("frequency_penalty")
            or (s.get("repetition_penalty") or 1.0) != 1.0
        ):
            return (
                "sampling penalties are not supported with speculative "
                "decoding (the verify pass has no penalty hook); run the "
                "worker without --spec"
            )
        if (
            self.config.spec_mode
            and (req.sampling_options or {}).get("logprobs")
        ):
            return (
                "logprobs are not supported with speculative decoding "
                "(the verify pass emits accepted drafts without per-token "
                "logprobs); run the worker without --spec"
            )
        return None

    def _check_guided(self, req: PreprocessedRequest) -> Optional[str]:
        """Validate + pre-compile a guided-decoding spec. Returns an error
        string (rejected request) or None. Like multimodal, silently
        dropping the constraint would be a WRONG answer, not a degraded
        one — unsupported layouts reject up front."""
        if not req.guided:
            return None
        cfg = self.config
        if cfg.spec_mode and not self._mixed_enabled:
            # fused guided rows are single-token and host-authoritative per
            # step, so they coexist with spec lanes on the mixed dispatch;
            # the split-only layout still rejects
            return (
                "guided decoding is incompatible with speculative decoding "
                "(run the worker without --spec)"
            )
        if cfg.pp_size > 1 or cfg.sp_size > 1:
            return "guided decoding is not supported on pp/sp layouts yet"
        if req.multimodal:
            return "guided decoding cannot be combined with multimodal parts"
        return None

    async def _compile_guided_async(self, req: PreprocessedRequest) -> Optional[str]:
        """Static checks + FSM compilation OFF the event loop (DFA subset
        construction + the full-vocab trie walk are pure-Python and can
        take seconds on a cold schema; in-flight streams must not stall)."""
        err = self._check_guided(req)
        if err is not None or not req.guided:
            return err
        try:
            fsm = await asyncio.to_thread(
                self._guided_compiler().compile, req.guided
            )
        except ValueError as e:
            return f"guided spec rejected: {e}"
        # hand the FSM to _new_slot directly: an LRU eviction between the
        # off-loop compile and slot creation must not re-run the compile
        # ON the event loop
        req._compiled_fsm = fsm
        return None

    def _guided_lane_mask(self, fsm, state: int) -> np.ndarray:
        """fsm.allowed trimmed/padded to the MODEL vocab width (the
        tokenizer vocab may differ; out-of-tokenizer logits rows are
        inadmissible)."""
        V = self.model_config.vocab_size
        row = fsm.allowed(state)
        if len(row) == V:
            return row
        if len(row) > V:
            return row[:V]
        out = np.zeros((V,), bool)
        out[: len(row)] = row
        return out

    def _new_slot(self, req: PreprocessedRequest, context: Context, suffix: str = "") -> _Slot:
        stop = req.stop_conditions or {}
        sampling = req.sampling_options or {}
        slot = _Slot(
            request_id=(req.request_id or f"jax-{self.num_requests}") + suffix,
            queue=asyncio.Queue(),
            context=context,
            prompt=list(req.token_ids),
            max_tokens=int(stop.get("max_tokens") or 128),
            min_tokens=int(stop.get("min_tokens") or 0),
            eos_ids=list(req.eos_token_ids or []),
            ignore_eos=bool(stop.get("ignore_eos")),
            stop_token_ids=list(stop.get("stop_token_ids") or []),
            # the adapter name salts the hash chain (reference lora_id in
            # protocols.rs:110-115): prefix cache / KVBM / router events all
            # key on these hashes, so two adapters sharing a text prefix can
            # never share KV
            seq=TokenBlockSequence(
                req.token_ids, self.config.page_size,
                salt=salt_hash(req.lora_name.encode())
                if req.lora_name else 0,
            ),
        )
        slot.kv_prompt = slot.prompt
        slot.mm = self._slot_mm(req)
        slot.temperature = float(
            sampling.get("temperature", self.config.default_temperature) or 0.0
        )
        slot.top_k = int(sampling.get("top_k") or 0)
        slot.top_p = float(sampling.get("top_p") or 1.0)
        slot.want_logprobs = bool(sampling.get("logprobs"))
        slot.presence_penalty = float(sampling.get("presence_penalty") or 0.0)
        slot.frequency_penalty = float(sampling.get("frequency_penalty") or 0.0)
        slot.repetition_penalty = float(
            sampling.get("repetition_penalty") or 1.0
        )
        # explicit seed => reproducible output independent of co-batched
        # traffic (counter-based draws, sampling.py); else a random one —
        # concurrent identical unseeded requests (n>1) must diverge
        import secrets as _secrets

        seed = sampling.get("seed")
        slot.sample_seed = (
            int(seed) & 0xFFFFFFFF if seed is not None
            else _secrets.randbits(32)
        )
        slot.want_top_logprobs = min(int(sampling.get("top_logprobs") or 0), 5)
        if req.guided:
            slot.guided_fsm = (
                getattr(req, "_compiled_fsm", None)
                or self._guided_compiler().compile(req.guided)
            )
            slot.guided_state = slot.guided_fsm.start_state
            self.guided_requests += 1
        if req.lora_name and self._lora is not None:
            pinned = getattr(req, "_lora_slot", None)
            slot.lora_idx = (
                pinned if pinned is not None
                else self._lora["names"].get(req.lora_name, 0)
            )
            if pinned is not None:
                # the _acquire_lora pin transfers to the slot (released
                # exactly once, at stream finish)
                slot.lora_name = req.lora_name
                req._lora_slot = None
            if slot.lora_idx:
                self.lora_requests += 1
        if len(slot.prompt) + slot.max_tokens > self.config.max_model_len:
            slot.max_tokens = max(self.config.max_model_len - len(slot.prompt), 1)
        slot.priority = int(req.priority or 0)
        slot.tenant = req.tenant or ""
        slot.migration = int(getattr(req, "migration", 0) or 0)
        slot.arrival_s = time.monotonic()
        self.scheduler.assign_deadline(slot)
        return slot

    def _morph_guard(self):
        """Refuse NEW streams mid-morph the same way the drain cut the
        in-flight ones: StreamSevered rides the `draining`-coded T_ERR so
        the caller's migration machinery re-routes instead of surfacing a
        terminal error. ("warm" is admitted — re-warm drives generate.)"""
        if self._morph_state in ("draining-role", "flipped"):
            raise StreamSevered(
                f"worker is morphing ({self._morph_state}); stream re-routed"
            )

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        self._morph_guard()
        self.start()
        req = (
            request
            if isinstance(request, PreprocessedRequest)
            else PreprocessedRequest.from_dict(request)
        )
        mm_err = self._check_multimodal(req)
        if mm_err is not None:
            # silently dropping image/audio parts would be a wrong answer,
            # not a degraded one (protocol contract in protocols/common.py).
            # Parts that arrived WITH encoder embeddings + positions are
            # spliced at prefill instead (E/P/D flow, _prefill_batch_mm).
            yield Annotated.from_error(mm_err).to_dict()
            return
        g_err = await self._compile_guided_async(req)
        if g_err is not None:
            yield Annotated.from_error(g_err).to_dict()
            return
        l_err = (
            self._check_lora(req) or self._check_logprobs(req)
            or self._acquire_lora(req)
        )
        if l_err is not None:
            yield Annotated.from_error(l_err).to_dict()
            return
        slot = self._new_slot(req, context)
        disagg = req.disagg_params or {}
        slot.return_kv = bool(disagg.get("return_kv"))
        slot.kv_pull = bool(disagg.get("kv_pull"))
        slot.kv_stream = bool(disagg.get("kv_stream"))
        slot.kv_holder = req.kv_holder
        self.num_requests += 1
        self._waiting.append(slot)
        self._wake.set()
        try:
            while True:
                item = await slot.queue.get()
                if item is None:
                    return
                if isinstance(item, Exception):
                    # _sever_all pushed a StreamSevered sentinel: raise it
                    # out of the handler so the request plane codes the
                    # T_ERR as `draining` and the caller migrates
                    raise item
                yield item
        finally:
            slot.done = True
            self._wake.set()

    async def _decode_entry_slot(self, request: Any, context: Context,
                                 first_token: Optional[int]):
        """Shared prologue of the disagg decode entries (from_kv / resume /
        from_pull): coerce + validate the request, build the "-d" slot,
        and catch the guided FSM up to the prefill worker's already-emitted
        first token. Returns (slot, None) or (None, error_string)."""
        self._morph_guard()
        self.start()
        req = (
            request
            if isinstance(request, PreprocessedRequest)
            else PreprocessedRequest.from_dict(request)
        )
        g_err = (
            await self._compile_guided_async(req) or self._check_lora(req)
            or self._check_logprobs(req) or self._acquire_lora(req)
        )
        if g_err is not None:
            return None, g_err
        slot = self._new_slot(req, context, suffix="-d")
        if slot.guided_fsm is not None and first_token is not None:
            slot.guided_state = slot.guided_fsm.advance(
                slot.guided_state, first_token
            )
        return slot, None

    async def _drain_decode_slot(self, slot: _Slot) -> AsyncIterator[dict]:
        """Shared epilogue: enqueue the slot and yield its stream until the
        terminal None, marking it done on consumer teardown."""
        self.num_requests += 1
        self._waiting.append(slot)
        self._wake.set()
        try:
            while True:
                item = await slot.queue.get()
                if item is None:
                    return
                if isinstance(item, Exception):
                    raise item  # morph-drain sentinel, see generate()
                yield item
        finally:
            slot.done = True
            self._wake.set()

    async def generate_decode_from_kv(
        self,
        request: Any,
        context: Context,
        first_token: int,
        kv_k_pages,
        kv_v_pages,
        n_tokens: int,
    ) -> AsyncIterator[dict]:
        """Disagg decode entry: continue decoding from remotely-prefilled KV
        (reference decode-with-kv_transfer_params, handlers.py:258-270).
        The first token was already produced by the prefill worker and is
        NOT re-emitted here."""
        slot, g_err = await self._decode_entry_slot(request, context, first_token)
        if g_err is not None:
            yield Annotated.from_error(g_err).to_dict()
            return
        slot.preloaded = (first_token, kv_k_pages, kv_v_pages, n_tokens)
        async for item in self._drain_decode_slot(slot):
            yield item

    async def generate_decode_resume(
        self, request: Any, context: Context, first_token: int
    ) -> AsyncIterator[dict]:
        """Disagg decode entry WITHOUT a usable KV payload (typed
        kv_format mismatch, docs/kvbm.md mixed-fleet rules): prefill the
        prompt locally and resume decoding from the prefill worker's
        already-emitted first token — the same fallback a failed pull
        takes, entered before any foreign bytes are interpreted."""
        slot, g_err = await self._decode_entry_slot(request, context, first_token)
        if g_err is not None:
            yield Annotated.from_error(g_err).to_dict()
            return
        slot.generated = 1
        slot.last_token = first_token
        slot.seq.append(first_token)
        slot.resume_token = first_token
        slot.prefill_pos = 0
        async for item in self._drain_decode_slot(slot):
            yield item

    async def generate_decode_from_pull(
        self, request: Any, context: Context, first_token: int, desc: dict
    ) -> AsyncIterator[dict]:
        """Disagg decode entry, pull path: the prefill worker staged the KV
        on its data plane; we allocate pages, then stream-inject chunks while
        the decode batch keeps stepping (transfer/compute overlap). Falls
        back to local prefill if the pull dies."""
        slot, g_err = await self._decode_entry_slot(request, context, first_token)
        if g_err is not None:
            yield Annotated.from_error(g_err).to_dict()
            return
        slot.preloaded = (first_token, None, None, int(desc["n_tokens"]))
        slot.pull_desc = desc
        async for item in self._drain_decode_slot(slot):
            yield item

    def begin_streamed_pull(
        self, request: Any, context: Context, desc: dict
    ) -> Optional[StreamedPullHandle]:
        """Disagg decode, streamed handoff (docs/disagg_serving.md): start
        pulling KV chunks off the prefill worker's EARLY descriptor while
        its prefill is still running — the transfer overlaps the peer's
        compute, and the first decode step dispatches as soon as the last
        chunk and the first token both land, instead of paying the whole
        transfer serially after prefill. Returns None for request kinds
        the preload path doesn't carry (guided/multimodal/bad-lora); the
        handler then rides the serial path."""
        self.start()
        req = (
            request
            if isinstance(request, PreprocessedRequest)
            else PreprocessedRequest.from_dict(request)
        )
        if req.guided is not None or req.multimodal:
            # guided FSM compilation is async and multimodal splices don't
            # ride the preload path: the serial handoff covers these
            return None
        if self._morph_state in ("draining-role", "flipped"):
            # mid-morph: fall to the serial path, whose _decode_entry_slot
            # raises StreamSevered so the caller re-routes
            return None
        if self._check_lora(req) is not None or self._check_logprobs(req) is not None:
            return None
        slot = self._new_slot(req, context, suffix="-d")
        slot.preloaded = (None, None, None, int(desc["n_tokens"]))
        slot.pull_desc = dict(desc)
        slot.first_token_fut = asyncio.get_running_loop().create_future()
        self.num_requests += 1
        self._waiting.append(slot)
        self._wake.set()
        return StreamedPullHandle(self, slot, str(desc.get("transfer_id", "")))

    def clear_kv_blocks(self) -> int:
        """Admin flush (reference clear-kv-blocks route, service_v2.rs:
        319-339): evict every unreferenced prefix-cache page (emitting
        removed events so routers un-index them) and drop the KVBM tiers.
        Active sequences keep their pages."""
        n = self.allocator.clear_cache()
        if self.kvbm is not None:
            n += self.kvbm.clear()
        return n

    def stats(self) -> dict:
        alloc_stats = self.allocator.stats()
        running = sum(1 for s in self.slots if s is not None)
        kv_nbytes = (
            int(self.kv_k.nbytes) + int(self.kv_v.nbytes)
            if hasattr(self.kv_k, "nbytes") else 0
        )
        out = {
            NUM_WAITING_REQS: len(self._waiting),
            NUM_RUNNING_REQS: running,
            "gpu_cache_usage_perc": self.allocator.active_pages / self.allocator.num_pages,
            "request_total_slots": self.config.max_num_seqs,
            # quantized KV density surface (docs/kvbm.md): the format, the
            # resident pool bytes (incl. scales), and the typed
            # mixed-precision rejections — what the bench's sessions-per-
            # HBM-budget gate and a fleet-misconfig alert read
            "kv_quant": self.config.kv_quant,
            "kv_pool_bytes": kv_nbytes,
            "kv_format_mismatches": self.kv_format_mismatches,
            **alloc_stats,
        }
        if self.kvbm is not None:
            out.update(self.kvbm.stats())
            # tier-chain effectiveness (docs/kvbm.md): G1 admission hit/miss
            # plus the onboard latency histogram the bench/planner read
            out["kvbm_g1_hit_blocks"] = self.kvbm_g1_hit_blocks
            out["kvbm_g1_miss_blocks"] = self.kvbm_g1_miss_blocks
            out["kvbm_onboard_count"] = self.kvbm_onboard_count
            out["kvbm_onboard_ms_sum"] = round(self.kvbm_onboard_ms_sum, 3)
            out["kvbm_onboard_hist"] = {
                **{
                    f"le_{b:g}ms": n
                    for b, n in zip(self._onboard_hist_bounds,
                                    self.kvbm_onboard_hist)
                },
                "inf": self.kvbm_onboard_hist[-1],
            }
        if self.data_plane is not None:
            out["kv_transfers_served"] = self.data_plane.transfers_served
            out["kv_bytes_served"] = self.data_plane.bytes_served
            # session-checkpoint pushes ACCEPTED into this worker's tiers
            # (the replica-holder side of durable decode sessions)
            out["kv_checkpoint_pushes"] = self.data_plane.checkpoint_pushes
            out["kv_checkpoint_blocks_received"] = (
                self.data_plane.checkpoint_blocks_received
            )
        out["kv_pulls_completed"] = self.kv_pulls_completed
        out["kv_pages_pulled"] = self.kv_pages_pulled
        # streamed disagg handoff (docs/disagg_serving.md): decode-side
        # overlap evidence + prefill-side stage accounting. The ratio is
        # the acceptance signal — >0 means first tokens reached clients
        # while KV tail chunks were still in flight
        out["disagg_streamed_handoffs"] = self.disagg_streamed_handoffs
        out["disagg_chunks_before_first_token"] = (
            self.disagg_chunks_before_first_token
        )
        out["disagg_first_token_before_last_chunk"] = (
            self.disagg_first_token_before_last_chunk
        )
        out["disagg_streamed_handoff_ratio"] = round(
            self.disagg_first_token_before_last_chunk
            / self.disagg_streamed_handoffs, 4
        ) if self.disagg_streamed_handoffs else 0.0
        out["kv_streamed_stages"] = self.kv_streamed_stages
        out["kv_streamed_fallbacks"] = self.kv_streamed_fallbacks
        # migration observability (docs/fault_tolerance.md): how many
        # streams resumed here after a worker death, what each resume
        # actually cost (tokens re-prefilled) and where the session
        # prefix came from — the kill-mid-decode CI arm gates on
        # resume_source_checkpoint > 0
        out["migrations_resumed"] = self.migrations_resumed
        out["migration_replayed_tokens"] = self.migration_replayed_tokens
        out["resume_source_checkpoint"] = self.resume_source_checkpoint
        out["resume_source_peer"] = self.resume_source_peer
        out["resume_source_local"] = self.resume_source_local
        out["resume_source_recompute"] = self.resume_source_recompute
        # role-morph telemetry (docs/autoscaling.md "Role morphing"):
        # per-role marginal throughput prices the planner's re-role arm;
        # the role/state gauges make a flip observable
        est_role = self.estimated_role_tok_s()
        out[SCHED_EST_PREFILL_TOK_S] = round(est_role["prefill"], 1)
        out[SCHED_EST_DECODE_TOK_S] = round(est_role["decode"], 1)
        out["engine_role"] = self._role
        out["morph_state"] = self._morph_state
        out["morphs_completed"] = self.morphs_completed
        out["morphs_rolled_back"] = self.morphs_rolled_back
        out["morph_drained_sessions"] = self.morph_drained_sessions
        out["morph_last_duration_s"] = round(self.morph_last_duration_s, 3)
        out["kv_skip_ahead_blocks"] = self.prefix_skip_ahead_blocks
        out["emit_batches"] = self.emit_batches
        out["emit_tokens"] = self.emit_tokens
        # ragged unified dispatch: is the fused path actually taken in
        # production, and what padding does each path pay per step
        # (docs/ragged_attention.md; jax_worker republishes these as
        # prometheus gauges)
        out["mixed_steps"] = self.mixed_steps
        out["split_steps"] = self.split_steps
        out["mixed_padding_frac"] = round(
            1.0 - self.mixed_real_tokens / self.mixed_padded_tokens, 4
        ) if self.mixed_padded_tokens else 0.0
        out["split_padding_frac"] = round(
            1.0 - self.split_real_tokens / self.split_padded_tokens, 4
        ) if self.split_padded_tokens else 0.0
        # per-kind fused coverage: which workloads actually ride the fused
        # path (ISSUE 19 CI gate: coverage >= 0.9 on blended traffic)
        out["mixed_rows_plain"] = self.mixed_rows_plain
        out["mixed_rows_guided"] = self.mixed_rows_guided
        out["mixed_rows_spec"] = self.mixed_rows_spec
        out["mixed_rows_lora"] = self.mixed_rows_lora
        denom = self.mixed_steps + self.split_steps
        out["mixed_coverage_frac"] = (
            round(self.mixed_steps / denom, 4) if denom else 1.0
        )
        if self._lora_pool is not None:
            out.update(self._lora_pool.stats())
        # dynosched: policy/targets, per-step decision counters, and the
        # queue/deadline view (published on the worker metrics topic, so
        # disagg decode workers and the planner see prefill-pool pressure)
        out.update(self.scheduler.stats())
        est = self.estimated_prefill_wait_ms()
        out[SCHED_EST_TTFT_MS] = round(est, 1) if est is not None else 0.0
        out[SCHED_EST_REQ_MS] = round(self.estimated_req_ms(), 1)
        recent = self.scheduler.recent_decisions()
        if recent:
            out["sched_last_decision"] = recent[-1]
        # list() is one atomic C-level snapshot: the jax-step thread keeps
        # inserting while we iterate (GUARDED_STATE: thread-confined)
        for tag, (cnt, tot) in list(self._dev_time.items()):
            out[f"dispatch_{tag}_count"] = cnt
            out[f"dispatch_{tag}_s"] = round(tot, 3)
        # compile telemetry (docs/compilation.md): XLA cache size per
        # staged surface plus the steady-state gate — programs compiled
        # AFTER the warmup baseline snapshot. dynocomp proves warmup
        # reachability statically; post_warmup_compiles proves the same
        # contract at runtime (>0 in steady state = a shape leaked past
        # the bucketing helpers or warmup missed a variant)
        sizes = self._surface_cache_sizes()
        out["compile_surfaces"] = {k: v for k, v in sizes.items() if v}
        out["compiled_variants"] = sum(sizes.values())
        base = self._warmup_compile_baseline
        out["post_warmup_compiles"] = sum(
            max(v - base.get(k, 0), 0) for k, v in sizes.items()
        ) if base is not None else 0
        if self.guided_requests:
            out["guided_requests"] = self.guided_requests
        if self.lora_requests:
            out["lora_requests"] = self.lora_requests
        if self.config.spec_mode:
            out["spec_num_drafts"] = self.spec_num_drafts
            out["spec_num_draft_tokens"] = self.spec_num_draft_tokens
            out["spec_num_accepted_tokens"] = self.spec_num_accepted_tokens
            out["spec_mean_accepted_len"] = (
                1.0 + self.spec_num_accepted_tokens / self.spec_num_drafts
                if self.spec_num_drafts else 0.0
            )
        return out

    def estimated_prefill_wait_ms(self, n_new_tokens: int = 0) -> Optional[float]:
        """Estimated local TTFT contribution of this engine's prefill
        queue for a hypothetical `n_new_tokens`-token arrival: (tokens
        still to prefill across admitted + waiting slots + the new
        prompt) x the cost model's observed per-token prefill rate.
        None until the model has seen a prefill (cold start) — callers
        (DisaggregatedRouter) fall back to the static threshold rule."""
        pending = int(n_new_tokens)
        for s in self.slots:
            if (
                s is not None and not s.done
                and s.preloaded is None and s.onboard is None
            ):
                pending += max(len(s.kv_prompt) - s.prefill_pos, 0)
        for s in self._waiting:
            pending += len(s.prompt)
        return self.scheduler.estimate_wait_ms(pending)

    def estimated_req_ms(self) -> float:
        """Marginal TTFT one more admitted request adds (the dynogate
        optimism-debt unit, docs/overload.md): a typical-length prompt at
        the cost model's observed per-token prefill rate. 0.0 when the
        model is cold or the queue is empty — the gate then corrects from
        the next published sched_est_ttft_ms instead."""
        per_tok = self.scheduler.cost.per_token("prefill")
        if per_tok is None:
            return 0.0
        lens = [
            len(s.kv_prompt) for s in self.slots
            if s is not None and not s.done
        ]
        lens += [len(s.prompt) for s in self._waiting]
        if not lens:
            return 0.0
        return (sum(lens) / len(lens)) * per_tok * 1000.0

    # ------------------------------------------------------------------ #
    # step loop
    # ------------------------------------------------------------------ #

    async def _step_loop(self):
        while not self._closed:
            has_active = any(s is not None for s in self.slots)
            if (
                not self._waiting
                and not has_active
                and not self._inflight
                and not self._pending_prefill
            ):
                self._wake.clear()
                await self._wake.wait()
                continue
            try:
                f = faults.FAULTS
                if f.enabled:
                    # dynochaos `engine.step`: a raised FaultError rides the
                    # organic step-failure path below (fail-all -> migration)
                    await f.on("engine.step")
                progressed = await self._step_once()
            except Exception as e:  # noqa: BLE001 — engine loop must not die silently
                logger.exception("engine step failed; failing active requests")
                self._fail_all(f"engine step failed: {type(e).__name__}: {e}")
                await asyncio.sleep(0.1)
                continue
            # yield to the event loop so streams flush between steps
            await asyncio.sleep(0 if progressed else 0.001)

    async def _step_once(self) -> bool:
        """One engine iteration: admit, dispatch (ONE fused mixed step
        when both prefill and decode are runnable, else prefill batch +
        decode block), then collect ALL host-needed values in one
        device_get."""
        self._admit_waiting()
        progressed = await self._run_injections()
        dispatched = False
        if await self._dispatch_mixed():
            progressed = True
        else:
            self._last_prefill_shape = self._last_decode_shape = None
            pf = False
            if not self._mixed_wait_drain:
                pf = await self._dispatch_prefill()
            progressed |= pf
            dispatched = await self._dispatch_decode()
            if pf and dispatched and self._last_prefill_shape \
                    and self._last_decode_shape:
                # a mixed-shaped step served by the split pair (mixed off,
                # variant kinds, pipeline in flight, planner refusal):
                # account its padding beside the fused path's
                self.split_steps += 1
                self.split_padded_tokens += (
                    self._last_prefill_shape[0] + self._last_decode_shape[0]
                )
                self.split_real_tokens += (
                    self._last_prefill_shape[1] + self._last_decode_shape[1]
                )
        # fetch the oldest block only once the pipeline is full or stalled,
        # so its host read overlaps the newer block's compute
        fetch_block = len(self._inflight) >= 2 or (
            bool(self._inflight) and not dispatched
        )
        progressed |= dispatched
        progressed |= await self._fetch_and_process(fetch_block)
        if self.kvbm is not None:
            # coalesce this step's block commits into ONE offload gather
            # (kvbm pipeline, docs/kvbm.md) — the only KVBM work the
            # device executor ever sees is that single dispatch
            self.kvbm.flush_step()
        return progressed

    # -- admission ------------------------------------------------------- #

    def _admit_waiting(self):
        still: List[_Slot] = []
        # sla policy: admit earliest-TTFT-deadline first (preempted victims
        # keep their original arrival, so they stay at the front exactly as
        # the legacy insert-at-0 intended); fifo: arrival order untouched
        for slot in self.scheduler.order_waiting(self._waiting):
            if slot.done or slot.context.is_stopped():
                self._emit_finish(slot, "cancelled")
                continue
            if not self._free_slots or not self._try_admit(slot):
                still.append(slot)
        self._waiting = still

    def _try_admit(self, slot: _Slot) -> bool:
        cfg = self.config
        if slot.preloaded is not None:
            # disagg decode role: all prompt pages fresh; KV arrives by
            # injection, not prefill
            n_pages = (len(slot.prompt) + cfg.page_size - 1) // cfg.page_size
            if not self.allocator.can_allocate(n_pages + 1):
                return False
            fresh = self.allocator.alloc_fresh(n_pages)
            if fresh is None:
                return False
            idx = self._free_slots.pop()
            slot.slot_idx = idx
            slot.pages = fresh
            slot.committed_hashes = []
            slot.prefill_pos = len(slot.prompt)
            self.slots[idx] = slot
            self.page_tables[idx, :] = SCRATCH_PAGE
            self.page_tables[idx, : len(fresh)] = [p + 1 for p in fresh]
            self.seq_lens[idx] = 0
            self.temps[idx] = slot.temperature
            self.top_ks[idx] = slot.top_k
            self.top_ps[idx] = slot.top_p
            self.lora_idx[idx] = slot.lora_idx
            self.seeds[idx] = slot.sample_seed
            self.presence[idx] = slot.presence_penalty
            self.frequency[idx] = slot.frequency_penalty
            self.repetition[idx] = slot.repetition_penalty
            self._fill_recent(idx, slot)
            slot.admit_seq = self._admit_counter = self._admit_counter + 1
            return True
        kv_prompt = slot.kv_prompt
        hashes = slot.seq.block_hashes()
        cached_pages = (
            self.allocator.acquire_cached(hashes) if cfg.enable_prefix_caching else []
        )
        n_cached = len(cached_pages)
        # KVBM: probe G2/G3 for the hashes the device cache missed; tier hits
        # are injected before prefill (onboard), extending the cached prefix.
        # The probe extends onto PEER tiers too (announcement mesh + the
        # router's holder hint — cluster KV fabric, docs/kvbm.md)
        onboard_hashes: List[int] = []
        hint_inst = None
        prompt_full_blocks = len(kv_prompt) // cfg.page_size
        if self.kvbm is not None and cfg.enable_prefix_caching:
            hint = slot.kv_holder or {}
            hint_inst = hint.get("instance")
            onboard_hashes = self.kvbm.probe(
                hashes[n_cached:prompt_full_blocks],
                hint_instance=hint_inst,
                hint_blocks=max(int(hint.get("blocks", 0)) - n_cached, 0),
            )
        # allocate the prompt's remaining pages now; generation pages grow later
        prompt_pages = (len(kv_prompt) + cfg.page_size - 1) // cfg.page_size
        fresh_prompt = max(prompt_pages - n_cached, 0)
        if not self.allocator.can_allocate(fresh_prompt + 1):
            self.allocator.release(cached_pages, hashes[:n_cached])
            return False
        fresh = self.allocator.alloc_fresh(fresh_prompt)
        if fresh is None:
            self.allocator.release(cached_pages, hashes[:n_cached])
            return False
        # admission is now certain: count G1 hit/miss and settle the
        # onboard budget HERE, not before the allocation checks — a
        # pool-pressured slot retries _try_admit every step, and counting
        # pre-failure would re-count the same request per retry
        if self.kvbm is not None and cfg.enable_prefix_caching:
            self.kvbm_g1_hit_blocks += n_cached
            self.kvbm_g1_miss_blocks += max(prompt_full_blocks - n_cached, 0)
            if onboard_hashes:
                # three-arm onboard budget (docs/kvbm.md cluster KV
                # fabric): local-tier load vs per-peer transfer rate vs
                # recompute — the cheapest source wins per span, and a
                # cold/slow peer never blocks TTFT past the headroom
                # (it loses to a local-prefix trim or full recompute).
                # Cold tiers / cold peers / cold cost model never defer,
                # same rule as the scheduler's CostModel. Under fifo
                # (headroom None) the budget only does source accounting.
                headroom_ms = self.scheduler.onboard_headroom_ms(slot)
                rate = self.scheduler.cost.per_token("prefill")
                onboard_hashes, _ = self.kvbm.budget_onboard(
                    list(onboard_hashes), headroom_ms,
                    rate * 1000.0 * cfg.page_size if rate is not None else None,
                    hint_instance=hint_inst,
                )
        n_onboard = len(onboard_hashes)
        if slot.migration:
            self._count_resume(slot, hashes, n_cached, onboard_hashes)
        idx = self._free_slots.pop()
        slot.slot_idx = idx
        slot.pages = cached_pages + fresh
        slot.committed_hashes = hashes[:n_cached]
        slot.prefill_pos = min((n_cached + n_onboard) * cfg.page_size, len(kv_prompt))
        if n_onboard:
            slot.onboard = (fresh[:n_onboard], onboard_hashes)
        # skip-ahead: if the whole prompt is cached, recompute the last token
        # (need its logits) — back off one position
        if slot.prefill_pos >= len(kv_prompt):
            slot.prefill_pos = len(kv_prompt) - 1
        self.slots[idx] = slot
        # host state
        self.page_tables[idx, :] = SCRATCH_PAGE
        phys = [p + 1 for p in slot.pages]  # +1: scratch shift
        self.page_tables[idx, : len(phys)] = phys
        self.seq_lens[idx] = 0
        self.temps[idx] = slot.temperature
        self.top_ks[idx] = slot.top_k
        self.top_ps[idx] = slot.top_p
        self.lora_idx[idx] = slot.lora_idx
        self.seeds[idx] = slot.sample_seed
        self.presence[idx] = slot.presence_penalty
        self.frequency[idx] = slot.frequency_penalty
        self.repetition[idx] = slot.repetition_penalty
        self._fill_recent(idx, slot)
        slot.admit_seq = self._admit_counter = self._admit_counter + 1
        self.scheduler.on_admit(slot)
        if (
            slot.kv_pull and slot.kv_stream and self.data_plane is not None
            and not (self._multihost and self.shard_addrs)
        ):
            # streamed disagg handoff: stage NOW, before any prefill runs —
            # the decode worker pulls chunks while we compute
            # (multi-host shard staging keeps the serial flow)
            self._stage_streamed_kv(slot)
        return True

    # -- device helpers -------------------------------------------------- #

    def _timed(self, fn, tag: str, shape: Optional[tuple] = None):
        """Wrap fn so its wall time accrues to self._dev_time[tag] (and,
        when `shape`=(bucket, lanes) is given, feeds the scheduler's
        per-shape cost model — the EWMA behind ITL budgeting and the
        disagg router's local-TTFT estimate)."""
        def timed(*a):
            t0 = time.perf_counter()
            try:
                return fn(*a)
            finally:
                dt = time.perf_counter() - t0
                cnt, tot = self._dev_time.get(tag, (0, 0.0))
                self._dev_time[tag] = (cnt + 1, tot + dt)
                if shape is not None:
                    self.scheduler.cost.observe(tag, shape[0], shape[1], dt)
        return timed

    async def _run_on_device(self, fn, *args, tag: str = None,
                             shape: Optional[tuple] = None):
        if tag is not None:
            fn = self._timed(fn, tag, shape)
        return await asyncio.get_running_loop().run_in_executor(
            self._device_exec, fn, *args
        )

    async def _fetch(self, tree):
        """One host read (single RTT) for an arbitrary pytree of device
        arrays, off the dispatch thread."""
        return await asyncio.get_running_loop().run_in_executor(
            self._fetch_exec, self._timed(jax.device_get, "fetch"), tree
        )

    def _bcast(self, tag: str, arrays: dict):
        """Mirror a device dispatch to follower hosts (SPMD: every host
        must enter the same jitted programs in the same order)."""
        if self._spmd is not None:
            self._spmd.send(tag, arrays)

    def _mark_lane_dirty(self, idx: int):
        """Lane state changed on host (admission/finish/resume): patch just
        that lane before the next block instead of a full carry reset."""
        if self._carry_valid and idx >= 0:
            self._dirty_lanes.add(idx)

    # -- replicated device programs (leader dispatches these after a
    # _bcast; followers replay them verbatim in run_follower) ------------ #

    def _dev_prefill(self, toks, positions, tables, ctx_lens, last_idx,
                     temps, top_ks, top_ps, seeds, pens, pen_rows):
        samp = SamplingParams(
            temperature=jnp.asarray(temps),
            top_k=jnp.asarray(top_ks),
            top_p=jnp.asarray(top_ps),
            seed=jnp.asarray(seeds),
            presence=jnp.asarray(pens[:, 0]),
            frequency=jnp.asarray(pens[:, 1]),
            repetition=jnp.asarray(pens[:, 2]),
        )
        first, self.kv_k, self.kv_v, self._rng = self._prefill_batch(
            self.params,
            self.kv_k,
            self.kv_v,
            jnp.asarray(toks),
            jnp.asarray(positions),
            jnp.asarray(tables),
            jnp.asarray(ctx_lens),
            jnp.asarray(last_idx),
            samp,
            self._rng,
            jnp.asarray(pen_rows),
        )
        return first

    def _dev_mixed(self, toks, positions, row_ids, tables, row_starts,
                   row_lens, ctx_lens, last_flat, temps, top_ks, top_ps,
                   seeds, pens, pen_rows, mask_packed=None, lora_idx=None):
        samp = SamplingParams(
            temperature=jnp.asarray(temps),
            top_k=jnp.asarray(top_ks),
            top_p=jnp.asarray(top_ps),
            seed=jnp.asarray(seeds),
            presence=jnp.asarray(pens[:, 0]),
            frequency=jnp.asarray(pens[:, 1]),
            repetition=jnp.asarray(pens[:, 2]),
        )
        args = (
            self.params,
            self.kv_k,
            self.kv_v,
            jnp.asarray(toks),
            jnp.asarray(positions),
            jnp.asarray(row_ids),
            jnp.asarray(tables),
            jnp.asarray(row_starts),
            jnp.asarray(row_lens),
            jnp.asarray(ctx_lens),
            jnp.asarray(last_flat),
            samp,
            self._rng,
            jnp.asarray(pen_rows),
        )
        if mask_packed is None and lora_idx is None:
            # plain pack: the lean program, byte-identical operands to the
            # pre-variant fused path
            first, self.kv_k, self.kv_v, self._rng = self._mixed_step(*args)
        else:
            # variant pack: the mask operand is always present (all-ones
            # for maskless packs — an exact no-op), the LoRA operand rides
            # iff adapters are registered (idx 0 rows are the base no-op),
            # so exactly ONE variant program exists per deployment
            lora = (
                self._lora_operand(lora_idx)
                if self._lora is not None and lora_idx is not None else None
            )
            first, self.kv_k, self.kv_v, self._rng = self._mixed_step_variant(
                *args, jnp.asarray(mask_packed), lora
            )
        return first

    def _dev_prefill_mm(self, toks, positions, tables, ctx_lens, last_idx,
                        temps, top_ks, top_ps, seeds, pens, pen_rows,
                        emb, emb_mask):
        samp = SamplingParams(
            temperature=jnp.asarray(temps),
            top_k=jnp.asarray(top_ks),
            top_p=jnp.asarray(top_ps),
            seed=jnp.asarray(seeds),
            presence=jnp.asarray(pens[:, 0]),
            frequency=jnp.asarray(pens[:, 1]),
            repetition=jnp.asarray(pens[:, 2]),
        )
        first, self.kv_k, self.kv_v, self._rng = self._prefill_batch_mm(
            self.params,
            self.kv_k,
            self.kv_v,
            jnp.asarray(toks),
            jnp.asarray(positions),
            jnp.asarray(tables),
            jnp.asarray(ctx_lens),
            jnp.asarray(last_idx),
            samp,
            self._rng,
            jnp.asarray(pen_rows),
            jnp.asarray(emb),
            jnp.asarray(emb_mask),
        )
        return first

    def _dev_prefill_guided(self, toks, positions, tables, ctx_lens, last_idx,
                            temps, top_ks, top_ps, seeds, pens, pen_rows,
                            mask):
        samp = SamplingParams(
            temperature=jnp.asarray(temps),
            top_k=jnp.asarray(top_ks),
            top_p=jnp.asarray(top_ps),
            seed=jnp.asarray(seeds),
            presence=jnp.asarray(pens[:, 0]),
            frequency=jnp.asarray(pens[:, 1]),
            repetition=jnp.asarray(pens[:, 2]),
        )
        first, self.kv_k, self.kv_v, self._rng = self._prefill_batch_guided(
            self.params,
            self.kv_k,
            self.kv_v,
            jnp.asarray(toks),
            jnp.asarray(positions),
            jnp.asarray(tables),
            jnp.asarray(ctx_lens),
            jnp.asarray(last_idx),
            samp,
            self._rng,
            jnp.asarray(pen_rows),
            jnp.asarray(mask),
        )
        return first

    def _lora_operand(self, idx):
        return {
            "a": self._lora["a"],
            "b": self._lora["b"],
            "scale": self._lora["scale"],
            "idx": jnp.asarray(idx),
        }

    def _dev_prefill_lora(self, toks, positions, tables, ctx_lens, last_idx,
                          temps, top_ks, top_ps, seeds, pens, pen_rows, idx):
        samp = SamplingParams(
            temperature=jnp.asarray(temps),
            top_k=jnp.asarray(top_ks),
            top_p=jnp.asarray(top_ps),
            seed=jnp.asarray(seeds),
            presence=jnp.asarray(pens[:, 0]),
            frequency=jnp.asarray(pens[:, 1]),
            repetition=jnp.asarray(pens[:, 2]),
        )
        first, self.kv_k, self.kv_v, self._rng = self._prefill_batch_lora(
            self.params,
            self.kv_k,
            self.kv_v,
            jnp.asarray(toks),
            jnp.asarray(positions),
            jnp.asarray(tables),
            jnp.asarray(ctx_lens),
            jnp.asarray(last_idx),
            samp,
            self._rng,
            jnp.asarray(pen_rows),
            self._lora_operand(idx),
        )
        return first

    def _dev_block_lora(self, idx):
        carry = self._carry
        (
            toks,
            tok_d,
            pos_d,
            sl_d,
            self.kv_k,
            self.kv_v,
            self._rng,
            self._pen_dev,
        ) = self._decode_block_lora(
            self.params,
            self.kv_k,
            self.kv_v,
            carry[0],
            carry[1],
            carry[2],
            self._tables_dev,
            self._samp_dev,
            self._rng,
            self._pen_dev,
            self._lora_operand(idx),
        )
        self._carry = (tok_d, pos_d, sl_d)
        return toks

    def _dev_reset(self, tokens, positions, seq_lens, page_tables, temps,
                   top_ks, top_ps, seeds, pens, recent, hist=None):
        self._samp_dev = SamplingParams(
            temperature=jnp.asarray(temps),
            top_k=jnp.asarray(top_ks),
            top_p=jnp.asarray(top_ps),
            seed=jnp.asarray(seeds),
            presence=jnp.asarray(pens[:, 0]),
            frequency=jnp.asarray(pens[:, 1]),
            repetition=jnp.asarray(pens[:, 2]),
        )
        self._carry = (
            jnp.asarray(tokens),
            jnp.asarray(positions),
            jnp.asarray(seq_lens),
        )
        self._pen_dev = jnp.asarray(recent)
        self._tables_dev = jnp.asarray(page_tables)
        if hist is not None:
            self._hist_dev = jnp.asarray(hist)

    def _dev_patch(self, lane_mask, table_mask, tokens, positions, seq_lens,
                   tables, temps, top_ks, top_ps, seeds, pens, recent,
                   hist=None):
        samp = self._samp_dev
        pens_cur = jnp.stack(
            [samp.presence, samp.frequency, samp.repetition], axis=1
        )
        (
            tok_d, pos_d, sl_d, tab_d, t_d, k_d, p_d, s_d, pen_d, rec_d,
        ) = self._patch_lanes(
            self._carry[0], self._carry[1], self._carry[2], self._tables_dev,
            samp.temperature, samp.top_k, samp.top_p, samp.seed,
            pens_cur, self._pen_dev,
            jnp.asarray(lane_mask), jnp.asarray(table_mask),
            jnp.asarray(tokens), jnp.asarray(positions), jnp.asarray(seq_lens),
            jnp.asarray(tables), jnp.asarray(temps), jnp.asarray(top_ks),
            jnp.asarray(top_ps), jnp.asarray(seeds), jnp.asarray(pens),
            jnp.asarray(recent),
        )
        self._carry = (tok_d, pos_d, sl_d)
        self._tables_dev = tab_d
        self._pen_dev = rec_d
        self._samp_dev = SamplingParams(
            temperature=t_d, top_k=k_d, top_p=p_d, seed=s_d,
            presence=pen_d[:, 0], frequency=pen_d[:, 1],
            repetition=pen_d[:, 2],
        )
        if hist is not None and self._hist_dev is not None:
            # dirty lanes take the host ring row; others keep the (newer)
            # device rows appended by in-flight spec blocks
            self._hist_dev = jnp.where(
                jnp.asarray(lane_mask)[:, None], jnp.asarray(hist),
                self._hist_dev,
            )

    def _dev_block(self):
        carry = self._carry
        if self._spec_block_fn is not None:
            (
                toks, n_emit, tok_d, pos_d, sl_d,
                self.kv_k, self.kv_v, self._rng, self._hist_dev,
            ) = self._spec_block_fn(
                self.params, self.kv_k, self.kv_v,
                carry[0], carry[1], carry[2],
                self._tables_dev, self._samp_dev, self._rng, self._hist_dev,
            )
            self._carry = (tok_d, pos_d, sl_d)
            return (toks, n_emit)
        (
            toks,
            tok_d,
            pos_d,
            sl_d,
            self.kv_k,
            self.kv_v,
            self._rng,
            self._pen_dev,
        ) = self._decode_block(
            self.params,
            self.kv_k,
            self.kv_v,
            carry[0],
            carry[1],
            carry[2],
            self._tables_dev,
            self._samp_dev,
            self._rng,
            self._pen_dev,
        )
        self._carry = (tok_d, pos_d, sl_d)
        return toks

    def _dev_block_guided(self, mask, lora_idx=None):
        carry = self._carry
        args = (
            self.params, self.kv_k, self.kv_v,
            carry[0], carry[1], carry[2],
            self._tables_dev, self._samp_dev, self._rng,
            jnp.asarray(mask), self._pen_dev,
        )
        if lora_idx is not None:
            out = self._decode_step_guided_lora(
                *args, self._lora_operand(lora_idx)
            )
        else:
            out = self._decode_step_guided(*args)
        (
            toks, tok_d, pos_d, sl_d, self.kv_k, self.kv_v, self._rng,
            self._pen_dev,
        ) = out
        self._carry = (tok_d, pos_d, sl_d)
        return toks

    def _dev_inject(self, page_ids, k_np, v_np):
        from ..ops.kv_quant import device_pages

        c = self.model_config
        mode = self.config.kv_quant
        # quantized payloads arrive as packed uint8 [L, n, PB] rows
        # (q bytes + scales, the host/wire layout) and unpack into the
        # QuantKV leaves here; fp payloads are the seed's jnp.asarray
        self.kv_k, self.kv_v = self._inject_pages(
            self.kv_k,
            self.kv_v,
            jnp.asarray(page_ids),
            device_pages(k_np, mode, self.config.page_size,
                         c.num_kv_heads, c.head_dim),
            device_pages(v_np, mode, self.config.page_size,
                         c.num_kv_heads, c.head_dim),
        )

    def _dev_extract(self, page_ids):
        """Gather pages to host (disagg KV hand-off). On a multi-host mesh
        the KV shards live on several hosts — process_allgather (a
        collective: followers run it too) assembles the full pages. Used
        only by the INLINE-payload fallback; the pull data plane moves
        per-host shards instead (_extract_local_shard)."""
        k, v = self._extract_pages(self.kv_k, self.kv_v, jnp.asarray(page_ids))
        if self._multihost:
            from jax.experimental import multihost_utils

            return (
                multihost_utils.process_allgather(k),
                multihost_utils.process_allgather(v),
            )
        from ..ops.kv_quant import host_pack_pages

        # fp: the seed's np.asarray; quantized: packed uint8 [L, n, PB]
        # rows (q bytes + scales) — the ONE host/wire page layout
        return host_pack_pages(k), host_pack_pages(v)

    def _kv_wire_meta(self):
        """(page_shape, dtype_name) as KV pages travel on the wire: the
        fp [L, ps, KH, D] layout, or the packed uint8 [L, PAGE_BYTES]
        rows of a quantized pool (ops/kv_quant.py host layout). Every
        disagg descriptor/payload carries kv_format beside this so a
        mixed-precision pairing fails typed, never misreads bytes."""
        c = self.model_config
        cfg = self.config
        if cfg.kv_quant != "none":
            from ..ops.kv_quant import kv_page_bytes

            pb = kv_page_bytes(
                cfg.page_size, c.num_kv_heads, c.head_dim, c.dtype,
                cfg.kv_quant,
            )
            return [c.num_layers, pb], "uint8"
        return (
            [c.num_layers, cfg.page_size, c.num_kv_heads, c.head_dim],
            str(jnp.zeros((), c.dtype).dtype),
        )

    def _kv_headwise_shards_ok(self) -> bool:
        """True iff every local KV-pool shard spans the FULL extent on all
        axes except the kv-head axis (3) — the only layout that
        _local_shard_views/_extract_local_shard (axis-3 concat) and
        _dev_inject_shard (global_shape widened on axis 3 only) can
        reassemble. A pool sharded on layers (pp multihost) or pages
        (dp-attention over a multi-host mesh) would be silently corrupted
        by the per-shard path, so such layouts must use the inline
        allgather transfer instead (advisor r3 finding)."""
        shape = self.kv_k.shape
        for s in self.kv_k.addressable_shards:
            for ax in (0, 1, 2, 4):
                sl = s.index[ax]
                if (sl.start or 0) != 0 or not (
                    sl.stop is None or sl.stop >= shape[ax]
                ):
                    return False
        return True

    def _local_shard_views(self):
        """This host's KV shard pieces, deduped across replicas and sorted
        by the sharded (kv-head) axis slice. Single-device arrays — safe to
        index at host-divergent times (no collectives)."""
        def pick(arr):
            seen = {}
            for s in arr.addressable_shards:
                key = tuple(
                    (sl.start or 0, sl.stop) for sl in s.index
                )
                if key not in seen:
                    seen[key] = s
            return [
                s for _, s in sorted(
                    seen.items(), key=lambda kv: kv[0][3][0]
                )
            ]
        return pick(self.kv_k), pick(self.kv_v)

    def local_shard_page_shape(self) -> List[int]:
        """[L, page, KH_local, D] of this host's combined shard."""
        ks, _ = self._local_shard_views()
        L = ks[0].data.shape[0]
        page = ks[0].data.shape[2]
        kh_local = sum(s.data.shape[3] for s in ks)
        d = ks[0].data.shape[4]
        return [L, page, kh_local, d]

    def _extract_local_shard(self, page_ids):
        """Gather the requested page rows of THIS host's shard only: a
        per-device gather on each addressable shard (no collective, no
        cross-host bytes). Returns numpy [L, n, page, KH_local, D]."""
        ids = jnp.asarray(page_ids)
        ks, vs = self._local_shard_views()
        k_parts = [np.asarray(s.data[:, ids]) for s in ks]
        v_parts = [np.asarray(s.data[:, ids]) for s in vs]
        k = k_parts[0] if len(k_parts) == 1 else np.concatenate(k_parts, axis=3)
        v = v_parts[0] if len(v_parts) == 1 else np.concatenate(v_parts, axis=3)
        return k, v

    def _dev_inject_shard(self, page_ids, k_local, v_local):
        """SPMD inject where each host supplies ITS OWN shard bytes: build a
        global array from process-local data (metadata-only; no cross-host
        transfer) and enter the same jitted scatter on every host."""
        from jax.sharding import NamedSharding, PartitionSpec

        if self._kv_sharding is not None:
            sharding = self._kv_sharding
        else:
            sharding = NamedSharding(self._mesh, PartitionSpec())
        L, n, page, d = (
            k_local.shape[0], k_local.shape[1], k_local.shape[2], k_local.shape[4]
        )
        global_shape = (L, n, page, self.model_config.num_kv_heads, d)
        k_g = jax.make_array_from_process_local_data(sharding, k_local, global_shape)
        v_g = jax.make_array_from_process_local_data(sharding, v_local, global_shape)
        self.kv_k, self.kv_v = self._inject_pages(
            self.kv_k, self.kv_v, jnp.asarray(page_ids), k_g, v_g
        )

    async def run_follower(self, receiver) -> None:
        """Follower-host loop: replay the leader's dispatch sequence.
        No scheduling, no control plane, no host bookkeeping — just the
        same device programs in the same order (reference analogue: vLLM
        node ranks > 0 joining the engine group, main.py:64-296)."""
        while True:
            tag, p = await receiver.recv()
            if tag == "stop":
                return
            if tag == "prefill":
                await self._run_on_device(
                    partial(
                        self._dev_prefill,
                        p["toks"], p["positions"], p["tables"], p["ctx_lens"],
                        p["last_idx"], p["temps"], p["top_ks"], p["top_ps"],
                        p["seeds"], p["pens"], p["pen_rows"],
                    )
                )
            elif tag == "prefill_mm":
                await self._run_on_device(
                    partial(
                        self._dev_prefill_mm,
                        p["toks"], p["positions"], p["tables"], p["ctx_lens"],
                        p["last_idx"], p["temps"], p["top_ks"], p["top_ps"],
                        p["seeds"], p["pens"], p["pen_rows"],
                        p["emb"], p["emb_mask"],
                    )
                )
            elif tag == "reset":
                await self._run_on_device(
                    partial(
                        self._dev_reset,
                        p["tokens"], p["positions"], p["seq_lens"],
                        p["page_tables"], p["temps"], p["top_ks"], p["top_ps"],
                        p["seeds"], p["pens"], p["recent"], p.get("hist"),
                    )
                )
            elif tag == "prefill_single":
                await self._run_on_device(
                    partial(
                        self._dev_prefill_single,
                        p["toks"], p["table"], p["ctx"][0], p["real"][0],
                        p["temps"], p["top_ks"], p["top_ps"], p["seeds"],
                        p["pens"], p["pen_rows"],
                    )
                )
            elif tag == "patch":
                await self._run_on_device(
                    partial(
                        self._dev_patch,
                        p["lane_mask"], p["table_mask"], p["tokens"],
                        p["positions"], p["seq_lens"], p["page_tables"],
                        p["temps"], p["top_ks"], p["top_ps"], p["seeds"],
                        p["pens"], p["recent"], p.get("hist"),
                    )
                )
            elif tag == "prefill_guided":
                await self._run_on_device(
                    partial(
                        self._dev_prefill_guided,
                        p["toks"], p["positions"], p["tables"], p["ctx_lens"],
                        p["last_idx"], p["temps"], p["top_ks"], p["top_ps"],
                        p["seeds"], p["pens"], p["pen_rows"], p["mask"],
                    )
                )
            elif tag == "prefill_lora":
                await self._run_on_device(
                    partial(
                        self._dev_prefill_lora,
                        p["toks"], p["positions"], p["tables"], p["ctx_lens"],
                        p["last_idx"], p["temps"], p["top_ks"], p["top_ps"],
                        p["seeds"], p["pens"], p["pen_rows"], p["idx"],
                    )
                )
            elif tag == "mixed":
                await self._run_on_device(
                    partial(
                        self._dev_mixed,
                        p["toks"], p["positions"], p["row_ids"], p["tables"],
                        p["row_starts"], p["row_lens"], p["ctx_lens"],
                        p["last_flat"], p["temps"], p["top_ks"], p["top_ps"],
                        p["seeds"], p["pens"], p["pen_rows"],
                        p.get("mask"), p.get("lora_idx"),
                    )
                )
            elif tag == "block":
                await self._run_on_device(self._dev_block)
            elif tag == "block_guided":
                await self._run_on_device(
                    partial(
                        self._dev_block_guided, p["mask"], p.get("lora_idx")
                    )
                )
            elif tag == "block_lora":
                await self._run_on_device(
                    partial(self._dev_block_lora, p["idx"])
                )
            elif tag == "inject":
                await self._run_on_device(
                    partial(self._dev_inject, p["page_ids"], p["k"], p["v"])
                )
            elif tag == "extract":
                await self._run_on_device(partial(self._dev_extract, p["page_ids"]))
            elif tag == "stage_shard":
                # prefill follower: pin OUR shard of these pages under the
                # leader-chosen transfer id; the decode worker's matching
                # host pulls it point-to-point
                tid = p["tid"].tobytes().decode()
                if self.data_plane is not None:
                    self._stage_local_shard(tid, p["page_ids"], lambda ok: None)
                    logger.info(
                        "staged shard %s (%d pages) on follower data plane",
                        tid, len(p["page_ids"]),
                    )
            elif tag == "unstage_shard":
                tid = p["tid"].tobytes().decode()
                if self.data_plane is not None:
                    self.data_plane.unstage_by_id(tid, ok=bool(p["ok"][0]))
            elif tag == "inject_shard":
                # decode follower: pull OUR shard's chunk from our peer
                # prefill host, then enter the same SPMD inject program
                import msgpack as _mp

                from ..llm.kv_transfer import pull_kv_range

                shards = {
                    s["host_id"]: s["addr"]
                    for s in _mp.unpackb(p["addrs"].tobytes(), raw=False)
                }
                tid = p["tid"].tobytes().decode()
                off, n = int(p["off"][0]), int(p["n"][0])
                k_loc, v_loc = await pull_kv_range(
                    shards[self.host_id], tid, off, n,
                    [int(x) for x in p["page_shape"]],
                    str(jnp.zeros((), self.model_config.dtype).dtype),
                )
                logger.info(
                    "follower host %d pulled shard chunk (%d, %d) from %s",
                    self.host_id, off, n, shards[self.host_id],
                )
                await self._run_on_device(
                    partial(self._dev_inject_shard, p["page_ids"], k_loc, v_loc)
                )
            else:
                logger.warning("unknown step tag %r", tag)

    # -- injections (disagg preload / KVBM onboard) ---------------------- #

    async def _run_injections(self) -> bool:
        did = False
        for slot in list(self.slots):
            if slot is not None and slot.preloaded is not None:
                await self._inject_preloaded(slot)
                did = True
        for slot in list(self.slots):
            if slot is not None and slot.onboard is not None:
                await self._inject_onboard(slot)
                did = True
        return did

    async def _inject_preloaded(self, slot: _Slot):
        """Decode role: write transferred KV pages into our cache and enter
        the decode batch as if we had prefilled locally."""
        first_token, k_np, v_np, n_tokens = slot.preloaded
        slot.preloaded = None
        if slot.pull_desc is not None:
            # pull path: stream chunks in a background task — the decode
            # batch keeps stepping while later pages are still in flight
            desc = slot.pull_desc
            slot.pull_desc = None
            task = asyncio.create_task(self._pull_kv_task(slot, desc, first_token))
            self._bg_tasks.add(task)
            task.add_done_callback(self._bg_tasks.discard)
            return
        page_ids = np.array([p + 1 for p in slot.pages], np.int32)
        self._bcast("inject", {"page_ids": page_ids, "k": np.asarray(k_np), "v": np.asarray(v_np)})
        await self._run_on_device(partial(self._dev_inject, page_ids, k_np, v_np))
        self._activate_transferred(slot, first_token)

    def _activate_transferred(self, slot: _Slot, first_token: int):
        """All prompt KV is in our pages: publish to the prefix cache and
        enter the decode batch (first token was emitted by the prefill
        worker — not re-emitted)."""
        self._commit_blocks(slot)
        slot.prefill_pos = len(slot.prompt)
        slot.generated = 1
        slot.last_token = first_token
        slot.seq.append(first_token)
        self.tokens[slot.slot_idx] = first_token
        self.seq_lens[slot.slot_idx] = len(slot.prompt) + 1
        self._fill_hist(slot.slot_idx, slot)
        self._fill_recent(slot.slot_idx, slot)
        self._mark_lane_dirty(slot.slot_idx)
        self._maybe_finish(slot, first_token)

    async def _pull_kv_task(self, slot: _Slot, desc_dict: dict,
                            first_token: Optional[int]):
        """Stream KV chunks from the staging prefill worker, injecting each
        as it lands. Any failure falls back to computing the prompt KV
        locally, resuming from the already-emitted first token — disagg
        stays strictly an optimization. `first_token=None` = streamed
        handoff: the pull started off the EARLY descriptor while the peer
        was still prefilling; the token arrives later via
        slot.first_token_fut (None result = handler abandoned us)."""
        from ..llm.kv_transfer import KvFormatError, KvTransferDescriptor, pull_kv

        desc = KvTransferDescriptor.from_dict(desc_dict)
        phys = np.array([p + 1 for p in slot.pages], np.int32)
        if desc.kv_format != self.config.kv_quant:
            # mixed-precision pairing: fail TYPED before any byte moves —
            # the except-path below falls back to a local prefill (and
            # counts it), instead of injecting misread pages
            self.kv_format_mismatches += 1
            err: Optional[Exception] = KvFormatError(
                f"peer stages kv_format={desc.kv_format!r}, this worker "
                f"runs {self.config.kv_quant!r}"
            )
        else:
            err = None
        streamed = slot.first_token_fut is not None
        chunks_before_first = 0
        first_before_last_chunk = False

        async def inject(off: int, n: int, k, v):
            nonlocal chunks_before_first, first_before_last_chunk
            if (
                slot.done
                or self._closed
                or slot.slot_idx < 0
                or self.slots[slot.slot_idx] is not slot
            ):
                raise asyncio.CancelledError("slot released mid-pull")
            fut = slot.first_token_fut
            if fut is not None:
                # overlap evidence: final value of first_before_last_chunk
                # = "the first token was already here when the LAST chunk
                # landed" (structurally impossible on the serial path)
                first_before_last_chunk = fut.done()
                if not fut.done():
                    chunks_before_first += 1
            ids = phys[off : off + n]
            if self._spmd is not None:
                self._bcast("inject", {"page_ids": ids, "k": np.asarray(k), "v": np.asarray(v)})
            await self._run_on_device(partial(self._dev_inject, ids, k, v))

        try:
            if err is not None:
                raise err
            if desc.shards is not None:
                await self._pull_kv_shards(slot, desc, phys)
            else:
                await pull_kv(desc, inject)
        except asyncio.CancelledError:
            # slot released mid-pull (inject raises) or engine close()
            # cancelled us: nothing to fall back to — propagate so the
            # task records itself cancelled, not finished
            raise
        except Exception as e:  # noqa: BLE001 — any pull failure -> local fallback
            if streamed:
                first_token = await self._await_first_token(slot)
                if first_token is None:
                    self._abandon_streamed_slot(slot)
                    return
            if slot.done or slot.slot_idx < 0 or self.slots[slot.slot_idx] is not slot:
                return
            logger.warning(
                "kv pull for %s failed (%s); prefilling locally", slot.request_id, e
            )
            slot.generated = 1
            slot.last_token = first_token
            slot.seq.append(first_token)
            slot.resume_token = first_token
            slot.prefill_pos = 0
            self._wake.set()
            return
        if streamed:
            first_token = await self._await_first_token(slot)
            if first_token is None:
                self._abandon_streamed_slot(slot)
                return
            self.disagg_streamed_handoffs += 1
            self.disagg_chunks_before_first_token += chunks_before_first
            if first_before_last_chunk:
                self.disagg_first_token_before_last_chunk += 1
        if slot.done or slot.slot_idx < 0 or self.slots[slot.slot_idx] is not slot:
            return
        logger.info(
            "kv pull complete for %s: %d pages via data plane %s",
            slot.request_id, desc.n_pages, desc.addr,
        )
        self.kv_pulls_completed += 1
        self.kv_pages_pulled += int(desc.n_pages)
        self._activate_transferred(slot, first_token)
        self._wake.set()

    async def _await_first_token(self, slot: _Slot) -> Optional[int]:
        """Streamed handoff: wait for the handler to deliver the prefill's
        first token (None = the handler abandoned the early pull)."""
        fut, slot.first_token_fut = slot.first_token_fut, None
        if fut is None:
            return None
        return await fut

    def _abandon_streamed_slot(self, slot: _Slot):
        """The handler abandoned an early pull (prefill failed or the
        transfer was re-staged): release the slot and unblock any stream
        consumer."""
        if slot.slot_idx >= 0 and self.slots[slot.slot_idx] is slot:
            self._release_slot(slot)
        slot.done = True
        slot.queue.put_nowait(None)
        self._wake.set()

    async def _pull_kv_shards(self, slot: _Slot, desc, phys: np.ndarray):
        """Multi-host shard pull: this (leader) host pulls ITS shard chunk
        by chunk; each chunk's inject is an SPMD dispatch where followers
        supply their OWN shard bytes (pulled from their peer host inside
        the inject_shard replay). No host ever moves another host's bytes;
        nothing is re-broadcast."""
        from ..llm.kv_transfer import pull_kv_range

        if not (self._multihost and self.shard_addrs):
            raise RuntimeError("sharded descriptor but this worker is not multi-host")
        if not self._kv_headwise_shards_ok():
            # raising here lands in _pull_and_activate's fallback: the
            # request prefills locally instead of injecting corrupt KV
            raise RuntimeError(
                "KV pool host-sharded beyond the kv-head axis; shard-wise "
                "inject unsupported for this layout"
            )
        shards = {s["host_id"]: s["addr"] for s in desc.shards}
        if len(shards) != len(self.shard_addrs):
            raise RuntimeError(
                f"shard count mismatch: peer has {len(shards)} hosts, we have "
                f"{len(self.shard_addrs)} — falling back to local prefill"
            )
        my_addr = shards[self.host_id]
        import msgpack as _mp

        addrs_blob = np.frombuffer(
            _mp.packb(desc.shards, use_bin_type=True), np.uint8
        )
        tid_blob = np.frombuffer(desc.transfer_id.encode(), np.uint8)
        off = 0
        while off < desc.n_pages:
            n = min(desc.chunk_pages, desc.n_pages - off)
            if (
                slot.done
                or self._closed
                or slot.slot_idx < 0
                or self.slots[slot.slot_idx] is not slot
            ):
                raise asyncio.CancelledError("slot released mid-pull")
            k_loc, v_loc = await pull_kv_range(
                my_addr, desc.transfer_id, off, n, desc.page_shape, desc.dtype
            )
            ids = phys[off : off + n]
            # bcast + dispatch in ONE synchronous segment: interleaving an
            # await between them could reorder against the step loop's own
            # bcast+dispatch pairs and diverge the SPMD program order
            self._bcast(
                "inject_shard",
                {
                    "tid": tid_blob,
                    "addrs": addrs_blob,
                    "page_ids": ids,
                    "off": np.array([off], np.int64),
                    "n": np.array([n], np.int64),
                    "page_shape": np.array(desc.page_shape, np.int64),
                },
            )
            fut = self._run_on_device(
                partial(self._dev_inject_shard, ids, k_loc, v_loc)
            )
            await fut
            off += n
        logger.info(
            "kv shard pull complete: %d pages from %s (host %d pulled only "
            "its own shard)", desc.n_pages, my_addr, self.host_id,
        )
        # tell the prefill leader the transfer is complete so it releases
        # (its on_done broadcast unpins the prefill followers' stages)
        try:
            from ..llm.kv_transfer import finish_transfer

            await finish_transfer(desc.addr, desc.transfer_id)
        except Exception:  # noqa: BLE001 — TTL reaper is the backstop
            logger.warning("could not signal transfer completion", exc_info=True)

    async def _inject_onboard(self, slot: _Slot):
        """KVBM onboard: scatter G2/G3 blocks into the freshly allocated
        device pages, then register them in the device prefix cache so
        concurrent sequences share them."""
        alloc_pages, hashes = slot.onboard
        slot.onboard = None
        t0 = time.perf_counter()
        try:
            # tier reads (host memcpy / disk memmap) run off the event loop,
            # serialized with offload stores on the same executor; remote
            # (G4/peer) blocks pull over the data plane first, resolved via
            # the announcement mesh with the router's holder hint as
            # fallback (cluster KV fabric)
            hint = slot.kv_holder or {}
            k_np, v_np = await self.kvbm.load_async(
                hashes, self._run_on_device,
                hint_instance=hint.get("instance"),
            )
        except Exception as e:
            from ..llm.kv_transfer import KvFormatError

            if not isinstance(e, (KeyError, faults.FaultError, KvFormatError)):
                raise
            if isinstance(e, KvFormatError):
                # mixed-precision fleet: the peer pull failed TYPED before
                # any bytes were misread — counted, loud, then the same
                # recompute fallback every onboard miss takes
                self.kv_format_mismatches += 1
                logger.warning("KVBM peer kv_format mismatch: %s", e)
            # block evicted between probe and load — or a dynochaos
            # `kvbm.onboard` error: fall back to computing that part of
            # the prompt (pages are already allocated); onboarding is a
            # latency optimization, never a correctness dependency
            logger.warning("KVBM onboard miss: %s; prefilling instead", e)
            n_known = len(slot.committed_hashes)
            slot.prefill_pos = n_known * self.config.page_size
            if slot.migration:
                # replayed-token accounting is OUTCOME-based: the
                # admission plan counted these blocks as reused, but the
                # pull died (dead peer, eviction race) and the span now
                # really re-prefills — an operator reading "what did the
                # death cost" must see it
                self.migration_replayed_tokens += (
                    len(hashes) * self.config.page_size
                )
            return
        # [n, layers, page, heads, dim] -> [layers, n, page, heads, dim]
        k_np = k_np.swapaxes(0, 1)
        v_np = v_np.swapaxes(0, 1)
        phys = np.array([p + 1 for p in alloc_pages], np.int32)  # scratch shift
        self._bcast("inject", {"page_ids": phys, "k": k_np, "v": v_np})
        await self._run_on_device(partial(self._dev_inject, phys, k_np, v_np))
        n_known = len(slot.committed_hashes)
        token_blocks = [
            b.tokens for b in slot.seq.blocks[n_known : n_known + len(hashes)]
        ]
        parent = slot.committed_hashes[-1] if slot.committed_hashes else None
        self.allocator.commit_hashes(alloc_pages, hashes, token_blocks, parent)
        slot.committed_hashes.extend(hashes)
        self._advance_kv_stream(slot)
        # (whole-prompt clamp already applied at admission, _try_admit)
        self._record_onboard_ms((time.perf_counter() - t0) * 1000.0)

    def _record_onboard_ms(self, ms: float):
        """Onboard-latency histogram (tier load + device inject, per
        onboard): the cache-effectiveness signal beside the hit counters."""
        for i, bound in enumerate(self._onboard_hist_bounds):
            if ms <= bound:
                self.kvbm_onboard_hist[i] += 1
                break
        else:
            self.kvbm_onboard_hist[-1] += 1
        self.kvbm_onboard_ms_sum += ms
        self.kvbm_onboard_count += 1

    # -- batched chunked prefill ----------------------------------------- #

    def _try_skip_ahead(self, s: _Slot) -> None:
        """Late-binding prefix reuse: blocks committed SINCE this slot was
        admitted (by a concurrent same-prefix request, possibly via the
        incremental chunk commit) cover part of the remaining prompt —
        swap the cached pages into the table and skip the compute. Only
        whole-page-aligned progress can splice; fresh slots only (resume/
        disagg/onboard slots carry their own page provenance)."""
        cfg = self.config
        if not cfg.enable_prefix_caching:
            return  # caching disabled must disable ALL reuse paths
        if s.generated or s.resume_token is not None or s.onboard is not None:
            return
        n_known = len(s.committed_hashes)
        if s.prefill_pos != n_known * cfg.page_size:
            return
        hashes = s.seq.block_hashes()
        prompt_full = len(s.kv_prompt) // cfg.page_size
        if n_known >= prompt_full:
            return
        extra = self.allocator.acquire_cached(hashes[n_known:prompt_full])
        if not extra:
            return
        self.prefix_skip_ahead_blocks += len(extra)
        old = s.pages[n_known : n_known + len(extra)]
        s.pages[n_known : n_known + len(extra)] = extra
        self.allocator.release(old, [])  # fresh, un-hashed -> free list
        s.committed_hashes.extend(hashes[n_known : n_known + len(extra)])
        self._advance_kv_stream(s)
        s.prefill_pos = (n_known + len(extra)) * cfg.page_size
        if s.prefill_pos >= len(s.kv_prompt):
            # whole prompt now cached: recompute the last token for logits
            s.prefill_pos = len(s.kv_prompt) - 1
        phys = [p + 1 for p in s.pages]
        self.page_tables[s.slot_idx, : len(phys)] = phys

    async def _dispatch_prefill(self) -> bool:
        """Pack prefill chunks from several slots into ONE dispatch.

        Shapes are bounded: batch lanes B_pf = prefill_batch_tokens/bucket
        (padded with dummy lanes), table length = pow2 context bucket + a
        scratch tail entry for padded positions — so compile variants stay
        few and cacheable."""
        cfg = self.config
        cands = []
        for s in self.slots:
            # prefill_pos has a single writer per LIVE slot (this dispatch
            # path); the pull-failure fallback rewrite only reaches slots
            # excluded from cands while their pull is in flight
            if s is None or s.prefill_pos >= len(s.kv_prompt):  # dynolint: disable=race-await-atomicity -- single writer per live slot; pull-path slots are filtered below
                continue
            if s.preloaded is not None or s.onboard is not None:
                continue
            if s.done or s.context.is_stopped():
                self._emit_finish(s, "cancelled")
                self._release_slot(s)
                continue
            self._try_skip_ahead(s)
            cands.append(s)
        if not cands:
            return False
        # dynosched: candidate order is the planner's call — fifo is the
        # legacy admit_seq sort bit-for-bit, sla is EDF over TTFT deadlines
        # with a starvation guard (docs/scheduler.md)
        cands = self.scheduler.order(cands)
        # guided / multimodal / LoRA slots ride different dispatch variants
        # (mask vs embedding splice vs adapter stack) and never share a
        # prefill batch with each OTHER; plain slots batch with any single
        # kind (they are exact no-ops under mask=all-true or adapter 0).
        # The excluded kind waits for a later dispatch — the planner's aging
        # tiebreak bounds that wait (a kind skipped starve_dispatches times
        # wins the batch outright, so no kind starves under a steady stream
        # of another kind).
        def _kind(s):
            if s.mm is not None:
                return "mm"
            if s.guided_fsm is not None:
                return "guided"
            if s.lora_idx:
                return "lora"
            return "plain"

        batch_kind = self.scheduler.pick_batch_kind(cands, _kind)
        if batch_kind != "plain":
            excluded = [s for s in cands if _kind(s) not in ("plain", batch_kind)]
            if excluded:
                for s in excluded:
                    s.sched_skips += 1
                cands = [s for s in cands if _kind(s) in ("plain", batch_kind)]

        if self._prefill_single is not None:
            s0 = cands[0]
            remaining = len(s0.kv_prompt) - s0.prefill_pos
            # pp: every prompt goes through the pipelined single-seq path
            # (layer-sharded weights make the batched path degenerate);
            # sp: only fresh long prompts ride the ring (history-free).
            # Multimodal slots never ride it (splice unsupported there —
            # _check_multimodal rejects those configs up front).
            use_single = not s0.mm and (
                cfg.pp_size > 1
                or (s0.prefill_pos == 0 and remaining >= cfg.ring_prefill_threshold)
            )
            if use_single:
                await self._dispatch_prefill_one(s0)
                return True
        # two lane variants per bucket — 1 (the lone-arrival TTFT case:
        # padding one request to the full lane budget multiplies its
        # prefill FLOPs by the budget) and the cap (batch case). Exactly
        # two keeps the lazily-compiled shape set small: every new shape
        # costs a multi-second XLA compile ON the serving path the first
        # time it occurs (persistent cache amortizes across restarts).
        # The planner chooses WITHIN that bounded shape space: fifo
        # reproduces the legacy head-candidate formula exactly; sla scores
        # shapes by slots-served/tokens-granted under the ITL budget and
        # may defer the dispatch entirely to protect decode cadence.
        has_decode = any(
            s is not None and s.generated > 0 and s.resume_token is None
            and s.prefill_pos >= len(s.kv_prompt)
            for s in self.slots
        )
        plan = self.scheduler.plan_prefill(cands, decode_active=has_decode)
        if plan is None:
            # ITL budget exhausted and no deadline at risk: prefill yields
            # this step; skipped candidates age toward the starvation guard
            for s in cands:
                s.sched_skips += 1
            return False
        bucket = plan.bucket
        lanes = plan.lanes
        chosen = plan.chosen
        for s in cands[len(chosen):]:
            s.sched_skips += 1
        B_pf = lanes

        # shared context-bounded table: pow2 pages covering the largest
        # (history + chunk), plus one guaranteed-scratch tail entry that
        # padded positions write to
        chunk_of = {}
        max_pages_needed = 1
        for s in chosen:
            chunk = min(len(s.kv_prompt) - s.prefill_pos, bucket)
            chunk_of[s.request_id] = chunk
            pages_needed = (s.prefill_pos + chunk + cfg.page_size - 1) // cfg.page_size
            max_pages_needed = max(max_pages_needed, pages_needed)
        ctx_pages = min(_next_pow2(max_pages_needed), cfg.max_pages_per_seq)
        P = ctx_pages + 1
        pad_pos = P * cfg.page_size - 1

        toks = np.zeros((B_pf, bucket), np.int32)
        positions = np.full((B_pf, bucket), pad_pos, np.int32)
        tables = np.full((B_pf, P), SCRATCH_PAGE, np.int32)
        ctx_lens = np.zeros((B_pf,), np.int32)
        last_idx = np.zeros((B_pf,), np.int32)
        temps = np.zeros((B_pf,), np.float32)
        top_ks = np.zeros((B_pf,), np.int32)
        top_ps = np.ones((B_pf,), np.float32)
        seeds = np.zeros((B_pf,), np.uint32)
        pens = np.zeros((B_pf, 3), np.float32)
        pens[:, 2] = 1.0  # repetition off
        W = self.config.penalty_window
        pen_rows = np.full((B_pf, W), -1, np.int32)
        meta = []
        for lane, s in enumerate(chosen):
            chunk = chunk_of[s.request_id]
            start = s.prefill_pos
            toks[lane, :chunk] = s.kv_prompt[start : start + chunk]
            positions[lane, :chunk] = np.arange(start, start + chunk)
            tables[lane, :ctx_pages] = self.page_tables[s.slot_idx][:ctx_pages]
            ctx_lens[lane] = start
            last_idx[lane] = chunk - 1
            temps[lane] = s.temperature
            top_ks[lane] = s.top_k
            top_ps[lane] = s.top_p
            seeds[lane] = s.sample_seed
            pens[lane] = (s.presence_penalty, s.frequency_penalty,
                          s.repetition_penalty)
            pen_rows[lane] = self.recent[s.slot_idx]
            s.sched_skips = 0  # granted a chunk: starvation clock restarts
            meta.append((s, chunk, lane))
        self._last_prefill_shape = (
            B_pf * bucket, sum(ch for _, ch, _ in meta)
        )

        if any(s.mm for s in chosen):
            # multimodal splice operands: encoder rows land at their
            # absolute prompt positions within this chunk window
            H = self.model_config.hidden_size
            emb = np.zeros((B_pf, bucket, H), np.float32)
            emb_mask = np.zeros((B_pf, bucket), bool)
            for s, chunk, lane in meta:
                if not s.mm:
                    continue
                start = s.prefill_pos  # chunk window [start, start+chunk)
                for pos0, arr in s.mm:
                    lo, hi = max(pos0, start), min(pos0 + len(arr), start + chunk)
                    if lo < hi:
                        emb[lane, lo - start : hi - start] = arr[lo - pos0 : hi - pos0]
                        emb_mask[lane, lo - start : hi - start] = True
            self._bcast(
                "prefill_mm",
                {
                    "toks": toks, "positions": positions, "tables": tables,
                    "ctx_lens": ctx_lens, "last_idx": last_idx, "temps": temps,
                    "top_ks": top_ks, "top_ps": top_ps, "seeds": seeds,
                    "pens": pens, "pen_rows": pen_rows,
                    "emb": emb, "emb_mask": emb_mask,
                },
            )
            first_dev = await self._run_on_device(
                partial(
                    self._dev_prefill_mm,
                    toks, positions, tables, ctx_lens, last_idx,
                    temps, top_ks, top_ps, seeds, pens, pen_rows,
                    emb, emb_mask,
                ),
                tag="prefill", shape=(bucket, B_pf),
            )
        elif any(s.guided_fsm is not None for s in chosen):
            # masked first-token sampling: guided lanes constrain the first
            # generated token the same way decode steps are constrained
            V = self.model_config.vocab_size
            mask = np.full((B_pf, (V + 7) // 8), 0xFF, np.uint8)
            for s, chunk, lane in meta:
                if s.guided_fsm is not None:
                    mask[lane] = np.packbits(self._guided_lane_mask(
                        s.guided_fsm, s.guided_state
                    ))
            self._bcast(
                "prefill_guided",
                {
                    "toks": toks, "positions": positions, "tables": tables,
                    "ctx_lens": ctx_lens, "last_idx": last_idx, "temps": temps,
                    "top_ks": top_ks, "top_ps": top_ps, "seeds": seeds,
                    "pens": pens, "pen_rows": pen_rows, "mask": mask,
                },
            )
            first_dev = await self._run_on_device(
                partial(
                    self._dev_prefill_guided,
                    toks, positions, tables, ctx_lens, last_idx,
                    temps, top_ks, top_ps, seeds, pens, pen_rows, mask,
                ),
                tag="prefill", shape=(bucket, B_pf),
            )
        elif any(s.lora_idx for s in chosen):
            lane_idx = np.zeros((B_pf,), np.int32)
            for s, chunk, lane in meta:
                lane_idx[lane] = s.lora_idx
            self._bcast(
                "prefill_lora",
                {
                    "toks": toks, "positions": positions, "tables": tables,
                    "ctx_lens": ctx_lens, "last_idx": last_idx, "temps": temps,
                    "top_ks": top_ks, "top_ps": top_ps, "seeds": seeds,
                    "pens": pens, "pen_rows": pen_rows, "idx": lane_idx,
                },
            )
            first_dev = await self._run_on_device(
                partial(
                    self._dev_prefill_lora,
                    toks, positions, tables, ctx_lens, last_idx,
                    temps, top_ks, top_ps, seeds, pens, pen_rows, lane_idx,
                ),
                tag="prefill", shape=(bucket, B_pf),
            )
        else:
            self._bcast(
                "prefill",
                {
                    "toks": toks, "positions": positions, "tables": tables,
                    "ctx_lens": ctx_lens, "last_idx": last_idx, "temps": temps,
                    "top_ks": top_ks, "top_ps": top_ps, "seeds": seeds,
                    "pens": pens, "pen_rows": pen_rows,
                },
            )
            first_dev = await self._run_on_device(
                partial(
                    self._dev_prefill,
                    toks, positions, tables, ctx_lens, last_idx, temps,
                    top_ks, top_ps, seeds, pens, pen_rows,
                ),
                tag="prefill", shape=(bucket, B_pf),
            )
        completions = []
        progressed = []
        for s, chunk, lane in meta:
            s.prefill_pos += chunk
            # commit confirmed at this dispatch's FETCH (execution proof)
            progressed.append((s, s.prefill_pos))
            if s.prefill_pos >= len(s.kv_prompt):
                completions.append((s, lane))
        self._pending_prefill.append(
            {"first": first_dev, "done": completions, "progressed": progressed}
        )
        return True

    async def _dispatch_prefill_one(self, slot: _Slot) -> None:
        """Single-sequence whole-remaining-prompt prefill through the
        parallel path (_prefill_single: ring over sp / pipeline over pp).
        Pads to a pow2 bucket so compile variants stay bounded."""
        cfg = self.config
        chunk = len(slot.kv_prompt) - slot.prefill_pos
        unit = max(cfg.sp_size, cfg.pp_size, 1)
        # pow2 bucket for bounded compile variants, then round UP to a unit
        # multiple (a non-pow2 sp/pp size would otherwise fail the ring's
        # divisibility check)
        T_pad = _next_pow2(chunk)
        T_pad = -(-T_pad // unit) * unit
        pages_needed = (slot.prefill_pos + chunk + cfg.page_size - 1) // cfg.page_size
        P = min(_next_pow2(pages_needed), cfg.max_pages_per_seq) + 1
        table = np.full((P,), SCRATCH_PAGE, np.int32)
        table[: min(len(slot.pages), P)] = [p + 1 for p in slot.pages[:P]]
        toks = np.zeros((T_pad,), np.int32)
        toks[:chunk] = slot.kv_prompt[slot.prefill_pos :]
        ctx = np.int32(slot.prefill_pos)
        real = np.int32(chunk)
        temps = np.array([slot.temperature], np.float32)
        top_ks = np.array([slot.top_k], np.int32)
        top_ps = np.array([slot.top_p], np.float32)
        seeds = np.array([slot.sample_seed], np.uint32)
        pens = np.array([[slot.presence_penalty, slot.frequency_penalty,
                          slot.repetition_penalty]], np.float32)
        pen_rows = self.recent[slot.slot_idx : slot.slot_idx + 1]
        self._bcast(
            "prefill_single",
            {
                "toks": toks, "table": table, "ctx": np.array([ctx]),
                "real": np.array([real]), "temps": temps,
                "top_ks": top_ks, "top_ps": top_ps, "seeds": seeds,
                "pens": pens, "pen_rows": pen_rows,
            },
        )
        first_dev = await self._run_on_device(
            partial(self._dev_prefill_single, toks, table, ctx, real, temps,
                    top_ks, top_ps, seeds, pens, pen_rows),
            tag="prefill", shape=(T_pad, 1),
        )
        self._last_prefill_shape = (T_pad, chunk)
        slot.prefill_pos += chunk
        self._pending_prefill.append({"first": first_dev, "done": [(slot, 0)]})

    def _dev_prefill_single(self, toks, table, ctx, real, temps, top_ks,
                            top_ps, seeds, pens, pen_rows):
        samp = SamplingParams(
            temperature=jnp.asarray(temps),
            top_k=jnp.asarray(top_ks),
            top_p=jnp.asarray(top_ps),
            seed=jnp.asarray(seeds),
            presence=jnp.asarray(pens[:, 0]),
            frequency=jnp.asarray(pens[:, 1]),
            repetition=jnp.asarray(pens[:, 2]),
        )
        first, self.kv_k, self.kv_v, self._rng = self._prefill_single(
            self.params, self.kv_k, self.kv_v,
            jnp.asarray(toks), jnp.asarray(table),
            jnp.asarray(ctx, jnp.int32), jnp.asarray(real, jnp.int32),
            self._rng, samp, jnp.asarray(pen_rows),
        )
        return first

    def _fill_recent(self, idx: int, slot: _Slot):
        """Load the lane's penalty window from the tokens so far (prompt +
        generated); ring-indexed by absolute position so device-side
        appends stay consistent across patches."""
        W = self.config.penalty_window
        toks = np.asarray(slot.seq.tokens, np.int32)
        row = self.recent[idx]
        row[:] = -1
        if len(toks):
            ps = np.arange(max(0, len(toks) - W), len(toks))
            row[ps % W] = toks[ps]

    def _fill_hist(self, idx: int, slot: _Slot):
        """Load the lane's history ring (host mirror) for n-gram drafting:
        the last spec_hist tokens of prompt-so-far + the current token.
        Uploaded to device by the reset/patch that follows lane dirtying."""
        if self.hist is None:
            return
        Hc = self.config.spec_hist
        toks = np.asarray(
            list(slot.kv_prompt) + [int(self.tokens[idx])], np.int32
        )
        L1 = len(toks)
        row = self.hist[idx]
        row[:] = 0
        ps = np.arange(max(0, L1 - Hc), L1)
        row[ps % Hc] = toks[ps]

    def _top_entry(self, slot: _Slot, tids, tlps) -> Optional[dict]:
        """Top-k alternatives for one emitted token, sliced to the
        request's ask (None when not requested — zero overhead)."""
        n = slot.want_top_logprobs
        if not n:
            return None
        return {
            "ids": [int(t) for t in tids[:n]],
            "logprobs": [float(v) for v in tlps[:n]],
        }

    def _finish_prefill(self, slot: _Slot, first: int,
                        first_lp: Optional[float] = None,
                        first_top: Optional[dict] = None):
        """Prompt KV fully computed; activate the slot for decode."""
        self._commit_blocks(slot)
        if slot.done or slot.context.is_stopped():
            self._emit_finish(slot, "cancelled")
            self._release_slot(slot)
            return
        if slot.resume_token is not None:
            # preempted resume: continue from the already-emitted pending
            # token; the freshly sampled token is discarded
            first = slot.resume_token
            slot.resume_token = None
            slot.last_token = first
            self.tokens[slot.slot_idx] = first
            self.seq_lens[slot.slot_idx] = len(slot.kv_prompt) + 1
            self._fill_hist(slot.slot_idx, slot)
            self._fill_recent(slot.slot_idx, slot)
            self._mark_lane_dirty(slot.slot_idx)
            return
        if slot.guided_fsm is not None:
            slot.guided_state = slot.guided_fsm.advance(
                slot.guided_state, first
            )
        self._emit_token(slot, first, first_lp, first_top)
        if not slot.done:
            slot.last_token = first
            slot.generated = 1
            slot.seq.append(first)
            self.tokens[slot.slot_idx] = first
            self.seq_lens[slot.slot_idx] = len(slot.kv_prompt) + 1
            self._fill_hist(slot.slot_idx, slot)
            self._fill_recent(slot.slot_idx, slot)
            self._mark_lane_dirty(slot.slot_idx)
            self._maybe_finish(slot, first)

    async def _emit_prefill_result(self, slot: _Slot, first_token: int,
                                   first_lp: Optional[float] = None,
                                   first_top: Optional[dict] = None):
        from ..llm.disagg import pack_kv_payload

        cfg = self.config
        n_prompt_pages = (len(slot.prompt) + cfg.page_size - 1) // cfg.page_size
        page_ids = np.array(
            [p + 1 for p in slot.pages[:n_prompt_pages]], np.int32
        )  # +1 scratch shift
        # the computed prompt KV is valid — publish full blocks to our own
        # prefix cache so repeat prefills of shared prefixes are free
        self._commit_blocks(slot)

        if slot.kv_stream_tid is not None and self.data_plane is not None \
                and not slot.done:
            # streamed handoff still alive: publish the final page + the
            # first token under the SAME transfer (the decode worker has
            # been pulling since admission)
            self._finish_streamed_kv(slot, first_token, first_lp, first_top)
            return
        if slot.kv_stream and not slot.done:
            # the early stage died mid-prefill (reaped TTL, severed pull):
            # fall through to a fresh serial stage — the decode worker's
            # failed early pull retries off the final descriptor
            slot.kv_stream_desc = None

        if slot.kv_pull and self.data_plane is not None and not slot.done:
            # fast path: stage the pages on the data plane and return only a
            # descriptor — the decode worker pulls chunks while we keep
            # serving; pages stay pinned until the pull finishes (or TTL)
            self._stage_kv_pull(slot, first_token, page_ids, first_lp,
                                first_top)
            return

        self._bcast("extract", {"page_ids": page_ids})
        k_np, v_np = await self._run_on_device(partial(self._dev_extract, page_ids))
        payload = pack_kv_payload(k_np, v_np, len(slot.prompt), cfg.page_size,
                                  kv_format=cfg.kv_quant)
        if not slot.done:
            out = LLMEngineOutput(
                token_ids=[first_token],
                log_probs=[first_lp]
                if (slot.want_logprobs and first_lp is not None) else None,
                top_logprobs=[first_top] if first_top else None,
                finish_reason="remote_prefill_done",
                kv_transfer_params=payload,
            ).to_dict()
            slot.queue.put_nowait(Annotated(data=out).to_dict())
            slot.queue.put_nowait(None)
            slot.done = True
        self._release_slot(slot)

    def _stage_kv_pull(self, slot: _Slot, first_token: int,
                       page_ids: np.ndarray,
                       first_lp: Optional[float] = None,
                       first_top: Optional[dict] = None):
        """Pin the finished prefill's pages on the data plane and answer with
        a descriptor. The extract callback gathers page CHUNKS lazily as the
        decode worker pulls, so the device gather overlaps the network (and
        on the in-process path never leaves the device). On a multi-host
        mesh each host stages ITS OWN SHARD under one transfer id (the
        stage_shard broadcast) and the descriptor carries the per-host
        rendezvous — the decode worker's hosts pull point-to-point."""
        import jax.numpy as jnp

        c = self.model_config
        cfg = self.config
        wire_shape, dtype_name = self._kv_wire_meta()

        def on_done(ok: bool):
            if not ok:
                logger.warning(
                    "kv pull for %s abandoned; releasing pages", slot.request_id
                )
            self._release_slot(slot)

        shard_path = bool(self._multihost and self.shard_addrs)
        if shard_path and not self._kv_headwise_shards_ok():
            # pool sharded beyond the kv-head axis: the per-shard path would
            # reassemble bytes under wrong layers/pages — use the inline
            # allgather transfer (correct for any sharding, more bytes)
            logger.warning(
                "KV pool is host-sharded beyond the kv-head axis; using the "
                "inline KV transfer path instead of per-shard pulls"
            )
            shard_path = False
        if shard_path:
            import secrets as _secrets

            tid = _secrets.token_hex(8)
            self._bcast(
                "stage_shard",
                {
                    "tid": np.frombuffer(tid.encode(), np.uint8),
                    "page_ids": page_ids,
                },
            )

            def on_done_shard(ok: bool):
                # release leader-side pages AND tell followers to unpin
                self._bcast(
                    "unstage_shard",
                    {
                        "tid": np.frombuffer(tid.encode(), np.uint8),
                        "ok": np.array([1 if ok else 0], np.int8),
                    },
                )
                on_done(ok)

            desc = self._stage_local_shard(tid, page_ids, on_done_shard)
            desc.n_tokens = len(slot.prompt)
            desc.shards = [
                {"host_id": h, "addr": a} for h, a in enumerate(self.shard_addrs)
            ]
        else:
            async def extract(off: int, n: int, device: bool):
                ids = page_ids[off : off + n]
                self._bcast("extract", {"page_ids": ids})
                if device and not self._multihost and cfg.kv_quant == "none":
                    # in-process path: hand over device arrays, no host
                    # staging (quantized pools always serialize to the
                    # packed host rows — the one wire layout)
                    return await self._run_on_device(
                        lambda: self._extract_pages(self.kv_k, self.kv_v, jnp.asarray(ids))
                    )
                return await self._run_on_device(partial(self._dev_extract, ids))

            desc = self.data_plane.stage(
                n_pages=int(len(page_ids)),
                n_tokens=len(slot.prompt),
                page_size=cfg.page_size,
                page_shape=wire_shape,
                dtype=dtype_name,
                kv_format=cfg.kv_quant,
                extract=extract,
                on_done=on_done,
            )
        out = LLMEngineOutput(
            token_ids=[first_token],
            log_probs=[first_lp]
            if (slot.want_logprobs and first_lp is not None) else None,
            top_logprobs=[first_top] if first_top else None,
            finish_reason="remote_prefill_done",
            kv_transfer_params={"pull": desc.to_dict()},
        ).to_dict()
        slot.queue.put_nowait(Annotated(data=out).to_dict())
        slot.queue.put_nowait(None)
        slot.done = True
        # NOT released here: pages stay pinned until on_done (pull or TTL)

    def _stage_streamed_kv(self, slot: _Slot):
        """Early-staged streamed handoff (docs/disagg_serving.md): stage
        the prompt's pages on the data plane AT ADMISSION and ship the
        descriptor immediately — chunks become pullable as prefill commits
        pages, so the decode worker's transfer overlaps our compute
        instead of serializing after it. Chunk granularity matches the
        prefill-chunk commit granularity; the last prompt page is held
        back until emit (its tail token's KV lands with the final chunk),
        which also guarantees the pull can only complete after the first
        token is on the wire. A transfer that dies mid-stream (reap /
        sever / abandoned puller) falls back to a fresh serial stage at
        emit — streamed handoff is strictly an optimization."""
        import jax.numpy as jnp

        c = self.model_config
        cfg = self.config
        n_prompt_pages = (len(slot.prompt) + cfg.page_size - 1) // cfg.page_size
        if n_prompt_pages <= 0:
            return

        async def extract(off: int, n: int, device: bool):
            # slot.pages is read LIVE (not snapshotted): _try_skip_ahead
            # may splice cached pages in mid-prefill — same contents,
            # different physical ids
            ids = np.array([p + 1 for p in slot.pages[off : off + n]], np.int32)
            self._bcast("extract", {"page_ids": ids})
            if device and not self._multihost and cfg.kv_quant == "none":
                return await self._run_on_device(
                    lambda: self._extract_pages(self.kv_k, self.kv_v, jnp.asarray(ids))
                )
            return await self._run_on_device(partial(self._dev_extract, ids))

        def on_done(ok: bool):
            if slot.kv_stream_tid is None:
                return  # engine-initiated abort (release/preempt/emit)
            slot.kv_stream_tid = None
            if slot.done:
                # prefill finished and the pull settled: pages release
                # here, exactly like the serial stage's on_done
                if not ok:
                    logger.warning(
                        "streamed kv pull for %s abandoned; releasing pages",
                        slot.request_id,
                    )
                self._release_slot(slot)
            elif not ok:
                # reaped/severed while prefill still runs: the emit path
                # stages a fresh serial transfer instead
                self.kv_streamed_fallbacks += 1

        wire_shape, wire_dtype = self._kv_wire_meta()
        desc = self.data_plane.stage(
            n_pages=n_prompt_pages,
            n_tokens=len(slot.prompt),
            page_size=cfg.page_size,
            page_shape=wire_shape,
            dtype=wire_dtype,
            kv_format=cfg.kv_quant,
            extract=extract,
            on_done=on_done,
            chunk_pages=max(cfg.max_prefill_chunk // cfg.page_size, 1),
            streamed=True,
            available_pages=min(
                len(slot.committed_hashes), n_prompt_pages - 1
            ),
        )
        slot.kv_stream_tid = desc.transfer_id
        slot.kv_stream_desc = desc.to_dict()
        self.kv_streamed_stages += 1
        # EARLY descriptor event (no token yet): the decode worker starts
        # pulling immediately, while we prefill
        out = LLMEngineOutput(
            kv_transfer_params={"pull": slot.kv_stream_desc}
        ).to_dict()
        slot.queue.put_nowait(Annotated(data=out).to_dict())

    def _advance_kv_stream(self, slot: _Slot):
        """Streamed handoff watermark: every committed prompt page is
        pullable, except the last prompt page which always waits for emit
        (_stage_streamed_kv invariant)."""
        if slot.kv_stream_tid is None or self.data_plane is None:
            return
        n_prompt_pages = (
            len(slot.prompt) + self.config.page_size - 1
        ) // self.config.page_size
        self.data_plane.advance_streamed(
            slot.kv_stream_tid,
            min(len(slot.committed_hashes), n_prompt_pages - 1),
        )

    def _finish_streamed_kv(self, slot: _Slot, first_token: int,
                            first_lp: Optional[float] = None,
                            first_top: Optional[dict] = None):
        """Prefill finished with a live streamed stage: publish the final
        watermark (the last — possibly partial — prompt page is now valid)
        and send the first token with the same descriptor. Pages stay
        pinned until the pull finishes (on_done), like the serial stage."""
        cfg = self.config
        n_prompt_pages = (len(slot.prompt) + cfg.page_size - 1) // cfg.page_size
        out = LLMEngineOutput(
            token_ids=[first_token],
            log_probs=[first_lp]
            if (slot.want_logprobs and first_lp is not None) else None,
            top_logprobs=[first_top] if first_top else None,
            finish_reason="remote_prefill_done",
            kv_transfer_params={"pull": slot.kv_stream_desc},
        ).to_dict()
        slot.queue.put_nowait(Annotated(data=out).to_dict())
        slot.queue.put_nowait(None)
        slot.done = True
        # watermark LAST: the moment it hits n_pages the pull can complete
        # and on_done releases the slot — done/queue state must be settled
        self.data_plane.advance_streamed(slot.kv_stream_tid, n_prompt_pages)
        # NOT released here: pages stay pinned until on_done (pull or TTL)

    def _stage_local_shard(self, tid: str, page_ids: np.ndarray, on_done):
        """Stage THIS host's KV shard of `page_ids` under transfer id `tid`
        on the local data plane (leader and followers run this — leader via
        _stage_kv_pull, followers via the stage_shard replay)."""
        import jax.numpy as jnp

        c = self.model_config
        cfg = self.config

        async def extract(off: int, n: int, device: bool):
            ids = page_ids[off : off + n]
            return await self._run_on_device(
                partial(self._extract_local_shard, ids)
            )

        return self.data_plane.stage(
            n_pages=int(len(page_ids)),
            n_tokens=0,
            page_size=cfg.page_size,
            page_shape=self.local_shard_page_shape(),
            dtype=str(jnp.zeros((), c.dtype).dtype),
            extract=extract,
            on_done=on_done,
            transfer_id=tid,
        )

    def _commit_blocks(self, slot: _Slot, upto_tokens: Optional[int] = None):
        """Bind filled prompt pages to their hashes -> prefix cache + events.

        `upto_tokens`: incremental commit after a confirmed prefill CHUNK
        (the fetch of its dispatch's first-token proves the device ran the
        program, so the pages hold real KV) — concurrent same-prefix
        requests start hitting these blocks before the whole prompt
        finishes, instead of redundantly recomputing a prefix another
        in-flight request already wrote."""
        hashes = slot.seq.block_hashes()
        n_known = len(slot.committed_hashes)
        limit = len(slot.kv_prompt)
        if upto_tokens is not None:
            limit = min(limit, upto_tokens)
        prompt_full_blocks = limit // self.config.page_size
        new_hashes = hashes[n_known:prompt_full_blocks]
        if new_hashes:
            pages = slot.pages[n_known : n_known + len(new_hashes)]
            token_blocks = [
                b.tokens for b in slot.seq.blocks[n_known : n_known + len(new_hashes)]
            ]
            parent = slot.committed_hashes[-1] if slot.committed_hashes else None
            self.allocator.commit_hashes(pages, new_hashes, token_blocks, parent)
            slot.committed_hashes.extend(new_hashes)
            self._advance_kv_stream(slot)
            if self.kvbm is not None:
                self.kvbm.offload_commit(
                    new_hashes, [p + 1 for p in pages], parent=parent
                )

    # -- decode ---------------------------------------------------------- #

    def _active_decode_indices(self) -> List[int]:
        out = []
        for i, slot in enumerate(self.slots):
            if slot is None:
                continue
            if slot.prefill_pos >= len(slot.kv_prompt) and slot.generated > 0 and slot.resume_token is None:
                out.append(i)
        return out

    def _grow_pages_for_block(self, active: List[int],
                              steps: Optional[int] = None) -> List[int]:
        """Ensure each active lane's pages cover `steps` decode steps
        (default: one fused block's max advance); preempt the newest
        sequence (or finish with 'length' as last resort) when the pool is
        exhausted. Returns the surviving active set."""
        cfg = self.config
        K = steps or cfg.block_advance
        for i in list(active):
            slot = self.slots[i]
            if slot is None:
                continue
            # clamp to the model-length bound: speculation past it writes to
            # the scratch page (decode_forward routes out-of-range positions
            # there), so no pages are needed beyond max_model_len
            last_pos = min(
                int(self.seq_lens[i]) - 1 + (K - 1), cfg.max_model_len - 1
            )
            needed_pages = last_pos // cfg.page_size + 1
            while len(slot.pages) < needed_pages:
                fresh = self.allocator.alloc_fresh(1)
                if fresh is not None:
                    slot.pages.extend(fresh)
                    self.page_tables[i, len(slot.pages) - 1] = fresh[0] + 1
                    if self._carry_valid:
                        # table-row-only patch: this lane's carry values on
                        # device are newer than host (blocks in flight)
                        self._dirty_tables.add(i)
                    continue
                if not self._preempt_one(exclude_idx=i):
                    # nothing left to preempt: finish with length
                    self._emit_finish(slot, "length")
                    self._release_slot(slot)
                    break
        return self._active_decode_indices()

    def _preempt_one(self, exclude_idx: int) -> bool:
        """Preempt the newest-admitted active sequence: commit its full
        blocks (so resume rides the prefix cache / KVBM), release pages,
        requeue. Reference: mocker scheduler watermark eviction
        (lib/llm/src/mocker/scheduler.rs:240)."""
        victims = [
            s
            for s in self.slots
            if s is not None and s.slot_idx != exclude_idx and s.generated > 0
        ]
        if not victims:
            return False
        victim = max(victims, key=lambda s: s.admit_seq)
        logger.info("preempting %s to reclaim pages", victim.request_id)
        self.num_preemptions += 1
        # resume state: recompute KV for everything except the pending token
        victim.resume_token = victim.last_token
        victim.kv_prompt = list(victim.seq.tokens[:-1])
        victim.prefill_pos = 0
        self._release_slot(victim)
        self._waiting.insert(0, victim)
        return True

    def _prefill_work_pending(self) -> bool:
        """True when prefill compute could actually be dispatched: a slot
        passing _dispatch_prefill's candidate filter (skip preloaded/
        onboard slots — their KV arrives by injection, not prefill), or an
        admittable waiter. An un-admittable waiter or an in-flight KV pull
        must NOT throttle decode."""
        if self._waiting and self._free_slots:
            return True
        return any(
            s is not None
            and s.prefill_pos < len(s.kv_prompt)
            and s.preloaded is None
            and s.onboard is None
            and not s.done
            for s in self.slots
        )

    def _host_ngram_draft(self, slot, d: int) -> List[int]:
        """Host-side n-gram draft for fused spec verify rows (mirrors the
        device draft in spec.py, but over the authoritative host token
        sequence — drafts only steer ACCEPTANCE rate, never correctness:
        every emitted token is a verified sample from the target model).
        Most-recent n-gram match wins; pads with the last token."""
        seq = slot.seq.tokens
        n = self.config.spec_ngram
        if n <= 0 or len(seq) < n:
            return [int(seq[-1])] * d
        gram = list(seq[len(seq) - n:])
        for start in range(len(seq) - n - 1, -1, -1):
            if list(seq[start:start + n]) == gram:
                follow = [int(t) for t in seq[start + n:start + n + d]]
                return follow + [int(seq[-1])] * (d - len(follow))
        return [int(seq[-1])] * d

    async def _dispatch_mixed(self) -> bool:
        """Unified mixed step (ROADMAP 2, "Ragged Paged Attention"): when
        there are BOTH runnable prefill chunks and active decode lanes,
        pack them into one flat ragged token buffer — prefill chunks as
        T>1 rows, decode lanes as T=1 rows with ctx = seq_len - 1 — and
        run ONE device call per layer stack instead of a prefill dispatch
        followed by a decode dispatch. Every decode lane advances one
        token; completed prompts sample their first token; both ride the
        same fetched [R] result. Guided rows carry a packed FSM mask
        operand, lora rows a per-row adapter index, and spec-eligible
        lanes pack 1+d one-token verify rows — the fused path is the
        default for blended traffic. Returns False (split path runs)
        whenever the fused step is inapplicable: mixed disabled, a
        multimodal candidate starved past its SLA (mm stays split-only),
        decode blocks in flight (their device carry owns lane state — the
        mixed step needs host-authoritative lanes), or the planner
        declines.

        Shapes stay bounded: flat tokens pow2-bucketed to
        config.mixed_max_tokens, ONE fixed row bucket
        (self._mixed_row_bucket — the row axis only sizes scalar
        operands), tables pow2-bucketed like the prefill dispatch. Row
        starts are aligned to the Pallas ragged kernel's q tile exactly
        when ops._pallas_eligible says the kernel will run; on the XLA
        reference path the packer is dense."""
        cfg = self.config
        self._mixed_wait_drain = False
        if not self._mixed_enabled:
            return False
        active = self._active_decode_indices()
        if not active:
            return False
        # spec fusion: every spec-eligible decode lane packs 1 + d
        # one-token verify rows (current token + d host n-gram drafts) —
        # the verify step IS a ragged mixed batch. Guided lanes stay
        # single-row (the next mask depends host-side on this token).
        d = cfg.spec_draft_len if cfg.spec_mode else 0
        n_spec_rows = sum(
            d for i in active if self.slots[i].guided_fsm is None
        ) if d else 0
        cands = []
        mm_starved = False
        for s in self.slots:
            if s is None or s.prefill_pos >= len(s.kv_prompt):  # dynolint: disable=race-await-atomicity -- single writer per live slot (same shape as _dispatch_prefill); pull-path slots filtered below
                continue
            if s.preloaded is not None or s.onboard is not None:
                continue
            if s.done or s.context.is_stopped():
                self._emit_finish(s, "cancelled")
                self._release_slot(s)
                continue
            if s.mm is not None:
                # multimodal stays split-only (embedding-splice operand):
                # exclude ONLY this slot — plain + fused kinds still fuse
                # this step — and age it toward the starvation guard
                s.sched_skips += 1
                if s.sched_skips >= self.scheduler.sla.starve_dispatches:
                    mm_starved = True
                continue
            self._try_skip_ahead(s)
            cands.append(s)
        if mm_starved:
            # a starved mm candidate must win the next batch outright:
            # yield the whole step to the split path, whose
            # pick_batch_kind starvation override serves it
            return False
        if not cands:
            return False
        cands = self.scheduler.order(cands)
        align = self._mixed_align
        plan = self.scheduler.plan_mixed(
            cands, n_decode=len(active), align=align,
            n_spec_rows=n_spec_rows,
        )
        if plan is None:
            return False  # nothing fuses (e.g. decode lanes fill the
            # budget) — split path runs at full rate, no hold
        if self._inflight or self._pending_prefill:
            # a decode block in flight owns these lanes' device carry, so
            # the fused step can't pack them yet. Signal the step loop to
            # HOLD the split prefill for one step while the pipeline
            # drains (the split dispatch would queue behind the in-flight
            # block on the device stream anyway) — the next step fuses.
            # Only worth it when a fused step is actually plannable,
            # hence AFTER the plan check.
            self._mixed_wait_drain = True
            # the held step grants nothing: every candidate ages, same as
            # a plan_prefill defer (the skipped _dispatch_prefill would
            # otherwise never age them on hold steps)
            for s in cands:
                s.sched_skips += 1
            return False
        # one decode step of page headroom (1 + d under spec: draft rows
        # write KV at speculative positions); growth can preempt —
        # re-filter both the decode set and the chosen prefill slots
        active = self._grow_pages_for_block(active, steps=1 + d)
        if not active:
            return False
        chosen = [
            (s, ch) for s, ch in zip(plan.chosen, plan.chunks)
            if s.slot_idx >= 0 and self.slots[s.slot_idx] is s
        ]
        if not chosen:
            return False
        # the dispatch is committed from here on — account it (plan_mixed
        # itself is pure, so an abandoned plan never skews the sched_*
        # grant counters the split path's plan_prefill also feeds)
        self.scheduler.commit_mixed(plan, chosen)
        # candidates the plan passed over age toward the starvation guard,
        # exactly as on the split path — fused steps must not exempt a
        # steady tight-deadline stream from starve_dispatches promotion
        granted_slots = {id(s) for s, _ in chosen}
        for s in cands:
            if id(s) not in granted_slots:
                s.sched_skips += 1

        def aligned(n: int) -> int:
            return -(-n // align) * align

        # the bucket cap floored to the alignment, mirroring plan_mixed's
        # budget: total <= cap by construction, and a non-aligned
        # mixed_max_tokens can never produce an N_pad the Pallas kernel's
        # N % tile_q assert would reject
        cap = cfg.mixed_max_tokens - cfg.mixed_max_tokens % align
        # recompute the decode row count against the SURVIVING active set
        # (page growth can preempt lanes out from under the plan)
        spec_lanes = {
            i for i in active
            if cfg.spec_mode and self.slots[i].guided_fsm is None
        }
        n_rows_decode = len(active) + d * len(spec_lanes)
        total = sum(aligned(ch) for _, ch in chosen) \
            + aligned(1) * n_rows_decode
        N_pad = min(_next_pow2(max(total, align)), cap)
        R_pad = self._mixed_row_bucket
        max_pages_needed = 1
        for s, ch in chosen:
            pages = (s.prefill_pos + ch + cfg.page_size - 1) // cfg.page_size
            max_pages_needed = max(max_pages_needed, pages)
        for i in active:
            extra = d if i in spec_lanes else 0
            pages = (int(self.seq_lens[i]) - 1 + extra) // cfg.page_size + 1
            max_pages_needed = max(max_pages_needed, pages)
        ctx_pages = min(_next_pow2(max_pages_needed), cfg.max_pages_per_seq)
        P = ctx_pages + 1
        pad_pos = P * cfg.page_size - 1  # pads write to the scratch tail

        W = cfg.penalty_window
        toks = np.zeros((N_pad,), np.int32)
        positions = np.full((N_pad,), pad_pos, np.int32)
        row_ids = np.full((N_pad,), R_pad - 1, np.int32)
        row_starts = np.full((R_pad,), N_pad, np.int32)
        row_lens = np.zeros((R_pad,), np.int32)
        ctx_lens = np.zeros((R_pad,), np.int32)
        tables = np.full((R_pad, P), SCRATCH_PAGE, np.int32)
        last_flat = np.zeros((R_pad,), np.int32)
        temps = np.zeros((R_pad,), np.float32)
        top_ks = np.zeros((R_pad,), np.int32)
        top_ps = np.ones((R_pad,), np.float32)
        seeds = np.zeros((R_pad,), np.uint32)
        pens = np.zeros((R_pad, 3), np.float32)
        pens[:, 2] = 1.0  # repetition off
        pen_rows = np.full((R_pad, W), -1, np.int32)

        # variant operands: a bitpacked per-row FSM mask whenever any
        # guided/lora row packs (all-ones rows are exact no-ops), plus
        # per-row adapter indices when adapters are registered (index 0 =
        # the all-zero base adapter). Pure-plain and pure-spec packs keep
        # the LEAN program — byte-identical operands to the split path.
        dec_slots = [self.slots[i] for i in active]
        any_guided = any(
            s.guided_fsm is not None for s, _ in chosen
        ) or any(s.guided_fsm is not None for s in dec_slots)
        any_lora = any(s.lora_idx for s, _ in chosen) or any(
            s.lora_idx for s in dec_slots
        )
        mask_packed = None
        lora_rows = None
        if any_guided or any_lora:
            V = self.model_config.vocab_size
            mask_packed = np.full((R_pad, (V + 7) // 8), 0xFF, np.uint8)
            if self._lora is not None:
                lora_rows = np.zeros((R_pad,), np.int32)

        off = 0
        row = 0
        meta = []  # prefill rows: (slot, chunk, row)
        decode_rows = []  # (row, lane_idx, slot)
        spec_rows = []  # (first_row, lane_idx, slot, draft) — 1+d rows each
        for s, chunk in chosen:
            start = s.prefill_pos
            row_starts[row] = off
            row_lens[row] = chunk
            ctx_lens[row] = start
            toks[off : off + chunk] = s.kv_prompt[start : start + chunk]
            positions[off : off + chunk] = np.arange(start, start + chunk)
            row_ids[off : off + aligned(chunk)] = row
            tables[row, :ctx_pages] = self.page_tables[s.slot_idx][:ctx_pages]
            last_flat[row] = off + chunk - 1
            temps[row] = s.temperature
            top_ks[row] = s.top_k
            top_ps[row] = s.top_p
            seeds[row] = s.sample_seed
            pens[row] = (s.presence_penalty, s.frequency_penalty,
                         s.repetition_penalty)
            pen_rows[row] = self.recent[s.slot_idx]
            if s.guided_fsm is not None:
                mask_packed[row] = np.packbits(self._guided_lane_mask(
                    s.guided_fsm, s.guided_state
                ))
                self.mixed_rows_guided += 1
            elif s.lora_idx:
                self.mixed_rows_lora += 1
            else:
                self.mixed_rows_plain += 1
            if lora_rows is not None:
                lora_rows[row] = s.lora_idx
            s.sched_skips = 0
            meta.append((s, chunk, row))
            off += aligned(chunk)
            row += 1
        for i in active:
            s = self.slots[i]
            L = int(self.seq_lens[i])
            spec_lane = i in spec_lanes
            draft = self._host_ngram_draft(s, d) if (spec_lane and d) else []
            row_toks = [int(self.tokens[i])] + draft
            first_row = row
            for j, tk in enumerate(row_toks):
                # row j carries one token at position L-1+j with ctx
                # L-1+j: it attends the lane's committed KV plus rows
                # 0..j-1 of THIS pack (their KV is written before
                # attention each layer), so row j's sample is exactly the
                # plain seeded decode draw at that position — the fused
                # verify's parity lever
                row_starts[row] = off
                row_lens[row] = 1
                ctx_lens[row] = L - 1 + j
                toks[off] = tk
                positions[off] = L - 1 + j
                row_ids[off : off + aligned(1)] = row
                tables[row, :ctx_pages] = self.page_tables[i][:ctx_pages]
                last_flat[row] = off
                temps[row] = self.temps[i]
                top_ks[row] = self.top_ks[i]
                top_ps[row] = self.top_ps[i]
                seeds[row] = self.seeds[i]
                if lora_rows is not None:
                    lora_rows[row] = s.lora_idx
                if not spec_lane:
                    pens[row] = (self.presence[i], self.frequency[i],
                                 self.repetition[i])
                    # the device pen ring (decode carry) is not
                    # host-visible; rebuild this lane's window from the
                    # authoritative token sequence (ring-indexed by
                    # absolute position, so the patch after the fetch
                    # stays consistent with it)
                    self._fill_recent(i, s)
                    pen_rows[row] = self.recent[i]
                    if s.guided_fsm is not None:
                        mask_packed[row] = np.packbits(
                            self._guided_lane_mask(
                                s.guided_fsm, s.guided_state
                            )
                        )
                # spec rows keep default pens: penalties/logprobs are
                # rejected under spec_mode at admission
                off += aligned(1)
                row += 1
            if spec_lane:
                spec_rows.append((first_row, i, s, draft))
                self.mixed_rows_spec += len(row_toks)
            else:
                decode_rows.append((first_row, i, s))
                if s.guided_fsm is not None:
                    self.mixed_rows_guided += 1
                elif s.lora_idx:
                    self.mixed_rows_lora += 1
                else:
                    self.mixed_rows_plain += 1

        payload = {
            "toks": toks, "positions": positions, "row_ids": row_ids,
            "tables": tables, "row_starts": row_starts,
            "row_lens": row_lens, "ctx_lens": ctx_lens,
            "last_flat": last_flat, "temps": temps, "top_ks": top_ks,
            "top_ps": top_ps, "seeds": seeds, "pens": pens,
            "pen_rows": pen_rows,
        }
        if mask_packed is not None:
            payload["mask"] = mask_packed
        if lora_rows is not None:
            payload["lora_idx"] = lora_rows
        self._bcast("mixed", payload)
        first_dev = await self._run_on_device(
            partial(
                self._dev_mixed, toks, positions, row_ids, tables,
                row_starts, row_lens, ctx_lens, last_flat, temps, top_ks,
                top_ps, seeds, pens, pen_rows, mask_packed, lora_rows,
            ),
            tag="mixed", shape=(N_pad, row),
        )
        completions = []
        progressed = []
        for s, chunk, row_i in meta:
            s.prefill_pos += chunk
            progressed.append((s, s.prefill_pos))
            if s.prefill_pos >= len(s.kv_prompt):
                completions.append((s, row_i))
        for row_i, i, s in decode_rows:
            self.seq_lens[i] += 1
        # spec lanes are NOT advanced here: acceptance is data-dependent
        # (resolved from the fetched [R] tokens), and mixed dispatches
        # drain this same step, so seq_lens stays authoritative for the
        # next dispatch.
        # rides the prefill-pending fetch (drained THIS step, so no decode
        # block can dispatch against the stale device carry in between)
        self._pending_prefill.append({
            "first": first_dev, "done": completions,
            "progressed": progressed, "decode": decode_rows,
            "spec": spec_rows,
        })
        self.mixed_steps += 1
        self.mixed_padded_tokens += N_pad
        self.mixed_real_tokens += sum(ch for _, ch, _ in meta) + n_rows_decode
        self._step_counter += 1
        return True

    async def _dispatch_decode(self) -> bool:
        cfg = self.config
        # prefill-priority depth cap: with dispatchable prefill work, keep
        # only ONE speculative block in flight — a new arrival's prefill
        # queues behind every in-flight block on the device stream, so
        # depth-2 doubles its queueing delay (TTFT) to buy decode overlap
        # it regains once the queue drains. Spec-decode blocks advance
        # lanes by a DATA-DEPENDENT amount, so host bookkeeping must be
        # corrected from each block's fetch before the next dispatches:
        # depth stays 1 (the verify pass amortizes weight streams instead).
        # guided lanes: the next step's mask depends on the token the
        # PREVIOUS step emitted, so while any guided slot is decode-active
        # the pipeline depth is 1 and every block must be fetched+processed
        # (FSM advanced) before the next dispatch.
        has_guided = any(
            s is not None and s.guided_fsm is not None
            and s.prefill_pos >= len(s.kv_prompt) and s.generated > 0
            for s in self.slots
        )
        depth = 1 if (
            cfg.spec_mode or has_guided or self._prefill_work_pending()
        ) else 2
        if len(self._inflight) >= depth:
            return False
        if not self._carry_valid and self._inflight:
            return False  # drain in-flight blocks before a state reset
        active = self._active_decode_indices()
        if not active:
            return False
        active = self._grow_pages_for_block(active)
        if not active:
            return False
        if not self._carry_valid and self._inflight:
            # growth/preemption invalidated the carry mid-pipeline: drain the
            # in-flight block first (its results update host state), THEN a
            # fresh upload is consistent
            return False

        B = cfg.max_num_seqs
        K = cfg.decode_block_steps
        # the DEVICE decode table keeps SCRATCH rows for every lane that is
        # not decode-active: inside a fused block, inactive lanes' seq_lens
        # still advance (lax.scan carries the whole batch), so their KV
        # writes would otherwise land at positions 0..K-1 of whatever the
        # host table row points at — including a PREFILLING slot's pages
        # (possibly shared prefix-cache pages). A scratch row routes all
        # such writes to the reserved scratch page by construction.
        if not self._carry_valid:
            # TAKE the dirt before building the upload: the dispatch below
            # suspends, and a background KV-pull activation landing during
            # that await marks fresh lanes dirty — clearing after the await
            # would erase their mark and leave stale lane state on device.
            # Taken synchronously with the array snapshot, new dirt simply
            # rides the next step's patch.
            self._dirty_lanes.clear()
            self._dirty_tables.clear()
            mask = np.zeros((B,), bool)
            for i in active:
                mask[i] = True
            positions = np.where(mask, self.seq_lens - 1, 0).astype(np.int32)
            seq_lens_step = np.where(mask, self.seq_lens, 0).astype(np.int32)
            tokens = np.where(mask, self.tokens, 0).astype(np.int32)
            tables = np.where(
                mask[:, None], self.page_tables, SCRATCH_PAGE
            ).astype(np.int32)
            hist = (
                np.where(mask[:, None], self.hist, 0).astype(np.int32)
                if self.hist is not None else None
            )
            pens = np.stack(
                [self.presence, self.frequency, self.repetition], axis=1
            )
            payload = {
                "tokens": tokens, "positions": positions,
                "seq_lens": seq_lens_step, "page_tables": tables,
                "temps": self.temps, "top_ks": self.top_ks,
                "top_ps": self.top_ps, "seeds": self.seeds,
                "pens": pens, "recent": self.recent,
            }
            if hist is not None:
                payload["hist"] = hist
            self._bcast("reset", payload)
            await self._run_on_device(
                partial(
                    self._dev_reset,
                    tokens, positions, seq_lens_step,
                    tables, self.temps.copy(),
                    self.top_ks.copy(), self.top_ps.copy(),
                    self.seeds.copy(), pens, self.recent.copy(), hist,
                ),
                tag="reset",
            )
            self._carry_valid = True
        elif self._dirty_lanes or self._dirty_tables:
            # per-lane patch: update just the changed lanes on device — no
            # pipeline drain, no full re-upload. Untouched lanes keep their
            # (newer) device carry; table_mask covers lanes whose page table
            # grew but whose carry must be preserved.  TAKE the dirty sets
            # atomically with the host-array snapshot (same reasoning as
            # the reset branch: dirt added during the dispatch await must
            # survive into the next step, not be cleared with this one).
            dirty_lanes, dirty_tables = self._dirty_lanes, self._dirty_tables
            self._dirty_lanes, self._dirty_tables = set(), set()
            lane_mask = np.zeros((B,), bool)
            for i in dirty_lanes:
                lane_mask[i] = True
            table_mask = lane_mask.copy()
            for i in dirty_tables:
                table_mask[i] = True
            active_mask = np.zeros((B,), bool)
            for i in active:
                active_mask[i] = True
            n_tokens = np.where(active_mask, self.tokens, 0).astype(np.int32)
            n_positions = np.where(active_mask, self.seq_lens - 1, 0).astype(np.int32)
            n_seq_lens = np.where(active_mask, self.seq_lens, 0).astype(np.int32)
            n_tables = np.where(
                active_mask[:, None], self.page_tables, SCRATCH_PAGE
            ).astype(np.int32)
            hist = self.hist.astype(np.int32) if self.hist is not None else None
            pens = np.stack(
                [self.presence, self.frequency, self.repetition], axis=1
            )
            payload = {
                "lane_mask": lane_mask, "table_mask": table_mask,
                "tokens": n_tokens, "positions": n_positions,
                "seq_lens": n_seq_lens, "page_tables": n_tables,
                "temps": self.temps, "top_ks": self.top_ks,
                "top_ps": self.top_ps, "seeds": self.seeds,
                "pens": pens, "recent": self.recent,
            }
            if hist is not None:
                payload["hist"] = hist
            self._bcast("patch", payload)
            await self._run_on_device(
                partial(
                    self._dev_patch, lane_mask, table_mask,
                    n_tokens, n_positions, n_seq_lens,
                    n_tables, self.temps.copy(),
                    self.top_ks.copy(), self.top_ps.copy(),
                    self.seeds.copy(), pens, self.recent.copy(), hist,
                ),
                tag="patch",
            )

        guided_lanes = [
            i for i in active if self.slots[i].guided_fsm is not None
        ]
        if guided_lanes:
            # single masked step: guided rows from each lane's FSM state,
            # unguided rows admit everything. Bitpacked: [B, V/8] uint8
            # host→device instead of a [B, V] bool (the per-step transfer
            # would otherwise dominate guided ITL through the tunnel).
            V = self.model_config.vocab_size
            packed = np.full((B, (V + 7) // 8), 0xFF, np.uint8)
            for i in guided_lanes:
                s = self.slots[i]
                packed[i] = np.packbits(
                    self._guided_lane_mask(s.guided_fsm, s.guided_state)
                )
            lora_idx = (
                self.lora_idx.copy()
                if any(self.slots[i].lora_idx for i in active) else None
            )
            payload = {"mask": packed}
            if lora_idx is not None:
                payload["lora_idx"] = lora_idx
            self._bcast("block_guided", payload)
            toks_dev = await self._run_on_device(
                partial(self._dev_block_guided, packed, lora_idx),
                tag="block_guided", shape=(1, B),
            )
            adv = 1
            kind = "block"
        elif any(self.slots[i].lora_idx for i in active):
            idx = self.lora_idx.copy()
            self._bcast("block_lora", {"idx": idx})
            toks_dev = await self._run_on_device(
                partial(self._dev_block_lora, idx), tag="block_lora",
                shape=(K, B),
            )
            # decode_block_lora always advances K steps — NOT
            # cfg.block_advance, which under a spec engine is the spec
            # program's worst-case spec_rounds*(1+d) bound
            adv = K
            kind = "block"
        else:
            self._bcast("block", {})
            toks_dev = await self._run_on_device(
                self._dev_block, tag="block", shape=(K, B)
            )
            adv = cfg.block_advance
            # only this branch runs the spec program under spec_mode;
            # guided/lora blocks above drain through _process_block
            kind = "spec" if cfg.spec_mode else "block"
        self._last_decode_shape = (B * adv, len(active) * adv)
        entry = {
            "lanes": [(i, self.slots[i]) for i in active],
            "toks": toks_dev, "kind": kind,
        }
        if kind == "spec":
            # spec blocks advance lanes by a data-dependent amount: record
            # the pre-dispatch seq_lens so the fetch can correct the
            # worst-case advance below to the device-true values
            entry["seq_before"] = {i: int(self.seq_lens[i]) for i in active}
        self._inflight.append(entry)
        # advance host bookkeeping by the block's max advance for the NEXT
        # block's page growth (exact for plain decode; an upper bound under
        # spec, corrected at fetch)
        for i in active:
            self.seq_lens[i] += adv
        self._step_counter += 1
        return True

    async def _fetch_and_process(self, fetch_block: bool) -> bool:
        """One RTT: fetch pending prefill first-tokens + the oldest in-flight
        decode block together, then run host bookkeeping/emission."""
        want_block = self._inflight[0] if (fetch_block and self._inflight) else None
        prefills = self._pending_prefill
        self._pending_prefill = []
        if want_block is None and not prefills:
            return False
        tree = (
            [p["first"] for p in prefills],
            want_block["toks"] if want_block is not None else None,
        )
        firsts_np, toks_np = await self._fetch(tree)

        for p, first in zip(prefills, firsts_np):
            for slot, upto in p.get("progressed", []):
                if slot.slot_idx < 0 or self.slots[slot.slot_idx] is not slot:
                    continue
                if slot.prefill_pos < len(slot.kv_prompt):
                    # mid-prompt: commit the chunk's full pages now so
                    # concurrent same-prefix requests can skip ahead
                    self._commit_blocks(slot, upto_tokens=upto)
            first_toks, first_lps, first_tids, first_tlps = first
            for slot, lane in p["done"]:
                if slot.slot_idx < 0 or self.slots[slot.slot_idx] is not slot:
                    continue  # released meanwhile (cancel)
                tok = int(first_toks[lane])
                lp = float(first_lps[lane])
                top = self._top_entry(slot, first_tids[lane], first_tlps[lane])
                if slot.return_kv:
                    await self._emit_prefill_result(slot, tok, lp, top)
                else:
                    self._finish_prefill(slot, tok, lp, top)
            # mixed-step decode rows: each active lane advanced ONE token
            # inside the fused dispatch — emit it and re-sync the (stale)
            # device decode carry for this lane via the patch path
            for row, i, slot_ref in p.get("decode", []):
                slot = self.slots[i]
                if slot is None or slot is not slot_ref:
                    continue  # released/preempted meanwhile
                if slot.done or slot.context.is_stopped():
                    self._emit_finish(slot, "cancelled")
                    self._release_slot(slot)
                    continue
                tok = int(first_toks[row])
                slot.seq.append(tok)
                slot.generated += 1
                slot.last_token = tok
                self.tokens[i] = tok
                if slot.guided_fsm is not None:
                    # fused guided decode: the mixed step is host-
                    # authoritative per step, so the FSM advances here —
                    # the next dispatch packs the updated mask
                    slot.guided_state = slot.guided_fsm.advance(
                        slot.guided_state, tok
                    )
                if self.hist is not None:
                    # keep the spec n-gram ring coherent for lanes that
                    # advanced outside the spec program (guided/plain
                    # rows under spec_mode); patch re-uploads it via
                    # _mark_lane_dirty below
                    self.hist[
                        i, (len(slot.seq.tokens) - 1) % self.config.spec_hist
                    ] = tok
                lp = float(first_lps[row])
                top = self._top_entry(slot, first_tids[row], first_tlps[row])
                self._emit_tokens(
                    slot, [tok],
                    [lp] if slot.want_logprobs else [],
                    [top] if top else [],
                )
                finish = self._finish_reason(slot, tok)
                if finish:
                    self._emit_finish(slot, finish)
                    self._release_slot(slot)
                else:
                    self._fill_recent(i, slot)
                    self._mark_lane_dirty(i)
                    self._maybe_commit_incremental(slot)
            # fused spec verify rows: lane i packed rows first_row..
            # first_row+d (current token + draft); row j's sample is the
            # plain seeded draw at position L-1+j, so accepting the
            # longest draft prefix matching the verified samples and
            # emitting n_acc+1 tokens is byte-identical to plain decode
            for first_row, i, slot_ref, draft in p.get("spec", []):
                slot = self.slots[i]
                if slot is None or slot is not slot_ref:
                    continue
                if slot.done or slot.context.is_stopped():
                    self._emit_finish(slot, "cancelled")
                    self._release_slot(slot)
                    continue
                d_n = len(draft)
                out = [int(first_toks[first_row + j]) for j in range(1 + d_n)]
                n_acc = 0
                while n_acc < d_n and out[n_acc] == draft[n_acc]:
                    n_acc += 1
                self.spec_num_drafts += 1
                self.spec_num_draft_tokens += d_n
                self.spec_num_accepted_tokens += n_acc
                L = int(self.seq_lens[i])
                Hc = self.config.spec_hist
                batch: List[int] = []
                finish = None
                for m, tok in enumerate(out[: n_acc + 1]):
                    slot.seq.append(tok)
                    slot.generated += 1
                    slot.last_token = tok
                    if self.hist is not None:
                        self.hist[i, (L + m) % Hc] = tok
                    batch.append(tok)
                    finish = self._finish_reason(slot, tok)
                    if finish:
                        break
                # seq_lens was NOT advanced at dispatch (acceptance is
                # data-dependent); commit the true advance now — rejected
                # rows' KV is garbage past seq_lens and gets overwritten
                # before it is ever attended
                self.seq_lens[i] = L + len(batch)
                self.tokens[i] = batch[-1]
                self._emit_tokens(slot, batch, [], [])
                if finish:
                    self._emit_finish(slot, finish)
                    self._release_slot(slot)
                else:
                    self._fill_recent(i, slot)
                    self._mark_lane_dirty(i)
                    self._maybe_commit_incremental(slot)

        if want_block is not None:
            self._inflight.popleft()
            # route by the block's dispatch kind, not cfg.spec_mode:
            # guided/lora blocks under a spec engine ride the K-step
            # decode_block programs and must drain through _process_block
            if want_block.get("kind") == "spec":
                self._process_spec_block(
                    want_block["lanes"], toks_np[0], toks_np[1],
                    want_block["seq_before"],
                )
            else:
                self._process_block(want_block["lanes"], *toks_np)
        return True

    def _process_spec_block(self, lanes: List[tuple], toks: np.ndarray,
                            n_emit: np.ndarray, seq_before: dict):
        """Emit a fetched spec block: toks [S, B, 1+d], n_emit [S, B].
        Per lane, each round contributes its first n_emit tokens; host
        seq_lens/tokens mirrors are corrected to the device-true values
        (dispatch advanced them by the worst-case bound)."""
        S, B, T = toks.shape
        Hc = self.config.spec_hist
        for i, slot_ref in lanes:
            slot = self.slots[i]
            if slot is None or slot is not slot_ref:
                continue
            true_adv = int(n_emit[:, i].sum())
            # device-authoritative mirrors (valid even if the slot finishes
            # below — the lane is re-patched on the next admission anyway)
            self.seq_lens[i] = seq_before[i] + true_adv
            self.tokens[i] = int(toks[S - 1, i, int(n_emit[S - 1, i]) - 1])
            # stats: engine-level acceptance (device view)
            self.spec_num_drafts += S
            self.spec_num_draft_tokens += S * (T - 1)
            self.spec_num_accepted_tokens += true_adv - S
            if slot.done or slot.context.is_stopped():
                self._emit_finish(slot, "cancelled")
                self._release_slot(slot)
                continue
            # the round's current token sits at position seq_before-1 (the
            # device carry was uploaded with positions = seq_lens - 1), so
            # emitted token t of a round lands at (pos + 1 + t) with
            # pos = seq_before - 1 — matching the device ring exactly
            pos = seq_before[i] - 1
            # all accepted rounds flow into one delta batch (same O(1)-per-
            # dispatch contract as _process_block); a stop mid-round
            # truncates host-side before anything reaches the client
            batch: List[int] = []
            finish = None
            for s in range(S):
                k = int(n_emit[s, i])
                for t in range(k):
                    tok = int(toks[s, i, t])
                    slot.seq.append(tok)
                    slot.generated += 1
                    slot.last_token = tok
                    if self.hist is not None:
                        self.hist[i, (pos + 1 + t) % Hc] = tok
                    batch.append(tok)
                    finish = self._finish_reason(slot, tok)
                    if finish:
                        break
                pos += k
                if finish:
                    break
            self._emit_tokens(slot, batch, [], [])
            if finish:
                self._emit_finish(slot, finish)
                self._release_slot(slot)
            else:
                self._maybe_commit_incremental(slot)

    def _process_block(self, lanes: List[tuple], toks: np.ndarray,
                       lps: np.ndarray, tids: np.ndarray,
                       tlps: np.ndarray):
        """Emit a fetched K-step block: per lane, append/emit tokens until a
        stop condition; excess speculated tokens are discarded. Lanes whose
        slot was preempted/released (or re-assigned) meanwhile are skipped —
        their speculated tokens were never emitted, so no client ever sees
        them."""
        K = toks.shape[0]
        for i, slot_ref in lanes:
            slot = self.slots[i]
            if slot is None or slot is not slot_ref:
                continue
            if slot.done or slot.context.is_stopped():
                self._emit_finish(slot, "cancelled")
                self._release_slot(slot)
                continue
            # the whole K-step block lands in ONE delta batch on the slot
            # queue: downstream (request plane, detokenizer, SSE) then pays
            # O(1) work per dispatch instead of per token. A mid-block
            # stop/eos truncates host-side — tokens past it were speculated
            # by the device and are never client-visible. The batch commits
            # atomically: resume/migration accounting counts it all-or-
            # nothing, exactly like the singleton emissions it replaces.
            batch: List[int] = []
            batch_lps: List[float] = []
            batch_tops: List[Optional[dict]] = []
            finish = None
            for k in range(K):
                tok = int(toks[k, i])
                slot.seq.append(tok)
                slot.generated += 1
                slot.last_token = tok
                self.tokens[i] = tok
                if slot.guided_fsm is not None:
                    slot.guided_state = slot.guided_fsm.advance(
                        slot.guided_state, tok
                    )
                if self.hist is not None:
                    # spec engine, non-spec block (guided/lora lanes):
                    # keep the n-gram ring coherent host-side
                    self.hist[
                        i, (len(slot.seq.tokens) - 1) % self.config.spec_hist
                    ] = tok
                batch.append(tok)
                if slot.want_logprobs:
                    batch_lps.append(float(lps[k, i]))
                    batch_tops.append(
                        self._top_entry(slot, tids[k, i], tlps[k, i])
                    )
                finish = self._finish_reason(slot, tok)
                if finish:
                    break
            self._emit_tokens(slot, batch, batch_lps, batch_tops)
            if finish:
                self._emit_finish(slot, finish)
                self._release_slot(slot)
            else:
                # durable sessions: newly-full generated blocks publish
                # now (prefix cache + KVBM + mesh + checkpoint), not at
                # release — a SIGKILL loses only the un-committed tail
                self._maybe_commit_incremental(slot)

    def _fail_all(self, message: str):
        """A step raised: the batch state is unreliable. Error every live
        request so callers can migrate/retry rather than hang."""
        self._inflight.clear()
        self._pending_prefill = []
        self._carry_valid = False
        self._dirty_lanes.clear()
        self._dirty_tables.clear()
        # no deadline may outlive its slot (chaos contract: an engine.step
        # fault mid-schedule leaves no orphaned scheduler state)
        self.scheduler.reset()
        for slot in list(self.slots):
            if slot is not None:
                if not slot.done:
                    slot.queue.put_nowait(Annotated.from_error(message).to_dict())
                    slot.queue.put_nowait(None)
                    slot.done = True
                self._release_slot(slot)
        for slot in self._waiting:
            if not slot.done:
                slot.queue.put_nowait(Annotated.from_error(message).to_dict())
                slot.queue.put_nowait(None)
                slot.done = True
        self._waiting = []

    def _sever_all(self, message: str) -> int:
        """Role-morph drain: deliberately cut every live stream with a
        StreamSevered sentinel (NOT _fail_all's terminal error chunk).
        The consumer loop raises it, the server codes the T_ERR as
        `draining`, and each caller's migration loop resumes the session
        on a peer from its durable checkpoint — zero lost items, a tail
        of latency. Batch state resets exactly like _fail_all; the
        severed queues are kept so morph() can wait for the sentinels to
        reach their consumers before flipping discovery."""
        self._inflight.clear()
        self._pending_prefill = []
        self._carry_valid = False
        self._dirty_lanes.clear()
        self._dirty_tables.clear()
        self.scheduler.reset()
        severed = 0
        queues: List[asyncio.Queue] = []
        # NO trailing None after the sentinel: the consumer RAISES on it
        # (never reads further), and a leftover None would keep the queue
        # non-empty forever — _await_sever_consumed watches q.empty() to
        # know the migration actually started
        for slot in list(self.slots):
            if slot is not None:
                if not slot.done:
                    slot.queue.put_nowait(StreamSevered(message))
                    slot.done = True
                    severed += 1
                    queues.append(slot.queue)
                self._release_slot(slot)
        for slot in self._waiting:
            if not slot.done:
                slot.queue.put_nowait(StreamSevered(message))
                slot.done = True
                severed += 1
                queues.append(slot.queue)
        self._waiting = []
        self._severed_queues = queues
        return severed

    # -- emission / teardown --------------------------------------------- #

    def _emit_token(self, slot: _Slot, token: int,
                    lp: Optional[float] = None,
                    top: Optional[dict] = None):
        if slot.done:
            return
        out = LLMEngineOutput(
            token_ids=[token],
            log_probs=[lp] if (slot.want_logprobs and lp is not None) else None,
            top_logprobs=[top] if top else None,
        ).to_dict()
        slot.queue.put_nowait(Annotated(data=out).to_dict())

    def _emit_tokens(self, slot: _Slot, tokens: List[int],
                     lps: List[float], tops: List[Optional[dict]]):
        """Emit a decode block's accepted tokens as ONE delta batch.
        `lps`/`tops` are 1:1 with `tokens` when the request asked for
        logprobs, else empty. The batch is committed atomically to the
        slot queue — the serving plane never sees a partial block."""
        if slot.done or not tokens:
            return
        out = LLMEngineOutput(
            token_ids=tokens,
            log_probs=lps if (slot.want_logprobs and lps) else None,
            top_logprobs=tops if any(tops) else None,
        ).to_dict()
        slot.queue.put_nowait(Annotated(data=out).to_dict())
        self.emit_batches += 1
        self.emit_tokens += len(tokens)

    def _finish_reason(self, slot: _Slot, token: int) -> Optional[str]:
        """Host-side stop check for one generated token (eos / stop token
        / length) — pure, so block loops can truncate before emitting."""
        if (
            not slot.ignore_eos
            and slot.generated >= slot.min_tokens
            and (token in slot.eos_ids or token in slot.stop_token_ids)
        ):
            return "eos"
        if slot.generated >= slot.max_tokens:
            return "length"
        return None

    def _maybe_finish(self, slot: _Slot, token: int):
        finish = self._finish_reason(slot, token)
        if finish:
            self._emit_finish(slot, finish)
            self._release_slot(slot)

    def _emit_finish(self, slot: _Slot, reason: str):
        if not slot.done:
            out = LLMEngineOutput(token_ids=[], finish_reason=reason).to_dict()
            slot.queue.put_nowait(Annotated(data=out).to_dict())
            slot.queue.put_nowait(None)
            slot.done = True
        # the stream is over: unpin its adapter (idempotent; preempted
        # slots never pass through here, so their pin survives requeue)
        self._release_lora_pin(slot)

    def _release_slot(self, slot: _Slot):
        if slot.done:
            # terminal release (finish / fail / sever) — NOT preemption,
            # which requeues the slot and must keep its adapter pinned
            self._release_lora_pin(slot)
        if slot.kv_stream_tid is not None and self.data_plane is not None:
            # streamed stage still live while its pages are being released
            # (preempt / cancel / engine failure): fail the transfer so
            # the pulling peer aborts instead of reading recycled pages
            tid, slot.kv_stream_tid = slot.kv_stream_tid, None
            self.data_plane.abort_streamed(tid)
        if slot.slot_idx >= 0 and self.slots[slot.slot_idx] is slot:
            self.scheduler.on_release(slot)
            # commit any full generated blocks before release so decode KV is
            # reusable (conversation prefix reuse / cheap preemption resume)
            self._commit_generated_blocks(slot)
            if self.kvbm is not None:
                # flush the stage NOW: release makes these pages evictable,
                # and the offload gather must enter the device queue before
                # any later dispatch that could recycle them (the step-end
                # flush would be too late for a mid-step release — preempt,
                # cancel from the generate() task)
                self.kvbm.flush_step()
            # releasing while blocks are in flight is safe: in-flight writes
            # for this lane land strictly AFTER its last committed position
            # (speculation starts past the fetched tokens), i.e. only on
            # free tail pages — and any reuse of those pages is re-written
            # by a later-dispatched (device-ordered) prefill/inject
            self.allocator.release(slot.pages, slot.committed_hashes)
            idx = slot.slot_idx
            self.slots[idx] = None
            self._free_slots.append(idx)
            self.page_tables[idx, :] = SCRATCH_PAGE
            self.seq_lens[idx] = 0
            slot.slot_idx = -1
            slot.pages = []
            self._mark_lane_dirty(idx)

    def _count_resume(self, slot: _Slot, hashes: List[int], n_cached: int,
                      onboard_hashes: List[int]):
        """Classify a migrated request's resume source at admission
        (docs/fault_tolerance.md): `checkpoint` when any reused block is a
        session-checkpoint replica (pushed here or mesh-tagged), `peer`
        when the onboard pulls plain fabric blocks from another worker,
        `local` when the survivor's own G1/tiers cover the prefix, else
        `recompute` (full prefill — the pre-checkpoint cost of a death)."""
        if slot.migration_counted:
            return
        slot.migration_counted = True
        self.migrations_resumed += 1
        ps = self.config.page_size
        reused_blocks = n_cached + len(onboard_hashes)
        self.migration_replayed_tokens += max(
            len(slot.kv_prompt) - reused_blocks * ps, 0
        )
        reused = list(hashes[:n_cached]) + list(onboard_hashes)
        if self.kvbm is not None and reused and self.kvbm.any_checkpoint(reused):
            self.resume_source_checkpoint += 1
        elif self.kvbm is not None and any(
            not self.kvbm.manager.has(h) for h in onboard_hashes
        ):
            self.resume_source_peer += 1
        elif reused_blocks:
            self.resume_source_local += 1
        else:
            self.resume_source_recompute += 1

    def _maybe_commit_incremental(self, slot: _Slot):
        """Step-loop arm of the generated-block commit (durable decode
        sessions): when a decode block just filled a page, publish it NOW
        — same _commit_generated_blocks spelling as release, so the two
        arms commit byte-identical blocks. The length guard keeps the
        per-step cost at two integer compares when nothing new is full."""
        if (
            not self._incremental_commit
            or slot.generated == 0
            or slot.slot_idx < 0
        ):
            return
        written = max(len(slot.seq.tokens) - 1, 0)
        if written // self.config.page_size > len(slot.committed_hashes):
            self._commit_generated_blocks(slot)

    def _commit_generated_blocks(self, slot: _Slot):
        if slot.generated == 0:
            # never produced a token: a prefill-role slot's valid pages
            # are exactly its incrementally-confirmed chunks (already in
            # committed_hashes), and a preloaded/streamed-pull decode
            # slot's injected pages are only ALL valid at activation
            # (generated >= 1). Committing past either point — e.g. on a
            # mid-prefill cancel or an aborted early pull — would publish
            # unwritten/half-injected pages into the prefix cache (and
            # KVBM + the announcement mesh): silent KV poisoning.
            return
        hashes = slot.seq.block_hashes()
        n_known = len(slot.committed_hashes)
        # only commit blocks whose KV is fully WRITTEN: the pending (last
        # sampled) token's KV never is — a block containing it would poison
        # the prefix cache with one missing position
        written = max(len(slot.seq.tokens) - 1, 0)
        full_written = written // self.config.page_size
        max_by_pages = min(full_written, len(slot.pages))
        new_hashes = hashes[n_known:max_by_pages]
        if new_hashes:
            pages = slot.pages[n_known : n_known + len(new_hashes)]
            token_blocks = [
                b.tokens for b in slot.seq.blocks[n_known : n_known + len(new_hashes)]
            ]
            parent = slot.committed_hashes[-1] if slot.committed_hashes else None
            self.allocator.commit_hashes(pages, new_hashes, token_blocks, parent)
            slot.committed_hashes.extend(new_hashes)
            self._advance_kv_stream(slot)
            if self.kvbm is not None:
                self.kvbm.offload_commit(
                    new_hashes, [p + 1 for p in pages], parent=parent
                )


def _resolve_model(name: str) -> llama.LlamaConfig:
    from ..models import moe

    registry = {
        "tiny": llama.LlamaConfig.tiny,
        "llama3-3b": llama.LlamaConfig.llama3_2_3b,
        "llama3-8b": llama.LlamaConfig.llama3_8b,
        "llama3-70b": llama.LlamaConfig.llama3_70b,
        "tiny-moe": moe.MoeConfig.tiny_moe,
        "mixtral-8x7b": moe.MoeConfig.mixtral_8x7b,
        "gptoss-120b": moe.MoeConfig.gptoss_120b,
    }
    if name in registry:
        return registry[name]()
    raise ValueError(f"unknown model {name!r}; known: {sorted(registry)}")
