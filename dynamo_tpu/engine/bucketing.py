"""Shape bucketing: the one module allowed to mint dispatch-shape sizes.

Every integer that becomes a jit-dispatch operand dimension must be
bounded — XLA compiles one program per distinct operand shape, so an
unbounded (request-derived) dimension turns steady-state serving into a
recompile storm: 20-40s per program through the axon remote-compile
tunnel, during which the step loop is frozen and discovery leases lapse.
The engine's defense is a closed bucket algebra: round UP to the next
power of two, then clamp to a config-derived cap, so the variant space
per surface is O(log(cap)) and warmup can precompile all of it.

`next_pow2` used to be spelled twice (engine/engine.py and
engine/scheduler/policy.py); this module is now the single spelling, and
`BUCKETING_HELPERS` below is the machine-readable registry of every
helper the `comp-shape-bucketing` dynolint rule accepts as a bounded
shape source. The registry is parsed from the AST (never imported) by
`analysis/comp/registry.py` — same contract as ENV_REGISTRY /
KNOWN_FAULT_POINTS / GUARDED_STATE / METRICS — so every value must stay
a pure literal. Registering a helper here is a claim that its RETURN
VALUE is bounded by configuration regardless of its argument; the
comp pack trusts this table, so additions belong in the same review as
the helper's bound proof.
"""

from __future__ import annotations

from typing import Sequence


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (1 for n <= 1).

    Collapses arbitrary integers onto the pow2 ladder, so the variant
    count is logarithmic in the largest value that can reach a dispatch
    site (admission-bounded lengths); page/row dimensions additionally
    clamp with `min(next_pow2(x), cap)` to a config ceiling.
    """
    return 1 << max(n - 1, 0).bit_length()


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """Smallest configured bucket that holds n (the largest if none do).

    The clamped ladder lookup used for prefill chunk sizing: `buckets`
    comes from config (`prefill_buckets`), so the return value is always
    a member of a config-fixed set — bounded by construction.
    """
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


#: Bounded shape sources the comp-shape-bucketing rule resolves against.
#: Keyed by bare helper name (callsites match with leading underscores
#: stripped, so `self._bucket_for(...)` and `planner.plan_prefill(...)`
#: both resolve). `bound`: what clamps the result. `returns`: what the
#: bounded value is used for at dispatch sites.
BUCKETING_HELPERS = {
    "next_pow2": {
        "module": "dynamo_tpu/engine/bucketing.py",
        "bound": "pow2 ladder over admission-bounded lengths; page/row "
                 "dims additionally clamp min(next_pow2(x), config cap)",
        "returns": "pow2 rounding for token/page/row dimensions",
    },
    "bucket_for": {
        "module": "dynamo_tpu/engine/bucketing.py",
        "bound": "config.prefill_buckets membership",
        "returns": "prefill chunk bucket",
    },
    "plan_prefill": {
        "module": "dynamo_tpu/engine/scheduler/policy.py",
        "bound": "bucket/lanes drawn from the engine's compile-variant "
                 "space (prefill_buckets x {1, lane cap})",
        "returns": "PrefillPlan with .bucket and .lanes dispatch dims",
    },
    "plan_mixed": {
        "module": "dynamo_tpu/engine/scheduler/policy.py",
        "bound": "min(next_pow2(total), mixed_max_tokens budget)",
        "returns": "MixedPlan with .bucket token dim",
    },
    "ragged_tile_q": {
        "module": "dynamo_tpu/ops/pallas_ragged_attention.py",
        "bound": "dtype-keyed kernel tile constant (8/16/32)",
        "returns": "mixed-dispatch row alignment unit",
    },
}
