"""Paged KV cache: device arrays + host-side page allocator with prefix reuse.

The TPU analogue of vLLM's paged KV + the reference mocker's KvManager:
  * device side: kv_k/kv_v [layers, num_pages, page_size, kv_heads, head_dim]
    (sharded over the tp axis on the kv_heads dim)
  * host side: free-list page allocator; pages keyed by chained block hash
    for prefix reuse (same hashes the router indexes, llm/tokens.py), with
    LRU eviction of unreferenced cached pages and KV stored/removed events.
"""

from __future__ import annotations

import logging
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..llm.mocker.kv_manager import KvEvent
from ..runtime.metrics import KV_ACTIVE_BLOCKS, KV_TOTAL_BLOCKS

logger = logging.getLogger(__name__)


def alloc_kv_arrays(
    num_layers: int,
    num_pages: int,
    page_size: int,
    num_kv_heads: int,
    head_dim: int,
    dtype=jnp.bfloat16,
    sharding=None,
    kv_quant: str = "none",
) -> Tuple[jax.Array, jax.Array]:
    """Allocate the K and V stores: plain fp arrays for kv_quant="none"
    (the seed behavior, byte-identical), ops/kv_quant.QuantKV pytrees
    (packed int8/int4 pages + per-page-per-head f32 scales) otherwise."""
    from ..ops.kv_quant import alloc_kv_store

    kv_k = alloc_kv_store(
        num_layers, num_pages, page_size, num_kv_heads, head_dim, dtype,
        kv_quant, sharding=sharding,
    )
    kv_v = alloc_kv_store(
        num_layers, num_pages, page_size, num_kv_heads, head_dim, dtype,
        kv_quant, sharding=sharding,
    )
    return kv_k, kv_v


@dataclass
class _CachedPage:
    page_id: int
    seq_hash: int
    ref_count: int = 0


class PageAllocator:
    """Host-side page pool with hash-keyed prefix cache
    (engine counterpart of mocker KvManager; emits the same KV events)."""

    def __init__(
        self,
        num_pages: int,
        page_size: int,
        event_sink: Optional[Callable[[KvEvent], None]] = None,
    ):
        self.num_pages = num_pages
        self.page_size = page_size
        self.event_sink = event_sink
        self._free: List[int] = list(range(num_pages - 1, -1, -1))
        self._by_hash: Dict[int, _CachedPage] = {}
        self._lru: OrderedDict[int, None] = OrderedDict()  # seq_hash -> None
        # cumulative prefix-cache hits (blocks re-referenced instead of
        # recomputed) — the KV router-benefit benchmark reads this
        self.prefix_hit_blocks_total = 0

    @property
    def free_pages(self) -> int:
        return len(self._free) + len(self._lru)

    @property
    def used_pages(self) -> int:
        return self.num_pages - len(self._free)

    @property
    def active_pages(self) -> int:
        """Pages referenced by live sequences (excludes LRU-cached)."""
        return self.used_pages - len(self._lru)

    def cached_prefix(self, seq_hashes: List[int]) -> List[int]:
        """Physical pages of the longest cached prefix."""
        pages = []
        for h in seq_hashes:
            page = self._by_hash.get(h)
            if page is None:
                break
            pages.append(page.page_id)
        return pages

    def can_allocate(self, n_new_pages: int) -> bool:
        return n_new_pages <= self.free_pages

    def acquire_cached(self, seq_hashes: List[int]) -> List[int]:
        """Reference the cached prefix pages; returns physical page ids."""
        out = []
        for h in seq_hashes:
            page = self._by_hash.get(h)
            if page is None:
                break
            if page.ref_count == 0:
                self._lru.pop(h, None)
            page.ref_count += 1
            out.append(page.page_id)
        self.prefix_hit_blocks_total += len(out)
        return out

    def alloc_fresh(self, n: int) -> Optional[List[int]]:
        """Allocate n un-hashed (in-flight) pages, evicting cached pages as
        needed."""
        while len(self._free) < n and self._lru:
            self._evict_one()
        if len(self._free) < n:
            return None
        return [self._free.pop() for _ in range(n)]

    def commit_hashes(self, pages: List[int], seq_hashes: List[int], token_blocks=None, parent_hash=None):
        """Bind freshly filled pages to their block hashes (after prefill or
        after a generation block completes) -> emits `stored`.

        Hashes already cached by a concurrent sequence are skipped, which
        can leave GAPS in the committed subsequence — `stored_event_runs`
        (the shared producer contract, llm/mocker/kv_manager.py) splits
        the emission into one event per contiguous run with true chain
        parents and aligned token_blocks, so the router's bounded index
        never links across a gap (the seed's single gapped event also
        misaligned token_blocks with the stored subset)."""
        from ..llm.mocker.kv_manager import stored_event_runs

        created = set()
        for page_id, h in zip(pages, seq_hashes):
            if h in self._by_hash:
                continue  # already cached by a concurrent sequence
            self._by_hash[h] = _CachedPage(page_id, h, ref_count=1)
            created.add(h)
        if created and self.event_sink:
            for ev in stored_event_runs(
                seq_hashes, created, token_blocks, parent_hash
            ):
                self.event_sink(ev)

    def release(self, pages: List[int], seq_hashes: List[int]):
        """Release a sequence's pages. Hashed pages go to LRU cache;
        un-hashed (partial) pages return to the free list."""
        hashed_pages = {}
        for h in seq_hashes:
            p = self._by_hash.get(h)
            if p is not None:
                hashed_pages[p.page_id] = p
        for page_id in pages:
            page = hashed_pages.get(page_id)
            if page is None:
                self._free.append(page_id)
            else:
                page.ref_count -= 1
                if page.ref_count <= 0:
                    page.ref_count = 0
                    self._lru[page.seq_hash] = None
                    self._lru.move_to_end(page.seq_hash)

    def _evict_one(self):
        h, _ = self._lru.popitem(last=False)
        page = self._by_hash.pop(h)
        self._free.append(page.page_id)
        if self.event_sink:
            self.event_sink(KvEvent("removed", [h]))

    def clear_cache(self) -> int:
        n = 0
        while self._lru:
            self._evict_one()
            n += 1
        return n

    def stats(self) -> dict:
        return {
            KV_ACTIVE_BLOCKS: self.used_pages - len(self._lru),
            KV_TOTAL_BLOCKS: self.num_pages,
            "kv_cached_blocks": len(self._lru),
            "kv_prefix_hit_blocks_total": self.prefix_hit_blocks_total,
        }
