"""On-device sampling: greedy / temperature / top-k / top-p, fully batched.

TPU-first: sampling runs inside the jitted decode step (no logits transfer
to host). Top-p is computed within a fixed top-K candidate set (K=64) so the
whole thing is static-shaped and cheap even at 128k vocab.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

TOPK_CAP = 64


def unpack_mask(packed: jax.Array, vocab: int) -> jax.Array:
    """[B, ceil(V/8)] uint8 (np.packbits big-endian layout) → [B, V] bool.
    Guided-decoding masks ride host→device bitpacked — 8-32x less
    transfer per step than a bool/f32 mask — and unpack on device with
    two elementwise ops."""
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = (packed[:, :, None] >> shifts) & jnp.uint8(1)
    return bits.reshape(packed.shape[0], -1)[:, :vocab].astype(bool)


class SamplingParams(NamedTuple):
    """Per-slot device-resident sampling state.

    `seed`: per-lane sampling seed (uint32). Sampling draws are derived
    from (seed, position) — NOT from a shared RNG stream — so a request
    with an explicit seed reproduces its output exactly, independent of
    what other traffic it was batched with, of lane placement, and of
    preemption/resume. (The engines the reference fronts can't promise
    batch-independent seeded sampling.)"""

    temperature: jax.Array  # [B] f32; <=0 means greedy
    top_k: jax.Array  # [B] i32; 0 = disabled
    top_p: jax.Array  # [B] f32; 1.0 = disabled
    seed: jax.Array = None  # [B] u32; per-lane sampling seed
    # OpenAI penalties, applied over a bounded recent-token window
    # (apply_logit_penalties; all-zero/1.0 is an exact identity)
    presence: jax.Array = None  # [B] f32; 0 = off
    frequency: jax.Array = None  # [B] f32; 0 = off
    repetition: jax.Array = None  # [B] f32; 1.0 = off

    @classmethod
    def full(cls, batch: int, temperature=0.0, top_k=0, top_p=1.0, seed=0,
             presence=0.0, frequency=0.0, repetition=1.0):
        return cls(
            temperature=jnp.full((batch,), temperature, jnp.float32),
            top_k=jnp.full((batch,), top_k, jnp.int32),
            top_p=jnp.full((batch,), top_p, jnp.float32),
            seed=jnp.full((batch,), seed, jnp.uint32),
            presence=jnp.full((batch,), presence, jnp.float32),
            frequency=jnp.full((batch,), frequency, jnp.float32),
            repetition=jnp.full((batch,), repetition, jnp.float32),
        )


def _candidates(logits: jax.Array) -> tuple:
    """Top-TOPK_CAP candidate set per row (sorted desc). approx_max_k is
    the TPU-native tiled reduction (recall ~1.0 at K=64 over 128k vocab)
    — exact top_k lowers to a full sort and dominated the decode step's
    fixed overhead. The max (candidate 0) is always exact."""
    V = logits.shape[-1]
    if V > 4096:
        return jax.lax.approx_max_k(logits, min(TOPK_CAP, V))
    return jax.lax.top_k(logits, min(TOPK_CAP, V))


def sample(
    logits: jax.Array,  # [B, V] f32
    params: SamplingParams,
    key: jax.Array,
    mask: jax.Array = None,  # [B, V] bool: admissible tokens (guided decoding)
    positions: jax.Array = None,  # [B] i32: per-lane draw counter (seeded path)
) -> jax.Array:
    """Returns sampled token ids [B]. With `positions` (and params.seed)
    the draw is counter-based per lane — batch-independent seeded
    sampling; without, the legacy shared-key categorical path runs
    (spec verify, profiler, compile-check callers)."""
    if mask is not None:
        # guided decoding: inadmissible tokens are removed BEFORE the
        # candidate extraction so the top-K set is drawn from the legal
        # vocabulary only (llm/guided.py token FSM masks)
        logits = jnp.where(mask, logits, -1e30)
    B, V = logits.shape
    cand_logits, cand_idx = _candidates(logits)
    greedy_tokens = cand_idx[:, 0]
    K = cand_logits.shape[1]

    temp = jnp.maximum(params.temperature, 1e-6)[:, None]
    scaled = cand_logits / temp

    # top-k mask within candidates (top_k<=0 or >K -> disabled)
    k_eff = jnp.where(
        (params.top_k <= 0) | (params.top_k > K), K, params.top_k
    )  # [B]
    rank = jnp.arange(K)[None, :]
    scaled = jnp.where(rank < k_eff[:, None], scaled, -jnp.inf)

    # top-p (nucleus) within candidates: keep the smallest prefix of the
    # sorted probs with cumulative mass >= top_p (candidates are sorted desc)
    probs = jax.nn.softmax(scaled, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < params.top_p[:, None]  # always keeps the first
    scaled = jnp.where(keep, scaled, -jnp.inf)

    if positions is not None and params.seed is not None:
        # counter-based per-lane draw: uniforms from (lane seed, position)
        # via gumbel-max — reproducible under re-batching, lane moves and
        # preemption resume (see SamplingParams.seed)
        def lane_u(s, p):
            k = jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(7), s), p
            )
            return jax.random.uniform(
                k, (K,), minval=1e-7, maxval=1.0 - 1e-7
            )

        u = jax.vmap(lane_u)(
            params.seed.astype(jnp.uint32), positions.astype(jnp.uint32)
        )  # [B, K]
        gumbel = -jnp.log(-jnp.log(u))
        sampled_pos = jnp.argmax(scaled + gumbel, axis=-1)
    else:
        sampled_pos = jax.random.categorical(key, scaled, axis=-1)  # [B]
    sampled_tokens = jnp.take_along_axis(cand_idx, sampled_pos[:, None], axis=1)[:, 0]

    return jnp.where(params.temperature <= 0.0, greedy_tokens, sampled_tokens)


TOP_LOGPROBS_N = 5  # OpenAI caps top_logprobs alternatives at 5


def sample_lp(
    logits: jax.Array,  # [B, V] f32 (possibly penalized — the sampling dist)
    params: SamplingParams,
    key: jax.Array,
    mask: jax.Array = None,
    positions: jax.Array = None,
    raw: jax.Array = None,  # pre-penalty logits for the REPORTED logprobs
) -> tuple:
    """sample() + RAW-model logprobs (log-softmax of the unscaled,
    unmasked logits — the OpenAI `logprobs` surface; under guided masks
    this honestly reports how (un)likely the forced token was).

    Returns (tokens [B] i32, logprobs [B] f32,
             top_ids [B, 5] i32, top_lps [B, 5] f32) — the top-5
    alternatives serve chat `top_logprobs` / legacy completions
    `logprobs=k`; the host slices to the requested k.

    Cost discipline: alternatives come from the RAW logits' candidate
    set (the same approx-top-K reduction sample() uses — no full-vocab
    sort on the step path); the only full-vocab extra is one logsumexp
    pass for normalization."""
    tokens = sample(logits, params, key, mask=mask, positions=positions)
    raw = (raw if raw is not None else logits).astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(raw, axis=-1)
    chosen = jnp.take_along_axis(raw, tokens[:, None], axis=-1)[:, 0]
    k = min(TOP_LOGPROBS_N, raw.shape[-1])
    cand_logits, cand_idx = _candidates(raw)
    top_ids = cand_idx[:, :k]
    top_vals = cand_logits[:, :k]
    return tokens, chosen - logz, top_ids, top_vals - logz[:, None]


def penalized(logits: jax.Array, params: SamplingParams,
              recent: jax.Array) -> jax.Array:
    """Apply the params' penalties over the lane's recent-token window
    (no-op when the fields are absent — legacy callers). Runtime-gated
    with lax.cond: when NO lane in the batch carries a penalty (the
    common case), the [B, V] counts scatter is skipped entirely at
    execution time — one program variant, near-zero idle cost."""
    if params.presence is None or recent is None:
        return logits
    active = jnp.any(
        (params.presence != 0.0)
        | (params.frequency != 0.0)
        | (params.repetition != 1.0)
    )
    return jax.lax.cond(
        active,
        lambda l: apply_logit_penalties(
            l, recent, params.presence, params.frequency, params.repetition
        ),
        lambda l: l,
        logits,
    )


def apply_logit_penalties(
    logits: jax.Array,  # [B, V]
    recent_tokens: jax.Array,  # [B, W] window of recent token ids (pad = -1)
    presence_penalty: jax.Array,  # [B]
    frequency_penalty: jax.Array,  # [B]
    repetition_penalty: jax.Array,  # [B] 1.0 = off
) -> jax.Array:
    """OpenAI-style penalties over a recent-token window, batched on device."""
    B, V = logits.shape
    W = recent_tokens.shape[1]
    valid = recent_tokens >= 0
    safe = jnp.where(valid, recent_tokens, 0)
    counts = jnp.zeros((B, V), jnp.float32).at[
        jnp.arange(B)[:, None], safe
    ].add(valid.astype(jnp.float32))
    present = counts > 0
    logits = logits - presence_penalty[:, None] * present
    logits = logits - frequency_penalty[:, None] * counts
    rep = repetition_penalty[:, None]
    logits = jnp.where(
        present & (rep != 1.0),
        jnp.where(logits > 0, logits / rep, logits * rep),
        logits,
    )
    return logits
