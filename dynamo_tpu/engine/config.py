"""JAX engine configuration (vLLM-engine-args role for the TPU engine)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


@dataclass
class EngineConfig:
    model: str = "tiny"  # models/registry key or path
    max_num_seqs: int = 64  # decode slot batch
    page_size: int = 64  # tokens per KV page == router block size
    num_pages: int = 2048  # HBM page pool size; 0 = auto-size from free
    # device memory after weights load (engine._auto_num_pages, vLLM's
    # gpu_memory_utilization role; DYN_HBM_UTILIZATION / DYN_HBM_BYTES)
    max_model_len: int = 8192
    max_prefill_chunk: int = 1024  # chunked-prefill bucket cap
    prefill_buckets: tuple = (128, 256, 512, 1024)
    enable_prefix_caching: bool = True
    # fused decode: K steps per dispatch (one host read per K*B tokens);
    # speculated tokens past a stop condition are discarded (bounded waste)
    decode_block_steps: int = 8
    # KV-write strategy inside the fused block (measured on v5e, llama3-3b
    # B=32 K=16):
    #   "scatter": per-step XLA scatter into the pool carried through the
    #     scan. Fastest at small pools (303 ms/block @ 392 pages) but the
    #     scatter materializes pool-sized copies — 941 ms @ 1024 pages.
    #   "local": pool stays READ-ONLY inside the scan; new KV accumulates
    #     in a [K]-entry buffer merged by the fused pallas kernel
    #     (ops/pallas_paged_attention._decode_local_kernel) and is written
    #     once per block. Needs decode_block_unroll > 1: under a rolled
    #     lax.scan XLA re-copies closed-over HBM arrays every iteration
    #     (~4 ms/GB/step). Near pool-size-invariant; compile time grows
    #     with the unroll factor.
    # DEFAULT = None = auto by platform (engine init): "local" on TPU —
    # production pools are auto-sized (num_pages=0 → thousands of pages on
    # a 16G v5e), where scatter's pool copies dominate (941 ms/block
    # @ 1024 pages vs ~300 projected local, r3 measurement; the r4
    # sweep's local arms finished ~25% faster by wall-clock before its
    # metric read crashed) — and "scatter" on CPU, where the pathology
    # doesn't exist and the unrolled local scan just multiplies compile
    # time. bench_sweep.py re-decides empirically per chip.
    decode_pool_mode: Optional[str] = None
    decode_block_unroll: int = 0  # 0 = auto: 4 under local, 1 under scatter
    # batched prefill: token budget per dispatch; lanes = budget // bucket
    prefill_batch_tokens: int = 1024
    max_prefill_batch: int = 8
    # weight-only quantization ("int8" | None): halves weight HBM traffic
    # and makes llama3-8b fit a single v5e chip beside a KV pool
    # (models/quant.py; reference analogue: FP8 recipes)
    quantize: Optional[str] = None
    # quantized KV cache ("none" | "int8" | "int4"; None = resolve from
    # DYN_KV_QUANT, default none): pages quantize on write with
    # per-page-per-head scales and dequantize inside the attention
    # kernels' VMEM window (ops/kv_quant.py, docs/kvbm.md). int8 halves /
    # int4 quarters KV bytes per page, so the auto-sized pool holds ~2x/4x
    # the pages — roughly 2x resident sessions at fixed HBM — and every
    # KVBM tier/peer-fabric/disagg transfer shrinks the same way. "none"
    # is the seed's exact fp path (byte-identical streams). Requires
    # tp_size == pp_size == sp_size == 1 (scale sharding is the
    # multi-chip follow-up).
    kv_quant: Optional[str] = None
    # speculative decoding (engine/spec.py; reference SpecDecodeStats
    # contract _core.pyi:269-301). "ngram" = self-drafting prompt-lookup:
    # draft spec_draft_len tokens from the most recent spec_ngram-gram
    # match in a device-resident history ring, verify them all in ONE
    # batched-prefill pass (one weight stream for up to 1+d tokens/lane).
    # Each fused block runs spec_rounds draft-verify rounds.
    spec_mode: Optional[str] = None
    spec_draft_len: int = 4
    spec_ngram: int = 2
    spec_hist: int = 512  # history ring size (tokens) per lane
    spec_rounds: int = 4
    # sampling defaults
    default_temperature: float = 0.0
    seed: int = 0
    # OpenAI penalties window: recent tokens tracked per lane ON DEVICE
    # (static shape; vLLM penalizes the full context — a bounded window
    # is the TPU-shaped approximation, covering the repetition loops
    # penalties exist to break)
    penalty_window: int = 256
    # parallelism (parallel/mesh.py)
    tp_size: int = 1
    dp_size: int = 1
    pp_size: int = 1  # pipeline stages (layers over the pp axis; decode and
    # prefill stream microbatches through parallel/pipeline.py)
    sp_size: int = 1  # sequence-parallel axis (ring-attention prefill)
    # route a fresh prompt through the ring-prefill path when it has at
    # least this many uncached tokens (and sp_size > 1)
    ring_prefill_threshold: int = 512
    # scheduling
    max_queue: int = 4096
    decode_batch_wait_s: float = 0.0  # wait to fill decode batch (0 = greedy)
    # SLA-aware step scheduling (engine/scheduler/, docs/scheduler.md).
    # None = resolve from the DYN_SCHED_POLICY / DYN_SLA_TTFT_MS /
    # DYN_SLA_ITL_MS env knobs; "fifo" preserves the legacy admit-order
    # dispatch bit-for-bit (sole exception: the batch-kind anti-starvation
    # guard, a fairness bug fix active under both policies), "sla" enables
    # the EDF + ITL-budget StepPlanner.
    sched_policy: Optional[str] = None
    ttft_target_ms: Optional[float] = None
    itl_target_ms: Optional[float] = None
    # ragged unified mixed dispatch (ops/pallas_ragged_attention.py,
    # docs/ragged_attention.md): when the planner has BOTH runnable prefill
    # chunks and active decode lanes, pack them into ONE flat ragged token
    # buffer and ONE device call per layer stack (ragged_forward) instead
    # of a prefill dispatch followed by a decode dispatch. Guided rows
    # (packed FSM-mask operand), multi-LoRA rows (adapter-index operand)
    # and speculative verify rows (1+d one-token rows per lane) fuse too;
    # only mm and pp/sp layouts ride their split variants. None = resolve
    # from DYN_MIXED_DISPATCH (default on).
    mixed_dispatch: Optional[bool] = None
    # LoRA adapter tier (models/lora_pool.py, docs/multi_lora.md): device
    # slots in the fixed-size HBM adapter stack; adapters beyond this
    # page in from the host roster on acquire (LRU eviction of unpinned
    # residents). None = resolve from DYN_LORA_POOL_SLOTS (default 8).
    lora_pool_slots: Optional[int] = None
    # flat-token budget of one mixed dispatch: decode rows + granted
    # prefill chunks, pow2-bucketed up to this cap. Bounds the mixed
    # compile-variant space exactly like prefill_buckets bounds prefill's
    # (one lazily-compiled variant per (token bucket, table bucket); the
    # row axis is a single fixed bucket, see engine._mixed_row_bucket).
    mixed_max_tokens: int = 2048
    # KVBM tiers (kvbm/manager.py); 0 disables a tier
    kvbm_host_blocks: int = 0
    kvbm_disk_blocks: int = 0
    kvbm_disk_path: Optional[str] = None
    # durable decode sessions (docs/fault_tolerance.md): commit newly-full
    # generated blocks DURING the step loop (prefix cache + KVBM offload +
    # announcement mesh + session checkpointing see a live session's KV as
    # it grows) instead of only at slot release. None = resolve from
    # DYN_KV_INCREMENTAL_COMMIT (default on). The commit content is
    # byte-identical either way; off restores the release-only arm.
    incremental_commit: Optional[bool] = None
    # serving role (docs/autoscaling.md "Role morphing"): which discovery
    # component this engine's worker registers under — "prefill",
    # "decode", or "both" (colocated). Flipped live by JaxEngine.morph();
    # the worker harness moves the discovery record on the flip.
    role: str = "decode"

    @property
    def max_pages_per_seq(self) -> int:
        return (self.max_model_len + self.page_size - 1) // self.page_size

    @property
    def block_advance(self) -> int:
        """Max tokens one fused block advances a lane: K plain decode
        steps, or spec_rounds draft-verify rounds of up to 1+d tokens."""
        if self.spec_mode:
            return self.spec_rounds * (self.spec_draft_len + 1)
        return self.decode_block_steps
