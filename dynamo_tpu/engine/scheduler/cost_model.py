"""Online per-shape step-time model.

Fed by the engine's existing `_timed` dispatch instrumentation: every
device dispatch reports (kind, bucket, lanes, seconds) and the model keeps
an EWMA per shape key. The step planner reads predictions on the event
loop while observations land on the jax-step device-executor thread, so
the table is lock-guarded (GUARDED_STATE: `CostModel._ewma`).

Shape keys mirror the engine's bounded compile-variant space:

  ("prefill", bucket, lanes)  — batched chunked prefill dispatches
  ("block", K, B)             — fused K-step decode blocks
  ("block_lora"/"block_guided", ...) — the variant dispatch kinds

An unknown shape predicts by scaling the nearest same-kind observation by
token volume (bucket * lanes); a kind never observed predicts None — the
planner treats "unknown" as "no constraint" rather than guessing.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

Key = Tuple[str, int, int]


class CostModel:
    def __init__(self, alpha: float = 0.25):
        self.alpha = float(alpha)
        self._lock = threading.Lock()
        # key -> (ewma_seconds, n_observations)
        self._ewma: Dict[Key, Tuple[float, int]] = {}

    def observe(self, kind: str, bucket: int, lanes: int, seconds: float) -> None:
        """One dispatch landed: fold its wall time into the shape's EWMA.
        Runs on the device-executor thread (the `_timed` wrapper)."""
        if seconds < 0:
            return
        key = (kind, int(bucket), int(lanes))
        with self._lock:
            cur = self._ewma.get(key)
            if cur is None:
                self._ewma[key] = (float(seconds), 1)
            else:
                val, n = cur
                # the first few samples move fast (warmup/compile outliers
                # wash out), then settle at alpha
                a = max(self.alpha, 1.0 / (n + 1)) if n < 8 else self.alpha
                self._ewma[key] = (val + a * (float(seconds) - val), n + 1)

    def predict(self, kind: str, bucket: int, lanes: int) -> Optional[float]:
        """Predicted seconds for one dispatch of this shape; None when the
        kind has never been observed."""
        key = (kind, int(bucket), int(lanes))
        with self._lock:
            cur = self._ewma.get(key)
            if cur is not None:
                return cur[0]
            # nearest same-kind shape, scaled by token volume
            want = max(int(bucket) * int(lanes), 1)
            best = None
            for (k, b, l), (val, _n) in self._ewma.items():
                if k != kind:
                    continue
                have = max(b * l, 1)
                d = abs(have - want)
                if best is None or d < best[0]:
                    best = (d, val, have)
            if best is None:
                return None
            _, val, have = best
            return val * (want / have)

    def per_token(self, kind: str) -> Optional[float]:
        """Mean observed seconds per token across this kind's shapes
        (observation-weighted) — the queue-drain rate estimate behind
        `estimated local TTFT` in the disagg router."""
        with self._lock:
            num = den = 0.0
            for (k, b, l), (val, n) in self._ewma.items():
                if k != kind:
                    continue
                toks = max(b * l, 1)
                num += (val / toks) * n
                den += n
            return (num / den) if den else None

    def snapshot(self) -> Dict[str, float]:
        """Shape table for stats/debugging: {"kind bxl": ewma_ms}."""
        with self._lock:
            return {
                f"{k} {b}x{l}": round(val * 1000.0, 3)
                for (k, b, l), (val, _n) in sorted(self._ewma.items())
            }

    def n_observations(self) -> int:
        with self._lock:
            return sum(n for _, n in self._ewma.values())
