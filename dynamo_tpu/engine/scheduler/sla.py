"""SLA plumbing: targets, policy selection, and deadline math.

`DYN_SCHED_POLICY` selects the step-scheduling policy:

  fifo  (default) — the legacy behavior, bit-for-bit: prefill candidates
        sort by admission order, the chunk cap is static, no deferral.
        The escape hatch stays default-off-safe. Sole exception: the
        batch-kind anti-starvation guard (policy.py:pick_batch_kind) is
        a fairness bug fix active under both policies — it only changes
        behavior in mixed-kind traffic that would otherwise starve.
  sla   — the StepPlanner (policy.py): EDF prefill ordering against TTFT
        deadlines, ITL-budgeted chunk sizing, starvation guard.

`DYN_SLA_TTFT_MS` / `DYN_SLA_ITL_MS` are the targets the sla policy
spends. Per-request `priority` (nvext.priority -> PreprocessedRequest ->
_Slot) scales the TTFT target: each +1 halves it, each -1 doubles it, so
deadlines — not queue position — encode urgency.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Optional

from ...runtime.config import env_float as _env_float

logger = logging.getLogger(__name__)

POLICIES = ("fifo", "sla")

#: dispatches a candidate may be skipped (by kind filtering or EDF
#: reordering) before the starvation guard forces it through
STARVE_DISPATCHES = 16


@dataclasses.dataclass(frozen=True)
class SlaConfig:
    policy: str = "fifo"
    ttft_target_ms: float = 2000.0
    itl_target_ms: float = 0.0  # 0 = no ITL budget
    starve_dispatches: int = STARVE_DISPATCHES

    @classmethod
    def from_env(
        cls,
        policy: Optional[str] = None,
        ttft_target_ms: Optional[float] = None,
        itl_target_ms: Optional[float] = None,
    ) -> "SlaConfig":
        """Explicit (EngineConfig/CLI) values win; env fills the rest."""
        if policy is None:
            policy = os.environ.get("DYN_SCHED_POLICY") or "fifo"
        policy = policy.strip().lower()
        if policy not in POLICIES:
            # an unknown policy must not take the serving path down — the
            # legacy behavior is the safe spelling of "I don't know"
            logger.warning(
                "DYN_SCHED_POLICY=%r unknown (want one of %s); using fifo",
                policy, "/".join(POLICIES),
            )
            policy = "fifo"
        if ttft_target_ms is None:
            ttft_target_ms = _env_float("DYN_SLA_TTFT_MS", 2000.0)
        if itl_target_ms is None:
            itl_target_ms = _env_float("DYN_SLA_ITL_MS", 0.0)
        return cls(
            policy=policy,
            ttft_target_ms=max(float(ttft_target_ms), 1.0),
            itl_target_ms=max(float(itl_target_ms), 0.0),
        )

    def deadline(self, arrival_s: float, priority: int = 0) -> float:
        """TTFT deadline (monotonic seconds) for a request that arrived at
        `arrival_s`: arrival + target, halved per +1 priority."""
        target_s = (self.ttft_target_ms / 1000.0) * (0.5 ** int(priority))
        return arrival_s + target_s
