"""StepPlanner: per-step prefill admission, ordering, and chunk sizing.

Each `_step_once` the engine asks the planner three questions the step
loop used to hardcode:

  1. `order(cands)` — which prefill candidate goes first. fifo: admission
     order (the legacy `admit_seq` sort, bit-for-bit). sla: earliest TTFT
     deadline first, with a starvation guard (a candidate skipped
     `starve_dispatches` times jumps the deadline order).
  2. `pick_batch_kind(cands, kind_of)` — which dispatch-variant kind
     (plain/guided/mm/lora) this batch serves. The legacy rule (first
     non-plain in order) starves a kind when ordering keeps another kind
     perpetually first; the aging tiebreak forces a skipped kind through
     after `starve_dispatches` misses. Active under BOTH policies — it is
     a fairness fix, not a policy feature (it only changes behavior in
     mixed-kind traffic that would otherwise starve).
  3. `plan_prefill(cands, ...)` — the dispatch shape: bucket, lane count,
     and which slots ride it. fifo reproduces the legacy formula exactly
     (bucket from the head candidate's chunk, lanes 1-or-cap). sla scores
     every (bucket, lanes) in the engine's bounded compile-variant space
     by (slots served, real tokens granted, less padding) and spends an
     explicit ITL budget: with decode active and `itl_target_ms` set, the
     projected per-token ITL of "decode block + this prefill" must stay
     under target — shapes are shrunk to fit, and when nothing fits the
     dispatch defers (unless a TTFT deadline is already at risk, which
     wins: SLA attainment is the objective, not decode smoothness).

Costs come from the shared CostModel (EWMA per dispatch shape, fed by the
engine's `_timed` instrumentation). Planner bookkeeping (`_deadlines`,
`_records`) is step-loop-confined (GUARDED_STATE) and cleared by the
engine's fail-all path so a chaos-killed step leaves no orphaned deadline
state.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..bucketing import bucket_for, next_pow2 as _next_pow2
from .cost_model import CostModel
from .sla import SlaConfig


@dataclass
class PrefillPlan:
    """One prefill dispatch decision."""

    bucket: int
    lanes: int  # device lane count (1 or the bucket's cap)
    chosen: List  # slots riding this dispatch, in lane order
    reason: str  # "fifo" | "coverage" | "itl-shrunk" | "deadline-override"
    budget_s: Optional[float] = None  # ITL prefill budget (None = no cap)
    predicted_s: Optional[float] = None
    slack_ms: Optional[float] = None  # min deadline slack among chosen


@dataclass
class MixedPlan:
    """One unified mixed-step dispatch decision: which prefill chunks ride
    the flat ragged buffer beside the active decode lanes, and how big
    the buffer is (engine `_dispatch_mixed`)."""

    bucket: int  # flat token bucket (pow2, <= config.mixed_max_tokens)
    chosen: List  # prefill slots riding this dispatch, in row order
    chunks: List[int]  # granted chunk per chosen slot (1:1 with chosen)
    n_decode: int  # decode rows packed beside the chunks
    reason: str  # "mixed" | "mixed-shrunk"
    predicted_s: Optional[float] = None  # CostModel("mixed", ...) estimate
    deferred_slots: int = 0  # candidates that did not fit this dispatch
    # speculative draft rows (engine spec fusion): EXTRA one-token rows
    # beyond n_decode — each spec-eligible decode lane packs 1 + d rows
    # (current token + d drafts), so the budget must reserve them too
    n_spec_rows: int = 0


#: EDF deadline quantum (s) inside which the per-tenant fairness tiebreak
#: may reorder candidates — far below any meaningful TTFT target delta
_TENANT_TIE_QUANTUM_S = 0.1
#: per-tenant served-token counts halve when the max passes this bound
_TENANT_DECAY = 1 << 20
#: tenant-key cardinality bound (the key is a client-controlled header):
#: past it, the least-served half is evicted — evicted tenants simply
#: read as debt 0 again
_TENANT_MAX = 4096


@dataclass
class _Decision:
    """Per-step decision record (bounded history for stats/debugging)."""

    t: float
    reason: str
    bucket: int = 0
    lanes: int = 0
    granted_tokens: int = 0
    granted_slots: int = 0
    deferred_slots: int = 0
    budget_ms: Optional[float] = None
    slack_ms: Optional[float] = None


class StepPlanner:
    """Owns the per-step schedule. `config` is the EngineConfig (duck-typed:
    prefill_buckets, prefill_batch_tokens, max_prefill_batch,
    max_prefill_chunk, decode_block_steps, max_num_seqs)."""

    def __init__(self, config, sla: SlaConfig, cost: Optional[CostModel] = None):
        self.config = config
        self.sla = sla
        self.cost = cost or CostModel()
        self._deadlines: Dict[str, float] = {}  # request_id -> deadline (mono s)
        self._records: deque = deque(maxlen=64)
        # dynogate per-tenant fairness (docs/overload.md): granted prefill
        # tokens per tenant key. Within a ~100ms EDF deadline bucket the
        # LEAST-served tenant dispatches first, so a noisy tenant's flood
        # cannot monopolize same-class capacity; across buckets EDF still
        # rules (SLA attainment outranks fairness). Counts halve past
        # _TENANT_DECAY so the debt is recent-history, not all-time.
        self._tenant_served: Dict[str, int] = {}
        # counters (monotonic; surfaced via stats())
        self.granted_chunks = 0
        self.granted_tokens = 0
        self.deferred_steps = 0
        self.starvation_overrides = 0
        self.itl_shrunk_steps = 0
        self.deadline_overrides = 0

    @property
    def policy(self) -> str:
        return self.sla.policy

    # -- slot lifecycle ------------------------------------------------- #

    def assign_deadline(self, slot) -> None:
        """Stamp the slot's TTFT deadline from its arrival + priority.
        Called at slot construction (any task); only reads SLA config."""
        slot.sched_deadline = self.sla.deadline(
            slot.arrival_s, getattr(slot, "priority", 0)
        )

    def on_admit(self, slot) -> None:
        """Track the admitted slot's deadline (step-loop only)."""
        self._deadlines[slot.request_id] = slot.sched_deadline

    def onboard_headroom_ms(self, slot) -> Optional[float]:
        """TTFT headroom a KVBM tier onboard may spend for this slot (ms;
        floor 0). None under fifo — no deadline means no budget, so the
        engine never trades a tier hit for recompute (docs/kvbm.md)."""
        if self.sla.policy != "sla":
            return None
        return max((slot.sched_deadline - time.monotonic()) * 1000.0, 0.0)

    def on_release(self, slot) -> None:
        self._deadlines.pop(slot.request_id, None)

    def reset(self) -> None:
        """Fail-all: the batch died; no deadline may outlive its slot."""
        self._deadlines.clear()

    # -- ordering -------------------------------------------------------- #

    def tenant_debt(self, slot) -> int:
        """Recent prefill tokens granted to the slot's tenant (0 for the
        default tenant or one never served)."""
        return self._tenant_served.get(getattr(slot, "tenant", "") or "", 0)

    def _note_tenant(self, slot, granted: int) -> None:
        tenant = getattr(slot, "tenant", "") or ""
        served = self._tenant_served.get(tenant, 0) + granted
        self._tenant_served[tenant] = served
        if served > _TENANT_DECAY:
            for t in list(self._tenant_served):
                self._tenant_served[t] //= 2
        if len(self._tenant_served) > _TENANT_MAX:
            keep = sorted(self._tenant_served.items(),
                          key=lambda kv: kv[1], reverse=True)
            self._tenant_served = dict(keep[: _TENANT_MAX // 2])

    def order(self, cands: List) -> List:
        """Prefill candidate order. fifo: admission order (bit-for-bit the
        legacy `admit_seq` sort). sla: EDF with the starvation guard, and
        — within a ~100ms deadline bucket — the least-served tenant first
        (the dynogate fairness tiebreak: same class, same urgency, the
        noisy tenant queues behind the quiet one)."""
        if self.sla.policy != "sla":
            return sorted(cands, key=lambda s: s.admit_seq)
        starve = self.sla.starve_dispatches

        def key(s):
            starved = 0 if s.sched_skips >= starve else 1
            return (starved, int(s.sched_deadline / _TENANT_TIE_QUANTUM_S),
                    self.tenant_debt(s), s.sched_deadline, s.admit_seq)

        return sorted(cands, key=key)

    def order_waiting(self, waiting: List) -> List:
        """Admission order for the waiting queue under sla: EDF by the
        deadline stamped at arrival (preempted victims keep their original
        arrival, so they stay at the front exactly as the legacy
        insert-at-0 intended). fifo: untouched."""
        if self.sla.policy != "sla" or len(waiting) < 2:
            return waiting
        return sorted(waiting, key=lambda s: (s.sched_deadline, s.admit_seq))

    def pick_batch_kind(self, cands: List, kind_of: Callable[[object], str]) -> str:
        """Which dispatch-variant kind this batch serves. Legacy rule:
        first non-plain candidate's kind. Aging tiebreak: a non-plain
        candidate skipped `starve_dispatches` times by this very filter
        wins outright, so no kind starves under a steady stream of
        another kind."""
        starve = self.sla.starve_dispatches
        starved = [
            s for s in cands
            if kind_of(s) != "plain" and s.sched_skips >= starve
        ]
        if starved:
            self.starvation_overrides += 1
            winner = min(starved, key=lambda s: (-s.sched_skips, s.admit_seq))
            return kind_of(winner)
        return next((k for k in map(kind_of, cands) if k != "plain"), "plain")

    # -- shape planning -------------------------------------------------- #

    def _lane_cap(self, bucket: int) -> int:
        cfg = self.config
        return max(1, min(cfg.prefill_batch_tokens // bucket, cfg.max_prefill_batch))

    def _bucket_for(self, n: int) -> int:
        return bucket_for(n, self.config.prefill_buckets)

    def plan_prefill(
        self,
        cands: List,
        decode_active: bool,
        now: Optional[float] = None,
    ) -> Optional[PrefillPlan]:
        """Choose the prefill dispatch shape; None = defer this step (the
        ITL budget is exhausted and no deadline is at risk). `cands` must
        already be in planner order."""
        cfg = self.config
        if now is None:
            now = time.monotonic()

        def remaining(s) -> int:
            return len(s.kv_prompt) - s.prefill_pos

        if self.sla.policy != "sla":
            # legacy formula, bit-for-bit: bucket from the head candidate's
            # chunk, lanes 1 (lone arrival) or the bucket's cap
            first_chunk = min(remaining(cands[0]), cfg.max_prefill_chunk)
            bucket = self._bucket_for(first_chunk)
            lanes = 1 if len(cands) == 1 else self._lane_cap(bucket)
            plan = PrefillPlan(
                bucket=bucket, lanes=lanes, chosen=cands[:lanes], reason="fifo"
            )
            self._note(plan, cands, now)
            return plan

        # ITL budget: with decode active, the next block's K tokens arrive
        # (block_time + this_prefill_time) later — keep that under
        # K * itl_target. Unknown block cost (cold model) = no constraint.
        budget_s = None
        if decode_active and self.sla.itl_target_ms > 0:
            blk = self.cost.predict(
                "block", cfg.decode_block_steps, cfg.max_num_seqs
            )
            if blk is not None:
                budget_s = max(
                    cfg.decode_block_steps * self.sla.itl_target_ms / 1000.0
                    - blk,
                    0.0,
                )

        # max_prefill_chunk caps the bucket exactly as the legacy formula
        # did (first_chunk = min(remaining, cap) before bucketing): the
        # score search must not hand out a bigger dispatch than the
        # operator's per-chunk latency bound allows
        max_bucket = self._bucket_for(cfg.max_prefill_chunk)
        shapes: List[Tuple[bool, Tuple[int, int, int], int, int, List, Optional[float]]] = []
        for b in cfg.prefill_buckets:
            if b > max_bucket:
                continue
            cap = self._lane_cap(b)
            chosen = cands[:cap]
            lanes = 1 if len(chosen) == 1 else cap
            t = self.cost.predict("prefill", b, lanes)
            granted = sum(min(remaining(s), b) for s in chosen)
            fits = budget_s is None or t is None or t <= budget_s
            # score: serve the most slots, then the most real tokens, then
            # the least padding (smaller bucket)
            score = (len(chosen), granted, -b)
            shapes.append((fits, score, b, lanes, chosen, t))

        feasible = [x for x in shapes if x[0]]
        if feasible:
            best = max(feasible, key=lambda x: x[1])
            reason = "coverage" if len(feasible) == len(shapes) else "itl-shrunk"
            if reason == "itl-shrunk":
                self.itl_shrunk_steps += 1
            _, _, b, lanes, chosen, t = best
            plan = PrefillPlan(
                bucket=b, lanes=lanes, chosen=chosen, reason=reason,
                budget_s=budget_s, predicted_s=t,
                slack_ms=self._min_slack_ms(chosen, now),
            )
            self._note(plan, cands, now)
            return plan

        # every shape busts the ITL budget. Defer — unless the head's TTFT
        # deadline is already at risk (negative slack) or it has starved:
        # TTFT attainment outranks decode smoothness.
        smallest = min(shapes, key=lambda x: x[2])
        _, _, b, lanes, chosen, t = smallest
        head = cands[0]
        slack_s = head.sched_deadline - now - (t or 0.0)
        if slack_s < 0 or head.sched_skips >= self.sla.starve_dispatches:
            self.deadline_overrides += 1
            plan = PrefillPlan(
                bucket=b, lanes=lanes, chosen=chosen,
                reason="deadline-override", budget_s=budget_s, predicted_s=t,
                slack_ms=self._min_slack_ms(chosen, now),
            )
            self._note(plan, cands, now)
            return plan
        self.deferred_steps += 1
        self._records.append(_Decision(
            t=now, reason="deferred", deferred_slots=len(cands),
            budget_ms=None if budget_s is None else budget_s * 1000.0,
            slack_ms=self._min_slack_ms(cands, now),
        ))
        return None

    def plan_mixed(
        self,
        cands: List,
        n_decode: int,
        align: int = 1,
        now: Optional[float] = None,
        n_spec_rows: int = 0,
    ) -> Optional[MixedPlan]:
        """Shape the unified mixed dispatch: greedily grant prefill chunks
        (planner order, each padded to the packer's row alignment) into
        the flat-token budget left beside `n_decode` one-token decode
        rows. `n_spec_rows` reserves EXTRA one-token rows for speculative
        draft verification riding the same buffer (engine spec fusion:
        each spec-eligible lane packs its current token plus d drafts).
        Returns None when nothing fits — the engine falls back to
        the split path for this step. `cands` must already be in planner
        order.

        Under sla with an ITL target, the mixed step IS the decode step
        (it advances every decode lane one token), so its predicted wall
        time is budgeted directly against `itl_target_ms`: chunks are
        halved until the CostModel("mixed", bucket, rows) estimate fits,
        floored at one aligned unit per chunk (a mixed step never defers
        outright — serving the decode lanes is the point).

        Pure: no counters or decision records — the engine may still
        abandon the plan (pipeline in flight, page-growth preemption);
        it calls `commit_mixed` with what actually dispatched."""
        cfg = self.config
        if now is None:
            now = time.monotonic()

        def aligned(n: int) -> int:
            return -(-n // align) * align

        # floor the budget to the packer alignment: every granted span is
        # a multiple of `align`, so an aligned budget keeps `space`
        # aligned throughout and no grant can overpack the flat buffer
        budget = cfg.mixed_max_tokens - cfg.mixed_max_tokens % align
        dec_tokens = aligned(1) * (n_decode + n_spec_rows)
        if dec_tokens >= budget:
            return None  # too many decode lanes to fuse a chunk beside

        chosen: List = []
        chunks: List[int] = []
        space = budget - dec_tokens
        for s in cands[: cfg.max_prefill_batch]:
            remaining = len(s.kv_prompt) - s.prefill_pos
            take = min(remaining, cfg.max_prefill_chunk, space)
            if take <= 0:
                break
            chosen.append(s)
            chunks.append(take)
            space -= aligned(take)

        if not chosen:
            return None

        total = budget - space
        bucket = min(_next_pow2(max(total, align)), budget)
        rows = len(chosen) + n_decode + n_spec_rows
        reason = "mixed"
        t = self.cost.predict("mixed", bucket, rows)
        if (
            self.sla.policy == "sla"
            and self.sla.itl_target_ms > 0
            and t is not None
        ):
            itl_budget = self.sla.itl_target_ms / 1000.0
            while t is not None and t > itl_budget and max(chunks) > align:
                # halve the biggest chunk (floored at one aligned unit)
                i = max(range(len(chunks)), key=lambda j: chunks[j])
                chunks[i] = max(align, chunks[i] // 2)
                total = dec_tokens + sum(aligned(ch) for ch in chunks)
                bucket = min(_next_pow2(max(total, align)), budget)
                t = self.cost.predict("mixed", bucket, rows)
                reason = "mixed-shrunk"
        return MixedPlan(
            bucket=bucket, chosen=chosen, chunks=chunks, n_decode=n_decode,
            reason=reason, predicted_s=t,
            deferred_slots=len(cands) - len(chosen),
            n_spec_rows=n_spec_rows,
        )

    def commit_mixed(
        self,
        plan: MixedPlan,
        dispatched,
        now: Optional[float] = None,
    ) -> None:
        """Account a mixed dispatch the engine actually committed.
        `dispatched` is the [(slot, chunk)] list that survived the
        engine's post-plan re-filter (page-growth preemption can drop
        slots) — counters and the decision record reflect dispatched
        work only, never an abandoned plan (the split path's plan_prefill
        would otherwise double-count the same step)."""
        if now is None:
            now = time.monotonic()
        slots = [s for s, _ in dispatched]
        granted = sum(ch for _, ch in dispatched)
        if plan.reason == "mixed-shrunk":
            self.itl_shrunk_steps += 1
        self.granted_chunks += len(slots)
        self.granted_tokens += granted
        for s, ch in dispatched:
            self._note_tenant(s, ch)
        self._records.append(_Decision(
            t=now, reason=plan.reason, bucket=plan.bucket,
            lanes=len(slots) + plan.n_decode + plan.n_spec_rows,
            granted_tokens=granted, granted_slots=len(slots),
            deferred_slots=plan.deferred_slots,
            slack_ms=self._min_slack_ms(slots, now),
        ))

    def _min_slack_ms(self, slots: List, now: float) -> Optional[float]:
        if not slots:
            return None
        return min((s.sched_deadline - now) * 1000.0 for s in slots)

    def _note(self, plan: PrefillPlan, cands: List, now: float) -> None:
        def remaining(s) -> int:
            return len(s.kv_prompt) - s.prefill_pos

        granted = sum(min(remaining(s), plan.bucket) for s in plan.chosen)
        self.granted_chunks += len(plan.chosen)
        self.granted_tokens += granted
        for s in plan.chosen:
            self._note_tenant(s, min(remaining(s), plan.bucket))
        self._records.append(_Decision(
            t=now, reason=plan.reason, bucket=plan.bucket, lanes=plan.lanes,
            granted_tokens=granted, granted_slots=len(plan.chosen),
            deferred_slots=len(cands) - len(plan.chosen),
            budget_ms=None if plan.budget_s is None else plan.budget_s * 1000.0,
            slack_ms=plan.slack_ms,
        ))

    # -- observability ---------------------------------------------------- #

    def estimate_wait_ms(self, pending_tokens: int) -> Optional[float]:
        """Estimated time to prefill `pending_tokens` through this engine
        (queue depth x cost model): the disagg router's "local TTFT"
        signal. None until the cost model has seen a prefill."""
        per_tok = self.cost.per_token("prefill")
        if per_tok is None or pending_tokens <= 0:
            return 0.0 if per_tok is not None else None
        return pending_tokens * per_tok * 1000.0

    def recent_decisions(self) -> List[dict]:
        out = []
        for d in list(self._records):
            out.append({
                "reason": d.reason, "bucket": d.bucket, "lanes": d.lanes,
                "granted_tokens": d.granted_tokens,
                "granted_slots": d.granted_slots,
                "deferred_slots": d.deferred_slots,
                "budget_ms": d.budget_ms,
                "slack_ms": None if d.slack_ms is None else round(d.slack_ms, 1),
            })
        return out

    def stats(self) -> dict:
        last = self._records[-1] if self._records else None
        out = {
            "sched_policy": self.sla.policy,
            "sched_ttft_target_ms": self.sla.ttft_target_ms,
            "sched_itl_target_ms": self.sla.itl_target_ms,
            "sched_granted_chunks": self.granted_chunks,
            "sched_granted_tokens": self.granted_tokens,
            "sched_deferred_steps": self.deferred_steps,
            "sched_itl_shrunk_steps": self.itl_shrunk_steps,
            "sched_deadline_overrides": self.deadline_overrides,
            "sched_starvation_overrides": self.starvation_overrides,
            "sched_pending_deadlines": len(self._deadlines),
            "sched_cost_observations": self.cost.n_observations(),
            "sched_tenants_served": len(self._tenant_served),
        }
        if last is not None:
            out["sched_last_budget_tokens"] = last.granted_tokens
            if last.slack_ms is not None:
                out["sched_last_slack_ms"] = round(last.slack_ms, 1)
        return out
