"""dynosched: the SLA-aware prefill/decode scheduler subsystem.

Owns every "what runs this step" decision the engine step loop used to
hardcode (ROADMAP item 1): which waiting/partial slots get prefill chunks,
how large the chunk budget is, and whether prefill defers to protect the
decode ITL budget. The same policy state drives conditional disaggregation
(llm/disagg.py consults the planner's estimated local TTFT) and the
planner's queue/deadline stats ride the worker metrics topic.

Pure host-side policy code — no jax imports — so the CPU mocker worker
shares the policy (llm/mocker/engine.py) without paying the jax import.
See docs/scheduler.md for the policy, knobs, and a worked ITL-budget
example.
"""

from .cost_model import CostModel
from .policy import MixedPlan, PrefillPlan, StepPlanner
from .sla import SlaConfig

__all__ = ["CostModel", "MixedPlan", "PrefillPlan", "SlaConfig", "StepPlanner"]
