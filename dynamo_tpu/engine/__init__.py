from .config import EngineConfig
from .engine import JaxEngine

__all__ = ["EngineConfig", "JaxEngine"]
