"""Engine package.

Lazy exports (PEP 562): `engine.py` imports jax at module scope, but the
`engine.scheduler` subpackage is pure host-side policy code that the CPU
mocker worker also uses — importing it must not drag jax (and its seconds
of import time) into jax-free processes.
"""

__all__ = ["EngineConfig", "JaxEngine"]


def __getattr__(name):
    if name == "EngineConfig":
        from .config import EngineConfig

        return EngineConfig
    if name == "JaxEngine":
        from .engine import JaxEngine

        return JaxEngine
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
