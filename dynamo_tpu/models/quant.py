"""Int8 weight-only quantization for the TPU engine.

Decode is weight-bandwidth-bound: every decoded token reads the full
weight set from HBM, so halving the bytes per weight nearly doubles the
decode ceiling — and it is the difference between Llama-3-8B (~16 GB
bf16) fitting a 16 GB-HBM v5e chip beside a KV pool or not (round-3
verdict #3). The reference ships quantized serving via engine
checkpoints (FP8 recipes, e.g. recipes/llama-3-70b); v5e has no native
fp8, so symmetric per-output-channel int8 is the TPU-native analogue.

Representation: a quantized leaf is a pytree node
    {"q": int8 [..., in, out],  "s": float32 [..., 1, out]}
(the scale keeps a singleton on the contraction axis, so it broadcasts
against the dot's result for ANY leading batch/layer dims). Matmuls read
int8 from HBM and dequantize in registers — XLA fuses the
convert-and-scale into the dot's operand read, so the MXU still sees
bf16 operands while HBM traffic halves. The scale multiplies AFTER the
dot: y = (x @ q) * s == x @ (q * s) for per-out-channel s, which also
commutes with TP all-reduces (row-parallel wo/w_down stay correct under
GSPMD).

Scope: the dense llama-family backbone (projections + embed + lm_head)
AND MoE expert stacks (via qeinsum — expert weights dominate MoE HBM
traffic, so they benefit most). The MoE router stays f32: it is tiny and
routing decisions are numerically sensitive.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "QMAX",
    "dequantize_leaf",
    "dequantize_symmetric",
    "embed_rows",
    "head_leaf",
    "is_quant",
    "pack_int4",
    "qdot",
    "qeinsum",
    "quantize_array",
    "quantize_symmetric",
    "quantize_tree",
    "scale_sharding",
    "symmetric_scale",
    "unpack_int4",
]

# ---------------------------------------------------------------------- #
# Shared symmetric-quantization primitives — the ONE spelling of
# quantize/dequantize math used by BOTH weight-only quantization (below)
# and the quantized KV cache (ops/kv_quant.py). int4 values live two to a
# byte (pack_int4/unpack_int4); scales are always f32.
# ---------------------------------------------------------------------- #

QMAX = {8: 127, 4: 7}  # symmetric ranges: int8 [-127,127], int4 [-7,7]


def symmetric_scale(amax, bits: int = 8, eps: float = 1e-8):
    """Scale s such that clip(round(x/s)) covers [-amax, amax] in `bits`-bit
    symmetric range. Works in numpy or jax (stays in the input namespace)."""
    xp = np if isinstance(amax, np.ndarray) else jnp
    return xp.maximum(amax, eps) / QMAX[bits]


def quantize_symmetric(x, s, bits: int = 8):
    """round(x/s) clipped to the symmetric `bits`-bit range, as int8 values
    (int4 values occupy int8 storage until pack_int4). `s` broadcasts."""
    xp = np if isinstance(x, np.ndarray) else jnp
    q = xp.clip(xp.round(x / s), -QMAX[bits], QMAX[bits])
    return q.astype(xp.int8)


def dequantize_symmetric(q, s, dtype=jnp.float32):
    """q * s in f32, cast to `dtype`. The inverse of quantize_symmetric for
    any bits (int4 must be unpacked first)."""
    xp = np if isinstance(q, np.ndarray) else jnp
    return (q.astype(xp.float32) * s).astype(dtype)


def pack_int4(q, axis: int = 0):
    """Pack int4 values (int8 storage, range [-7,7]) two-to-a-byte along
    `axis`, pairing index i with i + n/2: byte = (q[i] & 0xF) | (q[i+n/2]
    << 4). unpack_int4's concat(lo, hi) then restores the ORIGINAL order —
    no interleave, which matters inside the Pallas VMEM window where
    minor-dim shuffles are unsupported. `axis` length must be even."""
    xp = np if isinstance(q, np.ndarray) else jnp
    n = q.shape[axis]
    lo = xp.take(q, xp.arange(0, n // 2), axis=axis)
    hi = xp.take(q, xp.arange(n // 2, n), axis=axis)
    return ((lo & 0x0F) | (hi << 4)).astype(xp.int8)


def unpack_int4(packed, axis: int = 0):
    """Inverse of pack_int4: sign-extend both nibbles and concatenate along
    `axis` (lo half first), doubling that axis."""
    xp = np if isinstance(packed, np.ndarray) else jnp
    # arithmetic shifts on int8 sign-extend: (x << 4) >> 4 recovers the low
    # nibble's sign, x >> 4 the high nibble's
    lo = xp.right_shift(xp.left_shift(packed, 4), 4)
    hi = xp.right_shift(packed, 4)
    return xp.concatenate([lo, hi], axis=axis).astype(xp.int8)

# leaves of the llama tree that quantize (per-out-channel over the
# contraction axis -2); embed is special-cased (per-ROW scale, axis -1,
# because rows are gathered as output vectors and the transpose serves as
# the tied lm_head)
_LAYER_LEAVES = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def is_quant(leaf: Any) -> bool:
    return isinstance(leaf, dict) and "q" in leaf and "s" in leaf


def quantize_array(w, contract_axis: int = -2) -> Dict[str, Any]:
    """Symmetric int8 with a per-channel f32 scale over `contract_axis`
    (kept as a singleton dim so it broadcasts against the dot result).
    Works on numpy or jax arrays; stays in the input's array namespace.
    (One spelling: symmetric_scale/quantize_symmetric above — shared with
    the quantized KV cache's per-page-per-head scales, ops/kv_quant.py.)"""
    xp = np if isinstance(w, np.ndarray) else jnp
    wf = xp.asarray(w, dtype=xp.float32)
    amax = xp.max(xp.abs(wf), axis=contract_axis, keepdims=True)
    s = symmetric_scale(amax, bits=8)
    q = quantize_symmetric(wf, s, bits=8)
    return {"q": q, "s": s.astype(xp.float32)}


def dequantize_leaf(leaf, dtype=jnp.bfloat16):
    if not is_quant(leaf):
        return leaf
    return (leaf["q"].astype(jnp.float32) * leaf["s"]).astype(dtype)


def qdot(x: jax.Array, w, preferred_element_type=jnp.float32) -> jax.Array:
    """jnp.dot that accepts a raw weight array or a quantized leaf.
    For quantized leaves the int8 operand converts to x.dtype inside the
    dot (register-level, fused by XLA) and the scale applies to the f32
    accumulator, so precision matches dequantize-then-dot."""
    if not is_quant(w):
        return jnp.dot(x, w, preferred_element_type=preferred_element_type)
    y = jnp.dot(x, w["q"].astype(x.dtype),
                preferred_element_type=preferred_element_type)
    # s is [..., 1, out]; drop the contraction singleton so it broadcasts
    # against y's [..., out]
    return y * jnp.squeeze(w["s"], axis=-2)


def qeinsum(spec: str, x: jax.Array, w) -> jax.Array:
    """jnp.einsum over (x, w) accepting a quantized w. Valid when the
    contraction axis is w's axis -2 and w's remaining axes map IN ORDER
    onto the output's trailing axes — true for the expert matmuls
    ("ech,ehi->eci" and "eci,eih->ech": scale [E, 1, out] broadcasts
    against the [E, C, out] result without reshaping)."""
    if not is_quant(w):
        return jnp.einsum(spec, x, w, preferred_element_type=jnp.float32)
    y = jnp.einsum(spec, x, w["q"].astype(x.dtype),
                   preferred_element_type=jnp.float32)
    return y * w["s"]


def embed_rows(embed, tokens: jax.Array, dtype) -> jax.Array:
    """Embedding gather handling quantized tables (per-row scale [V, 1]:
    gather rows AND their scales)."""
    if not is_quant(embed):
        return embed[tokens]
    return (embed["q"][tokens].astype(jnp.float32) * embed["s"][tokens]).astype(dtype)


def head_leaf(params: Dict[str, Any]):
    """The LM head operand for qdot: lm_head when present, else the tied
    (possibly quantized) embedding transposed — a per-row embed scale
    [V, 1] transposes into a per-out-channel head scale [1, V]."""
    lm = params.get("lm_head")
    if lm is not None:
        return lm
    e = params["embed"]
    if not is_quant(e):
        return e.T
    return {"q": e["q"].T, "s": e["s"].T}


def quantize_tree(params: Dict[str, Any], consume: bool = False) -> Dict[str, Any]:
    """Quantize an already-built (e.g. random-init) llama/moe param tree
    in place of a checkpoint-time quantized load: backbone projections
    AND MoE expert stacks per-out-channel, embed per-row; norms and the
    f32 MoE router keep their dtype.

    consume=True MUTATES `params`, dropping each source leaf as soon as
    its quantized form exists. Without it the full-precision tree stays
    resident until the call returns — bf16 tree + f32 temporaries + int8
    outputs peak ~2.4x the model size, which OOMs a 16 GiB chip on 3b+
    models (use consume=True whenever the source tree is discarded, as
    the engine/worker/bench paths do)."""
    out = dict(params)
    emb = params["embed"]
    if consume:
        params["embed"] = None
    out["embed"] = quantize_array(emb, contract_axis=-1)
    del emb
    if params.get("lm_head") is not None:
        lm = params["lm_head"]
        if consume:
            params["lm_head"] = None
        out["lm_head"] = quantize_array(lm)
        del lm
    src = params["layers"]
    layers = dict(src)
    for name in _LAYER_LEAVES:
        # dense leaves are [L, in, out]; moe expert stacks are
        # [L, E, in, out] — both quantize per-out-channel over the
        # contraction axis -2 (expert scale [L, E, 1, out] broadcasts in
        # qeinsum). The f32 router is NOT in _LAYER_LEAVES and stays f32.
        if name in layers and not is_quant(layers[name]) and layers[name].ndim in (3, 4):
            w = layers[name]
            if consume:
                src[name] = None
            layers[name] = quantize_array(w)
            del w
    out["layers"] = layers
    return out


def scale_sharding(sharding, s_shape) -> Any:
    """NamedSharding for a scale tensor: the leaf's spec with every entry
    on a singleton axis of `s_shape` dropped (a size-1 axis cannot shard)."""
    from jax.sharding import NamedSharding, PartitionSpec

    spec = list(sharding.spec) + [None] * (len(s_shape) - len(sharding.spec))
    new = [None if s_shape[i] == 1 else spec[i] for i in range(len(s_shape))]
    return NamedSharding(sharding.mesh, PartitionSpec(*new))
