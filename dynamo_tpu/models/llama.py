"""Llama-family model in functional JAX (param pytrees, no framework).

The TPU engine's flagship dense architecture: RMSNorm, rotary embeddings,
GQA attention over a PAGED KV cache, SwiGLU MLP. Equivalent role to the
engine-side model implementations the reference delegates to vLLM/TRT-LLM
(SURVEY.md §2.5: TP must be implemented natively here).

Design notes (TPU-first):
  * all matmuls bf16 on the MXU; accumulation f32 via preferred_element_type
  * static shapes everywhere: prefill takes a fixed [chunk] token block,
    decode takes the full [max_seqs] slot batch with masking
  * KV cache is paged: [layers, pages, page_size, kv_heads, head_dim]; the
    engine passes page tables; attention gathers pages (ops/paged_attention)
  * tensor parallel: heads and MLP hidden sharded over the "tp" mesh axis
    via NamedSharding on params + cache (parallel/sharding.py); XLA inserts
    the all-reduces (scaling-book recipe), no manual collectives needed
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from .quant import embed_rows, head_leaf, qdot
from ..ops.kv_quant import kv_layer, kv_page_size, kv_write
from ..ops.paged_attention import (
    paged_attention_decode,
    prefill_attention,
    prefill_attention_batched,
    ragged_attention,
)
from ..parallel.mesh import PP_AXIS, SP_AXIS


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    max_position: int = 8192
    dtype: Any = jnp.bfloat16
    tie_embeddings: bool = False

    @classmethod
    def llama3_8b(cls, **overrides):
        return cls(
            vocab_size=128256,
            hidden_size=4096,
            intermediate_size=14336,
            num_layers=32,
            num_heads=32,
            num_kv_heads=8,
            head_dim=128,
            **overrides,
        )

    @classmethod
    def llama3_70b(cls, **overrides):
        return cls(
            vocab_size=128256,
            hidden_size=8192,
            intermediate_size=28672,
            num_layers=80,
            num_heads=64,
            num_kv_heads=8,
            head_dim=128,
            **overrides,
        )

    @classmethod
    def llama3_2_3b(cls, **overrides):
        """Llama-3.2-3B: the single-v5e-chip flagship (≈6.4GB bf16 params)."""
        return cls(
            vocab_size=128256,
            hidden_size=3072,
            intermediate_size=8192,
            num_layers=28,
            num_heads=24,
            num_kv_heads=8,
            head_dim=128,
            tie_embeddings=True,
            **overrides,
        )

    @classmethod
    def tiny(cls, **overrides):
        """CPU-test scale."""
        kw = dict(
            vocab_size=512,
            hidden_size=64,
            intermediate_size=128,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            head_dim=16,
            max_position=512,
        )
        kw.update(overrides)
        return cls(**kw)


def init_params(config: LlamaConfig, key: jax.Array) -> Dict[str, Any]:
    """Random-init parameter pytree (shape-compatible with HF llama weights;
    the loader maps safetensors onto the same tree when weights exist)."""
    c = config
    k_embed, k_layers, k_out = jax.random.split(key, 3)
    scale = 0.02

    def dense(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(c.dtype)

    layers = []
    keys = jax.random.split(k_layers, c.num_layers)
    q_dim = c.num_heads * c.head_dim
    kv_dim = c.num_kv_heads * c.head_dim
    for lk in keys:
        k1, k2, k3, k4, k5, k6, k7 = jax.random.split(lk, 7)
        layers.append(
            {
                "attn_norm": jnp.ones((c.hidden_size,), c.dtype),
                "wq": dense(k1, (c.hidden_size, q_dim)),
                "wk": dense(k2, (c.hidden_size, kv_dim)),
                "wv": dense(k3, (c.hidden_size, kv_dim)),
                "wo": dense(k4, (q_dim, c.hidden_size)),
                "mlp_norm": jnp.ones((c.hidden_size,), c.dtype),
                "w_gate": dense(k5, (c.hidden_size, c.intermediate_size)),
                "w_up": dense(k6, (c.hidden_size, c.intermediate_size)),
                "w_down": dense(k7, (c.intermediate_size, c.hidden_size)),
            }
        )
    params = {
        "embed": dense(k_embed, (c.vocab_size, c.hidden_size)),
        "layers": jax.tree.map(lambda *xs: jnp.stack(xs), *layers),
        "final_norm": jnp.ones((c.hidden_size,), c.dtype),
        "lm_head": None if c.tie_embeddings else dense(k_out, (c.hidden_size, c.vocab_size)),
    }
    return params


def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight


def rope_cos_sin(positions: jax.Array, head_dim: int, theta: float):
    """positions [...,] -> cos/sin [..., head_dim//2] (f32)."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x [..., heads, head_dim]; cos/sin broadcast over heads."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    cos = cos[..., None, :]
    sin = sin[..., None, :]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _mlp(layer, x, c: LlamaConfig):
    h = rms_norm(x, layer["mlp_norm"], c.rms_norm_eps)
    gate = qdot(h, layer["w_gate"])
    up = qdot(h, layer["w_up"])
    act = (jax.nn.silu(gate) * up).astype(c.dtype)
    return x + qdot(act, layer["w_down"]).astype(c.dtype)


def prefill_forward(
    params: Dict[str, Any],
    config: LlamaConfig,
    tokens: jax.Array,  # [chunk]
    positions: jax.Array,  # [chunk] absolute positions
    kv_k: jax.Array,  # [L, pages, page_size, kv_heads, head_dim]
    kv_v: jax.Array,
    page_table: jax.Array,  # [max_pages] pages of THIS sequence
    context_len: jax.Array,  # scalar: positions[<context_len] are valid history
    last_idx: Optional[jax.Array] = None,  # index of the last REAL token in the
    # (possibly padded) chunk; defaults to the final position
    mlp_fn=None,  # (layer, x, config) -> x; models/moe.py passes moe_mlp
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Process one prompt chunk of a single sequence; returns
    (logits_last [vocab], kv_k, kv_v) with the chunk's KV written into pages.

    Chunked prefill: the chunk attends causally to itself AND to already-
    written history via the page table (positions < chunk start).
    """
    c = config
    mlp_fn = mlp_fn or _mlp
    x = embed_rows(params["embed"], tokens, c.dtype)  # [T, H]
    cos, sin = rope_cos_sin(positions, c.head_dim, c.rope_theta)
    page_size = kv_page_size(kv_k)
    T = tokens.shape[0]
    # valid context = history + real (unpadded) chunk length; bounds the
    # Pallas prefill kernel's page streaming (pallas_prefill_attention.py)
    real_chunk = (last_idx + 1) if last_idx is not None else T
    total_len = context_len + real_chunk

    def body(x, kv_k, kv_v):
        new_k_chunks = []
        new_v_chunks = []
        for li in range(c.num_layers):
            layer = jax.tree.map(lambda p: p[li], params["layers"])
            h = rms_norm(x, layer["attn_norm"], c.rms_norm_eps)
            q = qdot(h, layer["wq"]).astype(c.dtype)
            k = qdot(h, layer["wk"]).astype(c.dtype)
            v = qdot(h, layer["wv"]).astype(c.dtype)
            q = q.reshape(-1, c.num_heads, c.head_dim)
            k = k.reshape(-1, c.num_kv_heads, c.head_dim)
            v = v.reshape(-1, c.num_kv_heads, c.head_dim)
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
            # write chunk KV into the pages for this sequence
            kv_k = _write_chunk(kv_k, li, k, positions, page_table, page_size)
            kv_v = _write_chunk(kv_v, li, v, positions, page_table, page_size)
            attn = prefill_attention(
                q, k, v, kv_layer(kv_k, li), kv_layer(kv_v, li), positions,
                page_table, context_len, total_len,
            )
            attn = attn.reshape(-1, c.num_heads * c.head_dim)
            x = x + qdot(attn, layer["wo"]).astype(c.dtype)
            x = mlp_fn(layer, x, c)
        return x, kv_k, kv_v

    x, kv_k, kv_v = body(x, kv_k, kv_v)
    x = rms_norm(x, params["final_norm"], c.rms_norm_eps)
    last = x[-1] if last_idx is None else x[last_idx]
    head = head_leaf(params)
    logits = qdot(last, head)
    return logits, kv_k, kv_v


def prefill_forward_batched(
    params: Dict[str, Any],
    config: LlamaConfig,
    tokens: jax.Array,  # [B, T] one chunk per sequence (padded to bucket)
    positions: jax.Array,  # [B, T] absolute positions (pads -> scratch tail)
    kv_k: jax.Array,  # [L, pages, page_size, kv_heads, head_dim]
    kv_v: jax.Array,
    page_tables: jax.Array,  # [B, max_pages] per-seq tables (ctx-bounded)
    context_lens: jax.Array,  # [B] history length per seq
    last_idx: jax.Array,  # [B] index of last REAL token per chunk
    mlp_fn=None,
    emb_override: Optional[jax.Array] = None,  # [B, T, H] multimodal rows
    emb_mask: Optional[jax.Array] = None,  # [B, T] True where override applies
    all_logits: bool = False,  # True: return [B, T, vocab] (spec verify)
    lora=None,  # models/lora.py stack + per-lane idx (multi-LoRA serving)
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Batched chunked prefill: one dispatch processes chunks of SEVERAL
    sequences (the round-1 engine serialized one chunk per loop iteration).
    Returns (logits_last [B, vocab], kv_k, kv_v) — or [B, T, vocab] under
    `all_logits` (the speculative-decoding verify pass, engine/spec.py,
    needs every chunk position's logits).

    `emb_override`/`emb_mask`: multimodal E/P/D splice — encoder-produced
    embedding rows replace the placeholder tokens' embeddings at their
    recorded positions (reference trtllm multimodal_epd.md flow)."""
    c = config
    mlp_fn = mlp_fn or _mlp
    B, T = tokens.shape
    x = embed_rows(params["embed"], tokens, c.dtype)  # [B, T, H]
    if emb_override is not None:
        x = jnp.where(emb_mask[..., None], emb_override.astype(c.dtype), x)
    cos, sin = rope_cos_sin(positions, c.head_dim, c.rope_theta)
    page_size = kv_page_size(kv_k)
    total_lens = context_lens + last_idx + 1  # [B] valid context per seq

    # route positions past the table to the scratch page (phys 0):
    # speculative verify chunks (engine/spec.py) may overshoot
    # max_model_len by up to the draft length near the boundary
    P_tab = page_tables.shape[1]
    logical = jnp.minimum(positions // page_size, P_tab - 1)
    phys = jnp.take_along_axis(page_tables, logical, axis=1)  # [B, T]
    phys = jnp.where(positions < P_tab * page_size, phys, 0)
    offs = positions % page_size

    from . import lora as lora_mod

    for li in range(c.num_layers):
        layer = jax.tree.map(lambda p: p[li], params["layers"])
        ll = lora_mod.layer_lora(lora, li)
        h = rms_norm(x, layer["attn_norm"], c.rms_norm_eps)
        q = lora_mod.proj(h, layer["wq"], qdot, ll, "wq").astype(c.dtype)
        k = lora_mod.proj(h, layer["wk"], qdot, ll, "wk").astype(c.dtype)
        v = lora_mod.proj(h, layer["wv"], qdot, ll, "wv").astype(c.dtype)
        q = q.reshape(B, T, c.num_heads, c.head_dim)
        k = k.reshape(B, T, c.num_kv_heads, c.head_dim)
        v = v.reshape(B, T, c.num_kv_heads, c.head_dim)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        kv_k = kv_write(kv_k, li, phys, offs, k)
        kv_v = kv_write(kv_v, li, phys, offs, v)
        attn = prefill_attention_batched(
            q, kv_layer(kv_k, li), kv_layer(kv_v, li), positions, page_tables,
            total_lens, context_lens
        )
        attn = attn.reshape(B, T, c.num_heads * c.head_dim)
        x = x + lora_mod.proj(attn, layer["wo"], qdot, ll, "wo").astype(c.dtype)
        x = mlp_fn(layer, x, c)

    x = rms_norm(x, params["final_norm"], c.rms_norm_eps)
    head = head_leaf(params)
    if all_logits:
        return qdot(x, head), kv_k, kv_v  # [B, T, vocab]
    last = x[jnp.arange(B), last_idx]  # [B, hidden]
    logits = qdot(last, head)
    return logits, kv_k, kv_v


def ragged_forward(
    params: Dict[str, Any],
    config: LlamaConfig,
    tokens: jax.Array,  # [N] flat packed: prefill chunks + decode singletons
    positions: jax.Array,  # [N] absolute positions (pads -> scratch tail)
    row_ids: jax.Array,  # [N] owning row per flat token
    kv_k: jax.Array,  # [L, pages, page_size, kv_heads, head_dim]
    kv_v: jax.Array,
    page_tables: jax.Array,  # [R, max_pages] per-row tables (ctx-bounded)
    row_starts: jax.Array,  # [R] flat index of each row's token 0
    row_lens: jax.Array,  # [R] real tokens per row (1 for decode rows)
    ctx_lens: jax.Array,  # [R] history length per row
    last_flat: jax.Array,  # [R] flat index of each row's LAST real token
    mlp_fn=None,
    lora=None,  # models/lora.py stack + PER-ROW idx (fused multi-LoRA)
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """The unified mixed-step forward: ONE pass over a flat ragged token
    buffer that packs prefill chunks (row_len > 1) and decode slots
    (row_len == 1, ctx = seq_len - 1) — the single device dispatch behind
    the engine's `_dispatch_mixed` (vs the split prefill-batch + decode
    dispatches). Returns (logits_last [R, vocab], kv_k, kv_v) with every
    row's chunk KV written into its pages; each row's last-token logits
    feed on-device sampling (the next decode token / the prefill first
    token). Attention rides ops/paged_attention.ragged_attention (Pallas
    ragged kernel on TPU, XLA reference elsewhere).

    `lora`: the engine's stacked adapter pair with `idx` a PER-ROW [R]
    adapter index; base rows carry index 0 (the all-zero adapter — an
    exact no-op), so a blended pack needs no masking. The per-row index
    is gathered to per-flat-token through `row_ids` and the delta rides
    lora.proj exactly as in prefill_forward_batched."""
    c = config
    mlp_fn = mlp_fn or _mlp
    x = embed_rows(params["embed"], tokens, c.dtype)  # [N, H]
    cos, sin = rope_cos_sin(positions, c.head_dim, c.rope_theta)
    page_size = kv_page_size(kv_k)

    # per-token physical page: gather the OWNING row's table, route pad
    # positions (and any overshoot) to the scratch page — same trick as
    # prefill_forward_batched, per flat token instead of per [B, T] cell
    P_tab = page_tables.shape[1]
    tab_tok = page_tables[row_ids]  # [N, max_pages]
    logical = jnp.minimum(positions // page_size, P_tab - 1)
    phys = jnp.take_along_axis(tab_tok, logical[:, None], axis=1)[:, 0]
    phys = jnp.where(positions < P_tab * page_size, phys, 0)
    offs = positions % page_size

    from . import lora as lora_mod

    if lora is not None:
        # per-row adapter index -> per-flat-token (lora_delta's 2-D path
        # treats the flat token axis as its batch axis)
        lora = dict(lora, idx=lora["idx"][row_ids])

    for li in range(c.num_layers):
        layer = jax.tree.map(lambda p: p[li], params["layers"])
        ll = lora_mod.layer_lora(lora, li)
        h = rms_norm(x, layer["attn_norm"], c.rms_norm_eps)
        q = lora_mod.proj(h, layer["wq"], qdot, ll, "wq").astype(c.dtype)
        k = lora_mod.proj(h, layer["wk"], qdot, ll, "wk").astype(c.dtype)
        v = lora_mod.proj(h, layer["wv"], qdot, ll, "wv").astype(c.dtype)
        q = q.reshape(-1, c.num_heads, c.head_dim)
        k = k.reshape(-1, c.num_kv_heads, c.head_dim)
        v = v.reshape(-1, c.num_kv_heads, c.head_dim)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        kv_k = kv_write(kv_k, li, phys, offs, k)
        kv_v = kv_write(kv_v, li, phys, offs, v)
        attn = ragged_attention(
            q, kv_layer(kv_k, li), kv_layer(kv_v, li), page_tables,
            row_starts, row_lens, ctx_lens
        )
        attn = attn.reshape(-1, c.num_heads * c.head_dim)
        x = x + lora_mod.proj(attn, layer["wo"], qdot, ll, "wo").astype(c.dtype)
        x = mlp_fn(layer, x, c)

    x = rms_norm(x, params["final_norm"], c.rms_norm_eps)
    last = x[last_flat]  # [R, hidden]
    head = head_leaf(params)
    logits = qdot(last, head)
    return logits, kv_k, kv_v


def prefill_forward_ring(
    params: Dict[str, Any],
    config: LlamaConfig,
    tokens: jax.Array,  # [T] whole prompt (padded to a multiple of sp)
    kv_k: jax.Array,  # [L, pages, page_size, kv_heads, head_dim]
    kv_v: jax.Array,
    page_table: jax.Array,  # [max_pages] this sequence's table
    real_len: jax.Array,  # scalar i32: tokens beyond this are padding
    mesh,
    axis_name: str = SP_AXIS,
    mlp_fn=None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Sequence-parallel whole-prompt prefill: the token dim is sharded over
    the ``sp`` mesh axis and attention is exact ring attention
    (ops/ring_attention.py — K/V blocks rotate over ICI, O(T/n) attention
    memory per device). This is the engine's long-context path (SURVEY.md
    §2.5 sequence-parallel row: absent upstream, native extension here);
    the reference handles long prompts only by chunking + disagg
    (disagg_router.rs:230 thresholds). History-free by design: prefix-cache
    hits fall back to chunked prefill.

    Returns (logits_of_last_real_token [vocab], kv_k, kv_v)."""
    from ..ops.ring_attention import ring_attention

    c = config
    mlp_fn = mlp_fn or _mlp
    T = tokens.shape[0]
    positions = jnp.arange(T, dtype=jnp.int32)
    x = embed_rows(params["embed"], tokens, c.dtype)  # [T, H]
    cos, sin = rope_cos_sin(positions, c.head_dim, c.rope_theta)
    page_size = kv_page_size(kv_k)

    # pad positions write to the scratch page (phys 0), real ones to the table
    logical = jnp.minimum(positions // page_size, page_table.shape[0] - 1)
    phys = jnp.where(positions < real_len, page_table[logical], 0)
    offs = positions % page_size

    for li in range(c.num_layers):
        layer = jax.tree.map(lambda p: p[li], params["layers"])
        h = rms_norm(x, layer["attn_norm"], c.rms_norm_eps)
        q = qdot(h, layer["wq"]).astype(c.dtype)
        k = qdot(h, layer["wk"]).astype(c.dtype)
        v = qdot(h, layer["wv"]).astype(c.dtype)
        q = q.reshape(T, c.num_heads, c.head_dim)
        k = k.reshape(T, c.num_kv_heads, c.head_dim)
        v = v.reshape(T, c.num_kv_heads, c.head_dim)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        kv_k = kv_k.at[li, phys, offs].set(k)
        kv_v = kv_v.at[li, phys, offs].set(v)
        attn = ring_attention(q, k, v, mesh, axis_name=axis_name, causal=True)
        attn = attn.reshape(T, c.num_heads * c.head_dim)
        x = x + qdot(attn, layer["wo"]).astype(c.dtype)
        x = mlp_fn(layer, x, c)

    x = rms_norm(x, params["final_norm"], c.rms_norm_eps)
    last = x[jnp.maximum(real_len - 1, 0)]
    head = head_leaf(params)
    logits = qdot(last, head)
    return logits, kv_k, kv_v


def _stage_layers_decode(local_params, local_kv, x, aux, valid, c, mlp_fn):
    """One pipeline stage's layers for a decode microbatch. local_kv =
    (kv_k, kv_v) with leading [L/S] layer axis; aux carries the
    microbatch's positions/page-table rows/seq lens; invalid (bubble)
    ticks write to the scratch page."""
    from ..ops.paged_attention import paged_attention_decode

    kv_k_loc, kv_v_loc = local_kv
    positions, tables, seq_lens = aux["positions"], aux["tables"], aux["seq_lens"]
    page_size = kv_k_loc.shape[2]
    cos, sin = rope_cos_sin(positions, c.head_dim, c.rope_theta)
    max_positions = tables.shape[1] * page_size
    logical = jnp.minimum(positions // page_size, tables.shape[1] - 1)
    phys = jnp.take_along_axis(tables, logical[:, None], axis=1)[:, 0]
    phys = jnp.where(valid & (positions < max_positions), phys, 0)
    offs = positions % page_size
    n_local = kv_k_loc.shape[0]
    for li in range(n_local):
        layer = jax.tree.map(lambda p: p[li], local_params)
        h = rms_norm(x, layer["attn_norm"], c.rms_norm_eps)
        q = qdot(h, layer["wq"]).astype(c.dtype)
        k = qdot(h, layer["wk"]).astype(c.dtype)
        v = qdot(h, layer["wv"]).astype(c.dtype)
        q = q.reshape(-1, c.num_heads, c.head_dim)
        k = k.reshape(-1, c.num_kv_heads, c.head_dim)
        v = v.reshape(-1, c.num_kv_heads, c.head_dim)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        kv_k_loc = kv_k_loc.at[li, phys, offs].set(k)
        kv_v_loc = kv_v_loc.at[li, phys, offs].set(v)
        attn = paged_attention_decode(q, kv_k_loc[li], kv_v_loc[li], tables, seq_lens)
        attn = attn.reshape(-1, c.num_heads * c.head_dim)
        x = x + qdot(attn, layer["wo"]).astype(c.dtype)
        x = mlp_fn(layer, x, c)
    return x, (kv_k_loc, kv_v_loc)


def decode_forward_pp(
    params: Dict[str, Any],
    config: LlamaConfig,
    tokens: jax.Array,  # [B]
    positions: jax.Array,  # [B]
    kv_k: jax.Array,  # [L, pages, page_size, KH, D] (pp-sharded on L)
    kv_v: jax.Array,
    page_tables: jax.Array,  # [B, max_pages]
    seq_lens: jax.Array,  # [B]
    mesh,
    num_microbatches: int = 0,
    mlp_fn=None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step with the LAYERS pipelined over the ``pp`` mesh axis:
    the batch splits into microbatches that stream through the stages
    (parallel/pipeline.py pipeline_apply_stateful; each stage owns the KV
    pool of its own layers). The reference delegates PP to its engines
    (SURVEY.md §2.5 PP row); here it is a native XLA schedule.
    Returns (logits [B, vocab], kv_k, kv_v)."""
    from ..parallel.pipeline import pipeline_apply_stateful, stack_stages

    c = config
    mlp_fn = mlp_fn or _mlp
    S = mesh.shape[PP_AXIS]
    B = tokens.shape[0]
    M = num_microbatches or min(S, B)
    while B % M:
        M -= 1
    mb = B // M
    L = kv_k.shape[0]

    stage_params = stack_stages(params["layers"], S)
    stage_kv = (
        kv_k.reshape(S, L // S, *kv_k.shape[1:]),
        kv_v.reshape(S, L // S, *kv_v.shape[1:]),
    )
    x = embed_rows(params["embed"], tokens, c.dtype)  # [B, H]
    x_mb = x.reshape(M, mb, -1)
    aux_mb = {
        "positions": positions.reshape(M, mb),
        "tables": page_tables.reshape(M, mb, -1),
        "seq_lens": seq_lens.reshape(M, mb),
    }

    def stage_fn(local_p, local_s, x, aux, valid):
        return _stage_layers_decode(local_p, local_s, x, aux, valid, c, mlp_fn)

    out, (kv_k_s, kv_v_s) = pipeline_apply_stateful(
        stage_params, stage_kv, x_mb, aux_mb, stage_fn, mesh
    )
    kv_k = kv_k_s.reshape(L, *kv_k.shape[1:])
    kv_v = kv_v_s.reshape(L, *kv_v.shape[1:])
    x = out.reshape(B, -1)
    x = rms_norm(x, params["final_norm"], c.rms_norm_eps)
    head = head_leaf(params)
    logits = qdot(x, head)
    return logits, kv_k, kv_v


def _stage_layers_prefill(local_params, local_kv, x, aux, valid, c, mlp_fn):
    """One pipeline stage's layers for a PREFILL microbatch (a contiguous
    token span of one sequence). Pipeline order = sequence order, so span
    j's KV is fully written at every stage before span j+1 arrives —
    chunked-prefill causality for free."""
    from ..ops.paged_attention import prefill_attention

    kv_k_loc, kv_v_loc = local_kv
    positions = aux["positions"]  # [t] absolute
    table = aux["table"]  # [max_pages]
    context_len = aux["context_len"]  # scalar: history before this span
    total_len = aux["total_len"]  # scalar: history + real tokens in span
    real_mask = aux["real_mask"]  # [t] bool: padding -> scratch writes
    page_size = kv_k_loc.shape[2]
    cos, sin = rope_cos_sin(positions, c.head_dim, c.rope_theta)
    logical = jnp.minimum(positions // page_size, table.shape[0] - 1)
    phys = jnp.where(valid & real_mask, table[logical], 0)
    offs = positions % page_size
    n_local = kv_k_loc.shape[0]
    for li in range(n_local):
        layer = jax.tree.map(lambda p: p[li], local_params)
        h = rms_norm(x, layer["attn_norm"], c.rms_norm_eps)
        q = qdot(h, layer["wq"]).astype(c.dtype)
        k = qdot(h, layer["wk"]).astype(c.dtype)
        v = qdot(h, layer["wv"]).astype(c.dtype)
        q = q.reshape(-1, c.num_heads, c.head_dim)
        k = k.reshape(-1, c.num_kv_heads, c.head_dim)
        v = v.reshape(-1, c.num_kv_heads, c.head_dim)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        kv_k_loc = kv_k_loc.at[li, phys, offs].set(k)
        kv_v_loc = kv_v_loc.at[li, phys, offs].set(v)
        attn = prefill_attention(
            q, k, v, kv_k_loc[li], kv_v_loc[li], positions, table,
            context_len, total_len,
        )
        attn = attn.reshape(-1, c.num_heads * c.head_dim)
        x = x + qdot(attn, layer["wo"]).astype(c.dtype)
        x = mlp_fn(layer, x, c)
    return x, (kv_k_loc, kv_v_loc)


def prefill_forward_pp(
    params: Dict[str, Any],
    config: LlamaConfig,
    tokens: jax.Array,  # [T] remaining prompt, padded to a multiple of M
    kv_k: jax.Array,
    kv_v: jax.Array,
    page_table: jax.Array,  # [max_pages]
    context_len: jax.Array,  # scalar: already-cached history length
    real_len: jax.Array,  # scalar: tokens beyond this are padding
    mesh,
    num_microbatches: int = 0,
    mlp_fn=None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Single-sequence prefill pipelined over ``pp``: the prompt splits into
    sequential token spans that stream through the layer stages. Returns
    (logits_of_last_real_token [vocab], kv_k, kv_v)."""
    from ..parallel.pipeline import pipeline_apply_stateful, stack_stages

    c = config
    mlp_fn = mlp_fn or _mlp
    S = mesh.shape[PP_AXIS]
    T = tokens.shape[0]
    M = num_microbatches or S
    while T % M:
        M -= 1
    t = T // M
    L = kv_k.shape[0]

    stage_params = stack_stages(params["layers"], S)
    stage_kv = (
        kv_k.reshape(S, L // S, *kv_k.shape[1:]),
        kv_v.reshape(S, L // S, *kv_v.shape[1:]),
    )
    positions = context_len + jnp.arange(T, dtype=jnp.int32)
    x = embed_rows(params["embed"], tokens, c.dtype).reshape(M, t, -1)
    span_starts = context_len + jnp.arange(M, dtype=jnp.int32) * t
    span_real = jnp.clip(real_len - jnp.arange(M) * t, 0, t)  # real tokens/span
    aux_mb = {
        "positions": positions.reshape(M, t),
        "table": jnp.broadcast_to(page_table, (M, page_table.shape[0])),
        "context_len": span_starts,
        "total_len": span_starts + span_real,
        "real_mask": (jnp.arange(T).reshape(M, t) < real_len),
    }

    def stage_fn(local_p, local_s, x, aux, valid):
        return _stage_layers_prefill(local_p, local_s, x, aux, valid, c, mlp_fn)

    out, (kv_k_s, kv_v_s) = pipeline_apply_stateful(
        stage_params, stage_kv, x, aux_mb, stage_fn, mesh
    )
    kv_k = kv_k_s.reshape(L, *kv_k.shape[1:])
    kv_v = kv_v_s.reshape(L, *kv_v.shape[1:])
    flat = out.reshape(T, -1)
    x = rms_norm(flat, params["final_norm"], c.rms_norm_eps)
    last = x[jnp.maximum(real_len - 1, 0)]
    head = head_leaf(params)
    logits = qdot(last, head)
    return logits, kv_k, kv_v


def _write_chunk(kv, layer_idx, vals, positions, page_table, page_size):
    """Scatter chunk KV [T, kv_heads, head_dim] into paged cache at absolute
    positions (page_table maps logical page -> physical page). Rides
    ops/kv_quant.kv_write — quantize-on-write under DYN_KV_QUANT, the
    seed's exact scatter otherwise."""
    logical_pages = positions // page_size
    phys_pages = page_table[logical_pages]
    offs = positions % page_size
    return kv_write(kv, layer_idx, phys_pages, offs, vals)


def decode_forward(
    params: Dict[str, Any],
    config: LlamaConfig,
    tokens: jax.Array,  # [B] one new token per slot
    positions: jax.Array,  # [B]
    kv_k: jax.Array,
    kv_v: jax.Array,
    page_tables: jax.Array,  # [B, max_pages]
    seq_lens: jax.Array,  # [B] lengths INCLUDING the new token
    mlp_fn=None,  # (layer, x, config) -> x; models/moe.py passes moe_mlp
    lora=None,  # models/lora.py stack + per-lane idx (multi-LoRA serving)
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step for the whole slot batch; returns
    (logits [B, vocab], kv_k, kv_v)."""
    from . import lora as lora_mod

    c = config
    mlp_fn = mlp_fn or _mlp
    x = embed_rows(params["embed"], tokens, c.dtype)  # [B, H]
    cos, sin = rope_cos_sin(positions, c.head_dim, c.rope_theta)
    page_size = kv_page_size(kv_k)

    for li in range(c.num_layers):
        layer = jax.tree.map(lambda p: p[li], params["layers"])
        ll = lora_mod.layer_lora(lora, li)
        h = rms_norm(x, layer["attn_norm"], c.rms_norm_eps)
        q = lora_mod.proj(h, layer["wq"], qdot, ll, "wq").astype(c.dtype)
        k = lora_mod.proj(h, layer["wk"], qdot, ll, "wk").astype(c.dtype)
        v = lora_mod.proj(h, layer["wv"], qdot, ll, "wv").astype(c.dtype)
        q = q.reshape(-1, c.num_heads, c.head_dim)
        k = k.reshape(-1, c.num_kv_heads, c.head_dim)
        v = v.reshape(-1, c.num_kv_heads, c.head_dim)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        # write each slot's new KV at its position. Positions past the table
        # (fused-block speculation overshooting max_model_len) route to
        # physical page 0 — the engine's reserved scratch page — instead of
        # XLA's silent clamp-to-last-page, which could corrupt a real
        # (possibly shared/committed) KV page.
        max_positions = page_tables.shape[1] * page_size
        logical = jnp.minimum(positions // page_size, page_tables.shape[1] - 1)
        phys = jnp.take_along_axis(page_tables, logical[:, None], axis=1)[:, 0]
        phys = jnp.where(positions < max_positions, phys, 0)
        offs = positions % page_size
        kv_k = kv_write(kv_k, li, phys, offs, k[:, 0] if k.ndim == 4 else k)
        kv_v = kv_write(kv_v, li, phys, offs, v[:, 0] if v.ndim == 4 else v)
        attn = paged_attention_decode(
            q, kv_layer(kv_k, li), kv_layer(kv_v, li), page_tables, seq_lens
        )
        attn = attn.reshape(-1, c.num_heads * c.head_dim)
        x = x + lora_mod.proj(attn, layer["wo"], qdot, ll, "wo").astype(c.dtype)
        x = mlp_fn(layer, x, c)

    x = rms_norm(x, params["final_norm"], c.rms_norm_eps)
    head = head_leaf(params)
    logits = qdot(x, head)
    return logits, kv_k, kv_v


def decode_forward_local(
    params: Dict[str, Any],
    config: LlamaConfig,
    tokens: jax.Array,  # [B] one new token per slot
    positions: jax.Array,  # [B]
    loc_k: tuple,  # L-tuple of [B, K, KH, D] block-local KV accumulators
    loc_v: tuple,
    step_idx: jax.Array,  # scalar i32: this step's slot in the local buffer
    kv_k: jax.Array,  # READ-ONLY pool (written once per block by the engine)
    kv_v: jax.Array,
    page_tables: jax.Array,  # [B, max_pages]
    pool_lens: jax.Array,  # [B] positions valid in the pool (block-start len)
    mlp_fn=None,
) -> Tuple[jax.Array, tuple, tuple]:
    """One decode step that does NOT write the KV pool: new K/V go into the
    block-local accumulators (per-layer tuple of small arrays so each
    update is an in-place dynamic-update-slice on its own carry leaf — one
    fused [L, ...] array would be re-materialized per layer), attention
    reads pool+local via paged_attention_decode_mixed. Keeping the multi-GB
    pool out of the scan carry is what makes the fused decode block's cost
    independent of pool size (see the op's docstring).
    Returns (logits, loc_k, loc_v)."""
    from ..ops.paged_attention import paged_attention_decode_mixed

    c = config
    mlp_fn = mlp_fn or _mlp
    x = embed_rows(params["embed"], tokens, c.dtype)
    cos, sin = rope_cos_sin(positions, c.head_dim, c.rope_theta)
    loc_k, loc_v = list(loc_k), list(loc_v)

    for li in range(c.num_layers):
        layer = jax.tree.map(lambda p: p[li], params["layers"])
        h = rms_norm(x, layer["attn_norm"], c.rms_norm_eps)
        q = qdot(h, layer["wq"]).astype(c.dtype)
        k = qdot(h, layer["wk"]).astype(c.dtype)
        v = qdot(h, layer["wv"]).astype(c.dtype)
        q = q.reshape(-1, c.num_heads, c.head_dim)
        k = k.reshape(-1, c.num_kv_heads, c.head_dim)
        v = v.reshape(-1, c.num_kv_heads, c.head_dim)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        loc_k[li] = loc_k[li].at[:, step_idx].set(k)
        loc_v[li] = loc_v[li].at[:, step_idx].set(v)
        attn = paged_attention_decode_mixed(
            q, kv_layer(kv_k, li), kv_layer(kv_v, li), page_tables, pool_lens,
            loc_k[li], loc_v[li], step_idx,
        )
        attn = attn.reshape(-1, c.num_heads * c.head_dim)
        x = x + qdot(attn, layer["wo"]).astype(c.dtype)
        x = mlp_fn(layer, x, c)

    x = rms_norm(x, params["final_norm"], c.rms_norm_eps)
    head = head_leaf(params)
    logits = qdot(x, head)
    return logits, tuple(loc_k), tuple(loc_v)


def param_count(params) -> int:
    return sum(x.size for x in jax.tree.leaves(params) if x is not None)
