"""HF safetensors checkpoint loading into model param pytrees.

Role of the reference's model resolution + weight loading (local_model.rs
LocalModelBuilder + the engines' HF loaders): map HuggingFace
llama/mixtral checkpoint tensors onto the functional param trees in
models/llama.py / models/moe.py, layer-stacked and optionally placed
straight onto a mesh with NamedShardings (one transfer per leaf, no
host-side full-model copy beyond the memory-mapped safetensors).

HF stores linear weights [out_features, in_features]; our trees use
[in, out] (x @ W), so projections transpose on load.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = [
    "load_llama_params",
    "load_moe_params",
    "resolve_model_path",
    "save_llama_as_hf",
]


def _looks_like_repo_id(s: str) -> bool:
    """org/name shape, no leading slash or drive, at most one separator."""
    if s.startswith(("/", ".", "~")) or "\\" in s:
        return False
    parts = s.split("/")
    return len(parts) == 2 and all(p and not p.startswith(".") for p in parts)


def resolve_model_path(
    path_or_repo: str,
    revision: Optional[str] = None,
    allow_download: Optional[bool] = None,
) -> str:
    """Resolve a local checkpoint directory OR a HuggingFace repo id to a
    directory of safetensors (reference LocalModelBuilder,
    lib/llm/src/local_model.rs:44-120: same local-path-else-hub contract).

    Repo ids resolve through the hub cache first (offline); an actual
    download happens only when allowed — `allow_download=True` or
    `DYN_HF_ALLOW_DOWNLOAD=1` — because serving environments are often
    egress-free and a surprise download would hang worker startup."""
    p = Path(os.path.expanduser(path_or_repo))
    if p.exists():
        return str(p)
    if not _looks_like_repo_id(path_or_repo):
        raise FileNotFoundError(
            f"model path {path_or_repo!r} does not exist and is not an "
            f"HF repo id"
        )
    from huggingface_hub import snapshot_download

    if allow_download is None:
        from ..runtime.config import env_bool

        allow_download = env_bool("DYN_HF_ALLOW_DOWNLOAD")
    try:
        return snapshot_download(
            path_or_repo, revision=revision, local_files_only=True
        )
    except Exception:
        if not allow_download:
            raise FileNotFoundError(
                f"{path_or_repo!r} is not a local path and not in the HF "
                f"cache; set DYN_HF_ALLOW_DOWNLOAD=1 (or pass "
                f"allow_download=True) to fetch it from the hub"
            ) from None
    return snapshot_download(
        path_or_repo,
        revision=revision,
        allow_patterns=["*.safetensors*", "*.json", "tokenizer*"],
    )


def _open_checkpoint(model_dir: str) -> Dict[str, Any]:
    """Tensor name -> lazily-loaded numpy array, handling both single-file
    and index-sharded safetensors layouts."""
    from safetensors import safe_open

    d = Path(resolve_model_path(model_dir))
    index = d / "model.safetensors.index.json"
    files: Dict[str, Path] = {}
    handles: Dict[Path, Any] = {}
    if index.exists():
        weight_map = json.loads(index.read_text())["weight_map"]
        for name, fn in weight_map.items():
            files[name] = d / fn
    else:
        sts = sorted(d.glob("*.safetensors"))
        if not sts:
            raise FileNotFoundError(f"no safetensors files under {model_dir}")
        for st in sts:
            # keep the handle from enumeration — don't mmap shards twice
            handles[st] = safe_open(st, framework="numpy")
            for name in handles[st].keys():
                files[name] = st

    class Reader:
        def __contains__(self, name: str) -> bool:
            return name in files

        def keys(self):
            return files.keys()

        def _handle(self, name: str):
            path = files[name]
            if path not in handles:
                handles[path] = safe_open(path, framework="numpy")
            return handles[path]

        def get(self, name: str) -> np.ndarray:
            return self._handle(name).get_tensor(name)

        def get_slice(self, name: str):
            """Lazy slicer: partial reads straight off the mmap, so sharded
            placement never materializes a whole tensor on host."""
            return self._handle(name).get_slice(name)

    return Reader()


def _np_dtype(dtype) -> np.dtype:
    import jax.numpy as jnp
    import ml_dtypes

    if dtype == jnp.bfloat16:
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(jnp.dtype(dtype))


def _to_dtype(x: np.ndarray, dtype) -> Any:
    # copy=False: checkpoints already in the target dtype (the common case
    # for bf16) cast for free instead of duplicating the largest leaves
    return x.astype(_np_dtype(dtype), copy=False)


def _place(x: np.ndarray, dtype, sharding=None):
    import jax

    arr = _to_dtype(x, dtype)
    if sharding is not None:
        return jax.device_put(arr, sharding)
    return jax.device_put(arr)


def _place_stacked(reader, names_fn, num_layers: int, transpose: bool, dtype, sharding=None):
    """Device-place a layer-stacked leaf [L, ...] without ever holding more
    than one host copy (unsharded) or one SHARD (sharded) in RAM.

    Round-1 version np.stack'ed every layer then astype'd — two full host
    copies of e.g. llama3-70B's [80, 8192, 28672] bf16 (~75 GB transient).
    Now: unsharded leaves assemble layer-by-layer into a single
    pre-allocated buffer; sharded leaves assemble each device shard from
    safetensors PARTIAL reads via jax.make_array_from_callback, so peak
    host memory is one shard."""
    import jax

    target = _np_dtype(dtype)
    first = reader.get_slice(names_fn(0))
    lshape = tuple(first.get_shape())
    if transpose:
        lshape = lshape[::-1]
    shape = (num_layers, *lshape)

    if sharding is None:
        out = np.empty(shape, target)
        for li in range(num_layers):
            m = reader.get(names_fn(li))
            out[li] = m.T if transpose else m  # in-place cast during assign
        return jax.device_put(out)

    def build_shard(index):
        li_sl = index[0]
        layer_idx = range(*li_sl.indices(num_layers))
        sub = tuple(
            slice(*s.indices(dim)) for s, dim in zip(index[1:], shape[1:])
        )
        shard = np.empty(
            (len(layer_idx), *(s.stop - s.start for s in sub)), target
        )
        for i, li in enumerate(layer_idx):
            sl = reader.get_slice(names_fn(li))
            if transpose:
                # slice of transpose == transposed slice (2D leaves)
                shard[i] = sl[sub[1], sub[0]].T
            else:
                shard[i] = sl[sub]
        return shard

    return jax.make_array_from_callback(shape, sharding, build_shard)


def _stack_layers(reader, names_fn, num_layers: int, transpose: bool) -> np.ndarray:
    mats = []
    for li in range(num_layers):
        m = reader.get(names_fn(li))
        mats.append(m.T if transpose else m)
    return np.stack(mats)


def _place_quant(qleaf: Dict[str, np.ndarray], sharding=None):
    """Device-place a host-quantized {"q", "s"} leaf; the scale gets the
    leaf's sharding with singleton axes unsharded."""
    import jax

    from .quant import scale_sharding

    if sharding is None:
        return {"q": jax.device_put(qleaf["q"]), "s": jax.device_put(qleaf["s"])}
    return {
        "q": jax.device_put(qleaf["q"], sharding),
        "s": jax.device_put(qleaf["s"], scale_sharding(sharding, qleaf["s"].shape)),
    }


class _TreeBuilder:
    """Shared backbone assembly (embed / attention / norms / lm_head) for
    the llama and moe trees — the MLP block is the only difference."""

    def __init__(self, reader, config, shardings: Optional[dict],
                 quantize: Optional[str] = None):
        if quantize not in (None, "int8"):
            raise ValueError(f"unknown quantize mode {quantize!r}")
        self.r = reader
        self.c = config
        self.sh = shardings or {}
        self.quantize = quantize

    def layer_sh(self, key):
        return self.sh.get("layers", {}).get(key) if self.sh else None

    def stacked(self, key, hf_fmt, transpose=True):
        from .quant import _LAYER_LEAVES

        if self.quantize == "int8" and key in _LAYER_LEAVES:
            return self._stacked_quant(key, hf_fmt, transpose)
        return _place_stacked(
            self.r,
            lambda li: hf_fmt.format(li=li),
            self.c.num_layers,
            transpose,
            self.c.dtype,
            self.layer_sh(key),
        )

    def _stacked_quant(self, key, hf_fmt, transpose):
        """Quantize a layer-stacked projection at load. Scales are per
        out-channel over the FULL contraction axis, so (unlike the bf16
        path) each layer's whole tensor is read to host before placement
        — peak host memory is one f32 layer leaf plus the int8 stack."""
        from .quant import quantize_array

        c = self.c
        first = self.r.get_slice(hf_fmt.format(li=0))
        lshape = tuple(first.get_shape())
        if transpose:
            lshape = lshape[::-1]
        q_buf = np.empty((c.num_layers, *lshape), np.int8)
        s_buf = np.empty((c.num_layers, 1, lshape[-1]), np.float32)
        for li in range(c.num_layers):
            m = self.r.get(hf_fmt.format(li=li))
            if transpose:
                m = m.T
            ql = quantize_array(np.asarray(m, np.float32))
            q_buf[li], s_buf[li] = ql["q"], ql["s"]
        return _place_quant({"q": q_buf, "s": s_buf}, self.layer_sh(key))

    def _backbone_embed(self):
        c, r, sh = self.c, self.r, self.sh
        emb = r.get("model.embed_tokens.weight")
        if self.quantize == "int8":
            from .quant import quantize_array

            # per-ROW scale: rows gather as output vectors, and the
            # transpose doubles as the tied lm_head (quant.head_leaf)
            return _place_quant(
                quantize_array(np.asarray(emb, np.float32), contract_axis=-1),
                sh.get("embed"),
            )
        return _place(emb, c.dtype, sh.get("embed"))

    def backbone(self) -> Dict[str, Any]:
        c, r, sh = self.c, self.r, self.sh
        params: Dict[str, Any] = {
            "embed": self._backbone_embed(),
            "layers": {
                "attn_norm": self.stacked(
                    "attn_norm", "model.layers.{li}.input_layernorm.weight",
                    transpose=False,
                ),
                "wq": self.stacked("wq", "model.layers.{li}.self_attn.q_proj.weight"),
                "wk": self.stacked("wk", "model.layers.{li}.self_attn.k_proj.weight"),
                "wv": self.stacked("wv", "model.layers.{li}.self_attn.v_proj.weight"),
                "wo": self.stacked("wo", "model.layers.{li}.self_attn.o_proj.weight"),
                "mlp_norm": self.stacked(
                    "mlp_norm",
                    "model.layers.{li}.post_attention_layernorm.weight",
                    transpose=False,
                ),
            },
            "final_norm": _place(
                r.get("model.norm.weight"), c.dtype, sh.get("final_norm")
            ),
        }
        if c.tie_embeddings or "lm_head.weight" not in r:
            params["lm_head"] = None
        elif self.quantize == "int8":
            from .quant import quantize_array

            params["lm_head"] = _place_quant(
                quantize_array(np.asarray(r.get("lm_head.weight").T, np.float32)),
                sh.get("lm_head"),
            )
        else:
            params["lm_head"] = _place(
                r.get("lm_head.weight").T, c.dtype, sh.get("lm_head")
            )
        return params


def load_llama_params(
    model_dir: str,
    config,
    shardings: Optional[dict] = None,
    quantize: Optional[str] = None,
) -> Dict[str, Any]:
    """Load an HF llama-family checkpoint into the models/llama.py tree.
    `shardings` (from LlamaShardings.param_shardings()) places each leaf on
    the mesh as it loads. `quantize="int8"` stores projections/embed/head
    as int8 + per-channel scales (models/quant.py) — llama3-8b drops from
    ~16 GB to ~8.5 GB and fits a v5e chip beside its KV pool.

    A .gguf path (file, or a directory holding one .gguf) takes the GGUF
    branch (load_llama_params_gguf)."""
    gg = _find_gguf(model_dir)
    if gg is not None:
        return load_llama_params_gguf(gg, config, shardings, quantize)
    b = _TreeBuilder(_open_checkpoint(model_dir), config, shardings, quantize)
    params = b.backbone()
    params["layers"].update(
        {
            "w_gate": b.stacked("w_gate", "model.layers.{li}.mlp.gate_proj.weight"),
            "w_up": b.stacked("w_up", "model.layers.{li}.mlp.up_proj.weight"),
            "w_down": b.stacked("w_down", "model.layers.{li}.mlp.down_proj.weight"),
        }
    )
    return params


def load_moe_params(
    model_dir: str,
    config,
    shardings: Optional[dict] = None,
    quantize: Optional[str] = None,
) -> Dict[str, Any]:
    """Load an HF mixtral-family checkpoint into the models/moe.py tree
    (block_sparse_moe.gate + experts.N.w1/w2/w3). `quantize="int8"`
    covers the attention backbone, embed/head AND the expert stacks
    (per-expert scales; the f32 router stays f32). A .gguf path takes
    the GGUF branch (ffn_*_exps / ffn_gate_inp naming)."""
    import jax.numpy as jnp

    gg = _find_gguf(model_dir)
    if gg is not None:
        return load_llama_params_gguf(gg, config, shardings, quantize)

    c = config
    b = _TreeBuilder(_open_checkpoint(model_dir), config, shardings, quantize)
    r = b.r

    def stacked_experts(key, hf_fmt):
        # -> [L, E, in, out]; int8 quantizes per (layer, expert) matrix
        # incrementally, bounding peak host memory at one f32 expert leaf
        if quantize == "int8":
            from .quant import quantize_array

            # shape from metadata only (get_slice): no data read
            lshape = tuple(r.get_slice(hf_fmt.format(li=0, e=0)).get_shape())[::-1]
            q_buf = np.empty((c.num_layers, c.num_experts, *lshape), np.int8)
            s_buf = np.empty(
                (c.num_layers, c.num_experts, 1, lshape[-1]), np.float32
            )
            for li in range(c.num_layers):
                for e in range(c.num_experts):
                    ql = quantize_array(
                        np.asarray(r.get(hf_fmt.format(li=li, e=e)).T, np.float32)
                    )
                    q_buf[li, e], s_buf[li, e] = ql["q"], ql["s"]
            return _place_quant({"q": q_buf, "s": s_buf}, b.layer_sh(key))
        layers = []
        for li in range(c.num_layers):
            layers.append(
                np.stack(
                    [r.get(hf_fmt.format(li=li, e=e)).T for e in range(c.num_experts)]
                )
            )
        return _place(np.stack(layers), c.dtype, b.layer_sh(key))

    params = b.backbone()
    params["layers"].update(
        {
            # router stays f32 (routing decisions are numerically sensitive)
            "router": _place(
                _stack_layers(
                    r,
                    lambda li: f"model.layers.{li}.block_sparse_moe.gate.weight",
                    c.num_layers,
                    transpose=True,
                ),
                jnp.float32,
                b.layer_sh("router"),
            ),
            # mixtral: w1=gate, w3=up, w2=down
            "w_gate": stacked_experts(
                "w_gate", "model.layers.{li}.block_sparse_moe.experts.{e}.w1.weight"
            ),
            "w_up": stacked_experts(
                "w_up", "model.layers.{li}.block_sparse_moe.experts.{e}.w3.weight"
            ),
            "w_down": stacked_experts(
                "w_down", "model.layers.{li}.block_sparse_moe.experts.{e}.w2.weight"
            ),
        }
    )
    return params


def save_llama_as_hf(params: Dict[str, Any], config, out_dir: str) -> None:
    """Export a models/llama.py tree in HF naming (round-trip testing and
    checkpoint interchange)."""
    from safetensors.numpy import save_file

    c = config
    os.makedirs(out_dir, exist_ok=True)
    tensors: Dict[str, np.ndarray] = {}

    def f32(x) -> np.ndarray:
        return np.asarray(x, dtype=np.float32)

    def f32t(x) -> np.ndarray:
        # safetensors requires contiguous buffers; .T alone is a view
        return np.ascontiguousarray(f32(x).T)

    tensors["model.embed_tokens.weight"] = f32(params["embed"])
    for li in range(c.num_layers):
        L = params["layers"]
        pre = f"model.layers.{li}"
        tensors[f"{pre}.input_layernorm.weight"] = f32(L["attn_norm"][li])
        tensors[f"{pre}.self_attn.q_proj.weight"] = f32t(L["wq"][li])
        tensors[f"{pre}.self_attn.k_proj.weight"] = f32t(L["wk"][li])
        tensors[f"{pre}.self_attn.v_proj.weight"] = f32t(L["wv"][li])
        tensors[f"{pre}.self_attn.o_proj.weight"] = f32t(L["wo"][li])
        tensors[f"{pre}.post_attention_layernorm.weight"] = f32(L["mlp_norm"][li])
        tensors[f"{pre}.mlp.gate_proj.weight"] = f32t(L["w_gate"][li])
        tensors[f"{pre}.mlp.up_proj.weight"] = f32t(L["w_up"][li])
        tensors[f"{pre}.mlp.down_proj.weight"] = f32t(L["w_down"][li])
    tensors["model.norm.weight"] = f32(params["final_norm"])
    if params.get("lm_head") is not None:
        tensors["lm_head.weight"] = f32t(params["lm_head"])
    save_file(tensors, os.path.join(out_dir, "model.safetensors"))


# --------------------------------------------------------------------- #
# GGUF checkpoints (llama.cpp naming) — reference parity note: the
# reference only reads GGUF *metadata* and delegates tensor serving to
# llamacpp (lib/llm/src/gguf/); here the tensors load straight into the
# JAX engine (llm/gguf.py load_tensor: f32 / f16 / q8_0).
# --------------------------------------------------------------------- #

def _find_gguf(path_or_repo: str):
    """The .gguf file a path denotes, or None for the safetensors branch."""
    p = Path(os.path.expanduser(str(path_or_repo)))
    if p.suffix == ".gguf" and p.exists():
        return str(p)
    if p.is_dir():
        ggufs = sorted(p.glob("*.gguf"))
        if len(ggufs) == 1 and not (p / "model.safetensors.index.json").exists() \
                and not list(p.glob("*.safetensors")):
            return str(ggufs[0])
    return None


_GGUF_LAYER_MAP = {
    # gguf name suffix -> (tree key, transpose). "transpose" swaps the
    # LAST TWO axes: gguf stores each (expert's) matrix [out, in], our
    # trees contract x @ W with [in, out].
    "attn_norm.weight": ("attn_norm", False),
    "attn_q.weight": ("wq", True),
    "attn_k.weight": ("wk", True),
    "attn_v.weight": ("wv", True),
    "attn_output.weight": ("wo", True),
    "ffn_norm.weight": ("mlp_norm", False),
    "ffn_gate.weight": ("w_gate", True),
    "ffn_up.weight": ("w_up", True),
    "ffn_down.weight": ("w_down", True),
}

# MoE ggufs (llama.cpp naming): stacked expert tensors + the router
_GGUF_MOE_LAYER_MAP = {
    "attn_norm.weight": ("attn_norm", False),
    "attn_q.weight": ("wq", True),
    "attn_k.weight": ("wk", True),
    "attn_v.weight": ("wv", True),
    "attn_output.weight": ("wo", True),
    "ffn_norm.weight": ("mlp_norm", False),
    "ffn_gate_inp.weight": ("router", True),
    "ffn_gate_exps.weight": ("w_gate", True),
    "ffn_up_exps.weight": ("w_up", True),
    "ffn_down_exps.weight": ("w_down", True),
}


def config_from_gguf(path_or_content):
    """LlamaConfig derived from a .gguf file's metadata + tensor shapes
    (the checkpoint is authoritative; no registry entry needed). Accepts
    a path or an already-parsed GgufContent (tokenizer-bearing metadata
    takes seconds to parse — don't parse twice)."""
    from ..llm.gguf import GgufContent, read_gguf

    g = (
        path_or_content
        if isinstance(path_or_content, GgufContent)
        else read_gguf(path_or_content, with_tensors=True)
    )
    from .llama import LlamaConfig

    emb = g.tensors.get("token_embd.weight")
    if emb is None:
        raise ValueError(f"{g.path}: no token_embd.weight tensor")
    vocab, hidden = emb.shape
    is_moe = "blk.0.ffn_gate_inp.weight" in g.tensors
    # critical geometry must COME FROM the file: silently defaulting
    # layers/heads would serve a truncated model as garbage tokens
    if not g.num_layers or not g.num_heads:
        raise ValueError(
            f"{g.path}: missing {g.architecture or '?'}.block_count / "
            f".attention.head_count metadata (architecture key "
            f"{g.metadata.get('general.architecture')!r})"
        )
    heads = int(g.num_heads)
    meta = g.metadata
    arch = g.architecture or "llama"
    gate = g.tensors.get(
        "blk.0.ffn_gate_exps.weight" if is_moe else "blk.0.ffn_gate.weight"
    )
    inter = (
        int(gate.shape[-2]) if is_moe and gate is not None
        else int(gate.shape[0]) if gate is not None
        else 4 * hidden
    )
    cls, extra = LlamaConfig, {}
    if is_moe:
        from .moe import MoeConfig

        n_exp = meta.get(f"{arch}.expert_count")
        n_used = meta.get(f"{arch}.expert_used_count")
        if not n_exp or not n_used:
            # silently defaulting top-k would route the wrong number of
            # experts and degrade output with no error anywhere
            raise ValueError(
                f"{g.path}: MoE gguf missing {arch}.expert_count / "
                f".expert_used_count metadata"
            )
        cls = MoeConfig
        extra = dict(
            num_experts=int(n_exp), num_experts_per_tok=int(n_used)
        )
    return cls(
        **extra,
        vocab_size=int(vocab),
        hidden_size=int(hidden),
        intermediate_size=inter,
        num_layers=int(g.num_layers),
        num_heads=heads,
        num_kv_heads=int(g.num_kv_heads or heads),
        head_dim=int(
            meta.get(f"{arch}.attention.key_length", hidden // heads)
        ),
        rope_theta=float(meta.get(f"{arch}.rope.freq_base", 10000.0)),
        rms_norm_eps=float(
            meta.get(f"{arch}.attention.layer_norm_rms_epsilon", 1e-5)
        ),
        max_position=int(g.context_length or 8192),
        tie_embeddings="output.weight" not in g.tensors,
    )


def load_llama_params_gguf(
    path,
    config=None,
    shardings: Optional[dict] = None,
    quantize: Optional[str] = None,
) -> Dict[str, Any]:
    """Load a .gguf llama-family checkpoint into the models/llama.py tree.
    Tensors dequantize to f32 on read (q8_0 included), then cast to the
    model dtype — or requantize per-out-channel when quantize="int8"
    (GGUF's per-32-group q8_0 granularity differs from the engine's
    per-channel scheme, so int8 serving goes through a requantize)."""
    from ..llm.gguf import load_tensor, read_gguf
    from .quant import quantize_array

    from .moe import MoeConfig

    g = read_gguf(path, with_tensors=True)
    c = config or config_from_gguf(g)
    is_moe = isinstance(c, MoeConfig)
    layer_map = _GGUF_MOE_LAYER_MAP if is_moe else _GGUF_LAYER_MAP
    sh = shardings or {}

    def place(arr, sharding, *, quant, contract_axis=-2):
        if quantize == "int8" and quant:
            return _place_quant(
                quantize_array(arr, contract_axis=contract_axis), sharding
            )
        return _place(arr, c.dtype, sharding)

    # one pre-sized buffer per layer-stacked leaf; only ONE layer's f32
    # tensor is transient at a time (the safetensors path's
    # _place_stacked/_stacked_quant discipline — a 70B q8_0 gguf must not
    # materialize ~280 GB of f32 lists)
    target = _np_dtype(c.dtype)
    layer_sh = sh.get("layers", {}) if sh else {}
    layers: Dict[str, Any] = {}
    for suffix, (key, transpose) in layer_map.items():
        info = g.tensors[f"blk.0.{suffix}"]
        lshape = (
            (*info.shape[:-2], info.shape[-1], info.shape[-2])
            if transpose else info.shape
        )
        # router stays f32 (numerically sensitive), norms keep dtype
        do_quant = quantize == "int8" and key not in (
            "attn_norm", "mlp_norm", "router"
        )
        if do_quant:
            q_buf = np.empty((c.num_layers, *lshape), np.int8)
            s_buf = np.empty((c.num_layers, *lshape[:-2], 1, lshape[-1]),
                             np.float32)
            for li in range(c.num_layers):
                arr = load_tensor(g, f"blk.{li}.{suffix}")
                ql = quantize_array(
                    np.swapaxes(arr, -1, -2) if transpose else arr
                )
                q_buf[li], s_buf[li] = ql["q"], ql["s"]
            layers[key] = _place_quant(
                {"q": q_buf, "s": s_buf}, layer_sh.get(key)
            )
        else:
            leaf_dtype = np.float32 if key == "router" else target
            buf = np.empty((c.num_layers, *lshape), leaf_dtype)
            for li in range(c.num_layers):
                arr = load_tensor(g, f"blk.{li}.{suffix}")
                buf[li] = np.swapaxes(arr, -1, -2) if transpose else arr
            layers[key] = _place(buf, leaf_dtype, layer_sh.get(key))

    params: Dict[str, Any] = {
        "layers": layers,
        "embed": place(
            load_tensor(g, "token_embd.weight"), sh.get("embed"),
            quant=True, contract_axis=-1,
        ),
        "final_norm": _place(
            load_tensor(g, "output_norm.weight"), c.dtype, sh.get("final_norm")
        ),
    }
    if "output.weight" in g.tensors and not c.tie_embeddings:
        params["lm_head"] = place(
            load_tensor(g, "output.weight").T, sh.get("lm_head"), quant=True
        )
    else:
        params["lm_head"] = None
    return params
