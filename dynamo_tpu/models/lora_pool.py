"""LoraPool: adapter paging at fleet scale — a KVBM-style HBM↔host tier
for LoRA stacks (docs/multi_lora.md "Adapter tier").

The stacked-adapter layout (models/lora.py stack_adapters) keeps every
adapter resident in HBM as one [N, L, in, r] / [N, L, r, out] pair per
target. That is the right shape for a handful of adapters, but a fleet
tenant roster (RTP-LLM serves thousands) cannot all live on device. The
pool keeps the DEVICE stack at a FIXED slot count (``DYN_LORA_POOL_SLOTS``
+ the always-zero base slot 0 — fixed N means adapter churn never changes
an operand shape, so onboarding never recompiles a dispatch variant) and
pages adapter weights between the host registry and device slots on
demand, pricing faults and latency exactly like KV onboarding
(kvbm/manager.py):

  * LRU eviction over UNPINNED slots only — a slot pins its adapter for
    the life of every stream using it, so eviction can never corrupt an
    in-flight sequence;
  * bounded refuse-newest: when every device slot is pinned, a cold
    acquire refuses (typed LoraPoolError, counted) instead of queueing
    unboundedly — the caller surfaces a clean rejection, never a silent
    base-model answer;
  * per-onboard latency EWMA (``estimate_onboard_ms``) so admission can
    price a cold adapter switch the way KVBM prices a tier load;
  * chaos: the ``lora.onboard`` fault point (runtime/faults.py) bites at
    the host→device copy — `error` refuses the acquire (counted),
    `delay` stretches it; either way the stream is rejected or late,
    never corrupt.

Counter surface (engine stats()/prometheus via runtime/metrics.py):
lora_pool_hits / lora_pool_misses / lora_pool_evictions /
lora_pool_refusals / lora_pool_onboard_ms.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from . import lora as lora_mod


class LoraPoolError(ValueError):
    """Typed adapter-tier refusal (unknown adapter, pinned-full pool, or
    an injected onboard fault) — callers reject the request up front."""


class LoraPool:
    """Fixed-slot device stack + host adapter registry.

    `stack` is the engine-facing dict ({"a", "b", "scale", "names"}) with
    the SAME structure stack_adapters returns; the pool mutates it in
    place on onboard/evict so the engine's `self._lora` reference stays
    live. `names` maps RESIDENT adapter name → device slot index; the
    full roster is `known_names()`."""

    def __init__(self, model_config, adapters, slots: int = 8,
                 dtype=None):
        self.model_config = model_config
        self.slots = max(1, int(slots))
        self._host: "OrderedDict[str, lora_mod.LoraAdapter]" = OrderedDict()
        self._resident: Dict[str, int] = {}  # name -> device slot (1..slots)
        self._pins: Dict[str, int] = {}  # name -> live-stream refcount
        self._lru: "OrderedDict[str, None]" = OrderedDict()  # resident order
        self._free: List[int] = list(range(1, self.slots + 1))
        # counters (engine stats() republishes as lora_pool_*)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.refusals = 0
        self.onboard_ms_sum = 0.0
        self.onboard_count = 0
        self._onboard_ewma_ms: Optional[float] = None
        self._r_max = 1
        self.stack = None
        self.register(adapters)

    # -- registry -------------------------------------------------------- #

    def register(self, adapters) -> None:
        """Append adapters to the host roster (idempotent per name). The
        device stack is (re)built only when the max rank grows — otherwise
        new adapters just become onboardable; the first `slots` names are
        onboarded eagerly so the pre-pool single-stack behavior (every
        registered adapter immediately servable, warmup compiles with a
        real adapter) is preserved for small rosters."""
        for ad in adapters:
            self._host[ad.name] = ad
        r_max = max([a.rank for a in self._host.values()], default=1)
        if self.stack is None or r_max > self._r_max:
            self._r_max = r_max
            self._rebuild_stack()
        for name in list(self._host):
            if len(self._resident) >= self.slots:
                break
            if name not in self._resident:
                self._onboard(name)

    def _rebuild_stack(self) -> None:
        """Fixed-N device stack: slot 0 is the all-zero base adapter,
        slots 1..S page. Rebuilding re-onboards whatever was resident."""
        c = self.model_config
        dims = lora_mod.target_dims(c)
        N = self.slots + 1
        stack = {"a": {}, "b": {}}
        for t in lora_mod.TARGETS:
            din, dout = dims[t]
            stack["a"][t] = jnp.zeros(
                (N, c.num_layers, din, self._r_max), c.dtype
            )
            stack["b"][t] = jnp.zeros(
                (N, c.num_layers, self._r_max, dout), c.dtype
            )
        stack["scale"] = jnp.ones((N,), jnp.float32)
        stack["names"] = {}
        was_resident = list(self._resident)
        self._resident = {}
        self._lru = OrderedDict()
        self._free = list(range(1, self.slots + 1))
        if self.stack is None:
            self.stack = stack
        else:
            self.stack.update(stack)
            self.stack["names"].clear()
        for name in was_resident:
            self._onboard(name)

    def known_names(self) -> List[str]:
        return list(self._host)

    # -- paging ---------------------------------------------------------- #

    def _onboard(self, name: str) -> int:
        """Copy one host adapter into a free device slot (faults priced
        like kvbm.onboard). Raises LoraPoolError on an injected `error`."""
        from ..runtime import faults

        ad = self._host[name]
        f = faults.FAULTS
        if f.enabled:
            act = f.check("lora.onboard")
            if act == "error":
                self.refusals += 1
                raise LoraPoolError(
                    f"adapter {name!r} onboard failed (injected); retry or "
                    "route to a replica with the adapter resident"
                )
            if act == "delay":
                time.sleep(0.05)
        t0 = time.perf_counter()
        slot = self._free.pop()
        dims = lora_mod.target_dims(self.model_config)
        L = self.model_config.num_layers
        for t in lora_mod.TARGETS:
            din, dout = dims[t]
            A = np.zeros((L, din, self._r_max), np.float32)
            B = np.zeros((L, self._r_max, dout), np.float32)
            if t in ad.a:
                A[:, :, : ad.rank] = np.asarray(ad.a[t], np.float32)
                B[:, : ad.rank, :] = np.asarray(ad.b[t], np.float32)
            self.stack["a"][t] = self.stack["a"][t].at[slot].set(
                jnp.asarray(A, self.model_config.dtype)
            )
            self.stack["b"][t] = self.stack["b"][t].at[slot].set(
                jnp.asarray(B, self.model_config.dtype)
            )
        self.stack["scale"] = self.stack["scale"].at[slot].set(ad.scale)
        self._resident[name] = slot
        self._lru[name] = None
        self.stack["names"][name] = slot
        ms = (time.perf_counter() - t0) * 1000.0
        self.onboard_ms_sum += ms
        self.onboard_count += 1
        self._onboard_ewma_ms = (
            ms if self._onboard_ewma_ms is None
            else 0.8 * self._onboard_ewma_ms + 0.2 * ms
        )
        return slot

    def _evict_one(self) -> bool:
        """Drop the least-recently-used UNPINNED resident adapter."""
        for name in list(self._lru):
            if self._pins.get(name, 0) > 0:
                continue
            slot = self._resident.pop(name)
            self._lru.pop(name)
            self.stack["names"].pop(name, None)
            self._free.append(slot)
            self.evictions += 1
            return True
        return False

    def acquire(self, name: str) -> int:
        """Resolve `name` to its device slot, onboarding on a miss, and
        pin it for one live stream (release() per acquire). Hot switch is
        a dict lookup — cost ≈ 0; cold switch pays one bounded onboard."""
        if name not in self._host:
            raise LoraPoolError(
                f"unknown LoRA adapter {name!r}; available: "
                f"{sorted(self._host)}"
            )
        slot = self._resident.get(name)
        if slot is not None:
            self.hits += 1
        else:
            self.misses += 1
            if not self._free and not self._evict_one():
                self.refusals += 1
                raise LoraPoolError(
                    f"adapter pool full ({self.slots} slots, all pinned by "
                    f"live streams); retry adapter {name!r} later"
                )
            slot = self._onboard(name)
        self._lru.move_to_end(name)
        self._pins[name] = self._pins.get(name, 0) + 1
        return slot

    def release(self, name: str) -> None:
        n = self._pins.get(name, 0)
        if n <= 1:
            self._pins.pop(name, None)
        else:
            self._pins[name] = n - 1

    def estimate_onboard_ms(self) -> Optional[float]:
        """Projected cold-switch cost (EWMA; None until first observed —
        a cold pool never defers, same rule as kvbm tiers)."""
        return self._onboard_ewma_ms

    def stats(self) -> dict:
        out = {
            "lora_pool_slots": self.slots,
            "lora_pool_resident": len(self._resident),
            "lora_pool_known": len(self._host),
            "lora_pool_hits": self.hits,
            "lora_pool_misses": self.misses,
            "lora_pool_evictions": self.evictions,
            "lora_pool_refusals": self.refusals,
            "lora_pool_onboard_ms": round(self.onboard_ms_sum, 3),
            "lora_pool_onboard_count": self.onboard_count,
        }
        if self._onboard_ewma_ms is not None:
            out["lora_pool_onboard_ewma_ms"] = round(self._onboard_ewma_ms, 3)
        return out
