"""Mixtral-family sparse-MoE model: llama attention + top-k expert MLP.

The reference serves wide-EP MoE models (DeepSeek-R1 recipe,
recipes/deepseek-r1/sglang-wideep/tep16p-dep16d-disagg.yaml: --ep-size 16)
by delegating to SGLang; here expert parallelism is native (SURVEY.md §2.5
row "Expert parallel (EP / wide-EP)"): experts live on the ``ep`` mesh axis
and tokens are dispatched GShard-style — a capacity-bounded one-hot
dispatch einsum whose [E, C, H] intermediate is sharding-constrained to
P("ep"), so GSPMD lowers the token shuffle to an all-to-all over ICI
instead of gather/scatter (the canonical TPU MoE pattern; see PAPERS.md).

Everything is static-shaped: top-k routing, cumsum slotting, and the expert
FFN batched over the expert dim on the MXU.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import EP_AXIS, SP_AXIS
from . import llama
from .llama import LlamaConfig, rms_norm
from .quant import qeinsum


@dataclass(frozen=True)
class MoeConfig(LlamaConfig):
    num_experts: int = 8
    num_experts_per_tok: int = 2
    capacity_factor: float = 1.25

    @classmethod
    def mixtral_8x7b(cls, **overrides):
        return cls(
            vocab_size=32000,
            hidden_size=4096,
            intermediate_size=14336,
            num_layers=32,
            num_heads=32,
            num_kv_heads=8,
            head_dim=128,
            rope_theta=1e6,
            num_experts=8,
            num_experts_per_tok=2,
            **overrides,
        )

    @classmethod
    def gptoss_120b(cls, **overrides):
        """gpt-oss-120b-shaped wide-MoE config (public architecture: 36
        layers, 128 experts top-4, ~5B active params; reference recipe
        recipes/gpt-oss-120b/trtllm/agg). Attention here is GQA (the
        repo's attention stack) at matching head geometry."""
        kw = dict(
            vocab_size=201088,
            hidden_size=2880,
            intermediate_size=2880,
            num_layers=36,
            num_heads=64,
            num_kv_heads=8,
            head_dim=64,
            rope_theta=150e3,
            num_experts=128,
            num_experts_per_tok=4,
        )
        kw.update(overrides)
        return cls(**kw)

    @classmethod
    def tiny_moe(cls, **overrides):
        kw = dict(
            vocab_size=512,
            hidden_size=64,
            intermediate_size=96,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            head_dim=16,
            max_position=512,
            num_experts=4,
            num_experts_per_tok=2,
        )
        kw.update(overrides)
        return cls(**kw)


def init_params(config: MoeConfig, key: jax.Array) -> Dict[str, Any]:
    c = config
    k_embed, k_layers, k_out = jax.random.split(key, 3)
    scale = 0.02

    def dense(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(c.dtype)

    layers = []
    keys = jax.random.split(k_layers, c.num_layers)
    q_dim = c.num_heads * c.head_dim
    kv_dim = c.num_kv_heads * c.head_dim
    E, I = c.num_experts, c.intermediate_size
    for lk in keys:
        k1, k2, k3, k4, k5, k6, k7, k8 = jax.random.split(lk, 8)
        layers.append(
            {
                "attn_norm": jnp.ones((c.hidden_size,), c.dtype),
                "wq": dense(k1, (c.hidden_size, q_dim)),
                "wk": dense(k2, (c.hidden_size, kv_dim)),
                "wv": dense(k3, (c.hidden_size, kv_dim)),
                "wo": dense(k4, (q_dim, c.hidden_size)),
                "mlp_norm": jnp.ones((c.hidden_size,), c.dtype),
                # router kept f32: tiny, and routing decisions are
                # numerically sensitive
                "router": jax.random.normal(k5, (c.hidden_size, E), jnp.float32)
                * scale,
                "w_gate": dense(k6, (E, c.hidden_size, I)),
                "w_up": dense(k7, (E, c.hidden_size, I)),
                "w_down": dense(k8, (E, I, c.hidden_size)),
            }
        )
    params = {
        "embed": dense(k_embed, (c.vocab_size, c.hidden_size)),
        "layers": jax.tree.map(lambda *xs: jnp.stack(xs), *layers),
        "final_norm": jnp.ones((c.hidden_size,), c.dtype),
        "lm_head": None if c.tie_embeddings else dense(k_out, (c.hidden_size, c.vocab_size)),
    }
    return params


def _constrain_ep(x: jax.Array) -> jax.Array:
    """Pin the expert dim (axis 0) to the ``ep`` mesh axis so GSPMD lowers
    dispatch/combine to an all-to-all. No-op when no mesh with an ``ep``
    axis is in context (single-chip, CPU tests)."""
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or EP_AXIS not in mesh.axis_names:
        return x
    return jax.lax.with_sharding_constraint(
        x, P(EP_AXIS, *([None] * (x.ndim - 1)))
    )


def expert_capacity(num_tokens: int, config: MoeConfig) -> int:
    """Static per-expert token capacity (round up to a multiple of 4 so the
    C dim tiles)."""
    c = math.ceil(
        num_tokens * config.num_experts_per_tok / config.num_experts
        * config.capacity_factor
    )
    return max(4, (c + 3) // 4 * 4)


def moe_mlp(layer: Dict[str, Any], x: jax.Array, c: MoeConfig) -> jax.Array:
    """Sparse MoE block for x [T, H]: top-k routing -> capacity-bounded
    one-hot dispatch -> batched expert SwiGLU -> weighted combine."""
    T, H = x.shape
    E, K = c.num_experts, c.num_experts_per_tok
    C = expert_capacity(T, c)

    h = rms_norm(x, layer["mlp_norm"], c.rms_norm_eps)
    logits = jnp.dot(h.astype(jnp.float32), layer["router"])  # [T, E]
    topv, topi = jax.lax.top_k(logits, K)  # [T, K]
    probs = jax.nn.softmax(topv, axis=-1)  # renormalized over chosen experts

    # combine weight per (token, expert); 0 where not routed
    combine = jnp.zeros((T, E), jnp.float32)
    combine = combine.at[jnp.arange(T)[:, None], topi].add(probs)
    routed = combine > 0.0  # [T, E]

    # slot within expert buffer: tokens claim slots in order; overflow drops
    pos = jnp.cumsum(routed.astype(jnp.int32), axis=0) - 1  # [T, E]
    keep = routed & (pos < C)
    dispatch = (
        jax.nn.one_hot(jnp.where(keep, pos, C), C, dtype=h.dtype)
        * keep[..., None]
    )  # [T, E, C]

    expert_in = _constrain_ep(jnp.einsum("tec,th->ech", dispatch, h))
    # qeinsum: expert stacks may be int8 (models/quant.py) — scale
    # [E, 1, out] applies to the f32 accumulator after the einsum
    gate = qeinsum("ech,ehi->eci", expert_in, layer["w_gate"])
    up = qeinsum("ech,ehi->eci", expert_in, layer["w_up"])
    act = (jax.nn.silu(gate) * up).astype(c.dtype)
    expert_out = _constrain_ep(
        qeinsum("eci,eih->ech", act, layer["w_down"])
    )

    out = jnp.einsum(
        "ech,tec->th", expert_out, dispatch.astype(jnp.float32) * combine[..., None]
    )
    return x + out.astype(c.dtype)


def decode_forward(
    params: Dict[str, Any],
    config: MoeConfig,
    tokens: jax.Array,  # [B]
    positions: jax.Array,  # [B]
    kv_k: jax.Array,
    kv_v: jax.Array,
    page_tables: jax.Array,  # [B, max_pages]
    seq_lens: jax.Array,  # [B]
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step for the slot batch; llama attention path with the
    sparse-MoE MLP swapped in. Returns (logits [B, vocab], kv)."""
    return llama.decode_forward(
        params, config, tokens, positions, kv_k, kv_v, page_tables, seq_lens,
        mlp_fn=moe_mlp,
    )


def decode_forward_pp(params, config, tokens, positions, kv_k, kv_v,
                      page_tables, seq_lens, mesh, num_microbatches=0):
    """Pipelined decode step (layers over pp), MoE MLP."""
    return llama.decode_forward_pp(
        params, config, tokens, positions, kv_k, kv_v, page_tables, seq_lens,
        mesh, num_microbatches=num_microbatches, mlp_fn=moe_mlp,
    )


def prefill_forward_pp(params, config, tokens, kv_k, kv_v, page_table,
                       context_len, real_len, mesh, num_microbatches=0):
    """Pipelined single-sequence prefill, MoE MLP."""
    return llama.prefill_forward_pp(
        params, config, tokens, kv_k, kv_v, page_table, context_len, real_len,
        mesh, num_microbatches=num_microbatches, mlp_fn=moe_mlp,
    )


def prefill_forward_ring(params, config, tokens, kv_k, kv_v, page_table,
                         real_len, mesh, axis_name=SP_AXIS):
    """Ring-attention whole-prompt prefill (sequence over sp), MoE MLP."""
    return llama.prefill_forward_ring(
        params, config, tokens, kv_k, kv_v, page_table, real_len, mesh,
        axis_name=axis_name, mlp_fn=moe_mlp,
    )


def decode_forward_local(
    params: Dict[str, Any],
    config: MoeConfig,
    tokens: jax.Array,
    positions: jax.Array,
    loc_k: jax.Array,
    loc_v: jax.Array,
    step_idx: jax.Array,
    kv_k: jax.Array,
    kv_v: jax.Array,
    page_tables: jax.Array,
    pool_lens: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Pool-read-only decode step (block-local KV accumulation), MoE MLP."""
    return llama.decode_forward_local(
        params, config, tokens, positions, loc_k, loc_v, step_idx,
        kv_k, kv_v, page_tables, pool_lens, mlp_fn=moe_mlp,
    )


def prefill_forward(
    params: Dict[str, Any],
    config: MoeConfig,
    tokens: jax.Array,  # [chunk]
    positions: jax.Array,
    kv_k: jax.Array,
    kv_v: jax.Array,
    page_table: jax.Array,  # [max_pages]
    context_len: jax.Array,
    last_idx: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One prompt chunk of a single sequence (chunked prefill), MoE MLP."""
    return llama.prefill_forward(
        params, config, tokens, positions, kv_k, kv_v, page_table, context_len,
        last_idx=last_idx, mlp_fn=moe_mlp,
    )


def ragged_forward(
    params: Dict[str, Any],
    config: MoeConfig,
    tokens: jax.Array,  # [N] flat packed mixed prefill+decode buffer
    positions: jax.Array,  # [N]
    row_ids: jax.Array,  # [N]
    kv_k: jax.Array,
    kv_v: jax.Array,
    page_tables: jax.Array,  # [R, max_pages]
    row_starts: jax.Array,  # [R]
    row_lens: jax.Array,  # [R]
    ctx_lens: jax.Array,  # [R]
    last_flat: jax.Array,  # [R]
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Unified mixed-step forward (engine `_dispatch_mixed`), MoE MLP —
    the flat buffer is already [tokens, H], exactly the shape expert
    dispatch wants."""
    return llama.ragged_forward(
        params, config, tokens, positions, row_ids, kv_k, kv_v,
        page_tables, row_starts, row_lens, ctx_lens, last_flat,
        mlp_fn=moe_mlp,
    )


def _moe_mlp_nd(layer, x, c):
    """moe_mlp over [B, T, H] (batched prefill flattens the token dims —
    expert dispatch is position-independent)."""
    if x.ndim == 3:
        B, T, H = x.shape
        return moe_mlp(layer, x.reshape(B * T, H), c).reshape(B, T, H)
    return moe_mlp(layer, x, c)


def prefill_forward_batched(
    params: Dict[str, Any],
    config: MoeConfig,
    tokens: jax.Array,  # [B, T]
    positions: jax.Array,  # [B, T]
    kv_k: jax.Array,
    kv_v: jax.Array,
    page_tables: jax.Array,  # [B, max_pages]
    context_lens: jax.Array,  # [B]
    last_idx: jax.Array,  # [B]
    emb_override: Optional[jax.Array] = None,
    emb_mask: Optional[jax.Array] = None,
    all_logits: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Batched chunked prefill (multiple sequences per dispatch), MoE MLP."""
    return llama.prefill_forward_batched(
        params, config, tokens, positions, kv_k, kv_v, page_tables,
        context_lens, last_idx, mlp_fn=_moe_mlp_nd,
        emb_override=emb_override, emb_mask=emb_mask, all_logits=all_logits,
    )
