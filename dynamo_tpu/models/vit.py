"""Vision Transformer encoder in JAX — the real E of multimodal E/P/D.

Replaces MockVisionEncoder behind the encode endpoint (llm/multimodal.py;
the mock stays for tests). Architecture matches HF `ViTModel` semantics
(CLS token, learned position embeddings, pre-LN blocks, GELU MLP) so
HF-exported checkpoints load directly — same param-loading discipline as
models/llama.py (random-init tree shape == checkpoint shape; the loader
maps safetensors/torch state dicts onto it). A LLaVA-style two-layer
projector maps patch tokens to the LLM hidden width for the engine's
prefill splice (engine/_prefill_batch_mm).

Reference parity: the trtllm multimodal processor runs the HF vision
tower on GPU (components/backends/trtllm/src/dynamo/trtllm/
multimodal_processor.py); here the tower is jitted JAX on the TPU's MXU
(patch embed as one big matmul, fused attention over a handful of
tokens).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_channels: int = 3
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    layer_norm_eps: float = 1e-12
    out_hidden: int = 768  # LLM hidden width the projector emits
    dtype: Any = jnp.float32

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def tiny(cls, **overrides):
        """CPU-test scale (mirrors LlamaConfig.tiny)."""
        kw = dict(
            image_size=32,
            patch_size=8,
            hidden_size=64,
            num_layers=2,
            num_heads=4,
            intermediate_size=128,
            out_hidden=64,
        )
        kw.update(overrides)
        return cls(**kw)


def _init_proj(config: ViTConfig, key: jax.Array) -> Dict[str, Any]:
    """Projector (LLaVA-style two-layer MLP) random init — shared by
    init_params and the HF-checkpoint loader (which random-inits the
    projector only when the export doesn't carry one)."""
    c = config
    k1, k2 = jax.random.split(key)
    return {
        "w1": (jax.random.normal(k1, (c.hidden_size, c.out_hidden),
                                 jnp.float32) * 0.02).astype(c.dtype),
        "b1": jnp.zeros((c.out_hidden,), c.dtype),
        "w2": (jax.random.normal(k2, (c.out_hidden, c.out_hidden),
                                 jnp.float32) * 0.02).astype(c.dtype),
        "b2": jnp.zeros((c.out_hidden,), c.dtype),
    }


def init_params(config: ViTConfig, key: jax.Array) -> Dict[str, Any]:
    """Random-init tree, shape-compatible with HF ViTModel weights
    (loader.load_vit_params maps checkpoints onto the same tree)."""
    c = config
    scale = 0.02
    ks = jax.random.split(key, 6 + c.num_layers)

    def dense(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(c.dtype)

    patch_dim = c.num_channels * c.patch_size * c.patch_size
    layers = []
    for lk in ks[6:]:
        k1, k2, k3, k4, k5, k6 = jax.random.split(lk, 6)
        layers.append({
            "ln1": {"w": jnp.ones((c.hidden_size,), c.dtype),
                    "b": jnp.zeros((c.hidden_size,), c.dtype)},
            "wq": dense(k1, (c.hidden_size, c.hidden_size)),
            "bq": jnp.zeros((c.hidden_size,), c.dtype),
            "wk": dense(k2, (c.hidden_size, c.hidden_size)),
            "bk": jnp.zeros((c.hidden_size,), c.dtype),
            "wv": dense(k3, (c.hidden_size, c.hidden_size)),
            "bv": jnp.zeros((c.hidden_size,), c.dtype),
            "wo": dense(k4, (c.hidden_size, c.hidden_size)),
            "bo": jnp.zeros((c.hidden_size,), c.dtype),
            "ln2": {"w": jnp.ones((c.hidden_size,), c.dtype),
                    "b": jnp.zeros((c.hidden_size,), c.dtype)},
            "w_up": dense(k5, (c.hidden_size, c.intermediate_size)),
            "b_up": jnp.zeros((c.intermediate_size,), c.dtype),
            "w_down": dense(k6, (c.intermediate_size, c.hidden_size)),
            "b_down": jnp.zeros((c.hidden_size,), c.dtype),
        })
    return {
        # patch embed: HF's Conv2d(stride=patch) == matmul over flattened
        # (C, ph, pw) patches — one MXU-shaped GEMM instead of a conv
        "patch_w": dense(ks[0], (patch_dim, c.hidden_size)),
        "patch_b": jnp.zeros((c.hidden_size,), c.dtype),
        "cls": dense(ks[1], (1, 1, c.hidden_size)),
        "pos": dense(ks[2], (1, c.n_patches + 1, c.hidden_size)),
        "layers": layers,
        "ln_f": {"w": jnp.ones((c.hidden_size,), c.dtype),
                 "b": jnp.zeros((c.hidden_size,), c.dtype)},
        # LLaVA-style projector to the LLM embedding width
        "proj": _init_proj(c, ks[3]),
    }


def _ln(x, p, eps):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * p["w"] + p["b"]


def forward(params: Dict[str, Any], config: ViTConfig, pixels: jax.Array) -> jax.Array:
    """pixels [B, C, H, W] (HF layout) → last hidden state
    [B, n_patches + 1, hidden] (CLS first), post final-LN — matches HF
    ViTModel.last_hidden_state."""
    c = config
    B = pixels.shape[0]
    P, nc = c.patch_size, c.num_channels
    n_side = c.image_size // P
    # [B, C, H, W] → [B, n_side, n_side, C, P, P] → [B, N, C*P*P]
    x = pixels.reshape(B, nc, n_side, P, n_side, P)
    x = x.transpose(0, 2, 4, 1, 3, 5).reshape(B, n_side * n_side, nc * P * P)
    x = x.astype(c.dtype) @ params["patch_w"] + params["patch_b"]
    cls = jnp.broadcast_to(params["cls"], (B, 1, c.hidden_size)).astype(c.dtype)
    x = jnp.concatenate([cls, x], axis=1) + params["pos"]

    H, D = c.num_heads, c.head_dim
    T = x.shape[1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, c.dtype))
    for lyr in params["layers"]:
        h = _ln(x, lyr["ln1"], c.layer_norm_eps)
        q = (h @ lyr["wq"] + lyr["bq"]).reshape(B, T, H, D)
        k = (h @ lyr["wk"] + lyr["bk"]).reshape(B, T, H, D)
        v = (h @ lyr["wv"] + lyr["bv"]).reshape(B, T, H, D)
        att = jnp.einsum("bthd,bshd->bhts", q, k) * scale
        att = jax.nn.softmax(att.astype(jnp.float32), axis=-1).astype(c.dtype)
        o = jnp.einsum("bhts,bshd->bthd", att, v).reshape(B, T, H * D)
        x = x + (o @ lyr["wo"] + lyr["bo"])
        h = _ln(x, lyr["ln2"], c.layer_norm_eps)
        h = jax.nn.gelu(h @ lyr["w_up"] + lyr["b_up"], approximate=False)
        x = x + (h @ lyr["w_down"] + lyr["b_down"])
    return _ln(x, params["ln_f"], c.layer_norm_eps)


def encode_tokens(params: Dict[str, Any], config: ViTConfig, pixels: jax.Array) -> jax.Array:
    """Full encoder: ViT → drop CLS → projector. [B, C, H, W] →
    [B, n_patches, out_hidden] — the rows the engine splices over
    placeholder positions."""
    h = forward(params, config, pixels)[:, 1:]
    p = params["proj"]
    h = jax.nn.gelu(h @ p["w1"] + p["b1"], approximate=False)
    return h @ p["w2"] + p["b2"]


# --------------------------------------------------------------------- #
# HF checkpoint mapping (loader discipline: models/loader.py)
# --------------------------------------------------------------------- #

def params_from_hf_state(state: Dict[str, np.ndarray], config: ViTConfig,
                         prefix: str = "") -> Dict[str, Any]:
    """Map an HF ViTModel state dict (torch tensors or numpy) onto the
    init_params tree. `prefix` handles nesting (e.g. "vit." for
    ViTForImageClassification exports). The projector is NOT part of HF
    ViT — absent keys leave it random-init (train/load separately)."""
    c = config

    def get(name):
        t = state[prefix + name]
        arr = t.numpy() if hasattr(t, "numpy") else np.asarray(t)
        return jnp.asarray(arr, c.dtype)

    conv_w = get("embeddings.patch_embeddings.projection.weight")
    # Conv2d [hidden, C, P, P] → matmul [(C, P, P) flat, hidden]; the
    # flatten order must match forward()'s (C, ph, pw) patch layout
    patch_w = jnp.transpose(conv_w.reshape(c.hidden_size, -1))
    params = {
        "patch_w": patch_w,
        "patch_b": get("embeddings.patch_embeddings.projection.bias"),
        "cls": get("embeddings.cls_token"),
        "pos": get("embeddings.position_embeddings"),
        "ln_f": {"w": get("layernorm.weight"), "b": get("layernorm.bias")},
        "layers": [],
    }
    for i in range(c.num_layers):
        p = f"encoder.layer.{i}."
        lin = lambda n: jnp.transpose(get(p + n + ".weight"))  # noqa: E731
        bias = lambda n: get(p + n + ".bias")  # noqa: E731
        params["layers"].append({
            "ln1": {"w": get(p + "layernorm_before.weight"),
                    "b": get(p + "layernorm_before.bias")},
            "wq": lin("attention.attention.query"),
            "bq": bias("attention.attention.query"),
            "wk": lin("attention.attention.key"),
            "bk": bias("attention.attention.key"),
            "wv": lin("attention.attention.value"),
            "bv": bias("attention.attention.value"),
            "wo": lin("attention.output.dense"),
            "bo": bias("attention.output.dense"),
            "ln2": {"w": get(p + "layernorm_after.weight"),
                    "b": get(p + "layernorm_after.bias")},
            "w_up": lin("intermediate.dense"),
            "b_up": bias("intermediate.dense"),
            "w_down": lin("output.dense"),
            "b_down": bias("output.dense"),
        })
    # projector: checkpoint-provided (LLaVA-style exports) or random —
    # only the 4 small proj arrays are generated, not a full init_params
    proj = _init_proj(c, jax.random.PRNGKey(0))
    for ours, theirs in (("w1", "proj.w1"), ("b1", "proj.b1"),
                         ("w2", "proj.w2"), ("b2", "proj.b2")):
        if prefix + theirs in state:
            proj[ours] = get(theirs)
    params["proj"] = proj
    return params


def load_vit_params(model_dir: str, config: ViTConfig) -> Dict[str, Any]:
    """Load an HF ViT export (safetensors or pytorch_model.bin) from a
    local directory — same resolve discipline as load_llama_params."""
    from pathlib import Path

    d = Path(model_dir)
    state: Dict[str, np.ndarray] = {}
    sts = sorted(d.glob("*.safetensors"))
    if sts:
        from safetensors.numpy import load_file

        for f in sts:
            state.update(load_file(str(f)))
    else:
        import torch

        bins = sorted(d.glob("*.bin"))
        if not bins:
            raise FileNotFoundError(f"no ViT weights under {model_dir}")
        for f in bins:
            state.update(torch.load(str(f), map_location="cpu",
                                    weights_only=True))
    prefix = "vit." if any(k.startswith("vit.") for k in state) else ""
    return params_from_hf_state(state, config, prefix=prefix)
