"""Multi-LoRA: per-request low-rank adapters served concurrently.

Reference surface: the block-hash contract carries `lora_id` so prefix
reuse is adapter-correct (lib/llm/src/kv_router/protocols.rs:110-115);
adapter execution itself lives in the reference's engines (vLLM
multi-LoRA). Here the native JAX engine owns it, TPU-first:

  * all adapters live STACKED in HBM: one [N, L, in, r] / [N, L, r, out]
    pair per target projection (index 0 is the all-zero "no adapter" —
    base-model lanes are exact no-ops, so mixed batches need no masking);
  * a per-lane adapter index gathers each lane's A/B at step time and the
    delta is two thin einsums fused into the projection — no weight
    swapping, no per-adapter dispatch;
  * KV separation comes from hashing, not copying: the adapter name salts
    the token block hashes (llm/tokens.py salt_hash), so the engine
    prefix cache, the KVBM registry, and the KV router all distinguish
    adapters automatically.

Checkpoint format: HF PEFT exports (adapter_model.safetensors +
adapter_config.json) with q/k/v/o_proj targets.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# target key → (per-layer input width fn, output width fn)
TARGETS = ("wq", "wk", "wv", "wo")
_PEFT_NAMES = {"q_proj": "wq", "k_proj": "wk", "v_proj": "wv", "o_proj": "wo"}


@dataclass
class LoraAdapter:
    """One adapter: per-target (A [L, in, r], B [L, r, out]) + scaling."""

    name: str
    rank: int
    scale: float  # alpha / rank
    a: Dict[str, jnp.ndarray] = field(default_factory=dict)
    b: Dict[str, jnp.ndarray] = field(default_factory=dict)


def target_dims(config) -> Dict[str, Tuple[int, int]]:
    """(in, out) widths per projection for a llama-family config."""
    q_dim = config.num_heads * config.head_dim
    kv_dim = config.num_kv_heads * config.head_dim
    H = config.hidden_size
    return {"wq": (H, q_dim), "wk": (H, kv_dim), "wv": (H, kv_dim),
            "wo": (q_dim, H)}


def init_adapter(config, name: str, key: jax.Array, rank: int = 8,
                 scale: float = 1.0,
                 targets: Sequence[str] = TARGETS) -> LoraAdapter:
    """Random adapter (tests / fine-tune init): A gaussian, B gaussian —
    a *non-zero* delta so serving tests can observe adapter effect."""
    c = config
    dims = target_dims(c)
    ad = LoraAdapter(name=name, rank=rank, scale=scale)
    keys = jax.random.split(key, 2 * len(targets))
    for i, t in enumerate(targets):
        din, dout = dims[t]
        ad.a[t] = (
            jax.random.normal(keys[2 * i], (c.num_layers, din, rank),
                              jnp.float32) * 0.05
        ).astype(c.dtype)
        ad.b[t] = (
            jax.random.normal(keys[2 * i + 1], (c.num_layers, rank, dout),
                              jnp.float32) * 0.05
        ).astype(c.dtype)
    return ad


def load_peft_adapter(path: str, config, name: str = None) -> LoraAdapter:
    """Load an HF PEFT export directory: adapter_config.json +
    adapter_model.safetensors (or .bin). PEFT stores per-layer
    lora_A.weight [r, in] / lora_B.weight [out, r]; delta = (alpha/r)·B@A.
    Same loader discipline as models/loader.py."""
    d = Path(path)
    cfg = json.loads((d / "adapter_config.json").read_text())
    rank = int(cfg.get("r", 8))
    alpha = float(cfg.get("lora_alpha", rank))
    state: Dict[str, np.ndarray] = {}
    st = d / "adapter_model.safetensors"
    if st.exists():
        from safetensors.numpy import load_file

        state = load_file(str(st))
    else:
        import torch

        bins = sorted(d.glob("adapter_model*.bin"))
        if not bins:
            raise FileNotFoundError(f"no adapter weights under {path}")
        state = {
            k: v.numpy()
            for k, v in torch.load(str(bins[0]), map_location="cpu",
                                   weights_only=True).items()
        }
    c = config
    ad = LoraAdapter(name=name or d.name, rank=rank, scale=alpha / rank)
    dims = target_dims(c)
    for peft_t, t in _PEFT_NAMES.items():
        a_rows, b_rows = [], []
        for li in range(c.num_layers):
            a_key = next(
                (k for k in state
                 if f"layers.{li}." in k and peft_t in k and "lora_A" in k),
                None,
            )
            if a_key is None:
                break
            b_key = a_key.replace("lora_A", "lora_B")
            # PEFT A [r, in] → ours [in, r]; B [out, r] → [r, out]
            a_rows.append(np.asarray(state[a_key]).T)
            b_rows.append(np.asarray(state[b_key]).T)
        if not a_rows:
            continue
        if len(a_rows) != c.num_layers:
            raise ValueError(
                f"adapter {ad.name!r}: target {peft_t} present for "
                f"{len(a_rows)}/{c.num_layers} layers"
            )
        din, dout = dims[t]
        a = np.stack(a_rows)
        b = np.stack(b_rows)
        if a.shape != (c.num_layers, din, rank) or b.shape != (
            c.num_layers, rank, dout
        ):
            raise ValueError(
                f"adapter {ad.name!r} target {t}: shapes {a.shape}/{b.shape} "
                f"do not match model dims ({din}/{dout}, r={rank})"
            )
        ad.a[t] = jnp.asarray(a, c.dtype)
        ad.b[t] = jnp.asarray(b, c.dtype)
    if not ad.a:
        raise ValueError(f"adapter {ad.name!r} has no supported targets")
    return ad


def stack_adapters(config, adapters: List[LoraAdapter]) -> Dict[str, Any]:
    """Adapters → the engine's device-resident stack. Index 0 is the
    all-zero base-model adapter; adapter i+1 = adapters[i]. All adapters
    are padded to the max rank (zero-padded ranks are exact no-ops).
    Returns {"a": {t: [N, L, in, r]}, "b": {t: [N, L, r, out]},
    "scale": [N] f32, "names": {name: idx}}."""
    c = config
    dims = target_dims(c)
    r_max = max([a.rank for a in adapters], default=1)
    N = len(adapters) + 1
    out_a, out_b = {}, {}
    for t in TARGETS:
        din, dout = dims[t]
        A = np.zeros((N, c.num_layers, din, r_max), np.float32)
        B = np.zeros((N, c.num_layers, r_max, dout), np.float32)
        for i, ad in enumerate(adapters):
            if t in ad.a:
                A[i + 1, :, :, : ad.rank] = np.asarray(
                    ad.a[t], np.float32
                )
                B[i + 1, :, : ad.rank, :] = np.asarray(
                    ad.b[t], np.float32
                )
        out_a[t] = jnp.asarray(A, c.dtype)
        out_b[t] = jnp.asarray(B, c.dtype)
    scale = np.ones((N,), np.float32)
    for i, ad in enumerate(adapters):
        scale[i + 1] = ad.scale
    return {
        "a": out_a,
        "b": out_b,
        "scale": jnp.asarray(scale),
        "names": {ad.name: i + 1 for i, ad in enumerate(adapters)},
    }


def lora_delta(h: jax.Array, stack_a: jax.Array, stack_b: jax.Array,
               idx: jax.Array, scale: jax.Array) -> jax.Array:
    """Per-lane low-rank delta for one layer's projection.

    h [B, in] or [B, T, in]; stack_a [N, in, r] (layer-sliced);
    stack_b [N, r, out]; idx [B] lane→adapter; scale [N].
    Returns scale[idx]·(h @ A[idx]) @ B[idx] — two thin matmuls whose
    FLOPs are r/out of the base projection."""
    A = stack_a[idx]  # [B, in, r]
    Bm = stack_b[idx]  # [B, r, out]
    s = scale[idx]
    if h.ndim == 2:
        d = jnp.einsum("bh,bhr->br", h, A)
        return (jnp.einsum("br,bro->bo", d, Bm) * s[:, None]).astype(h.dtype)
    d = jnp.einsum("bth,bhr->btr", h, A)
    return (
        jnp.einsum("btr,bro->bto", d, Bm) * s[:, None, None]
    ).astype(h.dtype)


def layer_lora(lora: Dict[str, Any], li: int):
    """Slice the stack to one layer: {t: ([N, in, r], [N, r, out])}."""
    if lora is None:
        return None
    return {
        "a": {t: v[:, li] for t, v in lora["a"].items()},
        "b": {t: v[:, li] for t, v in lora["b"].items()},
        "idx": lora["idx"],
        "scale": lora["scale"],
    }


def proj(h: jax.Array, w, qdot_fn, lora_layer, target: str) -> jax.Array:
    """Projection with optional per-lane LoRA delta (the hook llama.py's
    attention uses)."""
    y = qdot_fn(h, w)
    if lora_layer is not None and target in lora_layer["a"]:
        y = y + lora_delta(
            h, lora_layer["a"][target], lora_layer["b"][target],
            lora_layer["idx"], lora_layer["scale"],
        ).astype(y.dtype)
    return y
