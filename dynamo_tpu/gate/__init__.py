"""dynogate — frontend overload discipline (docs/overload.md).

Admission control at the HTTP edge (429 + Retry-After BEFORE
tokenization, driven by worker-published load signals), per-tenant
weighted fairness + token-bucket rate limits, and priority-aware load
shedding that keeps goodput flat as offered load passes capacity."""

from .config import GateConfig, parse_tenant_weights
from .fairness import TokenBucket, WfqEntry, WfqQueue
from .gate import AdmissionGate, GateDecision, retry_after_header
from .signals import InstanceLoad, LoadSignals

__all__ = [
    "AdmissionGate",
    "GateConfig",
    "GateDecision",
    "InstanceLoad",
    "LoadSignals",
    "TokenBucket",
    "WfqEntry",
    "WfqQueue",
    "parse_tenant_weights",
    "retry_after_header",
]
