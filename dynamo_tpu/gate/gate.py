"""dynogate: admission control, per-tenant fairness, and load shedding.

The overload discipline the frontend applies BEFORE tokenizing
(docs/overload.md; ROADMAP item 4 / FlexNPU & Nexus degraded-mode
framing): offered load past capacity is refused with HTTP 429 +
`Retry-After` instead of collapsing into convoy timeouts, one noisy
tenant cannot starve the rest, and when admitted load still passes
capacity the LOWEST SLA class sheds first — cleanly, from the gate
queue, never mid-stream.

Decision flow per request (``admit``):

  1. dynochaos `gate.admit` fault point (`reject` forces a 429).
  2. Per-tenant token bucket (`DYN_GATE_TENANT_RATE`): a tenant past its
     rate is told exactly when its bucket refills.
  3. Load check against the worker-published signals (signals.py): when
     the best ready instance's projected TTFT fits the request's SLA
     class headroom (class = nvext.priority, each +1 halves the target —
     the SlaConfig math), the request is admitted. Unknown signals admit.
  4. Otherwise the request waits in a weighted-fair queue (WFQ virtual
     time per tenant) for capacity, bounded by
     min(DYN_GATE_MAX_WAIT_MS, class headroom); the pump re-evaluates as
     signals refresh. Past the bound — or past DYN_GATE_MAX_QUEUE — it
     is SHED: lowest class first, newest first within a class.

All queue/virtual-time state is confined to the single `_pump` task
(GUARDED_STATE); `admit` only appends to an inbox queue and awaits its
entry's future, so admission decisions are serialized and untorn.
"""

from __future__ import annotations

import asyncio
import logging
import math
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from ..runtime import faults
from ..runtime.metrics import metric_spec
from .config import GateConfig
from .fairness import TokenBucket, WfqQueue
from .signals import LoadSignals

logger = logging.getLogger(__name__)

#: cap on any Retry-After the gate hands out (s): past this the estimate
#: is noise, and well-behaved clients should re-probe anyway
RETRY_AFTER_CAP_S = 30.0

#: cardinality bound on per-tenant accounting: the tenant key is a
#: client-controlled header, so without a cap a unique-tenant flood grows
#: counters/buckets/metric output without bound — the overflow tenant
#: absorbs everything past it
MAX_TRACKED_TENANTS = 1024
OVERFLOW_TENANT = "~other"


def _prom_label(value: str) -> str:
    """Prometheus label-value escaping (backslash, quote, newline): the
    tenant label is raw client input and must not be able to corrupt the
    /metrics exposition."""
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


@dataclass
class GateDecision:
    """The admission verdict the HTTP layer turns into 200-path or 429."""

    admitted: bool
    reason: str = "admitted"  # rate-limited | overloaded | shed | fault
    retry_after_s: float = 0.0
    projected_ttft_ms: Optional[float] = None
    queued_ms: float = 0.0


@dataclass
class _Pending:
    """Inbox payload: one request awaiting a pump decision."""

    model: str
    tenant: str
    priority: int
    enq_s: float
    counted: bool = False  # already counted in gate_parked_total
    fut: asyncio.Future = field(
        default_factory=lambda: asyncio.get_running_loop().create_future()
    )


class AdmissionGate:
    """One per frontend process. ``start()`` spawns the pump; models are
    registered by the ModelWatcher via ``track_model``."""

    def __init__(self, drt, config: Optional[GateConfig] = None):
        self.config = config or GateConfig.from_env()
        self.signals = LoadSignals(drt, self.config)
        self._inbox: asyncio.Queue = asyncio.Queue()
        self._waiting = WfqQueue(weight_of=self.config.weight)
        self._buckets: Dict[str, TokenBucket] = {}
        self._pump_task: Optional[asyncio.Task] = None
        self._closed = False
        # optimism debt: admissions since the model's last signal refresh
        # (each one pushes the true projected TTFT past the published
        # number until the next 0.25s publish lands)
        self._debt: Dict[str, int] = {}
        self._debt_seen: Dict[str, float] = {}
        # counters (monotonic; stats() + the frontend /metrics surface)
        self.admitted_total = 0
        self.rejected_total = 0
        self.shed_total = 0
        self.queued_total = 0
        self.rejected_by_reason: Dict[str, int] = {}
        self.per_tenant: Dict[str, Dict[str, int]] = {}
        self.retry_after_hist: Dict[str, int] = {
            "le_1s": 0, "le_2s": 0, "le_5s": 0, "le_10s": 0, "inf": 0,
        }

    # -- lifecycle -------------------------------------------------------- #

    async def start(self) -> "AdmissionGate":
        if self._pump_task is None:
            self._pump_task = asyncio.create_task(self._pump())
        return self

    async def close(self) -> None:
        self._closed = True
        if self._pump_task is not None:
            self._pump_task.cancel()
            self._pump_task = None
        # a shutdown must not 429 requests that were admissible: resolve
        # every parked entry as admitted and let the drain path finish them
        for entry in self._waiting.drain():
            pend: _Pending = entry.payload
            if not pend.fut.done():
                pend.fut.set_result(GateDecision(admitted=True))
        while not self._inbox.empty():
            pend = self._inbox.get_nowait()
            if not pend.fut.done():
                pend.fut.set_result(GateDecision(admitted=True))
        await self.signals.close()

    async def track_model(self, model: str, namespace: str, component: str,
                          client) -> None:
        await self.signals.track(model, namespace, component, client)

    async def untrack_model(self, model: str) -> None:
        await self.signals.untrack(model)

    # -- admission -------------------------------------------------------- #

    async def admit(self, model: str, tenant: str = "",
                    priority: int = 0) -> GateDecision:
        """The edge decision, taken BEFORE tokenization. Returns quickly
        on the uncontended path; under pressure the caller is parked in
        the WFQ until capacity frees or the shed bound hits."""
        if not self.config.enabled or self._closed:
            return GateDecision(admitted=True)
        tenant = tenant or "default"
        priority = max(min(int(priority or 0), 8), -8)

        f = faults.FAULTS
        if f.enabled and f.check("gate.admit") == "reject":
            return self._reject(model, tenant, "fault",
                                self.config.retry_after_floor_s)

        # token bucket: per-tenant rate limit, checked synchronously so
        # the deny and its Retry-After are deterministic per (clock, plan)
        if self.config.tenant_rate > 0:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                burst = self.config.tenant_burst or max(
                    2.0 * self.config.tenant_rate, 1.0
                )
                if len(self._buckets) >= MAX_TRACKED_TENANTS:
                    # drop buckets that have fully refilled (identical to
                    # a fresh tenant's) before folding into the overflow
                    # bucket — the header is client-controlled, the table
                    # must not be
                    for t, b in list(self._buckets.items()):
                        if b.wait_s(b.burst) <= 0:
                            del self._buckets[t]
                if len(self._buckets) >= MAX_TRACKED_TENANTS:
                    bucket = self._buckets.setdefault(
                        OVERFLOW_TENANT,
                        TokenBucket(self.config.tenant_rate, burst),
                    )
                else:
                    bucket = self._buckets.setdefault(
                        tenant, TokenBucket(self.config.tenant_rate, burst)
                    )
            if not bucket.try_take():
                return self._reject(
                    model, tenant, "rate-limited",
                    max(bucket.wait_s(), self.config.retry_after_floor_s),
                )

        # every load decision runs on the pump task (one event-loop hop):
        # the WFQ, virtual time and optimism debt stay single-task-
        # confined, so concurrent admissions cannot tear each other
        pend = _Pending(model=model, tenant=tenant, priority=priority,
                        enq_s=time.monotonic())
        self._inbox.put_nowait(pend)
        return await pend.fut

    # -- pump (single task: owns every queue/vtime/debt mutation) --------- #

    async def _pump(self) -> None:
        while True:
            try:
                pend = await asyncio.wait_for(self._inbox.get(), timeout=0.05)
            except asyncio.TimeoutError:
                pend = None
            now = time.monotonic()
            # drain the inbox into the WFQ (virtual finish times assigned
            # in arrival order)
            while pend is not None:
                deadline = pend.enq_s + min(
                    self.config.max_wait_ms,
                    self.config.class_headroom_ms(pend.priority),
                ) / 1000.0
                self._waiting.push(pend.tenant, pend.priority, pend.enq_s,
                                   deadline, payload=pend)
                try:
                    pend = self._inbox.get_nowait()
                except asyncio.QueueEmpty:
                    pend = None

            # shed pass FIRST: entries past their wait bound are hopeless
            # (serving them now would still blow their class SLA)
            for entry in self._waiting.expired(now):
                self._resolve_shed(entry, "shed-timeout")
            while self.config.max_queue and len(self._waiting) > self.config.max_queue:
                victim = self._waiting.shed_lowest()
                if victim is None:
                    break
                self._resolve_shed(victim, "shed-overflow")

            # admit pass: WFQ virtual-finish order; each entry is checked
            # against ITS class headroom, so a lenient class behind a
            # blocked tight one still drains. `scan_debt` charges each
            # admission WITHIN this scan before the next entry is judged
            # — without it one cycle's whole backlog slips under a single
            # projection reading (the burst over-admission hole).
            scan_debt: Dict[str, int] = {}

            def _fits(entry) -> bool:
                pend: _Pending = entry.payload
                if pend.fut.done():  # caller gave up (disconnect)
                    return True
                proj = self._projected(pend.model)
                if proj is not None and scan_debt.get(pend.model):
                    proj += scan_debt[pend.model] * \
                        self.signals.per_request_ms(pend.model)
                ok = proj is None or \
                    proj <= self.config.class_headroom_ms(pend.priority)
                if ok:
                    scan_debt[pend.model] = scan_debt.get(pend.model, 0) + 1
                return ok

            for entry in self._waiting.take(_fits):
                pend = entry.payload
                if pend.fut.done():
                    continue
                proj = self._projected(pend.model)
                decision = self._admit(
                    pend.model, pend.tenant, proj,
                    queued_ms=(time.monotonic() - pend.enq_s) * 1000.0,
                )
                pend.fut.set_result(decision)

            # whatever is left had to PARK for capacity (the overload
            # signal the stats surface reports as gate_parked_total)
            for entry in self._waiting.entries():
                pend = entry.payload
                if not pend.counted:
                    pend.counted = True
                    self.queued_total += 1

    def _resolve_shed(self, entry, reason: str) -> None:
        pend: _Pending = entry.payload
        if pend.fut.done():
            return
        self.shed_total += 1
        proj = self._projected(pend.model)
        retry = self._retry_after(proj, pend.priority)
        pend.fut.set_result(GateDecision(
            admitted=False, reason=reason, retry_after_s=retry,
            projected_ttft_ms=proj,
            queued_ms=(time.monotonic() - pend.enq_s) * 1000.0,
        ))
        self._count_reject(pend.tenant, reason, retry)

    # -- internals -------------------------------------------------------- #

    def _projected(self, model: str) -> Optional[float]:
        """Published projection plus the optimism debt of admissions the
        publisher has not seen yet; debt resets when a fresher sample
        lands."""
        proj = self.signals.projected_ttft_ms(model)
        if proj is None:
            return None
        last = self.signals.last_update(model)
        if last > self._debt_seen.get(model, 0.0):
            self._debt_seen[model] = last
            self._debt[model] = 0
        debt = self._debt.get(model, 0)
        if debt:
            proj += debt * self.signals.per_request_ms(model)
        return proj

    def _retry_after(self, proj: Optional[float], priority: int) -> float:
        """How long until this class plausibly fits: the projection's
        excess over the class headroom, floored and capped."""
        headroom = self.config.class_headroom_ms(priority)
        excess_s = ((proj or 0.0) - headroom) / 1000.0
        return min(max(excess_s, self.config.retry_after_floor_s),
                   RETRY_AFTER_CAP_S)

    def _tenant_counts(self, tenant: str) -> Dict[str, int]:
        """Per-tenant counter row, folding past the cardinality bound
        (the tenant key is client-controlled input)."""
        t = self.per_tenant.get(tenant)
        if t is None:
            if len(self.per_tenant) >= MAX_TRACKED_TENANTS:
                tenant = OVERFLOW_TENANT
            t = self.per_tenant.setdefault(
                tenant, {"admitted": 0, "rejected": 0})
        return t

    def _admit(self, model: str, tenant: str, proj: Optional[float],
               queued_ms: float = 0.0) -> GateDecision:
        self.admitted_total += 1
        self._debt[model] = self._debt.get(model, 0) + 1
        self._tenant_counts(tenant)["admitted"] += 1
        return GateDecision(admitted=True, projected_ttft_ms=proj,
                            queued_ms=queued_ms)

    def _reject(self, model: str, tenant: str, reason: str,
                retry_after_s: float) -> GateDecision:
        retry = min(max(retry_after_s, self.config.retry_after_floor_s),
                    RETRY_AFTER_CAP_S)
        self._count_reject(tenant, reason, retry)
        return GateDecision(
            admitted=False, reason=reason, retry_after_s=retry,
            projected_ttft_ms=self.signals.projected_ttft_ms(model),
        )

    def _count_reject(self, tenant: str, reason: str, retry: float) -> None:
        self.rejected_total += 1
        self.rejected_by_reason[reason] = \
            self.rejected_by_reason.get(reason, 0) + 1
        self._tenant_counts(tenant)["rejected"] += 1
        for bound, key in ((1, "le_1s"), (2, "le_2s"), (5, "le_5s"),
                           (10, "le_10s")):
            if retry <= bound:
                self.retry_after_hist[key] += 1
                break
        else:
            self.retry_after_hist["inf"] += 1

    # -- observability ---------------------------------------------------- #

    def stats(self) -> dict:
        out = {
            "gate_enabled": int(self.config.enabled),
            "gate_admitted_total": self.admitted_total,
            "gate_rejected_total": self.rejected_total,
            "gate_shed_total": self.shed_total,
            "gate_parked_total": self.queued_total,
            "gate_queue_depth": len(self._waiting),
            "gate_rejected_by_reason": dict(self.rejected_by_reason),
            "gate_retry_after_hist": dict(self.retry_after_hist),
            "gate_per_tenant": {
                t: dict(v) for t, v in self.per_tenant.items()
            },
        }
        out.update(self.signals.stats())
        return out

    def render_prometheus(self) -> bytes:
        """Prometheus text lines appended to the frontend /metrics render
        (hand-assembled: the counters live on this object so the soak and
        unit tests can read them without a registry scrape)."""
        ns = "dynamo_frontend_gate"

        def _help(name: str) -> str:
            # HELP text comes from the metrics contract registry, so the
            # exposition can never drift from docs/observability.md
            return (metric_spec(name) or {}).get("help", name)

        lines = [
            f"# HELP {ns}_admitted_total {_help(ns + '_admitted_total')}",
            f"# TYPE {ns}_admitted_total counter",
            f"{ns}_admitted_total {self.admitted_total}",
            f"# HELP {ns}_rejected_total {_help(ns + '_rejected_total')}",
            f"# TYPE {ns}_rejected_total counter",
            f"{ns}_rejected_total {self.rejected_total}",
            f"# HELP {ns}_shed_total {_help(ns + '_shed_total')}",
            f"# TYPE {ns}_shed_total counter",
            f"{ns}_shed_total {self.shed_total}",
            f"# HELP {ns}_queue_depth {_help(ns + '_queue_depth')}",
            f"# TYPE {ns}_queue_depth gauge",
            f"{ns}_queue_depth {len(self._waiting)}",
        ]
        if self.rejected_by_reason:
            lines.append(
                f"# HELP {ns}_rejected_by_reason_total "
                f"{_help(ns + '_rejected_by_reason_total')}"
            )
            lines.append(f"# TYPE {ns}_rejected_by_reason_total counter")
        for reason, n in sorted(self.rejected_by_reason.items()):
            # reason strings are produced by the gate itself, but escape
            # anyway: a label value must never break the exposition line
            lines.append(
                f'{ns}_rejected_by_reason_total'
                f'{{reason="{_prom_label(reason)}"}} {n}'
            )
        if self.per_tenant:
            lines.append(
                f"# HELP {ns}_tenant_requests_total "
                f"{_help(ns + '_tenant_requests_total')}"
            )
            lines.append(f"# TYPE {ns}_tenant_requests_total counter")
        for tenant, v in sorted(self.per_tenant.items()):
            for k in ("admitted", "rejected"):
                lines.append(
                    f'{ns}_tenant_requests_total'
                    f'{{tenant="{_prom_label(tenant)}",'
                    f'outcome="{_prom_label(k)}"}} {v[k]}'
                )
        lines.append(
            f"# HELP {ns}_retry_after_seconds "
            f"{_help(ns + '_retry_after_seconds')}"
        )
        lines.append(f"# TYPE {ns}_retry_after_seconds histogram")
        acc = 0
        for key in ("le_1s", "le_2s", "le_5s", "le_10s", "inf"):
            acc += self.retry_after_hist[key]
            le = key[3:].rstrip("s") if key != "inf" else "+Inf"
            lines.append(
                f'{ns}_retry_after_seconds_bucket'
                f'{{le="{_prom_label(le)}"}} {acc}'
            )
        lines.append(f"{ns}_retry_after_seconds_count {acc}")
        return ("\n".join(lines) + "\n").encode()


def retry_after_header(retry_after_s: float) -> str:
    """Retry-After is delta-seconds, integral, never 0 (RFC 9110 §10.2.3)."""
    return str(max(int(math.ceil(retry_after_s)), 1))
