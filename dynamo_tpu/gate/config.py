"""dynogate configuration: the `DYN_GATE_*` knob surface.

All knobs are registered in `runtime/config.py:ENV_REGISTRY` (enforced by
the env-registry dynolint rule) and rendered into docs/configuration.md.
`DYN_GATE=0` compiles the whole subsystem out of the frontend: no
admission checks, no metrics subscription, no router preference — streams
are byte-identical to a build without this package (docs/overload.md).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional

from ..runtime.config import env_bool, env_float, env_int


def parse_tenant_weights(spec: Optional[str]) -> Dict[str, float]:
    """`a=4,b=1` → {"a": 4.0, "b": 1.0}; malformed entries are skipped
    (a typo must not take admission down), non-positive weights clamp to
    the 1.0 default."""
    out: Dict[str, float] = {}
    for item in (spec or "").split(","):
        item = item.strip()
        if not item or "=" not in item:
            continue
        name, _, raw = item.partition("=")
        try:
            w = float(raw)
        except ValueError:
            continue
        if name.strip():
            out[name.strip()] = w if w > 0 else 1.0
    return out


@dataclasses.dataclass(frozen=True)
class GateConfig:
    """Resolved dynogate knobs (one instance per frontend process)."""

    enabled: bool = True
    #: base TTFT target (ms) for admission-class math; 0 = inherit
    #: DYN_SLA_TTFT_MS so the gate and the worker scheduler agree on what
    #: "on time" means. Class target = base x 0.5^priority (SlaConfig).
    ttft_ms: float = 0.0
    #: multiplier on the class TTFT target: admission rejects when the
    #: fleet's projected TTFT exceeds headroom x class target
    ttft_headroom: float = 1.5
    #: per-instance queue-depth watermark: the router prefers instances
    #: below it, and admission falls back to it when no worker publishes
    #: a TTFT estimate (fifo-policy fleets)
    queue_watermark: int = 16
    #: gate queue bound; past it the LOWEST class sheds first
    max_queue: int = 64
    #: cap (ms) on how long a request may wait in the gate queue before
    #: it is shed (the effective wait bound is min(this, class headroom))
    max_wait_ms: float = 1000.0
    #: HTTP header carrying the tenant key ("" disables tenant plumbing)
    tenant_header: str = "x-dynamo-tenant"
    #: per-tenant token-bucket rate (requests/s); 0 = unlimited
    tenant_rate: float = 0.0
    #: per-tenant bucket burst size; 0 = max(2 x rate, 1)
    tenant_burst: float = 0.0
    #: WFQ weights per tenant ("gold=4,free=1"); unlisted tenants weigh 1
    tenant_weights: Dict[str, float] = dataclasses.field(default_factory=dict)
    #: load signals older than this (s) are ignored — a cold/stale fleet
    #: view must admit, never reject on ghosts
    signal_ttl_s: float = 5.0
    #: minimum Retry-After (s) on any 429
    retry_after_floor_s: float = 1.0

    @classmethod
    def from_env(cls) -> "GateConfig":
        ttft = env_float("DYN_GATE_TTFT_MS", 0.0)
        if ttft <= 0:
            # inherit the scheduler's target: the gate's "will it be on
            # time" and the worker's "is it on time" must be one number
            ttft = env_float("DYN_SLA_TTFT_MS", 2000.0)
        return cls(
            enabled=env_bool("DYN_GATE", True),
            ttft_ms=max(ttft, 1.0),
            ttft_headroom=max(env_float("DYN_GATE_TTFT_HEADROOM", 1.5), 0.1),
            queue_watermark=max(env_int("DYN_GATE_QUEUE_WATERMARK", 16), 1),
            max_queue=max(env_int("DYN_GATE_MAX_QUEUE", 64), 0),
            max_wait_ms=max(env_float("DYN_GATE_MAX_WAIT_MS", 1000.0), 0.0),
            tenant_header=os.environ.get(
                "DYN_GATE_TENANT_HEADER", "x-dynamo-tenant"
            ),
            tenant_rate=max(env_float("DYN_GATE_TENANT_RATE", 0.0), 0.0),
            tenant_burst=max(env_float("DYN_GATE_TENANT_BURST", 0.0), 0.0),
            tenant_weights=parse_tenant_weights(
                os.environ.get("DYN_GATE_TENANT_WEIGHTS")
            ),
            signal_ttl_s=max(env_float("DYN_GATE_SIGNAL_TTL_S", 5.0), 0.1),
            retry_after_floor_s=max(
                env_float("DYN_GATE_RETRY_AFTER_FLOOR_S", 1.0), 0.0
            ),
        )

    def class_target_ms(self, priority: int) -> float:
        """The SLA class's TTFT target: each +1 of priority halves it,
        each -1 doubles it (the SlaConfig.deadline math, so an edge
        rejection and a worker deadline miss describe the same SLA)."""
        p = max(min(int(priority), 8), -8)
        return self.ttft_ms * (0.5 ** p)

    def class_headroom_ms(self, priority: int) -> float:
        """Admission ceiling: reject when the fleet's projected TTFT
        exceeds this — serving the request would blow its class SLA."""
        return self.class_target_ms(priority) * self.ttft_headroom

    def weight(self, tenant: str) -> float:
        return self.tenant_weights.get(tenant, 1.0)
