"""Per-tenant fairness primitives: token buckets and weighted fair queuing.

Both are pure, clock-injectable data structures (deterministic under a
fake clock — tests/test_gate.py drives them with one) consumed by
`gate.AdmissionGate`; neither holds asyncio state.
"""

from __future__ import annotations

import heapq
import itertools
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


class TokenBucket:
    """Classic token bucket: `rate` tokens/s refill, `burst` capacity.

    Refill is computed lazily from elapsed time (no timer task), so for a
    fixed clock sequence the admit/deny decisions are exactly
    reproducible — the determinism the gate's rate-limit tests pin."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = max(float(burst), 1.0)
        self._clock = clock
        self._tokens = self.burst  # start full: a new tenant gets its burst
        self._last = clock()

    def _refill(self, now: float) -> None:
        if now > self._last:
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rate
            )
        self._last = max(self._last, now)

    def try_take(self, n: float = 1.0) -> bool:
        self._refill(self._clock())
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    def wait_s(self, n: float = 1.0) -> float:
        """Seconds until `n` tokens will be available (the Retry-After a
        rate-limited tenant is told)."""
        self._refill(self._clock())
        missing = n - self._tokens
        if missing <= 0:
            return 0.0
        if self.rate <= 0:
            return float("inf")
        return missing / self.rate


@dataclass(order=True)
class WfqEntry:
    """One queued admission, ordered by WFQ virtual finish time."""

    vft: float
    seq: int
    tenant: str = field(compare=False)
    priority: int = field(compare=False, default=0)
    enq_s: float = field(compare=False, default=0.0)
    deadline_s: float = field(compare=False, default=0.0)  # shed-by time
    payload: object = field(compare=False, default=None)


class WfqQueue:
    """Weighted fair queue over tenants (virtual-time WFQ).

    Each tenant's entries finish at `max(V, last_finish[tenant]) + 1/w`,
    so a tenant flooding the queue only advances its OWN finish times —
    other tenants' entries keep interleaving at their weight share no
    matter how deep the flood (the no-starvation property
    tests/test_gate.py pins under an adversarial mix).

    Shedding is by SLA class: `shed_lowest()` picks the lowest-priority
    entry (newest first within a class), the explicit overload contract —
    premium classes are the last to go (docs/overload.md)."""

    def __init__(self, weight_of: Optional[Callable[[str], float]] = None):
        self._weight_of = weight_of or (lambda _t: 1.0)
        self._heap: List[WfqEntry] = []
        self._vtime = 0.0
        self._finish: Dict[str, float] = {}
        self._seq = itertools.count()

    def __len__(self) -> int:
        return len(self._heap)

    def entries(self) -> List[WfqEntry]:
        """Snapshot of the queued entries (no order guarantee)."""
        return list(self._heap)

    def push(self, tenant: str, priority: int, enq_s: float,
             deadline_s: float, payload: object = None) -> WfqEntry:
        # finish tags at-or-behind the virtual clock are equivalent to a
        # fresh tenant's — prune them so the table stays bounded by the
        # tenants actually ahead of V, not by every tenant key ever seen
        # (the header is client-controlled)
        if len(self._finish) > 1024:
            self._finish = {
                t: f for t, f in self._finish.items() if f > self._vtime
            }
        w = max(self._weight_of(tenant), 1e-9)
        vft = max(self._vtime, self._finish.get(tenant, 0.0)) + 1.0 / w
        self._finish[tenant] = vft
        entry = WfqEntry(
            vft=vft, seq=next(self._seq), tenant=tenant, priority=priority,
            enq_s=enq_s, deadline_s=deadline_s, payload=payload,
        )
        heapq.heappush(self._heap, entry)
        return entry

    def peek(self) -> Optional[WfqEntry]:
        return self._heap[0] if self._heap else None

    def pop(self) -> WfqEntry:
        entry = heapq.heappop(self._heap)
        # virtual time advances to the served entry's finish tag; tenants
        # that were idle re-enter at V (they do not bank unused service)
        self._vtime = max(self._vtime, entry.vft)
        return entry

    def take(self, pred: Callable[[WfqEntry], bool]) -> List[WfqEntry]:
        """Remove and return, in virtual-finish order, every entry `pred`
        accepts. Entries `pred` refuses stay queued with their tags
        intact — a blocked tight-SLA entry does not dam lenient classes
        behind it (each is judged against its OWN headroom)."""
        admitted: List[WfqEntry] = []
        kept: List[WfqEntry] = []
        for entry in sorted(self._heap):
            if pred(entry):
                admitted.append(entry)
                self._vtime = max(self._vtime, entry.vft)
            else:
                kept.append(entry)
        if admitted:
            self._heap = kept
            heapq.heapify(self._heap)
        return admitted

    def _refund(self, entry: WfqEntry) -> None:
        """Roll the tenant's finish tag back one service quantum: a shed
        entry was never served, and leaving its charge in place would
        starve the tenant's LATER requests below its weight share as a
        consequence of requests that were refused."""
        f = self._finish.get(entry.tenant)
        if f is not None:
            w = max(self._weight_of(entry.tenant), 1e-9)
            self._finish[entry.tenant] = f - 1.0 / w

    def shed_lowest(self) -> Optional[WfqEntry]:
        """Remove and return the entry overload sheds first: lowest SLA
        class, newest arrival within the class."""
        if not self._heap:
            return None
        victim = min(self._heap, key=lambda e: (e.priority, -e.seq))
        self._heap.remove(victim)
        heapq.heapify(self._heap)
        self._refund(victim)
        return victim

    def expired(self, now_s: float) -> List[WfqEntry]:
        """Remove and return every entry whose shed deadline passed."""
        out = [e for e in self._heap if e.deadline_s <= now_s]
        if out:
            keep = [e for e in self._heap if e.deadline_s > now_s]
            self._heap = keep
            heapq.heapify(self._heap)
            for entry in out:
                self._refund(entry)
        return out

    def drain(self) -> List[WfqEntry]:
        out, self._heap = self._heap, []
        return out
