"""Worker load signals: the gate's view of fleet pressure.

Every worker already publishes its engine stats on the discovery metrics
topic (`kv_metrics/{ns}/{comp}`, llm/kv_router/publisher.py
WorkerMetricsPublisher, 0.25s cadence): `sched_est_ttft_ms` (the
scheduler's queue-depth x cost-model prefill estimate — the same signal
the disagg router reads), `num_waiting_reqs`/`num_running_reqs`, and the
drain state rides discovery records. This module subscribes once per
(namespace, component), keeps a per-instance table, and answers the two
questions admission asks:

  * `projected_ttft_ms(model)` — the BEST ready instance's estimated
    TTFT (the router will pick a good instance, so the fleet is only
    overloaded when even the best one is). None when no fresh signal
    exists: a cold or stale view must admit, never reject on ghosts.
  * `queue_depth(instance)` — feeds the PushRouter watermark preference
    (below-watermark instances are dialed first) and the admission
    fallback for fleets whose workers publish no TTFT estimate.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..llm.kv_router.publisher import METRICS_TOPIC_FMT
from ..runtime import codec
from ..runtime.metrics import (
    NUM_RUNNING_REQS,
    NUM_WAITING_REQS,
    SCHED_EST_REQ_MS,
    SCHED_EST_TTFT_MS,
)
from .config import GateConfig

logger = logging.getLogger(__name__)


@dataclass
class InstanceLoad:
    """Last published load sample for one worker instance."""

    est_ttft_ms: Optional[float] = None  # None = worker publishes no estimate
    est_req_ms: Optional[float] = None  # marginal cost per admitted request
    queue_depth: int = 0  # waiting + running requests
    updated: float = 0.0  # monotonic receive time


class LoadSignals:
    """Per-component instance load tables fed by the metrics topic."""

    def __init__(self, drt, config: GateConfig):
        self.drt = drt
        self.config = config
        # (namespace, component) -> instance_id -> InstanceLoad; the table
        # for one component is mutated ONLY by its _watch task
        self._by_comp: Dict[Tuple[str, str], Dict[int, InstanceLoad]] = {}
        self._models: Dict[str, Tuple[str, str]] = {}  # model -> comp key
        self._clients: Dict[str, object] = {}  # model -> endpoint Client
        self._subs: Dict[Tuple[str, str], object] = {}
        self._tasks: Dict[Tuple[str, str], asyncio.Task] = {}
        self.samples_total = 0

    async def track(self, model: str, namespace: str, component: str,
                    client) -> None:
        """Follow `model`'s backend component; one subscription per
        (namespace, component) no matter how many models share it."""
        key = (namespace, component)
        self._models[model] = key
        self._clients[model] = client
        if key in self._tasks or self.drt.discovery is None:
            return
        # reserve the key SYNCHRONOUSLY: a concurrent track() for the
        # same component must not double-subscribe while we await
        self._tasks[key] = None
        try:
            sub = await self.drt.discovery.subscribe(
                METRICS_TOPIC_FMT.format(
                    namespace=namespace, component=component)
            )
        except BaseException:
            # a failed subscribe must not leave the reservation behind —
            # the retry (next model-card put) would see the key and skip,
            # leaving the gate permanently signal-blind for the component
            if self._tasks.get(key) is None:
                self._tasks.pop(key, None)
            raise
        if key not in self._tasks:  # untracked while subscribing
            await sub.cancel()
            return
        self._subs[key] = sub
        self._tasks[key] = asyncio.create_task(self._watch(key, sub))

    async def untrack(self, model: str) -> None:
        key = self._models.pop(model, None)
        self._clients.pop(model, None)
        if key is None or key in self._models.values():
            return  # another model still shares the component
        task = self._tasks.pop(key, None)
        if task is not None:
            task.cancel()
        sub = self._subs.pop(key, None)
        if sub is not None:
            await sub.cancel()
        self._by_comp.pop(key, None)

    async def close(self) -> None:
        # cancel sweep is synchronous (no yield of control until every
        # task is cancelled and the containers are clear)
        for task in list(self._tasks.values()):
            if task is not None:
                task.cancel()
        self._tasks.clear()
        subs = list(self._subs.values())
        self._subs.clear()
        for sub in subs:
            await sub.cancel()
        self._by_comp.clear()

    async def _watch(self, key: Tuple[str, str], sub) -> None:
        table = self._by_comp.setdefault(key, {})
        try:
            async for payload in sub:
                try:
                    msg = codec.unpack(payload)
                    stats = msg.get("stats", {})
                    inst = table.setdefault(int(msg["worker_id"]), InstanceLoad())
                    est = stats.get(SCHED_EST_TTFT_MS)
                    inst.est_ttft_ms = float(est) if est is not None else None
                    req = stats.get(SCHED_EST_REQ_MS)
                    inst.est_req_ms = float(req) if req is not None else None
                    inst.queue_depth = int(stats.get(NUM_WAITING_REQS, 0)) \
                        + int(stats.get(NUM_RUNNING_REQS, 0))
                    inst.updated = time.monotonic()
                    self.samples_total += 1
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 — load stats are advisory
                    logger.debug("bad gate metrics message", exc_info=True)
        except asyncio.CancelledError:
            raise

    # -- queries ---------------------------------------------------------- #

    def _fresh(self, model: str, now: Optional[float] = None
               ) -> List[Tuple[int, InstanceLoad]]:
        """Fresh samples for `model`'s READY instances (stale samples and
        draining/dead instances are invisible to admission)."""
        key = self._models.get(model)
        if key is None:
            return []
        table = self._by_comp.get(key) or {}
        client = self._clients.get(model)
        ready = None
        if client is not None:
            try:
                ready = set(client.ready_instance_ids())
            except Exception:  # noqa: BLE001 — discovery hiccup = no filter
                ready = None
        now = time.monotonic() if now is None else now
        out = []
        for iid, load in table.items():
            if ready is not None and iid not in ready:
                continue
            if now - load.updated > self.config.signal_ttl_s:
                continue
            out.append((iid, load))
        return out

    def projected_ttft_ms(self, model: str) -> Optional[float]:
        """Best ready instance's projected TTFT (ms). Instances that
        publish no estimate project from the queue-depth watermark
        instead: depth/watermark x class base target, so a fifo fleet
        still saturates the gate rather than bypassing it. None = no
        fresh signal (cold fleet: admit)."""
        best: Optional[float] = None
        for _iid, load in self._fresh(model):
            if load.est_ttft_ms is not None:
                est = load.est_ttft_ms
            else:
                est = (load.queue_depth / max(self.config.queue_watermark, 1)
                       ) * self.config.ttft_ms
            if best is None or est < best:
                best = est
        return best

    def per_request_ms(self, model: str) -> float:
        """Marginal TTFT cost of one more admitted request — the
        optimism-debt unit the gate charges for admissions between signal
        refreshes. Workers that publish `sched_est_req_ms` (their own
        service-rate view) are believed; otherwise fall back to the best
        instance's (estimate / depth), which underestimates an idle
        fleet's per-request service time but corrects within one publish
        interval."""
        samples = self._fresh(model)
        if not samples:
            return 0.0
        best = min(
            samples,
            key=lambda s: s[1].est_ttft_ms
            if s[1].est_ttft_ms is not None else float("inf"),
        )[1]
        if best.est_req_ms is not None:
            return best.est_req_ms
        if best.est_ttft_ms is None or best.est_ttft_ms <= 0:
            return 0.0
        return best.est_ttft_ms / max(best.queue_depth, 1)

    def last_update(self, model: str) -> float:
        """Newest sample time for the model's component (0.0 = never)."""
        key = self._models.get(model)
        table = self._by_comp.get(key) if key is not None else None
        if not table:
            return 0.0
        return max(load.updated for load in table.values())

    def queue_depth(self, namespace: str, component: str,
                    instance_id: int) -> Optional[int]:
        load = (self._by_comp.get((namespace, component)) or {}).get(instance_id)
        if load is None:
            return None
        if time.monotonic() - load.updated > self.config.signal_ttl_s:
            return None
        return load.queue_depth

    def prefer_below_watermark(self, namespace: str, component: str):
        """Instance-preference hook for PushRouter._pick: keep only
        instances below the gate's queue-depth watermark (unknown/fresh-
        less instances count as below — a new worker must not starve).
        Falls back to the full set when every instance is saturated, so
        the preference can degrade the choice but never empty it."""

        def prefer(ids: List[int]) -> List[int]:
            below = []
            for iid in ids:
                depth = self.queue_depth(namespace, component, iid)
                if depth is None or depth < self.config.queue_watermark:
                    below.append(iid)
            return below or ids

        return prefer

    def stats(self) -> dict:
        out = {"gate_signal_samples": self.samples_total}
        for (_ns, comp), table in self._by_comp.items():
            out[f"gate_instances_{comp}"] = len(table)
        return out
