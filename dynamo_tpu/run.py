"""Single-binary launcher: `python -m dynamo_tpu.run in=<input> out=<engine>`.

Role of the reference's dynamo-run CLI (launch/dynamo-run/src/main.rs:31,
flags.rs): one command that wires an input frontend to an engine —

    in=http            OpenAI HTTP server (default port 8000)
    in=text            interactive prompt loop on the terminal
    in=stdin           read one prompt from stdin, print the completion
    in=batch:FILE      process a JSONL file of {"text": ...} prompts
    out=mocker         spawn the fake engine worker (default)
    out=echo           trivial in-process echo engine
    out=jax            spawn the JAX TPU engine worker
    out=dyn://ns.comp.ep   attach to already-running workers

The launcher embeds the discovery service, spawns the chosen worker as a
subprocess (matching production process boundaries), watches for its model
card, and runs the chosen input.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import signal
import sys
from typing import List, Optional, Tuple

logger = logging.getLogger("dynamo_tpu.run")


def parse_spec(argv: List[str]) -> Tuple[str, str, argparse.Namespace]:
    spec = {"in": "text", "out": "mocker"}
    rest: List[str] = []
    for a in argv:
        if a.startswith("in="):
            spec["in"] = a[3:]
        elif a.startswith("out="):
            spec["out"] = a[4:]
        else:
            rest.append(a)
    ap = argparse.ArgumentParser(
        description="dynamo-tpu run", prog="python -m dynamo_tpu.run"
    )
    ap.add_argument("--model-name", default=None)
    ap.add_argument("--http-port", type=int, default=8000)
    ap.add_argument("--namespace", default="dynamo")
    ap.add_argument(
        "--router-mode", choices=["round-robin", "random", "kv"], default="round-robin"
    )
    ap.add_argument("--max-tokens", type=int, default=256)
    ap.add_argument("--prompt", default=None, help="one-shot prompt for in=text")
    ap.add_argument("--verbose", "-v", action="store_true")
    args = ap.parse_args(rest)
    return spec["in"], spec["out"], args


async def _spawn_worker(kind: str, args, discovery: str) -> Optional[asyncio.subprocess.Process]:
    """Start the engine worker subprocess for out=mocker|jax."""
    model = args.model_name or ("mock-model" if kind == "mocker" else "tiny")
    if kind == "mocker":
        cmd = [sys.executable, "-m", "dynamo_tpu.mocker",
               "--model-name", model, "--kv-events"]
    elif kind == "jax":
        cmd = [sys.executable, "-m", "dynamo_tpu.jax_worker", "--model", model]
    else:
        raise ValueError(kind)
    env = dict(os.environ)
    env["DYN_DISCOVERY_ENDPOINT"] = discovery
    proc = await asyncio.create_subprocess_exec(*cmd, env=env)
    logger.info("spawned %s worker pid=%d (model=%s)", kind, proc.pid, model)
    return proc


async def _serve_hf(drt, namespace: str, model: str, model_path: Optional[str]):
    """out=hf[:path] — in-process torch/transformers CPU engine (reference
    role: lib/engines/llamacpp + mistralrs, engines linked into the
    launcher). Random-init tiny model when no path is given."""
    from dynamo_tpu.llm.engines import HfCpuEngine
    from dynamo_tpu.llm.model_card import ModelDeploymentCard, register_llm
    from dynamo_tpu.runtime.compute import ComputePool

    # torch import + model init can take tens of seconds: build on the
    # compute pool so the discovery lease keepalive keeps running
    engine = await ComputePool.get().run(HfCpuEngine, model_path)
    endpoint = drt.namespace(namespace).component("hf").endpoint("generate")

    async def handler(request, context):
        async for item in engine.generate(request, context):
            yield item

    tokenizer = model_path if model_path else "byte:512"
    card = ModelDeploymentCard(name=model, tokenizer=tokenizer)
    await register_llm(endpoint, card)
    await endpoint.serve_endpoint(handler)


async def _serve_echo(drt, namespace: str, model: str):
    """out=echo — in-process engine that echoes the prompt tokens back
    (reference dynamo-run's echo engine: latency-path testing)."""
    from dynamo_tpu.llm.model_card import ModelDeploymentCard, register_llm

    endpoint = drt.namespace(namespace).component("echo").endpoint("generate")

    async def handler(request, context):
        for tok in request.get("token_ids", [])[: request.get(
            "stop_conditions", {}
        ).get("max_tokens") or None]:
            yield {"token_ids": [tok]}
        yield {"token_ids": [], "finish_reason": "stop"}

    card = ModelDeploymentCard(name=model, tokenizer="byte")
    await register_llm(endpoint, card)
    await endpoint.serve_endpoint(handler)


async def _wait_for_model(manager, timeout: float = 120.0) -> str:
    for _ in range(int(timeout / 0.2)):
        names = manager.names()
        if names:
            return names[0]
        await asyncio.sleep(0.2)
    raise TimeoutError("no model appeared in discovery")


async def _chat_once(pipeline, model: str, prompt: str, max_tokens: int) -> str:
    from dynamo_tpu.llm.protocols import ChatCompletionRequest
    from dynamo_tpu.runtime.engine import Context

    req = ChatCompletionRequest(
        model=model,
        messages=[{"role": "user", "content": prompt}],
        max_tokens=max_tokens,
        stream=True,
    )
    pre = pipeline.preprocessor.preprocess_chat(req)
    ctx = Context()
    parts: List[str] = []
    try:
        async for ann in pipeline.generate_preprocessed(pre, ctx):
            if ann.is_error():
                raise RuntimeError((ann.comment or ["engine error"])[0])
            if ann.event is not None or ann.data is None:
                continue
            if ann.data.text:
                print(ann.data.text, end="", flush=True)
                parts.append(ann.data.text)
            if ann.data.finish_reason:
                break
    finally:
        ctx.stop_generating()
    print()
    return "".join(parts)


async def amain(argv: List[str]) -> int:
    input_kind, out_kind, args = parse_spec(argv)
    if input_kind not in ("http", "text", "stdin") and not input_kind.startswith("batch:"):
        print(f"unknown in={input_kind}", file=sys.stderr)
        return 2
    if (
        out_kind not in ("mocker", "jax", "echo")
        and not out_kind.startswith("hf")
        and not out_kind.startswith("dyn://")
    ):
        print(f"unknown out={out_kind}", file=sys.stderr)
        return 2
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )

    from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
    from dynamo_tpu.runtime import DistributedRuntime, RouterMode, RuntimeConfig
    from dynamo_tpu.runtime.config import discovery_address

    cfg = RuntimeConfig.from_settings()
    drt = await DistributedRuntime.create(cfg, embed_discovery=True)
    host, port = discovery_address(cfg)
    discovery = f"tcp://{host}:{port}"

    worker_proc = None
    if out_kind in ("mocker", "jax"):
        worker_proc = await _spawn_worker(out_kind, args, discovery)
    elif out_kind == "echo":
        await _serve_echo(drt, args.namespace, args.model_name or "echo")
    elif out_kind.startswith("hf"):
        _, _, hf_path = out_kind.partition(":")
        await _serve_hf(
            drt, args.namespace, args.model_name or "hf-cpu", hf_path or None
        )
    # else dyn://: attach to whatever's registered

    manager = ModelManager()
    router_mode = RouterMode(args.router_mode)
    kv_router_factory = None
    if router_mode == RouterMode.KV:
        from dynamo_tpu.llm.kv_router import KvRouterConfig, make_kv_router_factory

        kv_router_factory = make_kv_router_factory(KvRouterConfig())
    watcher = ModelWatcher(drt, manager, router_mode, kv_router_factory)
    await watcher.start()

    try:
        if input_kind == "http":
            from dynamo_tpu.llm.http import HttpService

            service = HttpService(manager, host="0.0.0.0", port=args.http_port)
            await service.start()
            logger.info("OpenAI server ready on :%d", service.port)
            await drt.wait_for_shutdown()
            return 0

        model = await _wait_for_model(manager)
        pipeline = manager.get(model)

        if input_kind == "text":
            if args.prompt is not None:
                await _chat_once(pipeline, model, args.prompt, args.max_tokens)
                return 0
            print(f"model: {model} — interactive chat, ctrl-d to exit")
            loop = asyncio.get_running_loop()
            while True:
                try:
                    line = await loop.run_in_executor(None, input, "> ")
                except EOFError:
                    return 0
                if line.strip():
                    await _chat_once(pipeline, model, line, args.max_tokens)

        if input_kind == "stdin":
            prompt = sys.stdin.read().strip()
            if not prompt:
                print("empty stdin", file=sys.stderr)
                return 2
            await _chat_once(pipeline, model, prompt, args.max_tokens)
            return 0

        # input_kind was validated above: only batch: remains
        path = input_kind.split(":", 1)[1]
        n = 0
        with open(path) as f, open(path + ".out.jsonl", "w") as out:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                text = await _chat_once(
                    pipeline, model, rec["text"], args.max_tokens
                )
                out.write(json.dumps({"text": rec["text"], "response": text}) + "\n")
                n += 1
        logger.info("batch done: %d prompts -> %s.out.jsonl", n, path)
        return 0
    finally:
        # one shielded teardown coroutine: a Ctrl-C cancellation landing
        # mid-cleanup must not abandon the worker SIGTERM or the runtime
        # drain halfway through
        async def _teardown():
            await watcher.stop()
            if worker_proc is not None and worker_proc.returncode is None:
                worker_proc.send_signal(signal.SIGTERM)
                try:
                    await asyncio.wait_for(worker_proc.wait(), timeout=5)
                except asyncio.TimeoutError:
                    worker_proc.kill()
            await drt.close()

        await asyncio.shield(_teardown())


def main() -> None:
    try:
        code = asyncio.run(amain(sys.argv[1:]))
    except KeyboardInterrupt:
        code = 130
    raise SystemExit(code)


if __name__ == "__main__":
    main()
