"""Encode worker: `python -m dynamo_tpu.encode_worker` — the E of E/P/D.

Turns multimodal content parts into embedding tensors for the prefill
engine's splice (reference: the trtllm encode worker in
components/backends/trtllm/multimodal_epd.md; the processor role in
multimodal_processor.py). Registers a plain runtime endpoint (no model
card — it is not a generation model); the frontend's ModelPipeline calls
it when configured with --encoder (llm/service.py encode hop).
"""

import argparse
import asyncio
import logging

from dynamo_tpu.llm.multimodal import DEFAULT_MM_TOKENS, MockVisionEncoder, encode_parts
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig, init_logging

logger = logging.getLogger("dynamo_tpu.encode_worker")


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="dynamo-tpu encode worker (multimodal E/P/D)")
    ap.add_argument("--namespace", default="dynamo")
    ap.add_argument("--component", default="encoder")
    ap.add_argument("--endpoint", default="encode")
    ap.add_argument("--discovery", default=None, help="tcp://host:port of discovery")
    ap.add_argument("--hidden-size", type=int, default=None,
                    help="embedding width; defaults from --model")
    ap.add_argument("--model", default="tiny",
                    help="model registry key the embeddings target")
    ap.add_argument("--mm-tokens", type=int, default=DEFAULT_MM_TOKENS,
                    help="placeholder span length per content part (mock)")
    ap.add_argument("--encoder", choices=["mock", "vit"], default="mock",
                    help="mock: content-hash projection (tests); "
                         "vit: real JAX ViT (models/vit.py)")
    ap.add_argument("--vit-checkpoint", default=None,
                    help="local HF ViT export dir (safetensors/bin); "
                         "random-init when omitted")
    ap.add_argument("--vit-size", choices=["tiny", "base"], default="tiny",
                    help="ViT architecture when no checkpoint config")
    return ap.parse_args(argv)


async def main():
    init_logging()
    args = parse_args()
    cfg = RuntimeConfig.from_settings()
    if args.discovery:
        cfg.discovery_endpoint = args.discovery
    drt = await DistributedRuntime.create(cfg)
    # SIGTERM (planner scale-down) walks the graceful drain, not a hard exit
    drt.install_signal_handlers()

    hidden = args.hidden_size
    if hidden is None:
        from dynamo_tpu.engine.engine import _resolve_model

        hidden = _resolve_model(args.model).hidden_size
    if args.encoder == "vit":
        from dynamo_tpu.llm.multimodal import ViTEncoder
        from dynamo_tpu.models.vit import ViTConfig

        vcfg = ViTConfig() if args.vit_size == "base" else ViTConfig.tiny()
        encoder = ViTEncoder(
            config=vcfg, llm_hidden=hidden, checkpoint=args.vit_checkpoint
        )
    else:
        encoder = MockVisionEncoder(hidden, n_tokens=args.mm_tokens)
    n_encoded = 0

    endpoint = (
        drt.namespace(args.namespace).component(args.component).endpoint(args.endpoint)
    )

    async def handler(request, context):
        nonlocal n_encoded
        parts = request.get("multimodal") or []
        encoded = encode_parts(parts, encoder)
        n_encoded += len(encoded)
        logger.info("encoded %d part(s) (total %d)", len(encoded), n_encoded)
        yield {"data": {"multimodal": encoded, "n_tokens": encoder.n_tokens}}

    logger.info(
        "encode worker up: hidden=%d mm_tokens=%d instance=%x",
        hidden, encoder.n_tokens, drt.instance_id,
    )
    await endpoint.serve_endpoint(handler)
    await drt.wait_for_shutdown()
    await drt.close()  # graceful drain (runtime/component.py close())


if __name__ == "__main__":
    asyncio.run(main())
