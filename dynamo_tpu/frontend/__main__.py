"""OpenAI frontend: `python -m dynamo_tpu.frontend`.

Mirrors reference components/frontend (main.py) + lib/llm entrypoint
(input.rs:109 run_input / http.rs): starts (or embeds) the discovery
service, watches model cards, serves the OpenAI HTTP API with the chosen
router mode.
"""

import argparse
import asyncio
import logging
import os

from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
from dynamo_tpu.llm.http import HttpService
from dynamo_tpu.runtime import (
    DistributedRuntime,
    RouterMode,
    RuntimeConfig,
    init_logging,
)

logger = logging.getLogger("dynamo_tpu.frontend")


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="dynamo-tpu OpenAI frontend")
    ap.add_argument("--http-host", default="0.0.0.0")
    ap.add_argument("--http-port", type=int, default=8000)
    ap.add_argument("--grpc-port", type=int, default=0,
                    help="KServe gRPC port (0 = disabled; reference "
                    "lib/llm/src/grpc/service/kserve.rs)")
    ap.add_argument(
        "--router-mode",
        choices=["round-robin", "random", "kv"],
        default="round-robin",
    )
    ap.add_argument("--discovery", default=None, help="tcp://host:port of discovery")
    ap.add_argument("--encoder", default=None,
                    help="multimodal encode worker endpoint "
                    "('component' | 'ns/component' | 'ns/component/endpoint'): "
                    "adds the E/P/D encode hop to every model pipeline")
    ap.add_argument(
        "--embed-discovery",
        action="store_true",
        help="host the discovery service inside this process",
    )
    ap.add_argument("--kv-overlap-score-weight", type=float, default=1.0)
    ap.add_argument("--router-temperature", type=float, default=0.0)
    ap.add_argument("--router-replica-sync", "--mirror-routing",
                    action="store_true",
                    help="mirror routing decisions between KV-mode frontends "
                    "sharing discovery, so replica fleets keep one view of "
                    "active blocks / in-flight prefixes (reference "
                    "kv_router/subscriber.rs; docs/frontend_scaleout.md)")
    args = ap.parse_args(argv)
    # frontend replicas are stateless over shared discovery: the planner /
    # operator-lite scales them with ONE argv template, so each replica
    # offsets its listen ports by its index (DYN_WORKER_INDEX, the same
    # contract workers use; docs/frontend_scaleout.md)
    index = int(os.environ.get("DYN_WORKER_INDEX") or 0)
    if index:
        if args.http_port:
            args.http_port += index
        if args.grpc_port:
            args.grpc_port += index
    return args


async def main():
    init_logging()
    args = parse_args()
    cfg = RuntimeConfig.from_settings()
    if args.discovery:
        cfg.discovery_endpoint = args.discovery
    drt = await DistributedRuntime.create(cfg, embed_discovery=args.embed_discovery)
    # SIGTERM walks the graceful drain, not a hard exit mid-stream
    drt.install_signal_handlers()

    manager = ModelManager()
    router_mode = RouterMode(args.router_mode)

    # dynogate admission control (gate/, docs/overload.md): DYN_GATE=0
    # compiles the whole overload discipline out of this process
    gate = None
    from dynamo_tpu.gate import AdmissionGate, GateConfig

    gate_cfg = GateConfig.from_env()
    if gate_cfg.enabled:
        gate = AdmissionGate(drt, gate_cfg)
        await gate.start()
        logger.info(
            "admission gate active (ttft=%.0fms headroom=%.1fx watermark=%d)",
            gate_cfg.ttft_ms, gate_cfg.ttft_headroom, gate_cfg.queue_watermark,
        )

    kv_router_factory = None
    if router_mode == RouterMode.KV:
        from dynamo_tpu.llm.kv_router import KvRouterConfig, make_kv_router_factory

        kv_router_factory = make_kv_router_factory(
            KvRouterConfig(
                overlap_score_weight=args.kv_overlap_score_weight,
                router_temperature=args.router_temperature,
                replica_sync=args.router_replica_sync,
            )
        )

    watcher = ModelWatcher(
        drt, manager, router_mode, kv_router_factory, encoder=args.encoder,
        gate=gate,
    )
    await watcher.start()

    service = HttpService(
        manager, host=args.http_host, port=args.http_port, gate=gate
    )
    await service.start()
    grpc_service = None
    if args.grpc_port:
        from dynamo_tpu.llm.grpc import KserveGrpcService

        grpc_service = KserveGrpcService(
            manager, host=args.http_host, port=args.grpc_port
        )
        await grpc_service.start()
    logger.info("frontend ready on :%d (router=%s)", service.port, router_mode.value)
    await drt.wait_for_shutdown()
    if gate is not None:
        await gate.close()  # parked admissions resolve before the drain
    await drt.close()  # graceful drain (runtime/component.py close())


if __name__ == "__main__":
    asyncio.run(main())
