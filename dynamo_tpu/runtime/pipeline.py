"""Generic bidirectional operator pipeline.

The reference's pipeline framework (lib/runtime/src/pipeline.rs: PipelineIO
:88, Operator nodes with forward/backward edges under pipeline/nodes/,
composed by build_routed_pipeline common.rs:259-310) — the abstraction that
lets the serving chain

    SegmentSource -> Preprocessor.fwd -> Backend.fwd -> Migration.fwd ->
      ServiceBackend [network hop] -> Migration.bwd -> Backend.bwd ->
      Preprocessor.bwd -> frontend

be assembled from interchangeable nodes. Python redesign: an `Operator`
transforms the REQUEST on the way down (`forward`) and wraps the RESPONSE
STREAM on the way up (`backward`); `compose` folds a list of operators
around a sink into one `AsyncEngine`-shaped object. Operators that must
own the sink call entirely (retry loops like llm/migration.py) implement
`around` instead.
"""

from __future__ import annotations

import logging
from typing import Any, AsyncIterator, List, Optional, Sequence

from .engine import Context

logger = logging.getLogger(__name__)


class Operator:
    """One bidirectional pipeline node (reference Operator, pipeline.rs).

    Default implementations are pass-through; override any subset:
      * forward(request, context)  — transform the request going DOWN
      * backward(stream, request, context) — wrap the stream coming UP
      * around(next_engine, request, context) — own the sink call entirely
        (retry/migration semantics); when overridden, forward/backward of
        THIS node are not used.
    """

    async def forward(self, request: Any, context: Context) -> Any:
        return request

    def backward(
        self, stream: AsyncIterator[Any], request: Any, context: Context
    ) -> AsyncIterator[Any]:
        return stream

    def around(
        self, next_engine: "PipelineEngine", request: Any, context: Context
    ) -> Optional[AsyncIterator[Any]]:
        """Return a stream to take over the downstream call, or None to use
        the forward/backward path."""
        return None

    @property
    def name(self) -> str:
        return type(self).__name__


class ServiceBackend:
    """The sink: hands the (fully forward-transformed) request to an engine
    or router (reference ServiceBackend pipeline/nodes)."""

    def __init__(self, engine):
        self.engine = engine

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        stream = self.engine.generate(request, context)
        if hasattr(stream, "__await__"):
            stream = await stream
        async for item in stream:
            yield item


class PipelineEngine:
    """`compose(operators, sink)`: an AsyncEngine whose generate() runs
    request forward through each operator in order, calls the sink, then
    wraps the stream backward in reverse order."""

    def __init__(self, operators: Sequence[Operator], sink):
        self.operators: List[Operator] = list(operators)
        self.sink = sink

    def _tail(self, index: int) -> "PipelineEngine":
        """The sub-pipeline below operator `index` (for around())."""
        return PipelineEngine(self.operators[index + 1 :], self.sink)

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        async for item in self._run(0, request, context):
            yield item

    async def _run(
        self, index: int, request: Any, context: Context
    ) -> AsyncIterator[Any]:
        if index >= len(self.operators):
            async for item in self.sink.generate(request, context):
                yield item
            return
        op = self.operators[index]
        taken = op.around(self._tail(index), request, context)
        if taken is not None:
            async for item in taken:
                yield item
            return
        request = await op.forward(request, context)
        inner = self._run(index + 1, request, context)
        async for item in op.backward(inner, request, context):
            yield item


def compose(operators: Sequence[Operator], sink) -> PipelineEngine:
    """Fold operators around a sink (reference build_routed_pipeline
    common.rs:259-310 builds exactly this shape)."""
    return PipelineEngine(operators, sink)
