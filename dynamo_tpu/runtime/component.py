"""Component model + DistributedRuntime.

Mirrors reference lib/runtime: `Runtime` (lib.rs:70),
`DistributedRuntime::new` (distributed.rs:42), `Namespace` (component.rs:520)
→ `Component` (:120) → `Endpoint` (:358), live `Instance` records (:98)
written to discovery under the process's primary lease, and
`Client`/`InstanceSource` (component/client.rs:40,52) that watch instances.

Discovery layout:
  v1/instances/{namespace}/{component}/{endpoint}/{instance_id} -> Instance json
  v1/mdc/{namespace}/{component}/{model-slug}                   -> ModelDeploymentCard
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import secrets
import socket
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Dict, List, Optional

from . import codec
from .config import RuntimeConfig, discovery_address
from .discovery import DiscoveryClient, DiscoveryServer, Lease, Watch
from .engine import Context
from .request_plane import (
    EndpointStats,
    Handler,
    RequestPlaneClient,
    RequestPlaneServer,
)

logger = logging.getLogger(__name__)

INSTANCE_ROOT = "v1/instances/"
MODEL_ROOT = "v1/mdc/"


#: Instance lifecycle states written into the discovery record. `draining`
#: is published by the graceful-shutdown sequence the moment a worker stops
#: accepting new streams, so routers and the planner's capacity counter can
#: skip it WITHOUT waiting for the lease-revoke delete event to propagate.
#: `morphing` is the role-flip analogue (docs/autoscaling.md "Role
#: morphing"): the worker is live but mid prefill<->decode re-role — its
#: OUTGOING role stops taking new streams exactly like draining, except the
#: record flips back to `ready` (under the new component) instead of being
#: deleted.
STATE_READY = "ready"
STATE_DRAINING = "draining"
STATE_MORPHING = "morphing"

#: States a router must not pick for NEW streams: dialing either buys a
#: per-request `draining`-coded rejection (the server severs/refuses), so
#: `ready_instance_ids` filters both (PR 9 drain invariant, extended to
#: morphs).
UNROUTABLE_STATES = (STATE_DRAINING, STATE_MORPHING)


@dataclass
class Instance:
    """A live endpoint instance (reference Instance component.rs:98)."""

    namespace: str
    component: str
    endpoint: str
    instance_id: int
    address: str  # host:port of the worker's request-plane server
    subject: str  # routing subject within that server
    state: str = STATE_READY  # STATE_READY | STATE_DRAINING | STATE_MORPHING

    @property
    def path(self) -> str:
        return (
            f"{INSTANCE_ROOT}{self.namespace}/{self.component}/"
            f"{self.endpoint}/{self.instance_id:x}"
        )

    def to_json(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "Instance":
        return cls(**json.loads(raw))


class DistributedRuntime:
    """Process-wide distributed runtime: discovery client + primary lease +
    request-plane server/client (reference DistributedRuntime distributed.rs:42).

    `static_mode=True` skips discovery entirely (reference's etcd=None static
    mode) — endpoints are addressed directly by host:port.
    """

    def __init__(self, config: Optional[RuntimeConfig] = None, static_mode: bool = False):
        self.config = config or RuntimeConfig.from_settings()
        self.static_mode = static_mode
        self.instance_id = int.from_bytes(os.urandom(8), "big") >> 1
        self.discovery: Optional[DiscoveryClient] = None
        self.primary_lease: Optional[Lease] = None
        self._embedded_discovery: Optional[DiscoveryServer] = None
        self.server = RequestPlaneServer(host=self.config.request_plane_host)
        self.client = RequestPlaneClient(
            connect_timeout=self.config.request_plane_connect_timeout
        )
        self._server_started = False
        # two endpoints serving concurrently must not both start the
        # request-plane server: the loser's listening socket would leak
        # and its registrations would point at a dead port
        self._server_lock = asyncio.Lock()
        self._namespaces: Dict[str, Namespace] = {}
        self._leased_keys: Dict[str, bytes] = {}
        self._shutdown = asyncio.Event()
        self.etcd_root = ""  # prefix for multi-tenant stores (unused for now)
        # observability (reference: MetricsRegistry root on the DRT lib.rs:92,
        # SystemHealth system_health.rs, HealthCheckManager health_check.rs)
        from .health_check import HealthCheckManager
        from .metrics import MetricsRegistry
        from .system_status import SystemHealth, SystemStatusServer

        self.metrics = MetricsRegistry()
        self.system_health = SystemHealth()
        self.system_status_server: Optional[SystemStatusServer] = None
        self.health_check_manager: Optional[HealthCheckManager] = None
        if self.config.health_check_enabled:
            self.health_check_manager = HealthCheckManager(
                self,
                self.system_health,
                idle_timeout=self.config.health_check_idle_timeout,
                request_timeout=self.config.health_check_request_timeout,
            )

    @classmethod
    async def create(
        cls,
        config: Optional[RuntimeConfig] = None,
        static_mode: bool = False,
        embed_discovery: bool = False,
    ) -> "DistributedRuntime":
        """Connect to (or embed) the discovery service and grant the primary
        lease. With embed_discovery, this process hosts the control plane —
        typically the frontend does this when no external one is running."""
        drt = cls(config, static_mode)
        if not static_mode:
            host, port = discovery_address(drt.config)
            if embed_discovery:
                drt._embedded_discovery = DiscoveryServer(host="0.0.0.0", port=port)
                try:
                    await drt._embedded_discovery.start()
                except OSError:
                    drt._embedded_discovery = None  # someone else already runs it
            drt.discovery = await DiscoveryClient.connect(host, port)
            drt.primary_lease = await drt.discovery.grant_lease(
                ttl=drt.config.lease_ttl_s
            )
            drt.primary_lease.on_lost = drt._republish_leased_keys
        if drt.config.system_enabled:
            from .system_status import SystemStatusServer

            port = drt.config.system_port
            if port > 0:
                # planner-scaled replicas share one argv/env template
                # (docs/frontend_scaleout.md): offset the metrics port by
                # the replica index so co-located replicas don't collide
                port += int(os.environ.get("DYN_WORKER_INDEX") or 0)
            drt.system_status_server = SystemStatusServer(
                drt.system_health, drt.metrics,
                host=drt.config.system_host, port=port,
            )
            try:
                await drt.system_status_server.start()
            except OSError:
                # a taken port must degrade the scrape, never the replica:
                # fall back to an ephemeral port (logged; the prometheus
                # target is wrong until the operator fixes the offsets)
                logger.warning(
                    "system-status port %d already taken; serving metrics "
                    "on an ephemeral port instead", port,
                )
                drt.system_status_server = SystemStatusServer(
                    drt.system_health, drt.metrics,
                    host=drt.config.system_host, port=0,
                )
                await drt.system_status_server.start()
        if drt.health_check_manager is not None:
            drt.health_check_manager.start()
        return drt

    async def _republish_leased_keys(self, lease):
        """The primary lease expired (event loop stalled past TTL, e.g. long
        XLA compile) and was re-granted: restore every registration."""
        for key, value in list(self._leased_keys.items()):
            try:
                await self.discovery.put(key, value, lease)
            except (ConnectionError, RuntimeError):
                logger.warning("failed to re-publish %s after lease re-grant", key)

    async def put_leased(self, key: str, value: bytes):
        """Put a key under the primary lease and remember it so it survives
        lease re-grants."""
        self._leased_keys[key] = value
        if self.discovery is not None:
            await self.discovery.put(key, value, self.primary_lease)

    async def _mark_instances_draining(self):
        """Re-publish every served Instance record with state=`draining`
        BEFORE the lease revoke deletes it: watch consumers (PushRouter,
        planner capacity counts) see the put immediately, closing the
        window where a router still dials a worker that will only answer
        with a `draining` rejection."""
        if self.discovery is None:
            return
        for key, value in list(self._leased_keys.items()):
            if not key.startswith(INSTANCE_ROOT):
                continue
            try:
                inst = Instance.from_json(value)
                inst.state = STATE_DRAINING
                await self.discovery.put(key, inst.to_json(), self.primary_lease)
            except (ConnectionError, RuntimeError, ValueError, TypeError):
                pass  # best-effort: the revoke delete is the authority

    def install_signal_handlers(self):
        """SIGTERM/SIGINT trigger the graceful-shutdown sequence instead of
        the interpreter's default hard exit — this is what turns a planner
        scale-down (`LocalProcessConnector._kill` sends SIGTERM) into the
        drain path (mark draining → revoke lease → finish in-flight) rather
        than a mid-stream kill that every live request pays for."""
        import signal

        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, self.shutdown)
            except (NotImplementedError, RuntimeError):
                return  # platform without signal support (or non-main thread)

    async def ensure_server(self) -> str:
        """Start the request-plane server on first use; returns host:port."""
        async with self._server_lock:
            if not self._server_started:
                await self.server.start()
                self._server_started = True
        host = self.server.host
        if host in ("0.0.0.0", "::"):
            host = socket.gethostbyname(socket.gethostname())
        return f"{host}:{self.server.port}"

    def namespace(self, name: str) -> "Namespace":
        ns = self._namespaces.get(name)
        if ns is None:
            ns = Namespace(self, name)
            self._namespaces[name] = ns
        return ns

    def shutdown(self):
        self._shutdown.set()

    async def wait_for_shutdown(self):
        await self._shutdown.wait()

    async def close(self, graceful: bool = True):
        """Shutdown with the drain sequence the reference's graceful-
        shutdown contract requires (DYN_RUNTIME_GRACEFUL_SHUTDOWN_TIMEOUT):

          1. revoke the primary lease — instance keys vanish, routers stop
             picking this process for NEW requests;
          2. stop accepting new streams (listening socket closes, races
             that already hold our address get a `draining` rejection they
             treat as StreamLost);
          3. drain in-flight streams within the graceful timeout;
          4. force-cancel survivors.

        `graceful=False` skips 2-3 (crash-style teardown, used by tests
        that simulate worker death)."""
        self._shutdown.set()
        if self.health_check_manager is not None:
            await self.health_check_manager.stop()
        if graceful:
            await self._mark_instances_draining()
        if self.primary_lease is not None:
            await self.primary_lease.revoke()
        if graceful and self._server_started:
            drained = await self.server.drain(self.config.graceful_shutdown_timeout)
            if not drained:
                logger.warning(
                    "graceful drain timed out after %.1fs; force-cancelling %d stream(s)",
                    self.config.graceful_shutdown_timeout,
                    self.server.active_streams,
                )
        if self.system_status_server is not None:
            await self.system_status_server.stop()
        await self.client.close()
        await self.server.stop()
        if self.discovery is not None:
            await self.discovery.close()
        if self._embedded_discovery is not None:
            await self._embedded_discovery.stop()


class Namespace:
    """Logical grouping of components (reference component.rs:520)."""

    def __init__(self, drt: DistributedRuntime, name: str):
        self.drt = drt
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self.drt, self.name, name)


class Component:
    """A deployable service unit within a namespace (reference component.rs:120)."""

    def __init__(self, drt: DistributedRuntime, namespace: str, name: str):
        self.drt = drt
        self.namespace = namespace
        self.name = name

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self, name)

    async def create_service(self):
        """No-op placeholder for service-level registration; instances are
        registered per-endpoint at serve time (matches reference semantics
        where the NATS service is created lazily)."""
        return self

    @property
    def path(self) -> str:
        return f"{self.namespace}/{self.name}"


class Endpoint:
    """A named, servable function on a component (reference component.rs:358)."""

    def __init__(self, component: Component, name: str):
        self.component = component
        self.name = name

    @property
    def drt(self) -> DistributedRuntime:
        return self.component.drt

    @property
    def subject(self) -> str:
        return f"{self.component.namespace}.{self.component.name}.{self.name}"

    @property
    def path(self) -> str:
        return f"{self.component.path}/{self.name}"

    async def serve_endpoint(
        self,
        handler: Handler,
        *,
        metrics_labels: Optional[dict] = None,
        graceful: bool = True,
    ) -> "ServedEndpoint":
        """Register the handler on the process request-plane server and write
        the Instance record under the primary lease
        (reference serve_endpoint bindings lib.rs:641 → Ingress)."""
        drt = self.drt
        address = await drt.ensure_server()
        stats = drt.server.register(self.subject, handler)
        instance = Instance(
            namespace=self.component.namespace,
            component=self.component.name,
            endpoint=self.name,
            instance_id=drt.instance_id,
            address=address,
            subject=self.subject,
        )
        await drt.put_leased(instance.path, instance.to_json())
        logger.info("serving endpoint %s at %s (instance %x)", self.subject, address, instance.instance_id)
        return ServedEndpoint(self, instance, stats)

    async def client(self) -> "Client":
        client = Client(self)
        await client.start()
        return client

    def instance_prefix(self) -> str:
        return f"{INSTANCE_ROOT}{self.component.namespace}/{self.component.name}/{self.name}/"


class ServedEndpoint:
    def __init__(self, endpoint: Endpoint, instance: Instance, stats: EndpointStats):
        self.endpoint = endpoint
        self.instance = instance
        self.stats = stats

    async def remove(self):
        drt = self.endpoint.drt
        drt.server.unregister(self.endpoint.subject)
        drt._leased_keys.pop(self.instance.path, None)
        if drt.discovery is not None:
            await drt.discovery.delete(self.instance.path)

    async def set_state(self, state: str):
        """Re-publish this instance's discovery record with a new lifecycle
        state under the same primary lease. The role-morph sequence uses
        this to flip ready -> morphing before the drain (watch consumers
        stop routing new streams here immediately) and morphing -> ready on
        rollback — same put-before-authority discipline as
        `_mark_instances_draining`."""
        drt = self.endpoint.drt
        self.instance.state = state
        await drt.put_leased(self.instance.path, self.instance.to_json())


class Client:
    """Endpoint client with a live instance list
    (reference Client/InstanceSource component/client.rs:40,52).

    Watches the discovery prefix for this endpoint; `instances` is kept
    current as workers come and go.
    """

    def __init__(self, endpoint: Endpoint):
        self.endpoint = endpoint
        self.instances: Dict[int, Instance] = {}
        self._watch: Optional[Watch] = None
        self._watch_task: Optional[asyncio.Task] = None
        self._instances_event = asyncio.Event()
        self._default_router = None  # lazy PushRouter for .generate()
        self._closed = False

    async def start(self):
        drt = self.endpoint.drt
        if drt.discovery is None:
            return
        self._watch = await drt.discovery.watch_prefix(self.endpoint.instance_prefix())
        self._load_snapshot(self._watch.snapshot)
        self._watch_task = asyncio.create_task(self._watch_loop())

    def _load_snapshot(self, snapshot):
        """Reconcile the full instance set from a watch snapshot — on a
        re-watch this REPLACES the map, dropping instances that died while
        the watch was down (their deletes were never delivered)."""
        fresh = {}
        for item in snapshot:
            inst = Instance.from_json(item["value"])
            fresh[inst.instance_id] = inst
        self.instances.clear()
        self.instances.update(fresh)
        if self.instances:
            self._instances_event.set()
        else:
            self._instances_event.clear()

    async def _watch_loop(self):
        from .backoff import Backoff

        assert self._watch is not None
        # stable seed: re-watch timing reproduces across chaos re-runs
        backoff = Backoff.seeded(self.endpoint.subject, base=0.05, max_delay=1.0)
        while not self._closed:
            async for event in self._watch:
                backoff.reset()
                if event.type == "put":
                    inst = Instance.from_json(event.value)
                    self.instances[inst.instance_id] = inst
                    self._instances_event.set()
                elif event.type == "delete":
                    iid = int(event.key.rsplit("/", 1)[-1], 16)
                    self.instances.pop(iid, None)
                    if not self.instances:
                        self._instances_event.clear()
            # the watch ended without cancel(): the discovery connection
            # died. Reconnect + re-watch with backoff — the live instance
            # list is this client's routing authority and must not silently
            # freeze at its last state.
            drt = self.endpoint.drt
            while not self._closed:
                await backoff.wait()
                if not await drt.discovery.ensure_connected():
                    if drt.discovery._closed:
                        return  # runtime shut down under us — nothing to watch
                    continue
                try:
                    self._watch = await drt.discovery.watch_prefix(
                        self.endpoint.instance_prefix()
                    )
                except ConnectionError:
                    continue
                self._load_snapshot(self._watch.snapshot)
                logger.info(
                    "re-watching %s after discovery reconnect (%d instance(s))",
                    self.endpoint.instance_prefix(), len(self.instances),
                )
                break

    def instance_ids(self) -> List[int]:
        return sorted(self.instances.keys())

    def ready_instance_ids(self) -> List[int]:
        """Instances eligible for NEW streams: excludes workers whose
        discovery record is in `draining` state (scale-down in progress)
        or `morphing` state (live role flip in progress — the outgoing
        role's record is about to move under another component) — dialing
        either only buys a per-request rejection."""
        return sorted(
            iid for iid, inst in self.instances.items()
            if inst.state not in UNROUTABLE_STATES
        )

    async def wait_for_instances(self, timeout: float = 30.0) -> List[int]:
        """Block until at least one instance is live (reference
        wait_for_instances semantics used by workers at startup)."""
        await asyncio.wait_for(self._instances_event.wait(), timeout)
        return self.instance_ids()

    def add_static_instance(self, address: str, subject: Optional[str] = None, instance_id: int = 0):
        """Static mode: seed a fixed instance without discovery."""
        inst = Instance(
            namespace=self.endpoint.component.namespace,
            component=self.endpoint.component.name,
            endpoint=self.endpoint.name,
            instance_id=instance_id,
            address=address,
            subject=subject or self.endpoint.subject,
        )
        self.instances[inst.instance_id] = inst  # dynolint: disable=race-guarded-state -- static mode: discovery is off and the owning watch task never exists
        self._instances_event.set()

    async def direct(self, request: Any, instance_id: int, context: Optional[Context] = None):
        """Send to a specific instance (reference RouterMode::Direct)."""
        inst = self.instances.get(instance_id)
        if inst is None:
            from .request_plane import StreamLost

            raise StreamLost(f"instance {instance_id:x} not found for {self.endpoint.subject}")
        if context is not None:
            # migration reads this on StreamLost to exclude the corpse
            # from the retry's re-route (docs/fault_tolerance.md)
            context.routed_instance = int(instance_id)
        return await self.endpoint.drt.client.call(inst.address, inst.subject, request, context)

    async def generate(self, request: Any, context: Optional[Context] = None):
        """Round-robin convenience (full routing lives in PushRouter)."""
        from .push_router import PushRouter, RouterMode

        if self._default_router is None:
            self._default_router = PushRouter(self, RouterMode.ROUND_ROBIN)
        return await self._default_router.generate(request, context)

    async def close(self):
        self._closed = True
        if self._watch_task:
            self._watch_task.cancel()
        if self._watch:
            await self._watch.cancel()
