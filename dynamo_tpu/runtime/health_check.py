"""Canary health checks for served endpoints.

Role of the reference's HealthCheckManager
(lib/runtime/src/health_check.rs:39-162, DYN_HEALTH_CHECK_* config
config.rs:155-167): an endpoint that has been idle longer than
`idle_timeout` gets a canary request sent through the real request plane
(loopback to this process's own server — the full codec/dispatch path).
Success keeps the endpoint healthy; a timeout or stream error marks it
unhealthy in SystemHealth, which flips the status server's /health to 503
so orchestrators can restart the worker.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from .system_status import SystemHealth

logger = logging.getLogger(__name__)


@dataclass
class _Target:
    subject: str
    address: str
    path: str  # namespace/component/endpoint for SystemHealth
    canary: Any  # request payload the handler treats as a no-op probe
    stats: Any  # EndpointStats (idle tracking)
    consecutive_failures: int = 0


class HealthCheckManager:
    def __init__(
        self,
        drt,
        health: SystemHealth,
        idle_timeout: float = 60.0,
        request_timeout: float = 10.0,
        check_interval: Optional[float] = None,
    ):
        self.drt = drt
        self.health = health
        self.idle_timeout = idle_timeout
        self.request_timeout = request_timeout
        self.check_interval = check_interval or max(idle_timeout / 4, 0.5)
        self._targets: Dict[str, _Target] = {}
        self._task: Optional[asyncio.Task] = None

    def register(self, served_endpoint, canary_payload: Any) -> None:
        """Track a ServedEndpoint; `canary_payload` must be a request the
        handler completes quickly (reference: engines expose a designated
        health-check request)."""
        ep = served_endpoint
        t = _Target(
            subject=ep.instance.subject,
            address=ep.instance.address,
            path=f"{ep.instance.namespace}/{ep.instance.component}/{ep.instance.endpoint}",
            canary=canary_payload,
            stats=ep.stats,
        )
        self._targets[t.subject] = t
        self.health.set_endpoint_health(t.path, True)

    def unregister(self, subject: str) -> None:
        t = self._targets.pop(subject, None)
        if t is not None:
            self.health.remove_endpoint(t.path)

    def start(self) -> None:
        self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        # take-then-act: claim the task BEFORE awaiting, so a concurrent
        # stop() (or a start() racing a stop) never reaps the same task
        # twice or nulls out a fresh one
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.check_interval)
            now = time.monotonic()
            due = [
                t for t in self._targets.values()
                if now - t.stats.last_request_at >= self.idle_timeout
            ]
            if due:
                # concurrent probes: one wedged endpoint must not delay the
                # others' canaries past a single request_timeout
                await asyncio.gather(*(self._probe(t) for t in due))

    async def _probe(self, t: _Target) -> None:
        try:
            # the connect/call AND the drain share one timeout: a wedged
            # transport that never returns a stream must count as a failed
            # probe, not hang the canary loop forever
            async def call_and_drain():
                stream = await self.drt.client.call(t.address, t.subject, t.canary)
                async for _ in stream:
                    pass

            await asyncio.wait_for(call_and_drain(), timeout=self.request_timeout)
            if t.consecutive_failures:
                logger.info("endpoint %s recovered", t.path)
            t.consecutive_failures = 0
            self.health.set_endpoint_health(t.path, True)
        except Exception as e:  # noqa: BLE001 — any failure counts
            t.consecutive_failures += 1
            logger.warning(
                "health canary failed for %s (%d consecutive): %s",
                t.path, t.consecutive_failures, e,
            )
            self.health.set_endpoint_health(t.path, False)
