"""Hierarchical runtime metrics registry.

Role of the reference's MetricsRegistry (lib/runtime/src/metrics.rs,
MetricsRegistryEntry lib.rs:92): every level of the
DRT → namespace → component → endpoint hierarchy can mint Prometheus
counters/gauges/histograms that are automatically labeled with their
position in the hierarchy (dynamo_namespace / dynamo_component /
dynamo_endpoint), all collected into one process-wide registry that the
system status server exports at /metrics. Callback gauges mirror the
reference's metrics callbacks (scrape-time evaluation).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

HIERARCHY_LABELS = ("dynamo_namespace", "dynamo_component", "dynamo_endpoint")

# --------------------------------------------------------------------- #
# the metrics contract registry (dynomet)
# --------------------------------------------------------------------- #
# Cross-process metric KEY constants. These keys are spelled at a
# publisher in one process (engine/mocker stats() on the metrics topic)
# and re-spelled at consumers in OTHER processes (gate LoadSignals, the
# disagg router's prefill-queue watcher, the KV router's scheduler) — a
# rename at one end fails silently into fail-open admission, so both
# ends import the spelling from here and the `met-consume-symmetry`
# dynolint rule enforces that every wire-crossing key keeps at least one
# producer and one consumer.

NUM_WAITING_REQS = "num_waiting_reqs"
NUM_RUNNING_REQS = "num_running_reqs"
KV_ACTIVE_BLOCKS = "kv_active_blocks"
KV_TOTAL_BLOCKS = "kv_total_blocks"
SCHED_EST_TTFT_MS = "sched_est_ttft_ms"
SCHED_EST_REQ_MS = "sched_est_req_ms"
SCHED_EST_PREFILL_TOK_S = "sched_est_prefill_tok_s"
SCHED_EST_DECODE_TOK_S = "sched_est_decode_tok_s"

#: The observability contract: every metric key this package emits —
#: stats()-dict keys published on the metrics topic, prometheus names
#: minted by the frontend, and the hand-assembled exposition families.
#: The `met` dynolint pack parses this dict from the AST (never imports
#: this module) and cross-checks every emission and consumption site in
#: the tree against it; `--emit-metrics-docs` renders it into
#: docs/observability.md.
#:
#: Value fields (all literal — the registry must stay literal_eval-able):
#:   kind     counter | gauge | histogram | info ("info" = a string or
#:            structured value that must never be exported as a number)
#:   layer    engine | worker | frontend | kvbm | router | sched |
#:            planner | gate
#:   unit     human unit ("" for plain counts)
#:   help     one-line description (the docs table / HELP text)
#:   labels   bounded label names for labeled exposition families
#:   wire     True when the key crosses a process boundary and the
#:            symmetry rule requires >=1 producer AND >=1 consumer
#:   export   True when jax_worker republishes the stat as a
#:            dynamo_worker_<name> prometheus gauge (worker_exported_
#:            stats() drives that loop, so export drift is structural)
#:   dynamic  True when the key is emitted through an f-string or
#:            comprehension the analyzer cannot resolve (tier names,
#:            merged sub-dicts) — exempts the entry from the
#:            never-emitted check
#:   buckets  histogram bucket upper bounds (exposition + registry must
#:            agree; the kind rule compares ctor buckets against these)
METRICS = {
    # ---- engine core (published on the kv_metrics topic) -------------
    NUM_WAITING_REQS: {"kind": "gauge", "layer": "engine", "unit": "requests", "help": "Requests queued for prefill admission.", "wire": True, "export": True},
    NUM_RUNNING_REQS: {"kind": "gauge", "layer": "engine", "unit": "requests", "help": "Requests occupying decode slots.", "wire": True, "export": True},
    "gpu_cache_usage_perc": {"kind": "gauge", "layer": "engine", "unit": "fraction", "help": "Active KV pages / total pages.", "wire": True, "export": True},
    "request_total_slots": {"kind": "gauge", "layer": "engine", "unit": "slots", "help": "Configured max concurrent sequences.", "wire": True, "export": True},
    "kv_quant": {"kind": "info", "layer": "engine", "help": "KV cache quantization format (bf16/int8/int4)."},
    "kv_pool_bytes": {"kind": "gauge", "layer": "engine", "unit": "bytes", "help": "Resident KV pool bytes including scales.", "export": True},
    "kv_format_mismatches": {"kind": "counter", "layer": "engine", "help": "Typed mixed-precision KV transfer rejections.", "export": True},
    KV_ACTIVE_BLOCKS: {"kind": "gauge", "layer": "engine", "unit": "blocks", "help": "KV blocks referenced by live sequences.", "wire": True, "export": True},
    KV_TOTAL_BLOCKS: {"kind": "gauge", "layer": "engine", "unit": "blocks", "help": "Total KV blocks in the device pool.", "wire": True, "export": True},
    "kv_cached_blocks": {"kind": "gauge", "layer": "engine", "unit": "blocks", "help": "Unreferenced blocks held for prefix reuse.", "export": True},
    "kv_prefix_hit_blocks_total": {"kind": "counter", "layer": "engine", "unit": "blocks", "help": "Prefix-cache block hits at admission.", "export": True},
    "kv_transfers_served": {"kind": "counter", "layer": "engine", "help": "Data-plane KV transfers served to peers.", "export": True},
    "kv_bytes_served": {"kind": "counter", "layer": "engine", "unit": "bytes", "help": "Data-plane KV bytes served to peers.", "export": True},
    "kv_checkpoint_pushes": {"kind": "counter", "layer": "engine", "help": "Session-checkpoint pushes accepted into local tiers.", "export": True},
    "kv_checkpoint_blocks_received": {"kind": "counter", "layer": "engine", "unit": "blocks", "help": "Checkpoint blocks received from peers.", "export": True},
    "kv_pulls_completed": {"kind": "counter", "layer": "engine", "help": "Remote KV pulls completed (disagg onboarding).", "export": True},
    "kv_pages_pulled": {"kind": "counter", "layer": "engine", "unit": "blocks", "help": "KV pages pulled from remote workers.", "export": True},
    "disagg_streamed_handoffs": {"kind": "counter", "layer": "engine", "help": "Streamed prefill->decode handoffs started.", "export": True},
    "disagg_chunks_before_first_token": {"kind": "counter", "layer": "engine", "help": "KV chunks landed before the first decode token.", "export": True},
    "disagg_first_token_before_last_chunk": {"kind": "counter", "layer": "engine", "help": "First tokens emitted while KV chunks were in flight.", "export": True},
    "disagg_streamed_handoff_ratio": {"kind": "gauge", "layer": "engine", "unit": "fraction", "help": "Overlapped handoffs / streamed handoffs.", "export": True},
    "kv_streamed_stages": {"kind": "counter", "layer": "engine", "help": "Prefill-side streamed KV stages shipped.", "export": True},
    "kv_streamed_fallbacks": {"kind": "counter", "layer": "engine", "help": "Streamed handoffs that fell back to blocking pulls.", "export": True},
    "migrations_resumed": {"kind": "counter", "layer": "engine", "help": "Decode streams resumed here after a worker death.", "export": True},
    "migration_replayed_tokens": {"kind": "counter", "layer": "engine", "unit": "tokens", "help": "Tokens re-prefilled to resume migrated streams.", "export": True},
    "resume_source_checkpoint": {"kind": "counter", "layer": "engine", "help": "Migration resumes seeded from a peer checkpoint.", "export": True},
    "resume_source_peer": {"kind": "counter", "layer": "engine", "help": "Migration resumes seeded from live peer KV.", "export": True},
    "resume_source_local": {"kind": "counter", "layer": "engine", "help": "Migration resumes seeded from local tiers.", "export": True},
    "resume_source_recompute": {"kind": "counter", "layer": "engine", "help": "Migration resumes that fully re-prefilled.", "export": True},
    # role morphing (docs/autoscaling.md "Role morphing"): the live
    # prefill<->decode re-role state machine's outcome counters
    "engine_role": {"kind": "info", "layer": "engine", "help": "Current serving role (prefill/decode/both/aggregated)."},
    "morph_state": {"kind": "info", "layer": "engine", "help": "Role-morph state machine position (serving/draining-role/flipped/warm)."},
    "morphs_completed": {"kind": "counter", "layer": "engine", "help": "Live role morphs that reached the new role's warm state.", "export": True},
    "morphs_rolled_back": {"kind": "counter", "layer": "engine", "help": "Role morphs that failed mid-flight and restored the original role.", "export": True},
    "morph_drained_sessions": {"kind": "counter", "layer": "engine", "help": "In-flight sessions severed to peers by morph drains (resumed via migration).", "export": True},
    "morph_last_duration_s": {"kind": "gauge", "layer": "engine", "unit": "seconds", "help": "Wall-clock of the last completed morph (drain + flip + re-warm).", "export": True},
    "kv_skip_ahead_blocks": {"kind": "counter", "layer": "engine", "unit": "blocks", "help": "Prefill blocks skipped via prefix skip-ahead.", "export": True},
    "emit_batches": {"kind": "counter", "layer": "engine", "help": "Token delta batches emitted to streams.", "export": True},
    "emit_tokens": {"kind": "counter", "layer": "engine", "unit": "tokens", "help": "Tokens emitted to streams.", "export": True},
    "mixed_steps": {"kind": "counter", "layer": "engine", "help": "Fused mixed prefill+decode dispatch steps.", "export": True},
    "split_steps": {"kind": "counter", "layer": "engine", "help": "Split prefill/decode dispatch steps.", "export": True},
    # compile telemetry (engine/compile_registry.py, docs/compilation.md):
    # XLA cache growth per staged surface. post_warmup_compiles is THE
    # steady-state contract number — the compile smoke gates on 0
    "compile_surfaces": {"kind": "info", "layer": "engine", "help": "Per-surface XLA executable counts (COMPILE_SURFACES keys).", "dynamic": True},
    "compiled_variants": {"kind": "gauge", "layer": "engine", "unit": "programs", "help": "Total XLA executables across staged surfaces.", "export": True},
    "post_warmup_compiles": {"kind": "counter", "layer": "engine", "unit": "programs", "help": "XLA programs compiled after the warmup baseline (steady-state debt; 0 is the contract).", "export": True},
    "mixed_padding_frac": {"kind": "gauge", "layer": "engine", "unit": "fraction", "help": "Padding fraction paid by the mixed path.", "export": True},
    "split_padding_frac": {"kind": "gauge", "layer": "engine", "unit": "fraction", "help": "Padding fraction paid by the split path.", "export": True},
    # per-kind fused coverage (docs/ragged_attention.md "Row classes"):
    # proves blended guided/spec/lora traffic actually rides the fused
    # path; the blended-trace CI smoke gates mixed_coverage_frac >= 0.9
    "mixed_rows_plain": {"kind": "counter", "layer": "engine", "unit": "rows", "help": "Plain prefill/decode rows packed into fused mixed steps.", "export": True},
    "mixed_rows_guided": {"kind": "counter", "layer": "engine", "unit": "rows", "help": "Guided (FSM-masked) rows packed into fused mixed steps.", "export": True},
    "mixed_rows_spec": {"kind": "counter", "layer": "engine", "unit": "rows", "help": "Speculative verify rows packed into fused mixed steps.", "export": True},
    "mixed_rows_lora": {"kind": "counter", "layer": "engine", "unit": "rows", "help": "LoRA-adapter rows packed into fused mixed steps.", "export": True},
    "mixed_coverage_frac": {"kind": "gauge", "layer": "engine", "unit": "fraction", "help": "Fused steps / (fused + split) dispatch steps (1.0 before any step).", "export": True},
    # LoRA adapter tier (models/lora_pool.py, docs/multi_lora.md):
    # fixed-slot device stack paging adapters HBM<->host, KVBM-priced
    "lora_pool_slots": {"kind": "gauge", "layer": "engine", "unit": "slots", "help": "Configured device adapter slots (DYN_LORA_POOL_SLOTS).", "export": True},
    "lora_pool_resident": {"kind": "gauge", "layer": "engine", "unit": "adapters", "help": "Adapters currently resident in device slots.", "export": True},
    "lora_pool_known": {"kind": "gauge", "layer": "engine", "unit": "adapters", "help": "Adapters registered in the host roster.", "export": True},
    "lora_pool_hits": {"kind": "counter", "layer": "engine", "help": "Adapter acquires served from a resident slot (hot switch).", "export": True},
    "lora_pool_misses": {"kind": "counter", "layer": "engine", "help": "Adapter acquires that paid a cold onboard.", "export": True},
    "lora_pool_evictions": {"kind": "counter", "layer": "engine", "help": "Unpinned adapters evicted from device slots (LRU).", "export": True},
    "lora_pool_refusals": {"kind": "counter", "layer": "engine", "help": "Typed adapter-tier refusals (pinned-full pool or injected onboard fault).", "export": True},
    "lora_pool_onboard_ms": {"kind": "counter", "layer": "engine", "unit": "ms", "help": "Cumulative adapter onboard latency (mean = sum/count).", "export": True},
    "lora_pool_onboard_count": {"kind": "counter", "layer": "engine", "help": "Adapter onboard operations.", "export": True},
    "lora_pool_onboard_ewma_ms": {"kind": "gauge", "layer": "engine", "unit": "ms", "help": "EWMA adapter onboard latency (cold-switch price).", "dynamic": True, "export": True},
    "guided_requests": {"kind": "counter", "layer": "engine", "help": "Requests decoded under a guided-decoding FSM.", "export": True},
    "lora_requests": {"kind": "counter", "layer": "engine", "help": "Requests served through a LoRA adapter.", "export": True},
    "spec_num_drafts": {"kind": "counter", "layer": "engine", "help": "Speculative draft batches proposed.", "export": True},
    "spec_num_draft_tokens": {"kind": "counter", "layer": "engine", "unit": "tokens", "help": "Speculative tokens proposed by the draft model.", "export": True},
    "spec_num_accepted_tokens": {"kind": "counter", "layer": "engine", "unit": "tokens", "help": "Speculative tokens accepted by verification.", "export": True},
    "spec_mean_accepted_len": {"kind": "gauge", "layer": "engine", "unit": "tokens", "help": "Mean accepted length per draft (incl. bonus token).", "export": True},
    # ---- dynosched (engine/scheduler/policy.py) ----------------------
    "sched_policy": {"kind": "info", "layer": "sched", "help": "Active scheduling policy name."},
    "sched_ttft_target_ms": {"kind": "gauge", "layer": "sched", "unit": "ms", "help": "Configured TTFT SLA target.", "export": True},
    "sched_itl_target_ms": {"kind": "gauge", "layer": "sched", "unit": "ms", "help": "Configured ITL SLA target.", "export": True},
    "sched_granted_chunks": {"kind": "counter", "layer": "sched", "help": "Prefill chunks granted by the budgeter.", "export": True},
    "sched_granted_tokens": {"kind": "counter", "layer": "sched", "unit": "tokens", "help": "Prefill tokens granted by the budgeter.", "export": True},
    "sched_deferred_steps": {"kind": "counter", "layer": "sched", "help": "Steps where prefill was deferred for ITL.", "export": True},
    "sched_itl_shrunk_steps": {"kind": "counter", "layer": "sched", "help": "Steps where the chunk budget was shrunk for ITL.", "export": True},
    "sched_deadline_overrides": {"kind": "counter", "layer": "sched", "help": "Deadline-driven priority overrides.", "export": True},
    "sched_starvation_overrides": {"kind": "counter", "layer": "sched", "help": "Starvation-guard priority overrides.", "export": True},
    "sched_pending_deadlines": {"kind": "gauge", "layer": "sched", "help": "Requests with an armed TTFT deadline.", "export": True},
    "sched_cost_observations": {"kind": "counter", "layer": "sched", "help": "Cost-model samples observed.", "export": True},
    "sched_tenants_served": {"kind": "gauge", "layer": "sched", "help": "Distinct tenants the fairness tiebreak has served.", "export": True},
    "sched_last_budget_tokens": {"kind": "gauge", "layer": "sched", "unit": "tokens", "help": "Last step's granted token budget."},
    "sched_last_slack_ms": {"kind": "gauge", "layer": "sched", "unit": "ms", "help": "Last step's tightest deadline slack."},
    "sched_last_decision": {"kind": "info", "layer": "sched", "help": "Last scheduling decision tag."},
    SCHED_EST_TTFT_MS: {"kind": "gauge", "layer": "sched", "unit": "ms", "help": "Projected TTFT for one more admitted request — the gate's admission ceiling and the disagg router's routing signal.", "wire": True, "export": True},
    SCHED_EST_REQ_MS: {"kind": "gauge", "layer": "sched", "unit": "ms", "help": "Marginal TTFT cost of one more admitted request (the gate's optimism debt between publishes).", "wire": True, "export": True},
    SCHED_EST_PREFILL_TOK_S: {"kind": "gauge", "layer": "sched", "unit": "tok/s", "help": "Per-worker marginal prefill throughput estimate from the cost-model EWMAs — prices the planner's re-role (morph vs spawn) decision.", "wire": True, "export": True},
    SCHED_EST_DECODE_TOK_S: {"kind": "gauge", "layer": "sched", "unit": "tok/s", "help": "Per-worker marginal decode throughput estimate from the cost-model EWMAs — prices the planner's re-role (morph vs spawn) decision.", "wire": True, "export": True},
    # ---- KVBM tiers / offload / checkpoint (kvbm/) -------------------
    "kvbm_g1_hit_blocks": {"kind": "counter", "layer": "kvbm", "unit": "blocks", "help": "Device prefix-cache hits at admission (G1).", "export": True},
    "kvbm_g1_miss_blocks": {"kind": "counter", "layer": "kvbm", "unit": "blocks", "help": "Device prefix-cache misses at admission (G1).", "export": True},
    "kvbm_onboard_count": {"kind": "counter", "layer": "kvbm", "help": "Tier onboard operations.", "export": True},
    "kvbm_onboard_ms_sum": {"kind": "counter", "layer": "kvbm", "unit": "ms", "help": "Cumulative onboard latency (mean = sum/count).", "export": True},
    "kvbm_onboard_hist": {"kind": "histogram", "layer": "kvbm", "unit": "ms", "help": "Onboard latency histogram (stats-dict blob).", "buckets": (1.0, 5.0, 20.0, 100.0, 500.0)},
    "kvbm_offloaded_blocks": {"kind": "counter", "layer": "kvbm", "unit": "blocks", "help": "Blocks offloaded device->host.", "export": True},
    "kvbm_onboarded_blocks": {"kind": "counter", "layer": "kvbm", "unit": "blocks", "help": "Blocks onboarded back to device.", "export": True},
    "kvbm_disk_evictions": {"kind": "counter", "layer": "kvbm", "help": "Disk-tier evictions.", "dynamic": True, "export": True},
    "kvbm_dropped_blocks": {"kind": "counter", "layer": "kvbm", "unit": "blocks", "help": "Blocks dropped out of the tier chain.", "export": True},
    "kvbm_host_eviction_policy": {"kind": "info", "layer": "kvbm", "help": "Host tier eviction policy name."},
    "kvbm_disk_eviction_policy": {"kind": "info", "layer": "kvbm", "help": "Disk tier eviction policy name."},
    "kvbm_host_blocks": {"kind": "gauge", "layer": "kvbm", "unit": "blocks", "help": "Blocks resident in the host tier (G2).", "dynamic": True, "export": True},
    "kvbm_host_capacity": {"kind": "gauge", "layer": "kvbm", "unit": "blocks", "help": "Host tier capacity.", "dynamic": True},
    "kvbm_host_hits": {"kind": "counter", "layer": "kvbm", "help": "Host tier lookup hits.", "dynamic": True, "export": True},
    "kvbm_host_misses": {"kind": "counter", "layer": "kvbm", "help": "Host tier lookup misses.", "dynamic": True, "export": True},
    "kvbm_host_evictions": {"kind": "counter", "layer": "kvbm", "help": "Host tier evictions.", "dynamic": True, "export": True},
    "kvbm_disk_blocks": {"kind": "gauge", "layer": "kvbm", "unit": "blocks", "help": "Blocks resident in the disk tier (G3).", "dynamic": True, "export": True},
    "kvbm_disk_capacity": {"kind": "gauge", "layer": "kvbm", "unit": "blocks", "help": "Disk tier capacity.", "dynamic": True},
    "kvbm_disk_hits": {"kind": "counter", "layer": "kvbm", "help": "Disk tier lookup hits.", "dynamic": True, "export": True},
    "kvbm_disk_misses": {"kind": "counter", "layer": "kvbm", "help": "Disk tier lookup misses.", "dynamic": True, "export": True},
    "kvbm_host_load_ms_per_block": {"kind": "gauge", "layer": "kvbm", "unit": "ms", "help": "Observed host-tier load cost per block.", "dynamic": True},
    "kvbm_disk_load_ms_per_block": {"kind": "gauge", "layer": "kvbm", "unit": "ms", "help": "Observed disk-tier load cost per block.", "dynamic": True},
    "kvbm_offload_commit_calls": {"kind": "counter", "layer": "kvbm", "help": "Offload commit batches entered.", "export": True},
    "kvbm_offload_gathers": {"kind": "counter", "layer": "kvbm", "help": "Device gathers staged for offload.", "export": True},
    "kvbm_offload_queue_depth": {"kind": "gauge", "layer": "kvbm", "help": "Offload batches waiting in the pipeline.", "export": True},
    "kvbm_offload_staged_blocks": {"kind": "counter", "layer": "kvbm", "unit": "blocks", "help": "Blocks staged for offload.", "export": True},
    "kvbm_offload_batches_dropped": {"kind": "counter", "layer": "kvbm", "help": "Offload batches dropped under backpressure.", "export": True},
    "kvbm_offload_blocks_dropped": {"kind": "counter", "layer": "kvbm", "unit": "blocks", "help": "Blocks dropped under offload backpressure.", "export": True},
    "kvbm_offload_failures": {"kind": "counter", "layer": "kvbm", "help": "Offload batches that failed.", "export": True},
    "kvbm_onboard_recompute_fallbacks": {"kind": "counter", "layer": "kvbm", "help": "Onboards that fell back to recompute.", "export": True},
    "kvbm_onboard_src_local_blocks": {"kind": "counter", "layer": "kvbm", "unit": "blocks", "help": "Onboarded blocks sourced from local tiers.", "export": True},
    "kvbm_onboard_src_peer_blocks": {"kind": "counter", "layer": "kvbm", "unit": "blocks", "help": "Onboarded blocks pulled from peers.", "export": True},
    "kvbm_onboard_src_recompute_blocks": {"kind": "counter", "layer": "kvbm", "unit": "blocks", "help": "Onboard blocks recomputed.", "export": True},
    "kvbm_pending_offloads": {"kind": "gauge", "layer": "kvbm", "help": "Offload futures not yet committed.", "export": True},
    "kvbm_ckpt_blocks_staged": {"kind": "counter", "layer": "kvbm", "unit": "blocks", "help": "Checkpoint blocks staged for replication.", "export": True},
    "kvbm_ckpt_blocks_pushed": {"kind": "counter", "layer": "kvbm", "unit": "blocks", "help": "Checkpoint blocks pushed to replica holders.", "export": True},
    "kvbm_ckpt_bytes_pushed": {"kind": "counter", "layer": "kvbm", "unit": "bytes", "help": "Checkpoint bytes pushed to replica holders.", "export": True},
    "kvbm_ckpt_blocks_dropped": {"kind": "counter", "layer": "kvbm", "unit": "blocks", "help": "Checkpoint blocks dropped (refuse-newest backpressure).", "export": True},
    "kvbm_ckpt_push_failures": {"kind": "counter", "layer": "kvbm", "help": "Checkpoint pushes that failed.", "export": True},
    "kvbm_ckpt_format_refusals": {"kind": "counter", "layer": "kvbm", "help": "Checkpoint pushes refused on KV-format mismatch.", "export": True},
    "kvbm_ckpt_queue_depth": {"kind": "gauge", "layer": "kvbm", "help": "Checkpoint batches waiting to push.", "export": True},
    "kvbm_ckpt_last_peer": {"kind": "info", "layer": "kvbm", "help": "Last checkpoint replica peer address."},
    "kvbm_remote_onboards": {"kind": "counter", "layer": "kvbm", "help": "Onboards served from remote peers.", "export": True},
    "kvbm_remote_blocks_pulled": {"kind": "counter", "layer": "kvbm", "unit": "blocks", "help": "Blocks pulled over the cluster KV fabric.", "export": True},
    "kvbm_peer_bytes_pulled": {"kind": "counter", "layer": "kvbm", "unit": "bytes", "help": "Bytes pulled over the cluster KV fabric.", "export": True},
    "kvbm_peer_pull_failures": {"kind": "counter", "layer": "kvbm", "help": "Peer pulls that failed (quarantine feed).", "export": True},
    "kvbm_peer_pull_ms_sum": {"kind": "counter", "layer": "kvbm", "unit": "ms", "help": "Cumulative peer-pull latency (mean = sum/onboards).", "export": True},
    "kvbm_peer_pull_hist": {"kind": "histogram", "layer": "kvbm", "unit": "ms", "help": "Peer-pull latency histogram (stats-dict blob).", "buckets": (5.0, 20.0, 50.0, 100.0, 250.0, 1000.0)},
    "kvbm_known_remote_blocks": {"kind": "gauge", "layer": "kvbm", "unit": "blocks", "help": "Remote blocks known to the fabric index.", "export": True},
    "kvbm_quarantined_peers": {"kind": "gauge", "layer": "kvbm", "help": "Peers currently quarantined after pull failures.", "export": True},
    "kvbm_known_checkpoint_blocks": {"kind": "gauge", "layer": "kvbm", "unit": "blocks", "help": "Checkpoint blocks known cluster-wide.", "export": True},
    "kvbm_ckpt_ineligible_peers": {"kind": "gauge", "layer": "kvbm", "help": "Peers refused as checkpoint targets (format skew).", "export": True},
    "kvbm_peer_ms_per_block": {"kind": "info", "layer": "kvbm", "unit": "ms", "help": "Per-peer observed pull cost map (addr -> ms/block)."},
    # ---- dynogate (gate/, frontend process) --------------------------
    "gate_enabled": {"kind": "gauge", "layer": "gate", "help": "1 when the admission gate is active."},
    "gate_admitted_total": {"kind": "counter", "layer": "gate", "help": "Requests admitted by the gate."},
    "gate_rejected_total": {"kind": "counter", "layer": "gate", "help": "Requests rejected (429) by the gate."},
    "gate_shed_total": {"kind": "counter", "layer": "gate", "help": "Parked requests shed before admission."},
    "gate_parked_total": {"kind": "counter", "layer": "gate", "help": "Requests parked in the admission queue."},
    "gate_queue_depth": {"kind": "gauge", "layer": "gate", "help": "Requests currently parked at the gate."},
    "gate_rejected_by_reason": {"kind": "info", "layer": "gate", "help": "Rejection counts keyed by reason (stats-dict map)."},
    "gate_retry_after_hist": {"kind": "histogram", "layer": "gate", "unit": "seconds", "help": "Retry-After values handed out (stats-dict blob).", "buckets": (1.0, 2.0, 5.0, 10.0)},
    "gate_per_tenant": {"kind": "info", "layer": "gate", "help": "Bounded per-tenant admit/reject map."},
    "gate_signal_samples": {"kind": "counter", "layer": "gate", "help": "Worker metric samples folded into gate signals."},
    # ---- frontend prometheus exposition (llm/http, llm/migration) ----
    "dynamo_frontend_requests_total": {"kind": "counter", "layer": "frontend", "unit": "requests", "help": "HTTP LLM requests completed.", "labels": ("model", "endpoint", "status"), "wire": True},
    "dynamo_frontend_inflight_requests": {"kind": "gauge", "layer": "frontend", "unit": "requests", "help": "Requests currently being processed.", "labels": ("model", "endpoint")},
    "dynamo_frontend_request_duration_seconds": {"kind": "histogram", "layer": "frontend", "unit": "seconds", "help": "End-to-end request duration.", "labels": ("model", "endpoint"), "wire": True, "buckets": (0.05, 0.1, 0.25, 0.5, 1, 2, 4, 8, 16, 32, 64, 128)},
    "dynamo_frontend_time_to_first_token_seconds": {"kind": "histogram", "layer": "frontend", "unit": "seconds", "help": "Time to first token.", "labels": ("model",), "wire": True, "buckets": (0.01, 0.025, 0.05, 0.1, 0.2, 0.4, 0.8, 1.6, 3.2, 6.4, 12.8)},
    "dynamo_frontend_output_tokens_total": {"kind": "counter", "layer": "frontend", "unit": "tokens", "help": "Generated tokens delivered to clients.", "labels": ("model",), "wire": True},
    "dynamo_frontend_input_tokens_total": {"kind": "counter", "layer": "frontend", "unit": "tokens", "help": "Prompt tokens accepted.", "labels": ("model",), "wire": True},
    "dynamo_frontend_inter_token_latency_seconds": {"kind": "histogram", "layer": "frontend", "unit": "seconds", "help": "Mean inter-token latency per request.", "labels": ("model",), "wire": True, "buckets": (0.002, 0.005, 0.01, 0.02, 0.04, 0.08, 0.16, 0.32, 0.64, 1.28)},
    "dynamo_frontend_client_disconnects_total": {"kind": "counter", "layer": "frontend", "help": "Client disconnects mid-stream.", "labels": ("model",)},
    "dynamo_frontend_tokens_per_frame": {"kind": "histogram", "layer": "frontend", "unit": "tokens", "help": "Generated tokens per streamed delta batch.", "labels": ("model",), "buckets": (1, 2, 4, 8, 16, 32, 64, 128)},
    "dynamo_frontend_migrations_total": {"kind": "counter", "layer": "frontend", "help": "Stream migrations started after worker loss."},
    "dynamo_frontend_migration_replayed_tokens_total": {"kind": "counter", "layer": "frontend", "unit": "tokens", "help": "Tokens replayed into migration retry prompts."},
    "dynamo_frontend_migrations_exhausted_total": {"kind": "counter", "layer": "frontend", "help": "Streams that ran out of migration budget."},
    "dynamo_frontend_gate_admitted_total": {"kind": "counter", "layer": "gate", "help": "Gate admissions (exposition view)."},
    "dynamo_frontend_gate_rejected_total": {"kind": "counter", "layer": "gate", "help": "Gate rejections (exposition view)."},
    "dynamo_frontend_gate_shed_total": {"kind": "counter", "layer": "gate", "help": "Parked requests shed (exposition view)."},
    "dynamo_frontend_gate_queue_depth": {"kind": "gauge", "layer": "gate", "help": "Parked requests right now (exposition view)."},
    "dynamo_frontend_gate_rejected_by_reason_total": {"kind": "counter", "layer": "gate", "help": "Gate rejections by bounded reason.", "labels": ("reason",)},
    "dynamo_frontend_gate_tenant_requests_total": {"kind": "counter", "layer": "gate", "help": "Per-tenant admit/reject counts (bounded tenant set).", "labels": ("tenant", "outcome")},
    "dynamo_frontend_gate_retry_after_seconds": {"kind": "histogram", "layer": "gate", "unit": "seconds", "help": "Retry-After values handed out.", "buckets": (1.0, 2.0, 5.0, 10.0)},
    # ---- KV router / indexer (frontend process) ----------------------
    "index_blocks": {"kind": "gauge", "layer": "router", "unit": "blocks", "help": "Blocks tracked by the KV event index."},
    "index_max_blocks": {"kind": "gauge", "layer": "router", "unit": "blocks", "help": "Index capacity (0 = unbounded)."},
    "index_evicted_blocks": {"kind": "counter", "layer": "router", "unit": "blocks", "help": "Index entries evicted at capacity."},
    "index_mappings": {"kind": "gauge", "layer": "router", "help": "hash->worker mappings held."},
    "index_memory_bytes_estimate": {"kind": "gauge", "layer": "router", "unit": "bytes", "help": "Estimated index memory footprint."},
    "events_applied": {"kind": "counter", "layer": "router", "help": "KV events applied to the index."},
    # ---- vLLM-dialect aliases (read-if-present by protocols) ---------
    "request_active_slots": {"kind": "gauge", "layer": "router", "unit": "slots", "help": "vLLM-dialect alias of num_running_reqs (read if present)."},
    "num_requests_waiting": {"kind": "gauge", "layer": "router", "unit": "requests", "help": "vLLM-dialect alias of num_waiting_reqs (read if present)."},
    "data_parallel_rank": {"kind": "gauge", "layer": "router", "help": "Publisher's data-parallel rank (read if present)."},
    "gpu_prefix_cache_hit_rate": {"kind": "gauge", "layer": "router", "unit": "fraction", "help": "vLLM-dialect prefix hit rate (read if present)."},
    "spec_decode": {"kind": "info", "layer": "router", "help": "Nested speculative-decode stats blob (read if present)."},
    # ---- runtime plumbing (worker process) ---------------------------
    "frames_total": {"kind": "counter", "layer": "worker", "help": "Request-plane frames handled by the endpoint."},
    "items_total": {"kind": "counter", "layer": "worker", "help": "Stream items delivered by the endpoint."},
    "frames_binary": {"kind": "counter", "layer": "worker", "help": "Zero-copy binary frames on the token wire path."},
    "compute_threads": {"kind": "gauge", "layer": "worker", "help": "Compute-pool worker threads."},
    "compute_tasks_run": {"kind": "counter", "layer": "worker", "help": "Tasks run on the compute pool."},
}


def worker_exported_stats() -> Tuple[str, ...]:
    """Stats keys jax_worker republishes as dynamo_worker_<name> prometheus
    gauges (system-status /metrics). Driven by the registry so a key added
    to METRICS with export=True is exported without touching the worker —
    the 'published but never exported' drift class is gone structurally.
    Only scalar kinds are exportable; the registry seeds keep info/
    histogram entries unexported and the met-kind-discipline rule enforces
    it."""
    return tuple(
        name for name, spec in METRICS.items() if spec.get("export")
    )


def metric_spec(name: str) -> Optional[dict]:
    """Registry entry for `name`, or None. Exposition helpers use this to
    keep HELP/TYPE lines consistent with the contract."""
    return METRICS.get(name)


class MetricsRegistry:
    """One node in the metrics hierarchy. The root owns the
    prometheus-client CollectorRegistry; children share it and add labels."""

    def __init__(
        self,
        prefix: str = "dynamo",
        _registry: Optional[CollectorRegistry] = None,
        _labels: Optional[Dict[str, str]] = None,
        _root: Optional["MetricsRegistry"] = None,
    ):
        self.prefix = prefix
        self.registry = _registry or CollectorRegistry()
        self.labels = dict(_labels or {})
        self._root = _root or self
        if _root is None:
            self._metrics: Dict[str, object] = {}
            self._lock = threading.Lock()
            self._callbacks: List[Callable[[], None]] = []

    # -- hierarchy ----------------------------------------------------------
    def child(self, level: str, name: str) -> "MetricsRegistry":
        labels = dict(self.labels)
        labels[level] = name
        return MetricsRegistry(
            self.prefix, _registry=self.registry, _labels=labels, _root=self._root
        )

    def for_namespace(self, name: str) -> "MetricsRegistry":
        return self.child("dynamo_namespace", name)

    def for_component(self, name: str) -> "MetricsRegistry":
        return self.child("dynamo_component", name)

    def for_endpoint(self, name: str) -> "MetricsRegistry":
        return self.child("dynamo_endpoint", name)

    # -- metric constructors -------------------------------------------------
    # every metric carries ALL hierarchy labels ("" when minted above that
    # level): one prometheus collector can then serve the same metric name
    # from any depth, and label arity never conflicts
    def _label_names(self, extra: Sequence[str]) -> Tuple[str, ...]:
        return HIERARCHY_LABELS + tuple(extra)

    def _label_values(self) -> Tuple[str, ...]:
        return tuple(self.labels.get(k, "") for k in HIERARCHY_LABELS)

    def _get_or_create(self, cls, name: str, doc: str, extra_labels: Sequence[str], **kw):
        root = self._root
        full = f"{self.prefix}_{name}"
        names = self._label_names(extra_labels)
        with root._lock:
            cached = root._metrics.get(full)
            if cached is None:
                metric = cls(full, doc, names, registry=self.registry, **kw)
                root._metrics[full] = (metric, names, kw)
                return metric
            metric, cached_names, cached_kw = cached
            if cached_names != names:
                raise ValueError(
                    f"metric {full} already registered with labels "
                    f"{cached_names}, requested {names}"
                )
            if cached_kw != kw:
                raise ValueError(
                    f"metric {full} already registered with options "
                    f"{cached_kw}, requested {kw} (e.g. differing buckets)"
                )
        return metric

    def counter(self, name: str, doc: str = "", extra_labels: Sequence[str] = ()):
        m = self._get_or_create(Counter, name, doc or name, extra_labels)
        return m.labels(*self._label_values()) if not extra_labels else _Partial(m, self._label_values())

    def gauge(self, name: str, doc: str = "", extra_labels: Sequence[str] = ()):
        m = self._get_or_create(Gauge, name, doc or name, extra_labels)
        return m.labels(*self._label_values()) if not extra_labels else _Partial(m, self._label_values())

    def histogram(
        self,
        name: str,
        doc: str = "",
        extra_labels: Sequence[str] = (),
        buckets: Optional[Sequence[float]] = None,
    ):
        kw = {"buckets": tuple(buckets)} if buckets else {}
        m = self._get_or_create(Histogram, name, doc or name, extra_labels, **kw)
        return m.labels(*self._label_values()) if not extra_labels else _Partial(m, self._label_values())

    def callback_gauge(self, name: str, doc: str, fn: Callable[[], float]):
        """Gauge whose value is computed at scrape time (reference metrics
        callbacks): re-evaluated by render()."""
        g = self.gauge(name, doc)
        root = self._root

        def update():
            try:
                g.set(fn())
            except Exception:  # noqa: BLE001 — scrape must not die
                pass

        root._callbacks.append(update)
        return g

    # -- export ---------------------------------------------------------------
    def render(self) -> bytes:
        for cb in self._root._callbacks:
            cb()
        return generate_latest(self.registry)


class _Partial:
    """Metric bound to the hierarchy labels, awaiting the extra labels."""

    def __init__(self, metric, hier_values: Tuple[str, ...]):
        self._metric = metric
        self._hier = hier_values

    def labels(self, *values: str):
        return self._metric.labels(*self._hier, *values)
