"""Hierarchical runtime metrics registry.

Role of the reference's MetricsRegistry (lib/runtime/src/metrics.rs,
MetricsRegistryEntry lib.rs:92): every level of the
DRT → namespace → component → endpoint hierarchy can mint Prometheus
counters/gauges/histograms that are automatically labeled with their
position in the hierarchy (dynamo_namespace / dynamo_component /
dynamo_endpoint), all collected into one process-wide registry that the
system status server exports at /metrics. Callback gauges mirror the
reference's metrics callbacks (scrape-time evaluation).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

HIERARCHY_LABELS = ("dynamo_namespace", "dynamo_component", "dynamo_endpoint")


class MetricsRegistry:
    """One node in the metrics hierarchy. The root owns the
    prometheus-client CollectorRegistry; children share it and add labels."""

    def __init__(
        self,
        prefix: str = "dynamo",
        _registry: Optional[CollectorRegistry] = None,
        _labels: Optional[Dict[str, str]] = None,
        _root: Optional["MetricsRegistry"] = None,
    ):
        self.prefix = prefix
        self.registry = _registry or CollectorRegistry()
        self.labels = dict(_labels or {})
        self._root = _root or self
        if _root is None:
            self._metrics: Dict[str, object] = {}
            self._lock = threading.Lock()
            self._callbacks: List[Callable[[], None]] = []

    # -- hierarchy ----------------------------------------------------------
    def child(self, level: str, name: str) -> "MetricsRegistry":
        labels = dict(self.labels)
        labels[level] = name
        return MetricsRegistry(
            self.prefix, _registry=self.registry, _labels=labels, _root=self._root
        )

    def for_namespace(self, name: str) -> "MetricsRegistry":
        return self.child("dynamo_namespace", name)

    def for_component(self, name: str) -> "MetricsRegistry":
        return self.child("dynamo_component", name)

    def for_endpoint(self, name: str) -> "MetricsRegistry":
        return self.child("dynamo_endpoint", name)

    # -- metric constructors -------------------------------------------------
    # every metric carries ALL hierarchy labels ("" when minted above that
    # level): one prometheus collector can then serve the same metric name
    # from any depth, and label arity never conflicts
    def _label_names(self, extra: Sequence[str]) -> Tuple[str, ...]:
        return HIERARCHY_LABELS + tuple(extra)

    def _label_values(self) -> Tuple[str, ...]:
        return tuple(self.labels.get(k, "") for k in HIERARCHY_LABELS)

    def _get_or_create(self, cls, name: str, doc: str, extra_labels: Sequence[str], **kw):
        root = self._root
        full = f"{self.prefix}_{name}"
        names = self._label_names(extra_labels)
        with root._lock:
            cached = root._metrics.get(full)
            if cached is None:
                metric = cls(full, doc, names, registry=self.registry, **kw)
                root._metrics[full] = (metric, names, kw)
                return metric
            metric, cached_names, cached_kw = cached
            if cached_names != names:
                raise ValueError(
                    f"metric {full} already registered with labels "
                    f"{cached_names}, requested {names}"
                )
            if cached_kw != kw:
                raise ValueError(
                    f"metric {full} already registered with options "
                    f"{cached_kw}, requested {kw} (e.g. differing buckets)"
                )
        return metric

    def counter(self, name: str, doc: str = "", extra_labels: Sequence[str] = ()):
        m = self._get_or_create(Counter, name, doc or name, extra_labels)
        return m.labels(*self._label_values()) if not extra_labels else _Partial(m, self._label_values())

    def gauge(self, name: str, doc: str = "", extra_labels: Sequence[str] = ()):
        m = self._get_or_create(Gauge, name, doc or name, extra_labels)
        return m.labels(*self._label_values()) if not extra_labels else _Partial(m, self._label_values())

    def histogram(
        self,
        name: str,
        doc: str = "",
        extra_labels: Sequence[str] = (),
        buckets: Optional[Sequence[float]] = None,
    ):
        kw = {"buckets": tuple(buckets)} if buckets else {}
        m = self._get_or_create(Histogram, name, doc or name, extra_labels, **kw)
        return m.labels(*self._label_values()) if not extra_labels else _Partial(m, self._label_values())

    def callback_gauge(self, name: str, doc: str, fn: Callable[[], float]):
        """Gauge whose value is computed at scrape time (reference metrics
        callbacks): re-evaluated by render()."""
        g = self.gauge(name, doc)
        root = self._root

        def update():
            try:
                g.set(fn())
            except Exception:  # noqa: BLE001 — scrape must not die
                pass

        root._callbacks.append(update)
        return g

    # -- export ---------------------------------------------------------------
    def render(self) -> bytes:
        for cb in self._root._callbacks:
            cb()
        return generate_latest(self.registry)


class _Partial:
    """Metric bound to the hierarchy labels, awaiting the extra labels."""

    def __init__(self, metric, hier_values: Tuple[str, ...]):
        self._metric = metric
        self._hier = hier_values

    def labels(self, *values: str):
        return self._metric.labels(*self._hier, *values)
