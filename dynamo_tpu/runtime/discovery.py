"""Built-in discovery / KV-store service: the control-plane "etcd".

The reference uses etcd for service discovery, leases, model cards and NIXL
metadata (lib/runtime/src/transports/etcd.rs:95, Lease :43, kv_watch_prefix
:325).  This image ships no etcd binary, so dynamo-tpu provides an
etcd-semantics service as part of the framework: a single asyncio TCP server
offering

  * a revisioned key-value store (put / get / get_prefix / delete)
  * atomic create (fails if the key exists — reference `kv_create`)
  * leases with TTL + keepalive; lease death deletes attached keys
  * prefix watches streaming PUT/DELETE events (reference kv_watch_prefix)
  * distributed locks built on atomic-create + lease

It can run standalone (`python -m dynamo_tpu.runtime.discovery`) or embedded
in the frontend process.  Protocol: two-part frames (codec.py), multiplexed
by `req_id`; watch events are server-pushed with a `watch_id`.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from . import codec, faults
from .backoff import Backoff
from .codec import (
    OP_CREATE,
    OP_DELETE,
    OP_DELETE_PREFIX,
    OP_GET,
    OP_GET_PREFIX,
    OP_LEASE_GRANT,
    OP_LEASE_KEEPALIVE,
    OP_LEASE_REVOKE,
    OP_PUBLISH,
    OP_PUT,
    OP_STATUS,
    OP_SUBSCRIBE,
    OP_UNSUBSCRIBE,
    OP_UNWATCH,
    OP_WATCH,
    PUSH_MSG,
    PUSH_WATCH,
)

logger = logging.getLogger(__name__)

PUT = "put"
DELETE = "delete"


# --------------------------------------------------------------------------- #
# Server
# --------------------------------------------------------------------------- #


@dataclass
class _KeyRecord:
    value: bytes
    lease_id: int
    create_revision: int
    mod_revision: int


@dataclass
class _Lease:
    lease_id: int
    ttl: float
    deadline: float
    keys: set = field(default_factory=set)


@dataclass
class _Watcher:
    watch_id: int
    prefix: str
    writer: asyncio.StreamWriter


class DiscoveryServer:
    """In-process etcd-role server. State is in-memory; durability is not a
    goal (the reference treats etcd state as lease-scoped soft state too)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host, self.port = host, port
        self._kv: Dict[str, _KeyRecord] = {}
        self._leases: Dict[int, _Lease] = {}
        self._watchers: Dict[int, _Watcher] = {}
        self._revision = 0
        self._lease_ids = itertools.count(1)
        self._watch_ids = itertools.count(1)
        self._server: Optional[asyncio.base_events.Server] = None
        self._reaper: Optional[asyncio.Task] = None
        self._connections: set[asyncio.StreamWriter] = set()
        self._subs: Dict[str, List[_Watcher]] = {}  # topic -> subscribers
        self._subs_by_id: Dict[int, _Watcher] = {}

    # -- lifecycle ---------------------------------------------------------- #

    async def start(self) -> Tuple[str, int]:
        self._server = await asyncio.start_server(self._serve_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._reaper = asyncio.create_task(self._reap_leases())
        logger.info("discovery server listening on %s:%d", self.host, self.port)
        return self.host, self.port

    async def stop(self):
        if self._reaper:
            self._reaper.cancel()
        if self._server:
            self._server.close()
        # close live connections, else wait_closed() blocks on their handlers
        for writer in list(self._connections):
            writer.close()
        if self._server:
            await self._server.wait_closed()

    async def _reap_leases(self):
        while True:
            await asyncio.sleep(0.5)
            now = time.monotonic()
            dead = [l for l in self._leases.values() if l.deadline < now]
            for lease in dead:
                # revoking awaits (watch notifications) — a keepalive can
                # land between the scan above and this revoke, and killing
                # a just-refreshed lease would drop a live worker from the
                # serving set. Re-check the CURRENT deadline.
                if lease.lease_id not in self._leases:
                    continue
                if self._leases[lease.lease_id].deadline >= now:
                    continue
                logger.info("lease %d expired; deleting %d keys", lease.lease_id, len(lease.keys))
                await self._revoke(lease.lease_id)

    async def _revoke(self, lease_id: int):
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return
        for key in list(lease.keys):
            await self._delete_key(key)

    # -- kv ops ------------------------------------------------------------- #

    async def _put(self, key: str, value: bytes, lease_id: int, create_only: bool) -> dict:
        existing = self._kv.get(key)
        if create_only and existing is not None:
            return {"ok": False, "error": "key exists"}
        if lease_id and lease_id not in self._leases:
            return {"ok": False, "error": f"unknown lease {lease_id}"}
        self._revision += 1
        rec = _KeyRecord(
            value=value,
            lease_id=lease_id,
            create_revision=existing.create_revision if existing else self._revision,
            mod_revision=self._revision,
        )
        self._kv[key] = rec
        if existing and existing.lease_id and existing.lease_id != lease_id:
            old = self._leases.get(existing.lease_id)
            if old:
                old.keys.discard(key)
        if lease_id:
            self._leases[lease_id].keys.add(key)
        await self._notify(PUT, key, value)
        return {"ok": True, "revision": self._revision}

    async def _delete_key(self, key: str) -> bool:
        rec = self._kv.pop(key, None)
        if rec is None:
            return False
        self._revision += 1
        if rec.lease_id:
            lease = self._leases.get(rec.lease_id)
            if lease:
                lease.keys.discard(key)
        await self._notify(DELETE, key, b"")
        return True

    async def _notify(self, ev_type: str, key: str, value: bytes):
        for w in list(self._watchers.values()):
            if key.startswith(w.prefix):
                try:
                    await codec.write_frame(
                        w.writer,
                        {"push": PUSH_WATCH, "watch_id": w.watch_id, "type": ev_type, "key": key},
                        value,
                    )
                except (ConnectionError, RuntimeError):
                    self._watchers.pop(w.watch_id, None)

    def _drop_sub(self, sub: _Watcher):
        self._subs_by_id.pop(sub.watch_id, None)
        lst = self._subs.get(sub.prefix)
        if lst and sub in lst:
            lst.remove(sub)

    # -- connection handling ------------------------------------------------ #

    async def _serve_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        conn_watches: List[int] = []
        self._connections.add(writer)
        try:
            while True:
                frame = await codec.read_frame(reader)
                if frame is None:
                    break
                control, payload = frame
                resp, resp_payload = await self._dispatch(
                    control, payload, writer, conn_watches
                )
                resp["req_id"] = control.get("req_id")
                await codec.write_frame(writer, resp, resp_payload)
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except ValueError as e:
            logger.warning("dropping connection speaking a bad protocol: %s", e)
        finally:
            for wid in conn_watches:
                if wid < 0:
                    sub = self._subs_by_id.get(-wid)
                    if sub:
                        self._drop_sub(sub)
                else:
                    self._watchers.pop(wid, None)
            # Leases survive connection loss until TTL expiry (like etcd):
            # a client that reconnects fast enough keeps its registration.
            self._connections.discard(writer)
            writer.close()

    async def _dispatch(
        self, control: dict, payload: bytes, writer, conn_watches
    ) -> Tuple[dict, bytes]:
        op = control.get("op")
        if op == OP_PUT:
            r = await self._put(
                control["key"], payload, control.get("lease_id", 0), create_only=False
            )
            return r, b""
        if op == OP_CREATE:
            r = await self._put(
                control["key"], payload, control.get("lease_id", 0), create_only=True
            )
            return r, b""
        if op == OP_GET:
            rec = self._kv.get(control["key"])
            if rec is None:
                return {"ok": True, "found": False}, b""
            return {"ok": True, "found": True, "revision": rec.mod_revision}, rec.value
        if op == OP_GET_PREFIX:
            prefix = control["prefix"]
            items = [
                {"key": k, "value": rec.value, "revision": rec.mod_revision}
                for k, rec in sorted(self._kv.items())
                if k.startswith(prefix)
            ]
            return {"ok": True, "revision": self._revision}, codec.pack(items)
        if op == OP_DELETE:
            deleted = await self._delete_key(control["key"])
            return {"ok": True, "deleted": deleted}, b""
        if op == OP_DELETE_PREFIX:
            keys = [k for k in list(self._kv) if k.startswith(control["prefix"])]
            deleted = 0
            for k in keys:
                # each delete awaits watcher notification — skip keys a
                # concurrent op already removed during an earlier await
                if k not in self._kv:
                    continue
                await self._delete_key(k)
                deleted += 1
            return {"ok": True, "deleted": deleted}, b""
        if op == OP_LEASE_GRANT:
            ttl = float(control.get("ttl", 10.0))
            lease = _Lease(next(self._lease_ids), ttl, time.monotonic() + ttl)
            self._leases[lease.lease_id] = lease
            return {"ok": True, "lease_id": lease.lease_id, "ttl": ttl}, b""
        if op == OP_LEASE_KEEPALIVE:
            lease = self._leases.get(control["lease_id"])
            if lease is None:
                return {"ok": False, "error": "lease expired"}, b""
            lease.deadline = time.monotonic() + lease.ttl
            return {"ok": True, "ttl": lease.ttl}, b""
        if op == OP_LEASE_REVOKE:
            await self._revoke(control["lease_id"])
            return {"ok": True}, b""
        if op == OP_WATCH:
            wid = next(self._watch_ids)
            self._watchers[wid] = _Watcher(wid, control["prefix"], writer)
            conn_watches.append(wid)
            # initial snapshot so watchers don't race registration
            items = [
                {"key": k, "value": rec.value, "revision": rec.mod_revision}
                for k, rec in sorted(self._kv.items())
                if k.startswith(control["prefix"])
            ]
            return {"ok": True, "watch_id": wid}, codec.pack(items)
        if op == OP_UNWATCH:
            self._watchers.pop(control["watch_id"], None)
            return {"ok": True}, b""
        if op == OP_PUBLISH:
            # NATS-core-role pub/sub: fan out to live topic subscribers, no
            # persistence (KV events, metrics broadcast)
            topic = control["topic"]
            for sub in list(self._subs.get(topic, [])):
                try:
                    await codec.write_frame(
                        sub.writer,
                        {"push": PUSH_MSG, "sub_id": sub.watch_id, "topic": topic},
                        payload,
                    )
                except (ConnectionError, RuntimeError):
                    self._drop_sub(sub)
            return {"ok": True}, b""
        if op == OP_SUBSCRIBE:
            wid = next(self._watch_ids)
            sub = _Watcher(wid, control["topic"], writer)
            self._subs.setdefault(control["topic"], []).append(sub)
            self._subs_by_id[wid] = sub
            conn_watches.append(-wid)  # negative marks a topic sub
            return {"ok": True, "sub_id": wid}, b""
        if op == OP_UNSUBSCRIBE:
            sub = self._subs_by_id.get(control["sub_id"])
            if sub:
                self._drop_sub(sub)
            return {"ok": True}, b""
        if op == OP_STATUS:
            return {
                "ok": True,
                "revision": self._revision,
                "keys": len(self._kv),
                "leases": len(self._leases),
            }, b""
        return {"ok": False, "error": f"unknown op {op}"}, b""


# --------------------------------------------------------------------------- #
# Client
# --------------------------------------------------------------------------- #


@dataclass
class WatchEvent:
    type: str  # "put" | "delete"
    key: str
    value: bytes


class Watch:
    """A live prefix watch: initial snapshot + async event stream."""

    def __init__(self, watch_id: int, snapshot: List[dict], client: "DiscoveryClient"):
        self.watch_id = watch_id
        self.snapshot = snapshot
        self._queue: asyncio.Queue[Optional[WatchEvent]] = asyncio.Queue()
        self._client = client

    def __aiter__(self):
        return self

    async def __anext__(self) -> WatchEvent:
        ev = await self._queue.get()
        if ev is None:
            raise StopAsyncIteration
        return ev

    async def get(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        if timeout is None:
            return await self._queue.get()
        try:
            return await asyncio.wait_for(self._queue.get(), timeout)
        except asyncio.TimeoutError:
            return None

    async def cancel(self):
        await self._client._unwatch(self.watch_id)
        self._queue.put_nowait(None)


class Subscription:
    """A live topic subscription (NATS-core role): async stream of payloads."""

    def __init__(self, sub_id: int, topic: str, client: "DiscoveryClient"):
        self.sub_id = sub_id
        self.topic = topic
        self._queue: asyncio.Queue[Optional[bytes]] = asyncio.Queue()
        self._client = client

    def __aiter__(self):
        return self

    async def __anext__(self) -> bytes:
        item = await self._queue.get()
        if item is None:
            raise StopAsyncIteration
        return item

    async def get(self, timeout: Optional[float] = None) -> Optional[bytes]:
        if timeout is None:
            return await self._queue.get()
        try:
            return await asyncio.wait_for(self._queue.get(), timeout)
        except asyncio.TimeoutError:
            return None

    async def cancel(self):
        self._client._subs.pop(self.sub_id, None)
        try:
            await self._client._call({"op": OP_UNSUBSCRIBE, "sub_id": self.sub_id})
        except ConnectionError:
            pass
        self._queue.put_nowait(None)


class Lease:
    """Client-side lease handle with a background keepalive task
    (reference: Lease etcd.rs:43 — primary lease keeps instances alive).

    If a keepalive discovers the lease expired server-side (e.g. the event
    loop was blocked past the TTL by a long XLA compile), `on_lost` is
    invoked so the owner can re-grant and re-publish its keys."""

    def __init__(self, lease_id: int, ttl: float, client: "DiscoveryClient"):
        self.lease_id = lease_id
        self.ttl = ttl
        self._client = client
        self._task: Optional[asyncio.Task] = None
        self.alive = True
        self.on_lost: Optional[Callable] = None  # async callback

    def start_keepalive(self):
        self._task = asyncio.create_task(self._keepalive_loop())

    async def _keepalive_loop(self):
        interval = max(self.ttl / 3.0, 0.2)
        while self.alive:
            await asyncio.sleep(interval)
            f = faults.FAULTS
            if f.enabled and f.check("discovery.lease") == "drop":
                # simulate server-side expiry (reaped TTL): revoke behind our
                # own back so the NEXT keepalive walks the lost/re-grant path
                try:
                    await self._client._call(
                        {"op": OP_LEASE_REVOKE, "lease_id": self.lease_id}
                    )
                except ConnectionError:
                    pass
            try:
                resp = await self._client._call({"op": OP_LEASE_KEEPALIVE, "lease_id": self.lease_id})
                if not resp[0].get("ok"):
                    logger.warning(
                        "lease %d lost (%s); attempting re-grant",
                        self.lease_id,
                        resp[0].get("error"),
                    )
                    if await self._regrant():
                        continue
                    self.alive = False
            except ConnectionError:
                # the discovery socket died, not the lease: reconnect with
                # backoff inside the TTL budget, then re-grant — a worker
                # must not silently fall out of the serving set because of
                # one TCP reset
                logger.warning(
                    "lease %d keepalive connection lost; reconnecting", self.lease_id
                )
                deadline = time.monotonic() + self.ttl
                if await self._client.ensure_connected(deadline=deadline) and \
                        await self._regrant():
                    continue
                self.alive = False

    async def _regrant(self) -> bool:
        try:
            resp, _ = await self._client._call({"op": OP_LEASE_GRANT, "ttl": self.ttl})
            if not resp.get("ok"):
                return False
            self.lease_id = resp["lease_id"]
            if self.on_lost is not None:
                await self.on_lost(self)
            logger.info("lease re-granted as %d; keys re-published", self.lease_id)
            return True
        except ConnectionError:
            return False

    async def revoke(self):
        self.alive = False
        if self._task:
            self._task.cancel()
        try:
            await self._client._call({"op": OP_LEASE_REVOKE, "lease_id": self.lease_id})
        except ConnectionError:
            pass


class DiscoveryClient:
    """Async client for the discovery service. One TCP connection,
    multiplexed by req_id; watch pushes are routed to Watch queues."""

    def __init__(self, host: str, port: int):
        self.host, self.port = host, port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._req_ids = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._watches: Dict[int, Watch] = {}
        self._subs: Dict[int, Subscription] = {}
        self._recv_task: Optional[asyncio.Task] = None
        self._lock = asyncio.Lock()
        self._closed = False
        self._reconnect_lock = asyncio.Lock()

    @classmethod
    async def connect(
        cls, host: str, port: int, retries: int = 50, delay: float = 0.1
    ) -> "DiscoveryClient":
        client = cls(host, port)
        last_err: Optional[Exception] = None
        for _ in range(retries):
            try:
                client._reader, client._writer = await asyncio.open_connection(host, port)
                client._recv_task = asyncio.create_task(client._recv_loop())
                return client
            except OSError as e:
                last_err = e
                await asyncio.sleep(delay)
        raise ConnectionError(f"cannot reach discovery service at {host}:{port}: {last_err}")

    async def close(self):
        self._closed = True
        if self._recv_task:
            self._recv_task.cancel()
        if self._writer:
            self._writer.close()
        # the recv loop may have ALREADY exited (connection died earlier,
        # subscriptions left parked awaiting a reconnect that now never
        # comes): flush terminators unconditionally — a duplicate None
        # past the first is harmless
        for watch in self._watches.values():
            watch._queue.put_nowait(None)
        for sub in self._subs.values():
            sub._queue.put_nowait(None)

    async def ensure_connected(
        self, deadline: Optional[float] = None, backoff: Optional[Backoff] = None
    ) -> bool:
        """Re-establish the discovery socket after a loss, with backoff.

        Watches do NOT survive (the server binds them to the connection) —
        holders re-watch via `watch_prefix` (component.Client does this);
        topic subscriptions are re-established here in place, keeping the
        Subscription objects valid. Returns False once `deadline` passes
        or the client was deliberately closed."""
        if self._closed:
            return False
        if self._writer is not None and not self._writer.is_closing():
            return True
        async with self._reconnect_lock:
            if self._closed:
                return False
            if self._writer is not None and not self._writer.is_closing():
                return True  # another caller reconnected while we waited
            if backoff is None:
                # stable seed: reconnect timing reproduces across re-runs
                backoff = Backoff.seeded(
                    f"{self.host}:{self.port}", base=0.05, max_delay=1.0
                )
            while not self._closed:
                try:
                    self._reader, self._writer = await asyncio.open_connection(
                        self.host, self.port
                    )
                    self._recv_task = asyncio.create_task(self._recv_loop())
                    break
                except OSError:
                    if not await backoff.wait(deadline):
                        return False
            if self._closed:
                return False
            for sub in list(self._subs.values()):
                try:
                    resp, _ = await self._call({"op": OP_SUBSCRIBE, "topic": sub.topic})
                    self._subs.pop(sub.sub_id, None)
                    sub.sub_id = resp["sub_id"]
                    self._subs[sub.sub_id] = sub
                except (ConnectionError, KeyError):
                    logger.warning("failed to re-subscribe %s after reconnect", sub.topic)
            logger.info("discovery connection re-established to %s:%d", self.host, self.port)
            return True

    async def _recv_loop(self):
        # capture THIS connection's streams: after a reconnect the old
        # loop's finally must close the dead writer, never the fresh one
        reader, writer = self._reader, self._writer
        assert reader is not None
        try:
            while True:
                frame = await codec.read_frame(reader)
                if frame is None:
                    break
                f = faults.FAULTS
                if f.enabled and f.check("discovery.watch") == "disconnect":
                    # drop the whole control-plane connection: watches end,
                    # pending calls fail — exercising the re-watch path
                    writer.close()
                    break
                control, payload = frame
                if control.get("push") == PUSH_WATCH:
                    watch = self._watches.get(control["watch_id"])
                    if watch:
                        watch._queue.put_nowait(
                            WatchEvent(control["type"], control["key"], payload)
                        )
                    continue
                if control.get("push") == PUSH_MSG:
                    sub = self._subs.get(control["sub_id"])
                    if sub:
                        sub._queue.put_nowait(payload)
                    continue
                fut = self._pending.pop(control.get("req_id"), None)
                if fut and not fut.done():
                    fut.set_result((control, payload))
        except ConnectionError:
            pass
        except asyncio.CancelledError:
            raise  # cleanup below still runs; the task records cancelled
        finally:
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("discovery connection lost"))
            self._pending.clear()
            # watches end (server state died with the connection): holders
            # notice the None and re-watch after ensure_connected
            for watch in list(self._watches.values()):
                watch._queue.put_nowait(None)
            self._watches.clear()
            if self._closed:
                # deliberate close: end subscription iterators too. On an
                # accidental loss they stay parked — ensure_connected
                # re-subscribes them in place.
                for sub in self._subs.values():
                    sub._queue.put_nowait(None)
            # an organic EOF (server died/restarted) must mark this
            # connection dead, or ensure_connected() would report the
            # corpse healthy and every later _call() would park forever
            writer.close()

    async def _call(self, control: dict, payload: bytes = b"") -> Tuple[dict, bytes]:
        if self._writer is None or self._writer.is_closing():
            raise ConnectionError("discovery client not connected")
        req_id = next(self._req_ids)
        control["req_id"] = req_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        try:
            async with self._lock:
                await codec.write_frame(self._writer, control, payload)
        except (ConnectionError, OSError):
            self._pending.pop(req_id, None)
            raise ConnectionError("discovery connection lost")
        resp, resp_payload = await fut
        if not resp.get("ok", False) and "error" in resp:
            # callers inspect; we only raise for connection-level problems
            pass
        return resp, resp_payload

    # -- public api --------------------------------------------------------- #

    async def put(self, key: str, value: bytes, lease: Optional[Lease] = None):
        resp, _ = await self._call(
            {"op": OP_PUT, "key": key, "lease_id": lease.lease_id if lease else 0}, value
        )
        if not resp["ok"]:
            raise RuntimeError(f"put {key} failed: {resp.get('error')}")

    async def create(self, key: str, value: bytes, lease: Optional[Lease] = None) -> bool:
        """Atomic create; returns False if the key already exists
        (reference etcd kv_create)."""
        resp, _ = await self._call(
            {"op": OP_CREATE, "key": key, "lease_id": lease.lease_id if lease else 0}, value
        )
        if not resp["ok"] and resp.get("error") == "key exists":
            return False
        if not resp["ok"]:
            raise RuntimeError(f"create {key} failed: {resp.get('error')}")
        return True

    async def get(self, key: str) -> Optional[bytes]:
        resp, payload = await self._call({"op": OP_GET, "key": key})
        return payload if resp.get("found") else None

    async def get_prefix(self, prefix: str) -> List[dict]:
        _, payload = await self._call({"op": OP_GET_PREFIX, "prefix": prefix})
        return codec.unpack(payload)

    async def delete(self, key: str) -> bool:
        resp, _ = await self._call({"op": OP_DELETE, "key": key})
        return bool(resp.get("deleted"))

    async def delete_prefix(self, prefix: str) -> int:
        resp, _ = await self._call({"op": OP_DELETE_PREFIX, "prefix": prefix})
        return int(resp.get("deleted", 0))

    async def grant_lease(self, ttl: float = 10.0, keepalive: bool = True) -> Lease:
        resp, _ = await self._call({"op": OP_LEASE_GRANT, "ttl": ttl})
        lease = Lease(resp["lease_id"], resp["ttl"], self)
        if keepalive:
            lease.start_keepalive()
        return lease

    async def watch_prefix(self, prefix: str) -> Watch:
        resp, payload = await self._call({"op": OP_WATCH, "prefix": prefix})
        watch = Watch(resp["watch_id"], codec.unpack(payload), self)
        self._watches[watch.watch_id] = watch
        return watch

    async def _unwatch(self, watch_id: int):
        self._watches.pop(watch_id, None)
        try:
            await self._call({"op": OP_UNWATCH, "watch_id": watch_id})
        except ConnectionError:
            pass

    async def publish(self, topic: str, payload: bytes):
        """Fire-and-forget topic publish (NATS-core role)."""
        await self._call({"op": OP_PUBLISH, "topic": topic}, payload)

    async def subscribe(self, topic: str) -> Subscription:
        resp, _ = await self._call({"op": OP_SUBSCRIBE, "topic": topic})
        sub = Subscription(resp["sub_id"], topic, self)
        self._subs[sub.sub_id] = sub
        return sub

    async def lock(self, name: str, lease: Lease, retries: int = 100, delay: float = 0.05) -> bool:
        """Simple distributed lock: atomic-create a lock key under a lease
        (released on lease death), retrying until acquired."""
        key = f"v1/locks/{name}"
        for _ in range(retries):
            if await self.create(key, str(lease.lease_id).encode(), lease):
                return True
            await asyncio.sleep(delay)
        return False

    async def unlock(self, name: str):
        await self.delete(f"v1/locks/{name}")

    async def status(self) -> dict:
        resp, _ = await self._call({"op": OP_STATUS})
        return resp


# --------------------------------------------------------------------------- #
# Standalone entrypoint
# --------------------------------------------------------------------------- #


def main():
    import argparse

    from .logging import init_logging

    init_logging()
    ap = argparse.ArgumentParser(description="dynamo-tpu discovery service")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=2379)
    args = ap.parse_args()

    async def run():
        server = DiscoveryServer(args.host, args.port)
        await server.start()
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
