"""dynamo_tpu.runtime — the distributed runtime (reference: lib/runtime).

Public surface mirrors the reference's `dynamo.runtime` Python package:
DistributedRuntime, Namespace/Component/Endpoint, Context, AsyncEngine,
PushRouter/RouterMode, discovery client/server, config, logging.
"""

from . import faults
from .backoff import Backoff
from .config import RuntimeConfig, discovery_address
from .component import (
    Client,
    Component,
    DistributedRuntime,
    Endpoint,
    Instance,
    Namespace,
    ServedEndpoint,
    INSTANCE_ROOT,
    MODEL_ROOT,
)
from .discovery import DiscoveryClient, DiscoveryServer, Lease, Watch, WatchEvent
from .engine import AsyncEngine, Context, FnEngine, ResponseStream, collect
from .logging import (
    DistributedTraceContext,
    current_trace,
    init_logging,
    parse_traceparent,
    set_trace,
)
from .push_router import PushRouter, RouterMode
from .request_plane import (
    DeadlineExceeded,
    EndpointStats,
    EngineError,
    RequestPlaneClient,
    RequestPlaneServer,
    StreamLost,
)

__all__ = [
    "AsyncEngine",
    "Backoff",
    "Client",
    "Component",
    "Context",
    "DeadlineExceeded",
    "DiscoveryClient",
    "DiscoveryServer",
    "DistributedRuntime",
    "DistributedTraceContext",
    "Endpoint",
    "EndpointStats",
    "EngineError",
    "FnEngine",
    "Instance",
    "INSTANCE_ROOT",
    "Lease",
    "MODEL_ROOT",
    "Namespace",
    "PushRouter",
    "RequestPlaneClient",
    "RequestPlaneServer",
    "ResponseStream",
    "RouterMode",
    "RuntimeConfig",
    "ServedEndpoint",
    "StreamLost",
    "Watch",
    "WatchEvent",
    "collect",
    "current_trace",
    "discovery_address",
    "faults",
    "init_logging",
    "parse_traceparent",
    "set_trace",
]
