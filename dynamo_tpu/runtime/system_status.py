"""System status server: /health, /live, /metrics.

Role of the reference's system status server
(lib/runtime/src/system_status_server.rs + system_health.rs): a small HTTP
server per process, enabled by DYN_SYSTEM_ENABLED/DYN_SYSTEM_PORT,
reporting liveness (process up), readiness (endpoint health states), and
the process metrics registry.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Optional, Tuple

from aiohttp import web

from .metrics import MetricsRegistry

logger = logging.getLogger(__name__)


class SystemHealth:
    """Endpoint-state-driven system health (reference system_health.rs):
    the process is ready iff every registered endpoint is healthy."""

    def __init__(self):
        self._endpoints: Dict[str, bool] = {}

    def set_endpoint_health(self, endpoint_path: str, healthy: bool) -> None:
        self._endpoints[endpoint_path] = healthy

    def remove_endpoint(self, endpoint_path: str) -> None:
        self._endpoints.pop(endpoint_path, None)

    @property
    def healthy(self) -> bool:
        return all(self._endpoints.values()) if self._endpoints else True

    def snapshot(self) -> dict:
        return {
            "status": "healthy" if self.healthy else "unhealthy",
            "endpoints": dict(self._endpoints),
        }


class SystemStatusServer:
    def __init__(
        self,
        health: SystemHealth,
        metrics: Optional[MetricsRegistry] = None,
        host: str = "0.0.0.0",
        port: int = 0,
    ):
        self.health = health
        self.metrics = metrics
        self.host = host
        self.port = port
        self._runner: Optional[web.AppRunner] = None

    async def start(self) -> Tuple[str, int]:
        app = web.Application()
        app.router.add_get("/health", self._health)
        app.router.add_get("/live", self._live)
        app.router.add_get("/metrics", self._metrics)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        logger.info("system status server on %s:%d", self.host, self.port)
        return self.host, self.port

    async def stop(self) -> None:
        # take-then-act: cleanup() suspends, and a concurrent stop() passing
        # the None-check during that await would run cleanup twice
        runner, self._runner = self._runner, None
        if runner is not None:
            await runner.cleanup()

    async def _health(self, request: web.Request) -> web.Response:
        snap = self.health.snapshot()
        return web.json_response(snap, status=200 if self.health.healthy else 503)

    async def _live(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "live"})

    async def _metrics(self, request: web.Request) -> web.Response:
        body = self.metrics.render() if self.metrics is not None else b""
        return web.Response(body=body, content_type="text/plain", charset="utf-8")
