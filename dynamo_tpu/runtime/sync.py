"""Concurrency registry: the single spelling of who guards what.

The serving plane mutates shared state from four kinds of context — the
asyncio event loop's many tasks, the engine's single-threaded device
executor (``jax-step``), its host-fetch thread, and the KVBM store path
that rides the device executor — and the classic failure is not a crash
but a check-then-act sequence silently torn by an ``await`` or an
unlocked cross-thread read.  ``GUARDED_STATE`` below is the machine-
checked table of every attribute whose guard discipline the
``race-guarded-state`` dynolint rule enforces project-wide, in the same
single-spelling pattern as ``ENV_REGISTRY`` (config.py), ``FRAME_TAGS``
(codec.py) and ``KNOWN_FAULT_POINTS`` (faults.py).

Guard grammar (the value string):

  ``lock:<attr>``
      Every access (read or write) of the attribute inside the owning
      class happens under ``with self.<attr>`` / ``async with
      self.<attr>`` on the named lock.  ``__init__`` is exempt
      (construction precedes sharing).

  ``single-task:<owner>``
      Mutations are confined to the asyncio task whose body is
      ``<owner>``: every mutation site must sit in ``<owner>`` or a
      function (transitively) called from it.  Reads from other tasks
      are allowed — the event loop makes a sync read atomic — which is
      exactly why check-then-act ACROSS an await needs the
      ``race-await-atomicity`` rule instead.

  ``thread:<owner>``
      Same confinement check, but ``<owner>`` runs on a dedicated
      non-event-loop thread (the engine's device executor); readers on
      other threads must take an atomic snapshot (``list(d.items())``)
      rather than iterate live state.

A registry entry whose class, attribute, guard lock, or owner function
no longer exists FIRES — the table cannot drift from the code.  The
table renders into docs/concurrency.md via
``python -m dynamo_tpu.analysis --emit-sync-docs`` (freshness-tested),
so the guard conventions future schedulers must land into are readable
without opening this file.
"""

from __future__ import annotations

#: "Class.attr" -> guard spec (grammar above).  Keep keys as plain string
#: literals: the race rules parse this file's AST and never import it.
GUARDED_STATE = {
    # KVBM tier state: written on the kvbm-tier thread (batched offload
    # stores; the device-exec thread on the DYN_KVBM_PIPELINE=0 inline
    # path), read on the event loop (admission probe) — the lock is the
    # only thing standing between them.
    "KvBlockManager.host": "lock:_lock",
    "KvBlockManager.disk": "lock:_lock",
    "KvBlockManager.offloaded_blocks": "lock:_lock",
    "KvBlockManager.onboarded_blocks": "lock:_lock",
    "KvBlockManager.disk_evictions": "lock:_lock",
    "KvBlockManager.dropped_blocks": "lock:_lock",
    "KvBlockManager._load_ms": "lock:_lock",
    # cluster KV fabric: hashes dropped from ALL tiers pending their
    # `evicted` mesh retraction — appended on the kvbm-tier thread's
    # store path, drained wherever announcements fire.
    "KvBlockManager._evicted_pending": "lock:_lock",
    # legacy inline offload count: bumped on the event loop, dropped in
    # the executor's done-callback thread.
    "KvbmConnector._pending": "lock:_pending_lock",
    # kvbm offload pipeline (docs/kvbm.md): the event loop stages commits
    # and flushes them into batches, the device-exec thread marks a
    # batch's gather ready, the kvbm-tier thread consumes — three
    # contexts, one condition variable's lock over all of it.
    "KvbmConnector._staged": "lock:_offload_cv",
    "KvbmConnector._queue": "lock:_offload_cv",
    "KvbmConnector._inflight_hashes": "lock:_offload_cv",
    "KvbmConnector._processing": "lock:_offload_cv",
    "KvbmConnector._stopped": "lock:_offload_cv",
    "KvbmConnector.offload_gathers": "lock:_offload_cv",
    "KvbmConnector.offload_blocks_dropped": "lock:_offload_cv",
    "KvbmConnector.offload_failures": "lock:_offload_cv",
    # per-source onboard decision counters (cluster KV fabric): bumped at
    # admission on the event loop, read by stats() from any context.
    "KvbmConnector.onboard_src_local_blocks": "lock:_offload_cv",
    "KvbmConnector.onboard_src_peer_blocks": "lock:_offload_cv",
    "KvbmConnector.onboard_src_recompute_blocks": "lock:_offload_cv",
    # engine decode pipeline: the step-loop task owns the in-flight block
    # queue and prefill-completion list; ROADMAP item 1's scheduler must
    # keep mutations inside the step loop (or take over this entry).
    "JaxEngine._inflight": "single-task:_step_loop",
    "JaxEngine._pending_prefill": "single-task:_step_loop",
    "JaxEngine._carry_valid": "single-task:_step_loop",
    # per-dispatch-type device occupancy: mutated only inside the `timed`
    # wrapper, which runs on the jax-step device-executor thread; readers
    # (stats) take a list() snapshot.
    "JaxEngine._dev_time": "thread:timed",
    # dynosched (engine/scheduler/): the cost model's per-shape EWMA is
    # written on the jax-step thread (the `timed` wrapper observes every
    # dispatch) and read on the event loop (planning, stats, the disagg
    # TTFT estimate) — the lock is the only thing between them. Planner
    # bookkeeping (deadline table, decision records) stays confined to
    # the engine step loop, per the convention this registry was seeded
    # to enforce on ROADMAP item 1's scheduler.
    "CostModel._ewma": "lock:_lock",
    # live role morphing (docs/autoscaling.md "Role morphing"): the
    # serving role and the morph state machine's position are mutated
    # only inside the engines' `morph` coroutine (one morph at a time —
    # morph() refuses re-entry); generate/admission/stats read them from
    # other tasks, which the event loop makes atomic per read.
    "JaxEngine._role": "single-task:morph",
    "JaxEngine._morph_state": "single-task:morph",
    "MockEngine._role": "single-task:morph",
    "MockEngine._morph_state": "single-task:morph",
    "StepPlanner._deadlines": "single-task:_step_loop",
    "StepPlanner._records": "single-task:_step_loop",
    # dynogate tenant-fairness tiebreak bookkeeping: granted tokens per
    # tenant, fed by the planner's own accounting calls (all reached from
    # the engine step loop, like the deadline table above).
    "StepPlanner._tenant_served": "single-task:_step_loop",
    # dynogate (gate/gate.py): every WFQ/virtual-time/debt mutation is
    # confined to the gate's single pump task; `admit` only appends to
    # the inbox asyncio.Queue and awaits its entry's future, so
    # admission decisions cannot tear across requests.
    "AdmissionGate._waiting": "single-task:_pump",
    "AdmissionGate._debt": "single-task:_pump",
    "AdmissionGate._debt_seen": "single-task:_pump",
    # endpoint instance table: the watch task is the only mutator once
    # the client is started (static mode carries a reasoned waiver).
    "Client.instances": "single-task:_watch_loop",
    # SLA planner loop (planner/planner_core.py): the governor's committed
    # target and streak/cooldown counters are owned end-to-end by the
    # planner's own `run` task (observe → adjust → reconcile, serially);
    # the soak and unit tests drive the same methods single-task too.
    "Planner._target": "single-task:run",
    "Planner._below_streak": "single-task:run",
    "Planner._intervals_since_change": "single-task:run",
    # re-role arms (docs/autoscaling.md "Role morphing"): the colocate
    # streak is governor state like the counters above — owned by the
    # planner's run task end to end.
    "Planner._colocate_streak": "single-task:run",
    # connector replica bookkeeping: written only by set_replicas /
    # reconcile, both reached from the planner's run task.
    "LocalProcessConnector._want": "single-task:run",
    "InProcWorkerPool._want": "single-task:run",
    # the in-proc pool's worker list moves with _want: every mutation
    # (spawn/retire/morph/kill) happens in connector methods reached from
    # the planner's run task; other tasks only snapshot-read it.
    "InProcWorkerPool.workers": "single-task:run",
    # deploy/planner reconcilers: one _PollLoop task per reconciler owns
    # the failure-backoff and revision bookkeeping end to end.
    "GraphController._failures": "single-task:reconcile_once",
    "GraphController._retry_at": "single-task:reconcile_once",
    "GraphReconciler._applied_base": "single-task:reconcile_once",
    "GraphReconciler.applied_revision": "single-task:reconcile_once",
    "OperatorLite.applied_revision": "single-task:reconcile_once",
}
