"""Streaming engine abstraction + cancellation contexts.

Mirrors reference lib/runtime/src/engine.rs: `AsyncEngine` (:201) is the
universal request→response-stream interface every layer speaks;
`AsyncEngineContext` (:112) carries id + cancellation ("kill switch")
down the pipeline; `ResponseStream` (:213) pairs a stream with its context.

In dynamo-tpu an engine is any object with
    async def generate(request, context) -> AsyncIterator[response]
Operators (preprocessor, backend, migration, router) wrap engines; the
outermost stream is consumed by the HTTP frontend.
"""

from __future__ import annotations

import asyncio
import secrets
import time
from typing import Any, AsyncIterator, Awaitable, Callable, Optional, Protocol, runtime_checkable


class Context:
    """Cancellation context propagated through the pipeline
    (reference AsyncEngineContext engine.rs:112).

    `stop_generating` = graceful: finish the current token, emit a final
    usage chunk. `kill` = hard: stop streaming immediately. Child contexts
    form a cancellation tree like the reference's token hierarchy.

    A context may carry a `deadline` (absolute `time.monotonic()` value):
    the end-to-end budget for the request. Connect attempts, retry loops
    (migration) and backoff waits clip to it — past the deadline they stop
    retrying and surface a clean error instead of spinning. Children
    inherit the tightest deadline on the parent chain; the deadline also
    crosses the request plane (`deadline_ms` on the wire) so worker-side
    contexts see the same budget.
    """

    def __init__(
        self,
        id: Optional[str] = None,
        parent: Optional["Context"] = None,
        deadline: Optional[float] = None,
    ):
        self._id = id or secrets.token_hex(8)
        self._stopped = asyncio.Event()
        self._killed = asyncio.Event()
        self._parent = parent
        self._deadline = deadline
        self._children: list[Context] = []
        # the worker instance the last routed dial targeted (set by
        # Client.direct): when the stream dies, migration reads this to
        # exclude the dead instance from the retry's re-route
        # (docs/fault_tolerance.md "Request migration")
        self.routed_instance: Optional[int] = None
        if parent is not None:
            parent._children.append(self)

    @property
    def id(self) -> str:
        return self._id

    @property
    def deadline(self) -> Optional[float]:
        """Effective deadline: the tightest on the parent chain."""
        own = self._deadline
        if self._parent is not None:
            inherited = self._parent.deadline
            if inherited is not None and (own is None or inherited < own):
                return inherited
        return own

    def set_deadline(self, seconds_from_now: float) -> "Context":
        self._deadline = time.monotonic() + seconds_from_now
        return self

    def time_remaining(self) -> Optional[float]:
        """Seconds until the deadline (>= 0), or None when unbounded."""
        dl = self.deadline
        return None if dl is None else max(0.0, dl - time.monotonic())

    def deadline_exceeded(self) -> bool:
        dl = self.deadline
        return dl is not None and time.monotonic() >= dl

    def is_stopped(self) -> bool:
        return self._stopped.is_set() or (self._parent is not None and self._parent.is_stopped())

    def is_killed(self) -> bool:
        return self._killed.is_set() or (self._parent is not None and self._parent.is_killed())

    def stop_generating(self):
        self._stopped.set()
        for child in self._children:
            child.stop_generating()

    def kill(self):
        self._killed.set()
        self._stopped.set()
        for child in self._children:
            child.kill()

    async def stopped(self):
        """Wait until stop is requested."""
        await self._wait_event(lambda c: c._stopped)

    async def killed(self):
        """Wait until hard kill is requested."""
        await self._wait_event(lambda c: c._killed)

    async def _wait_event(self, get_event):
        if self._parent is None:
            await get_event(self).wait()
            return
        parent_task = asyncio.create_task(self._parent._wait_event(get_event))
        own_task = asyncio.create_task(get_event(self).wait())
        done, pending = await asyncio.wait(
            [parent_task, own_task], return_when=asyncio.FIRST_COMPLETED
        )
        for task in pending:
            task.cancel()

    def child(self, id: Optional[str] = None) -> "Context":
        return Context(id=id or self._id, parent=self)


@runtime_checkable
class AsyncEngine(Protocol):
    """The universal streaming engine interface (reference engine.rs:201)."""

    def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        ...


class FnEngine:
    """Adapt a plain async-generator function into an AsyncEngine."""

    def __init__(self, fn: Callable[[Any, Context], AsyncIterator[Any]], name: str = "fn"):
        self._fn = fn
        self.name = name

    def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        return self._fn(request, context)


class ResponseStream:
    """An async response stream bound to its engine context
    (reference ResponseStream engine.rs:213)."""

    def __init__(self, stream: AsyncIterator[Any], context: Context):
        self._stream = stream
        self.context = context

    def __aiter__(self):
        return self

    async def __anext__(self):
        if self.context.is_killed():
            raise StopAsyncIteration
        return await self._stream.__anext__()


async def collect(stream: AsyncIterator[Any]) -> list:
    """Drain a stream into a list (test helper)."""
    return [item async for item in stream]
