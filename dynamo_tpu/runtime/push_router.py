"""Client-side request routing across endpoint instances.

Mirrors reference PushRouter with RouterMode {RoundRobin, Random, Direct, KV}
(lib/runtime/src/pipeline/network/egress/push_router.rs:71). The KV mode is
implemented by KvPushRouter in llm/kv_router (it picks an instance by cache
overlap, then delegates here via `direct`).
"""

from __future__ import annotations

import enum
import random
from typing import Any, AsyncIterator, Callable, List, Optional

from .component import Client
from .engine import Context
from .request_plane import StreamLost


class RouterMode(str, enum.Enum):
    ROUND_ROBIN = "round-robin"
    RANDOM = "random"
    DIRECT = "direct"
    KV = "kv"


def request_excluded_instances(request: Any) -> List[int]:
    """Per-request dead-instance exclusions (`router.exclude_instances`,
    set by migration retries — docs/fault_tolerance.md): routers must not
    dial these even while the corpse's lease lingers in discovery."""
    router = (
        request.get("router") if isinstance(request, dict)
        else getattr(request, "router", None)
    )
    if not isinstance(router, dict):
        return []
    try:
        return [int(i) for i in router.get("exclude_instances") or []]
    except (TypeError, ValueError):
        return []


class PushRouter:
    """Route requests over the live instances of an endpoint client
    (reference push_router.rs:71)."""

    def __init__(
        self,
        client: Client,
        mode: RouterMode = RouterMode.ROUND_ROBIN,
        direct_instance: Optional[int] = None,
        prefer: Optional[Callable[[List[int]], List[int]]] = None,
    ):
        self.client = client
        self.mode = mode
        self.direct_instance = direct_instance
        # load-aware instance preference (dynogate, docs/overload.md):
        # narrows the candidate set to instances below the gate's
        # queue-depth watermark, so a saturated-but-ready worker is not
        # dialed like an idle one. The hook may degrade the choice but
        # never empty it (it falls back to the full set); DIRECT mode is
        # pinned and bypasses it.
        self.prefer = prefer
        self._rr_index = 0

    def _pick(self, exclude: set) -> int:
        if self.mode == RouterMode.DIRECT:
            # pinned routing has no failover set: a dead pinned instance
            # must fail fast after ONE StreamLost, not be re-dialed once
            # per live instance
            if self.direct_instance is None:
                raise ValueError("direct mode requires an instance id")
            if self.direct_instance in exclude:
                raise StreamLost(
                    f"pinned instance {self.direct_instance:x} unavailable "
                    f"for {self.client.endpoint.subject}"
                )
            return self.direct_instance
        # NEW streams only target ready instances: a `draining` discovery
        # record means the worker is mid-scale-down and will reject the
        # stream anyway — skipping it here saves a dial + rejection per
        # request during the drain window
        ids = [i for i in self.client.ready_instance_ids() if i not in exclude]
        if not ids:
            raise StreamLost(f"no instances for {self.client.endpoint.subject}")
        if self.prefer is not None and len(ids) > 1:
            preferred = [i for i in self.prefer(ids) if i not in exclude]
            if preferred:
                ids = preferred
        if self.mode == RouterMode.RANDOM:
            return random.choice(ids)
        # round-robin default
        inst = ids[self._rr_index % len(ids)]
        self._rr_index += 1
        return inst

    async def generate(
        self, request: Any, context: Optional[Context] = None
    ) -> AsyncIterator[Any]:
        """Pick an instance and issue the request. On connect failure, retry
        the remaining instances once each before giving up. Failed instances
        are only skipped within this call — discovery (lease expiry) is the
        authority on permanent removal. A migration retry additionally
        names its dead worker(s) in `router.exclude_instances`: the corpse
        is never dialed even while its lease lingers."""
        tried: set = set(request_excluded_instances(request))
        last_err: Optional[Exception] = None
        for _ in range(max(1, len(self.client.instance_ids()))):
            try:
                instance_id = self._pick(exclude=tried)
            except StreamLost:
                break
            try:
                return await self.client.direct(request, instance_id, context)
            except StreamLost as e:
                last_err = e
                tried.add(instance_id)
                continue
        raise last_err or StreamLost("no instances available")
