"""Two-part wire codec for the request/response planes.

Mirrors the reference's TwoPartCodec
(lib/runtime/src/pipeline/network/codec/two_part.rs): every message is a
control header (msgpack map) plus an opaque payload, length-prefixed so it
can be streamed over a raw TCP connection.

Frame layout (little-endian):
    u32 magic 0xD7A0C0DE | u32 header_len | u32 payload_len | header | payload
"""

from __future__ import annotations

import asyncio
import struct
from array import array
from typing import Any, List, Optional, Tuple

import msgpack

MAGIC = 0xD7A0C0DE
_HDR = struct.Struct("<III")
MAX_FRAME = 1 << 30  # 1 GiB sanity bound

# --------------------------------------------------------------------- #
# Wire-frame tag registry
# --------------------------------------------------------------------- #
# The single spelling of every dispatch tag the serving plane's framed
# protocols put on the wire. Producers and consumers import these
# constants; the `flow-frame-protocol` dynolint rule checks that every
# tag literal reaching a frame dict or a dispatch comparison resolves
# into FRAME_TAGS, and that the producer and consumer sets stay
# symmetric per channel (a tag emitted with no dispatch arm — or a
# dispatch arm no producer can reach — is protocol drift and fails CI).
# See docs/wire_protocol.md.

# request/response plane, "t" channel (runtime/request_plane.py)
T_REQ = "req"
T_CANCEL = "cancel"
T_PING = "ping"
T_PONG = "pong"
T_DATA = "data"
T_DONE = "done"
T_ERR = "err"
T_LOST = "lost"  # synthesized client-side on connection loss; never sent

# discovery control plane, "op" channel (runtime/discovery.py)
OP_PUT = "put"
OP_CREATE = "create"
OP_GET = "get"
OP_GET_PREFIX = "get_prefix"
OP_DELETE = "delete"
OP_DELETE_PREFIX = "delete_prefix"
OP_LEASE_GRANT = "lease_grant"
OP_LEASE_KEEPALIVE = "lease_keepalive"
OP_LEASE_REVOKE = "lease_revoke"
OP_WATCH = "watch"
OP_UNWATCH = "unwatch"
OP_PUBLISH = "publish"
OP_SUBSCRIBE = "subscribe"
OP_UNSUBSCRIBE = "unsubscribe"
OP_STATUS = "status"

# discovery server->client pushes, "push" channel (runtime/discovery.py)
PUSH_WATCH = "watch"
PUSH_MSG = "msg"

# payload encodings riding T_DATA frames, "enc" channel
# (runtime/request_plane.py).  Absent = msgpack (the default payload
# serializer).  A stream NEGOTIATES binary encodings: the client's T_REQ
# carries `bin: 1` and the server answers pure token-delta batches with
# `enc: "tok"` frames; anything the encoding cannot carry (finish
# reasons, logprobs, text riders) falls back to msgpack per frame.
ENC_TOK = "tok"

# machine-readable error codes riding T_ERR frames, "code" channel
# (runtime/request_plane.py).  The human `error` string is for logs; the
# code is what clients DISPATCH on — drift here is the same silent-hang
# class as an unconsumed frame tag, so ERR_CODES holds producer/consumer
# symmetry exactly like FRAME_TAGS.
ERR_DRAINING = "draining"
ERR_DEADLINE = "deadline"

FRAME_TAGS = {
    "t": {
        T_REQ: "open a stream: subject + packed request payload",
        T_CANCEL: "cancel a stream (kill=bool: hard vs graceful stop)",
        T_PING: "transport liveness probe",
        T_PONG: "liveness probe reply",
        T_DATA: "one stream item (n=k: payload is k coalesced items)",
        T_DONE: "clean end of stream",
        T_ERR: "terminal stream error (code=draining: retry elsewhere)",
        T_LOST: "local marker: connection died mid-stream (never on wire)",
    },
    "op": {
        OP_PUT: "write a key (optionally lease-attached)",
        OP_CREATE: "atomic create: fails if the key exists",
        OP_GET: "read one key",
        OP_GET_PREFIX: "read all keys under a prefix",
        OP_DELETE: "delete one key",
        OP_DELETE_PREFIX: "delete all keys under a prefix",
        OP_LEASE_GRANT: "grant a TTL lease",
        OP_LEASE_KEEPALIVE: "refresh a lease's deadline",
        OP_LEASE_REVOKE: "revoke a lease (deletes attached keys)",
        OP_WATCH: "start a prefix watch (reply carries snapshot)",
        OP_UNWATCH: "end a prefix watch",
        OP_PUBLISH: "fan a payload out to topic subscribers",
        OP_SUBSCRIBE: "subscribe to a topic",
        OP_UNSUBSCRIBE: "end a topic subscription",
        OP_STATUS: "server status snapshot",
    },
    "push": {
        PUSH_WATCH: "server-pushed watch event (type=put|delete)",
        PUSH_MSG: "server-pushed topic message",
    },
    "enc": {
        ENC_TOK: "T_DATA payload is packed u32 token deltas (zero-copy "
                 "token path), not msgpack; absent enc = msgpack",
    },
}

#: wire error codes on T_ERR frames; checked by flow-frame-protocol as
#: the "code" channel (emit/consume symmetry, dead entries fire)
ERR_CODES = {
    ERR_DRAINING: "worker draining: clients treat as StreamLost and retry "
                  "another instance",
    ERR_DEADLINE: "end-to-end deadline passed worker-side: clients raise "
                  "DeadlineExceeded so migration stops retrying",
}


def encode_frame(control: dict, payload: bytes = b"") -> bytes:
    header = msgpack.packb(control, use_bin_type=True)
    return _HDR.pack(MAGIC, len(header), len(payload)) + header + payload


def decode_frame(buf: bytes) -> Tuple[dict, bytes]:
    magic, hlen, plen = _HDR.unpack_from(buf, 0)
    if magic != MAGIC:
        raise ValueError(f"bad frame magic {magic:#x}")
    off = _HDR.size
    header = msgpack.unpackb(buf[off : off + hlen], raw=False)
    payload = bytes(buf[off + hlen : off + hlen + plen])
    return header, payload


async def read_frame(reader: asyncio.StreamReader) -> Optional[Tuple[dict, bytes]]:
    """Read one frame; returns None on clean EOF at a frame boundary."""
    try:
        head = await reader.readexactly(_HDR.size)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    magic, hlen, plen = _HDR.unpack(head)
    if magic != MAGIC:
        raise ValueError(f"bad frame magic {magic:#x}")
    if hlen + plen > MAX_FRAME:
        raise ValueError(f"frame too large: {hlen + plen}")
    body = await reader.readexactly(hlen + plen)
    header = msgpack.unpackb(body[:hlen], raw=False)
    return header, body[hlen:]


async def write_frame(
    writer: asyncio.StreamWriter, control: dict, payload: bytes = b""
):
    # corked write: hand the transport the three segments in one call
    # instead of concatenating header+payload into a fresh buffer — on the
    # token hot path the payload is the large part and must not be copied
    header = msgpack.packb(control, use_bin_type=True)
    writer.writelines(
        (_HDR.pack(MAGIC, len(header), len(payload)), header, payload)
    )
    await writer.drain()


def pack(obj: Any) -> bytes:
    """Payload serializer used across the request plane."""
    return msgpack.packb(obj, use_bin_type=True)


def unpack(data: bytes) -> Any:
    return msgpack.unpackb(data, raw=False)


# --------------------------------------------------------------------- #
# ENC_TOK binary token-delta payload (zero-copy token path)
# --------------------------------------------------------------------- #
# Steady-state decode traffic is a stream of pure token deltas — either
# bare `{"token_ids": [...]}` dicts or the engines' Annotated wrapper
# `{"data": {"token_ids": [...]}}`; encoding each as a msgpack map (and
# re-materializing k dicts per frame on the frontend) is pure per-token
# overhead. ENC_TOK packs a whole coalesced batch of one shape as flat
# little-endian u32s:
#
#     u32 n_items | u32 flags | u32 len[n_items] | u32 ids[sum(len)]
#
# `flags` bit 0 records the wrapper (0 = bare, 1 = Annotated-wrapped) so
# decode reproduces the msgpack path's dicts SHAPE-identically; all other
# bits are reserved — a future variant sets one, and decoders reject what
# they don't speak instead of misreading. Item boundaries are preserved.

_TOK_HDR = struct.Struct("<II")
_TOK_FLAG_WRAPPED = 1  # items were {"data": {"token_ids": [...]}}
# array typecode with a 4-byte item (platform-dependent: "I" on every
# supported platform, "L" kept as a guard for exotic ABIs)
_U32 = "I" if array("I").itemsize == 4 else "L"
assert array(_U32).itemsize == 4, "no 4-byte unsigned array typecode"
_BIG_ENDIAN = struct.pack("=I", 1) != struct.pack("<I", 1)


def token_delta_kind(item: Any) -> int:
    """0 = not a pure token delta (must ride msgpack); 1 = bare
    `{"token_ids": [...]}`; 2 = Annotated-wrapped
    `{"data": {"token_ids": [...]}}` (what the engines emit). Anything
    else — finish reasons, text riders, logprobs, annotation events —
    forces the frame back to msgpack. Shape-only (hot path): id VALUES
    are validated by the array pack itself, which raises on anything
    outside u32 and falls back to msgpack (try_pack_token_run)."""
    if type(item) is not dict or len(item) != 1:
        return 0
    ids = item.get("token_ids")
    if ids is not None:
        return 1 if type(ids) is list and ids else 0
    d = item.get("data")
    if type(d) is dict and len(d) == 1:
        ids = d.get("token_ids")
        if type(ids) is list and ids:
            return 2
    return 0


def pack_token_items(items: List[dict], wrapped: bool = False) -> bytes:
    """Encode pure token-delta items of ONE shape (`wrapped` selects the
    Annotated wrapper); the caller guarantees a uniform
    `token_delta_kind` for every item. Raises TypeError/OverflowError on
    ids outside u32 — callers fall back to msgpack."""
    if wrapped:
        items = [it["data"] for it in items]
    lens = array(_U32, [len(it["token_ids"]) for it in items])
    ids = array(_U32)
    for it in items:
        ids.extend(it["token_ids"])
    if _BIG_ENDIAN:  # wire order is little-endian
        lens.byteswap()
        ids.byteswap()
    flags = _TOK_FLAG_WRAPPED if wrapped else 0
    return _TOK_HDR.pack(len(items), flags) + lens.tobytes() + ids.tobytes()


def try_pack_token_run(items: List[Any]) -> Optional[Tuple[bytes, int]]:
    """Pack the LEADING run of pure same-shape token deltas as an ENC_TOK
    payload. Returns (payload, run_length), or None when items[0] is not
    a clean token delta (the whole batch then rides msgpack)."""
    kind = token_delta_kind(items[0])
    if not kind:
        return None
    pos = 1
    while pos < len(items) and token_delta_kind(items[pos]) == kind:
        pos += 1
    try:
        return pack_token_items(items[:pos], wrapped=kind == 2), pos
    except (TypeError, OverflowError):
        # exotic ids (negative, > u32, non-int): msgpack carries anything
        return None


def unpack_token_items(payload: bytes, merge: bool = False) -> List[dict]:
    """Decode an ENC_TOK payload back into item dicts, in order.

    merge=False reproduces the msgpack path's items shape- and
    boundary-identically. merge=True returns ONE item carrying the whole
    frame's ids — the request-plane client uses this: item boundaries
    inside a frame of pure token deltas carry no information (the
    frontend's merge_token_deltas concatenates every same-tick delta
    anyway), and one dict per frame instead of k is most of the decode
    saving. Token counts, order, and the wrapper shape are preserved."""
    n_items, flags = _TOK_HDR.unpack_from(payload, 0)
    if flags & ~_TOK_FLAG_WRAPPED:
        raise ValueError(f"unknown ENC_TOK flags {flags:#x}")
    wrapped = bool(flags & _TOK_FLAG_WRAPPED)
    off = _TOK_HDR.size
    lens = array(_U32)
    lens.frombytes(payload[off : off + 4 * n_items])
    off += 4 * n_items
    ids = array(_U32)
    ids.frombytes(payload[off:])
    if _BIG_ENDIAN:
        lens.byteswap()
        ids.byteswap()
    total = sum(lens)
    if total != len(ids):
        raise ValueError(
            f"ENC_TOK payload inconsistent: lens sum {total} != {len(ids)} ids"
        )
    if merge:
        d: dict = {"token_ids": ids.tolist()}
        return [{"data": d} if wrapped else d]
    out: List[dict] = []
    pos = 0
    tolist = ids.tolist()
    for n in lens:
        d = {"token_ids": tolist[pos : pos + n]}
        out.append({"data": d} if wrapped else d)
        pos += n
    return out
