"""Two-part wire codec for the request/response planes.

Mirrors the reference's TwoPartCodec
(lib/runtime/src/pipeline/network/codec/two_part.rs): every message is a
control header (msgpack map) plus an opaque payload, length-prefixed so it
can be streamed over a raw TCP connection.

Frame layout (little-endian):
    u32 magic 0xD7A0C0DE | u32 header_len | u32 payload_len | header | payload
"""

from __future__ import annotations

import asyncio
import struct
from typing import Any, Optional, Tuple

import msgpack

MAGIC = 0xD7A0C0DE
_HDR = struct.Struct("<III")
MAX_FRAME = 1 << 30  # 1 GiB sanity bound


def encode_frame(control: dict, payload: bytes = b"") -> bytes:
    header = msgpack.packb(control, use_bin_type=True)
    return _HDR.pack(MAGIC, len(header), len(payload)) + header + payload


def decode_frame(buf: bytes) -> Tuple[dict, bytes]:
    magic, hlen, plen = _HDR.unpack_from(buf, 0)
    if magic != MAGIC:
        raise ValueError(f"bad frame magic {magic:#x}")
    off = _HDR.size
    header = msgpack.unpackb(buf[off : off + hlen], raw=False)
    payload = bytes(buf[off + hlen : off + hlen + plen])
    return header, payload


async def read_frame(reader: asyncio.StreamReader) -> Optional[Tuple[dict, bytes]]:
    """Read one frame; returns None on clean EOF at a frame boundary."""
    try:
        head = await reader.readexactly(_HDR.size)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    magic, hlen, plen = _HDR.unpack(head)
    if magic != MAGIC:
        raise ValueError(f"bad frame magic {magic:#x}")
    if hlen + plen > MAX_FRAME:
        raise ValueError(f"frame too large: {hlen + plen}")
    body = await reader.readexactly(hlen + plen)
    header = msgpack.unpackb(body[:hlen], raw=False)
    return header, body[hlen:]


async def write_frame(
    writer: asyncio.StreamWriter, control: dict, payload: bytes = b""
):
    # corked write: hand the transport the three segments in one call
    # instead of concatenating header+payload into a fresh buffer — on the
    # token hot path the payload is the large part and must not be copied
    header = msgpack.packb(control, use_bin_type=True)
    writer.writelines(
        (_HDR.pack(MAGIC, len(header), len(payload)), header, payload)
    )
    await writer.drain()


def pack(obj: Any) -> bytes:
    """Payload serializer used across the request plane."""
    return msgpack.packb(obj, use_bin_type=True)


def unpack(data: bytes) -> Any:
    return msgpack.unpackb(data, raw=False)
