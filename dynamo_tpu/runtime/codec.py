"""Two-part wire codec for the request/response planes.

Mirrors the reference's TwoPartCodec
(lib/runtime/src/pipeline/network/codec/two_part.rs): every message is a
control header (msgpack map) plus an opaque payload, length-prefixed so it
can be streamed over a raw TCP connection.

Frame layout (little-endian):
    u32 magic 0xD7A0C0DE | u32 header_len | u32 payload_len | header | payload
"""

from __future__ import annotations

import asyncio
import struct
from typing import Any, Optional, Tuple

import msgpack

MAGIC = 0xD7A0C0DE
_HDR = struct.Struct("<III")
MAX_FRAME = 1 << 30  # 1 GiB sanity bound

# --------------------------------------------------------------------- #
# Wire-frame tag registry
# --------------------------------------------------------------------- #
# The single spelling of every dispatch tag the serving plane's framed
# protocols put on the wire. Producers and consumers import these
# constants; the `flow-frame-protocol` dynolint rule checks that every
# tag literal reaching a frame dict or a dispatch comparison resolves
# into FRAME_TAGS, and that the producer and consumer sets stay
# symmetric per channel (a tag emitted with no dispatch arm — or a
# dispatch arm no producer can reach — is protocol drift and fails CI).
# See docs/wire_protocol.md.

# request/response plane, "t" channel (runtime/request_plane.py)
T_REQ = "req"
T_CANCEL = "cancel"
T_PING = "ping"
T_PONG = "pong"
T_DATA = "data"
T_DONE = "done"
T_ERR = "err"
T_LOST = "lost"  # synthesized client-side on connection loss; never sent

# discovery control plane, "op" channel (runtime/discovery.py)
OP_PUT = "put"
OP_CREATE = "create"
OP_GET = "get"
OP_GET_PREFIX = "get_prefix"
OP_DELETE = "delete"
OP_DELETE_PREFIX = "delete_prefix"
OP_LEASE_GRANT = "lease_grant"
OP_LEASE_KEEPALIVE = "lease_keepalive"
OP_LEASE_REVOKE = "lease_revoke"
OP_WATCH = "watch"
OP_UNWATCH = "unwatch"
OP_PUBLISH = "publish"
OP_SUBSCRIBE = "subscribe"
OP_UNSUBSCRIBE = "unsubscribe"
OP_STATUS = "status"

# discovery server->client pushes, "push" channel (runtime/discovery.py)
PUSH_WATCH = "watch"
PUSH_MSG = "msg"

# machine-readable error codes riding T_ERR frames, "code" channel
# (runtime/request_plane.py).  The human `error` string is for logs; the
# code is what clients DISPATCH on — drift here is the same silent-hang
# class as an unconsumed frame tag, so ERR_CODES holds producer/consumer
# symmetry exactly like FRAME_TAGS.
ERR_DRAINING = "draining"
ERR_DEADLINE = "deadline"

FRAME_TAGS = {
    "t": {
        T_REQ: "open a stream: subject + packed request payload",
        T_CANCEL: "cancel a stream (kill=bool: hard vs graceful stop)",
        T_PING: "transport liveness probe",
        T_PONG: "liveness probe reply",
        T_DATA: "one stream item (n=k: payload is k coalesced items)",
        T_DONE: "clean end of stream",
        T_ERR: "terminal stream error (code=draining: retry elsewhere)",
        T_LOST: "local marker: connection died mid-stream (never on wire)",
    },
    "op": {
        OP_PUT: "write a key (optionally lease-attached)",
        OP_CREATE: "atomic create: fails if the key exists",
        OP_GET: "read one key",
        OP_GET_PREFIX: "read all keys under a prefix",
        OP_DELETE: "delete one key",
        OP_DELETE_PREFIX: "delete all keys under a prefix",
        OP_LEASE_GRANT: "grant a TTL lease",
        OP_LEASE_KEEPALIVE: "refresh a lease's deadline",
        OP_LEASE_REVOKE: "revoke a lease (deletes attached keys)",
        OP_WATCH: "start a prefix watch (reply carries snapshot)",
        OP_UNWATCH: "end a prefix watch",
        OP_PUBLISH: "fan a payload out to topic subscribers",
        OP_SUBSCRIBE: "subscribe to a topic",
        OP_UNSUBSCRIBE: "end a topic subscription",
        OP_STATUS: "server status snapshot",
    },
    "push": {
        PUSH_WATCH: "server-pushed watch event (type=put|delete)",
        PUSH_MSG: "server-pushed topic message",
    },
}

#: wire error codes on T_ERR frames; checked by flow-frame-protocol as
#: the "code" channel (emit/consume symmetry, dead entries fire)
ERR_CODES = {
    ERR_DRAINING: "worker draining: clients treat as StreamLost and retry "
                  "another instance",
    ERR_DEADLINE: "end-to-end deadline passed worker-side: clients raise "
                  "DeadlineExceeded so migration stops retrying",
}


def encode_frame(control: dict, payload: bytes = b"") -> bytes:
    header = msgpack.packb(control, use_bin_type=True)
    return _HDR.pack(MAGIC, len(header), len(payload)) + header + payload


def decode_frame(buf: bytes) -> Tuple[dict, bytes]:
    magic, hlen, plen = _HDR.unpack_from(buf, 0)
    if magic != MAGIC:
        raise ValueError(f"bad frame magic {magic:#x}")
    off = _HDR.size
    header = msgpack.unpackb(buf[off : off + hlen], raw=False)
    payload = bytes(buf[off + hlen : off + hlen + plen])
    return header, payload


async def read_frame(reader: asyncio.StreamReader) -> Optional[Tuple[dict, bytes]]:
    """Read one frame; returns None on clean EOF at a frame boundary."""
    try:
        head = await reader.readexactly(_HDR.size)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    magic, hlen, plen = _HDR.unpack(head)
    if magic != MAGIC:
        raise ValueError(f"bad frame magic {magic:#x}")
    if hlen + plen > MAX_FRAME:
        raise ValueError(f"frame too large: {hlen + plen}")
    body = await reader.readexactly(hlen + plen)
    header = msgpack.unpackb(body[:hlen], raw=False)
    return header, body[hlen:]


async def write_frame(
    writer: asyncio.StreamWriter, control: dict, payload: bytes = b""
):
    # corked write: hand the transport the three segments in one call
    # instead of concatenating header+payload into a fresh buffer — on the
    # token hot path the payload is the large part and must not be copied
    header = msgpack.packb(control, use_bin_type=True)
    writer.writelines(
        (_HDR.pack(MAGIC, len(header), len(payload)), header, payload)
    )
    await writer.drain()


def pack(obj: Any) -> bytes:
    """Payload serializer used across the request plane."""
    return msgpack.packb(obj, use_bin_type=True)


def unpack(data: bytes) -> Any:
    return msgpack.unpackb(data, raw=False)
