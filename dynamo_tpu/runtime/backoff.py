"""Shared retry backoff: exponential with deterministic jitter.

Every reconnect/retry loop in the serving plane (RequestPlaneClient
redials, DiscoveryClient re-watch, Migration retries) uses this one
policy so recovery behavior is uniform and — given a seed — fully
deterministic, which the dynochaos soak tests rely on. Jitter comes from
a seeded `random.Random`, not the global RNG: two processes with the
same seed retry on the same schedule, and a test re-run reproduces the
exact timing it asserted on.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
import zlib
from typing import Awaitable, Callable, Optional, TypeVar

logger = logging.getLogger(__name__)

T = TypeVar("T")


class Backoff:
    """Exponential backoff with deterministic jitter.

    delay(n) = min(max_delay, base * factor**n) * (1 + jitter * U(-1, 1))

    where U is drawn from a Random seeded at construction. `deadline`
    (absolute `time.monotonic()` value) clips every wait so a retry loop
    can never sleep past its request's budget.
    """

    def __init__(
        self,
        base: float = 0.05,
        factor: float = 2.0,
        max_delay: float = 2.0,
        jitter: float = 0.1,
        seed: Optional[int] = None,
    ):
        self.base = base
        self.factor = factor
        self.max_delay = max_delay
        self.jitter = jitter
        self._rng = random.Random(seed)
        self.attempt = 0

    @classmethod
    def seeded(cls, key: str, **kwargs) -> "Backoff":
        """Backoff whose jitter is seeded from a stable string (request id,
        endpoint subject, host:port) — the one idiom every retry loop uses
        so chaos re-runs reproduce their timing."""
        return cls(seed=zlib.crc32(key.encode()), **kwargs)

    def reset(self):
        self.attempt = 0

    def next_delay(self) -> float:
        raw = min(self.max_delay, self.base * (self.factor ** self.attempt))
        self.attempt += 1
        if self.jitter:
            raw *= 1.0 + self.jitter * self._rng.uniform(-1.0, 1.0)
        return max(0.0, raw)

    async def wait(self, deadline: Optional[float] = None) -> bool:
        """Sleep the next delay. Returns False (without sleeping the full
        delay) when `deadline` would be crossed — the caller should stop
        retrying."""
        delay = self.next_delay()
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            if delay >= remaining:
                await asyncio.sleep(remaining)
                return False
        await asyncio.sleep(delay)
        return True


async def retry_async(
    fn: Callable[[], Awaitable[T]],
    *,
    attempts: int,
    backoff: Backoff,
    desc: str = "operation",
    log: Optional[logging.Logger] = None,
) -> T:
    """Bounded retry with backoff: call `fn` up to `attempts` times,
    sleeping the backoff between failures but NEVER after the last one
    (an exhausted retry cycle must not add dead delay to the failing
    path). CancelledError passes straight through; when every attempt
    fails the LAST exception is re-raised for the caller to classify.

    The one retry idiom the planner loop's callers share (metrics scrape,
    connector apply, replica spawn) so attempt accounting and logging
    cannot drift between copies."""
    last: Optional[BaseException] = None
    n = max(1, attempts)
    for attempt in range(n):
        try:
            return await fn()
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — retried, then re-raised
            last = e
            (log or logger).warning(
                "%s failed (attempt %d/%d): %s", desc, attempt + 1, n, e
            )
            if attempt + 1 < n:
                await backoff.wait()
    assert last is not None
    raise last
